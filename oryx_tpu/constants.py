"""Special-token and index constants.

Reference parity: oryx/constants.py in gallenvara/oryx (reference mount was
empty this round; values follow the LLaVA/Oryx family conventions recorded in
SURVEY.md §2).
"""

# Label value ignored by the cross-entropy loss (visual spans, prompt spans).
IGNORE_INDEX = -100

# Sentinel token id used *host-side only* to mark where visual embeddings are
# spliced into the text stream. Never reaches the embedding table: the splicer
# (oryx_tpu/models/splice.py) replaces it with an index map before jit.
IMAGE_TOKEN_INDEX = -200

DEFAULT_IMAGE_TOKEN = "<image>"
DEFAULT_VIDEO_TOKEN = "<video>"
DEFAULT_IM_START_TOKEN = "<im_start>"
DEFAULT_IM_END_TOKEN = "<im_end>"

# Modality tags used by the data pipeline and the Dynamic Compressor ratio
# selection (image -> 1x, multi-image/short video -> 4x, long video -> 16x).
MODALITY_IMAGE = "image"
MODALITY_MULTI_IMAGE = "multi_image"
MODALITY_VIDEO = "video"

# Area-compression ratio per modality (downsample factor per spatial side is
# sqrt of this). SURVEY.md §2 "Dynamic Compressor".
COMPRESSOR_RATIO = {
    MODALITY_IMAGE: 1,
    MODALITY_MULTI_IMAGE: 4,
    MODALITY_VIDEO: 16,
}
