"""ctypes binding for the native host-pipeline kernels (native/loader.cpp).

The C++ library fuses resize+normalize+patchify in one pass and fans a
batch out over a std::thread pool — the framework's equivalent of the
reference's native data-loader floor (PIL-SIMD/torchvision resize + torch
DataLoader worker processes, SURVEY.md §3.1). Falls back cleanly when the
shared library hasn't been built: callers gate on `is_available()`.

Build: `make -C native/` (or `build()` below drives it).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
LIB_NAME = "liboryx_loader.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed = False


def _lib_path() -> str:
    return os.environ.get(
        "ORYX_NATIVE_LIB", os.path.join(_NATIVE_DIR, LIB_NAME)
    )


def build(quiet: bool = True) -> bool:
    """Compile the shared library in-tree. Returns success.

    Cross-PROCESS safe: an exclusive flock serializes concurrent builders
    (the module `_lock` only covers threads), and the Makefile writes to a
    temp file + atomic rename so a concurrent dlopen never maps a
    truncated .so.
    """
    if not os.path.isdir(_NATIVE_DIR):
        return False
    import fcntl

    lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
    try:
        with open(lock_path, "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            # Always run make: it is a no-op when the .so is newer than
            # loader.cpp, and handles stale-library rebuilds; the lock
            # only serializes concurrent builders.
            r = subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                capture_output=quiet, text=True, timeout=120,
            )
            return r.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        path = _lib_path()
        if not os.path.exists(path):
            if os.environ.get("ORYX_NATIVE_AUTOBUILD", "1") != "1" or not build():
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            _lib_failed = True
            return None
        lib.oryx_preprocess_image.restype = ctypes.c_int
        lib.oryx_preprocess_image.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_void_p,
        ]
        lib.oryx_batch_preprocess.restype = ctypes.c_int
        lib.oryx_batch_preprocess.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.c_float, ctypes.c_float,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)), ctypes.c_int,
        ]
        _lib = lib
        return _lib


def is_available() -> bool:
    return _load() is not None


def _img_meta(img: np.ndarray) -> tuple[np.ndarray, int]:
    """Contiguous array + dtype code (0=uint8, 1=float32)."""
    if img.dtype == np.uint8:
        return np.ascontiguousarray(img), 0
    return np.ascontiguousarray(img, dtype=np.float32), 1


def preprocess_image(
    img: np.ndarray,
    out_hw: tuple[int, int],
    patch: int,
    mean: float,
    std: float,
    out: np.ndarray | None = None,
) -> np.ndarray:
    """Fused resize(align_corners=False) + normalize + patchify.

    Returns float32 [gh*gw, patch*patch*C] patch rows (written into `out`
    when given — e.g. a row slice of the packed patches buffer).
    """
    lib = _load()
    assert lib is not None, "native loader unavailable; gate on is_available()"
    img, dtype = _img_meta(img)
    H, W, C = img.shape
    oh, ow = out_hw
    rows = (oh // patch) * (ow // patch)
    if out is None:
        out = np.empty((rows, patch * patch * C), np.float32)
    assert out.dtype == np.float32 and out.flags.c_contiguous
    assert out.shape == (rows, patch * patch * C), (out.shape, rows)
    rc = lib.oryx_preprocess_image(
        img.ctypes.data_as(ctypes.c_void_p), dtype, H, W, C, oh, ow, patch,
        mean, std, out.ctypes.data_as(ctypes.c_void_p),
    )
    if rc != 0:
        raise RuntimeError(f"oryx_preprocess_image failed: {rc}")
    return out


def batch_preprocess(
    images: list[np.ndarray],
    out_hws: list[tuple[int, int]],
    patch: int,
    mean: float,
    std: float,
    outs: list[np.ndarray] | None = None,
    num_threads: int = 0,
) -> list[np.ndarray]:
    """Thread-pool batch version of `preprocess_image`.

    outs may alias disjoint row slices of one packed buffer, so the pool
    writes the final device layout directly.
    """
    lib = _load()
    assert lib is not None, "native loader unavailable; gate on is_available()"
    n = len(images)
    if n == 0:
        return []
    metas = [_img_meta(img) for img in images]
    C = metas[0][0].shape[2]
    if outs is None:
        outs = [
            np.empty(((oh // patch) * (ow // patch), patch * patch * C),
                     np.float32)
            for oh, ow in out_hws
        ]
    arr_i = lambda vals: (ctypes.c_int * n)(*vals)
    img_ptrs = (ctypes.c_void_p * n)(
        *[m[0].ctypes.data_as(ctypes.c_void_p).value for m in metas]
    )
    out_ptrs = (ctypes.POINTER(ctypes.c_float) * n)(
        *[o.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for o in outs]
    )
    rc = lib.oryx_batch_preprocess(
        n, img_ptrs, arr_i([m[1] for m in metas]),
        arr_i([m[0].shape[0] for m in metas]),
        arr_i([m[0].shape[1] for m in metas]),
        arr_i([m[0].shape[2] for m in metas]),
        arr_i([hw[0] for hw in out_hws]), arr_i([hw[1] for hw in out_hws]),
        patch, mean, std, out_ptrs, num_threads,
    )
    if rc != 0:
        raise RuntimeError(f"oryx_batch_preprocess failed: {rc}")
    return outs
