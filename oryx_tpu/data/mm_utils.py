"""Multimodal host-side utilities: prompt tokenization with image sentinels
and native-resolution image/video preprocessing.

Reference parity: `oryx/mm_utils.py` (SURVEY.md §2 "MM utils"; reference
mount empty — behavior reconstructed): `tokenizer_image_token()` splits the
prompt on "<image>" and interleaves the IMAGE_TOKEN_INDEX sentinel;
preprocessing keeps the native aspect ratio, snapping dimensions to patch
multiples and capping total patch count (the arbitrary-resolution contract
of OryxViT). All numpy/PIL on host — nothing here is traced.
"""

from __future__ import annotations

import math

import numpy as np

from oryx_tpu.constants import (
    DEFAULT_IMAGE_TOKEN,
    IMAGE_TOKEN_INDEX,
)

# SigLIP normalization (mean/std 0.5 per channel).
IMAGE_MEAN = 0.5
IMAGE_STD = 0.5


def tokenizer_image_token(
    prompt: str,
    tokenizer,
    image_token_index: int = IMAGE_TOKEN_INDEX,
) -> np.ndarray:
    """Tokenize a prompt containing "<image>" placeholders into int32 ids
    with sentinel values at image positions.

    Mirrors the reference's chunk-split approach: tokenize each text chunk
    separately (add_special_tokens off) and join with the sentinel, so the
    sentinel never perturbs neighboring tokenization.
    """
    chunks = prompt.split(DEFAULT_IMAGE_TOKEN)
    ids: list[int] = []
    for i, chunk in enumerate(chunks):
        if i > 0:
            ids.append(image_token_index)
        if chunk:
            ids.extend(tokenizer.encode(chunk, add_special_tokens=False))
    return np.asarray(ids, dtype=np.int32)


def resize_to_patch_grid(
    hw: tuple[int, int],
    patch_size: int,
    max_patches: int,
    min_patches: int = 1,
) -> tuple[int, int]:
    """Choose output (H, W) pixels: native aspect ratio, dims snapped to
    patch multiples, total patches capped at max_patches (downscale only)."""
    h, w = hw
    scale = 1.0
    ph, pw = max(1, round(h / patch_size)), max(1, round(w / patch_size))
    if ph * pw > max_patches:
        scale = math.sqrt(max_patches / (ph * pw))
        ph = max(min_patches, int(ph * scale))
        pw = max(min_patches, int(pw * scale))
        while ph * pw > max_patches:  # int rounding guard
            if ph >= pw:
                ph -= 1
            else:
                pw -= 1
    return ph * patch_size, pw * patch_size


def preprocess_image(
    image: np.ndarray,
    patch_size: int,
    max_patches: int,
) -> np.ndarray:
    """uint8/float [H, W, 3] → normalized float32 [H', W', 3] with H', W'
    patch multiples at native aspect ratio (bilinear resize)."""
    img = np.asarray(image)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    else:
        img = img.astype(np.float32)
    H, W = img.shape[:2]
    Ht, Wt = resize_to_patch_grid((H, W), patch_size, max_patches)
    if (Ht, Wt) != (H, W):
        img = _bilinear_resize(img, Ht, Wt)
    return (img - IMAGE_MEAN) / IMAGE_STD


def _bilinear_resize(img: np.ndarray, Ht: int, Wt: int) -> np.ndarray:
    """Bilinear resize, align_corners=False semantics (pure numpy)."""
    H, W, C = img.shape
    sy = (np.arange(Ht, dtype=np.float32) + 0.5) * (H / Ht) - 0.5
    sx = (np.arange(Wt, dtype=np.float32) + 0.5) * (W / Wt) - 0.5
    y0f, x0f = np.floor(sy), np.floor(sx)
    # y1 must come from the UNCLIPPED floor: at the low edge both taps clamp
    # to row 0 (torch bilinear align_corners=False edge semantics).
    y0 = np.clip(y0f.astype(np.int64), 0, H - 1)
    y1 = np.clip(y0f.astype(np.int64) + 1, 0, H - 1)
    x0 = np.clip(x0f.astype(np.int64), 0, W - 1)
    x1 = np.clip(x0f.astype(np.int64) + 1, 0, W - 1)
    ly = (sy - y0f)[:, None, None]
    lx = (sx - x0f)[None, :, None]
    top = img[y0][:, x0] * (1 - lx) + img[y0][:, x1] * lx
    bot = img[y1][:, x0] * (1 - lx) + img[y1][:, x1] * lx
    return (top * (1 - ly) + bot * ly).astype(np.float32)


def sample_frames(num_frames_available: int, num_frames: int) -> np.ndarray:
    """Uniform frame-index sampling for video (reference: decord-based
    uniform sampling; the decode itself stays a host-side CPU dependency,
    SURVEY.md §2a last row)."""
    if num_frames_available <= num_frames:
        return np.arange(num_frames_available)
    idx = np.linspace(0, num_frames_available - 1, num_frames)
    return np.round(idx).astype(np.int64)


def get_model_name_from_path(model_path: str) -> str:
    parts = model_path.strip("/").split("/")
    if parts[-1].startswith("checkpoint-") and len(parts) > 1:
        return parts[-2] + "_" + parts[-1]
    return parts[-1]
