"""Host-side media loading: images, and video as frame dirs or decord files.

Reference parity: the reference loads images with PIL and videos with decord
inside its dataset/inference scripts (SURVEY.md §2 "MM utils", §2a last row:
video decode stays a host-side CPU dependency). Decord is optional here; a
directory of frame images always works.
"""

from __future__ import annotations

import os
import re

import numpy as np

from oryx_tpu.data import mm_utils

IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp")


def _natural_key(name: str) -> tuple:
    """Sort key treating digit runs numerically, so frame_2 < frame_10."""
    return tuple(
        int(part) if part.isdigit() else part
        for part in re.split(r"(\d+)", name)
    )


def load_image(path: str) -> np.ndarray:
    from PIL import Image

    return np.asarray(Image.open(path).convert("RGB"))


def load_video_frames(path: str, num_frames: int) -> list[np.ndarray]:
    """Uniformly sample `num_frames` from a video file (decord) or a
    directory of frame images (always available)."""
    if os.path.isdir(path):
        names = sorted(
            (n for n in os.listdir(path) if n.lower().endswith(IMAGE_EXTS)),
            key=_natural_key,
        )
        if not names:
            raise FileNotFoundError(f"no frame images under {path}")
        idx = mm_utils.sample_frames(len(names), num_frames)
        return [load_image(os.path.join(path, names[i])) for i in idx]
    try:
        import decord
    except ImportError as e:
        raise RuntimeError(
            f"decoding {path} needs decord; pass a directory of frames "
            "instead"
        ) from e
    vr = decord.VideoReader(path)
    idx = mm_utils.sample_frames(len(vr), num_frames)
    return [vr[int(i)].asnumpy() for i in idx]


def load_record_media(
    rec: dict, *, media_root: str = "", num_frames: int = 64
) -> tuple[list[np.ndarray], bool]:
    """Load a dataset record's media → (frames/images, is_video).

    Record schema follows the training data (train/data.py): "image" is a
    path or list of paths, "video" a file or frames dir.
    """
    join = lambda p: os.path.join(media_root, p) if media_root else p
    if rec.get("video") is not None:
        return load_video_frames(join(rec["video"]), num_frames), True
    img = rec.get("image")
    if img is None:
        return [], False
    paths = [img] if isinstance(img, str) else list(img)
    return [load_image(join(p)) for p in paths], False
