"""Conversation prompt templating.

Reference parity: oryx/conversation.py (LLaVA-family; SURVEY.md §2
"Conversation templating"). Pure host-side CPU code — templates build the
prompt strings that mm_utils.tokenizer_image_token() then tokenizes.
"""

from __future__ import annotations

import dataclasses
from enum import Enum, auto


class SeparatorStyle(Enum):
    PLAIN = auto()      # bare concatenation (stage-1 projector pretraining)
    TWO = auto()        # vicuna-style two separators
    CHATML = auto()     # Qwen/ChatML: <|im_start|>role\n...<|im_end|>\n
    LLAMA_2 = auto()    # [INST] <<SYS>>...<</SYS>> ... [/INST] reply </s>


@dataclasses.dataclass
class Conversation:
    """A left-to-right conversation being built into a prompt string."""

    system: str
    roles: tuple[str, str]
    messages: list[list[str | None]]
    sep_style: SeparatorStyle = SeparatorStyle.CHATML
    sep: str = "<|im_end|>\n"
    sep2: str | None = None
    version: str = "qwen"

    def get_prompt(self) -> str:
        if self.sep_style == SeparatorStyle.CHATML:
            parts = []
            if self.system:
                parts.append(f"<|im_start|>system\n{self.system}{self.sep}")
            for role, msg in self.messages:
                if msg is None:
                    # Generation prompt: open the assistant turn.
                    parts.append(f"<|im_start|>{role}\n")
                else:
                    parts.append(f"<|im_start|>{role}\n{msg}{self.sep}")
            return "".join(parts)
        if self.sep_style == SeparatorStyle.TWO:
            seps = [self.sep, self.sep2 or self.sep]
            out = self.system + seps[0] if self.system else ""
            for i, (role, msg) in enumerate(self.messages):
                if msg is None:
                    # Trailing space matches the training-side prefix
                    # tokenization (train/data._template_parts emits
                    # "{role}: " unsupervised) — "ASSISTANT:" vs
                    # "ASSISTANT: " tokenize differently.
                    out += f"{role}: "
                else:
                    out += f"{role}: {msg}{seps[i % 2]}"
            return out
        if self.sep_style == SeparatorStyle.PLAIN:
            out = ""
            for _, msg in self.messages:
                if msg is not None:
                    out += msg + (self.sep or "")
            return out
        if self.sep_style == SeparatorStyle.LLAMA_2:
            # [INST] turn pairs; the system prompt rides inside the first
            # user turn's <<SYS>> block (llama-2-chat convention).
            sys_block = (
                f"<<SYS>>\n{self.system}\n<</SYS>>\n\n" if self.system else ""
            )
            out = ""
            for i, (role, msg) in enumerate(self.messages):
                if role == self.roles[0]:
                    body = (sys_block + (msg or "")) if i == 0 else (msg or "")
                    out += f"{self.sep}[INST] {body} [/INST]"
                elif msg is None:
                    out += ""  # generation prompt: reply follows [/INST]
                else:
                    out += f" {msg} {self.sep2}"
            return out
        raise ValueError(f"unknown sep style {self.sep_style}")

    def append_message(self, role: str, message: str | None) -> None:
        self.messages.append([role, message])

    def copy(self) -> "Conversation":
        return Conversation(
            system=self.system,
            roles=self.roles,
            messages=[[r, m] for r, m in self.messages],
            sep_style=self.sep_style,
            sep=self.sep,
            sep2=self.sep2,
            version=self.version,
        )

    @property
    def stop_str(self) -> str:
        if self.sep_style == SeparatorStyle.CHATML:
            return "<|im_end|>"
        if self.sep_style == SeparatorStyle.LLAMA_2:
            return self.sep2 or "</s>"
        return self.sep2 or self.sep


conv_qwen = Conversation(
    system="You are a helpful assistant.",
    roles=("user", "assistant"),
    messages=[],
    sep_style=SeparatorStyle.CHATML,
    sep="<|im_end|>\n",
    version="qwen",
)

conv_plain = Conversation(
    system="",
    roles=("", ""),
    messages=[],
    sep_style=SeparatorStyle.PLAIN,
    sep="\n",
    version="plain",
)

conv_vicuna = Conversation(
    system=(
        "A chat between a curious user and an artificial intelligence "
        "assistant. The assistant gives helpful, detailed, and polite "
        "answers to the user's questions."
    ),
    roles=("USER", "ASSISTANT"),
    messages=[],
    sep_style=SeparatorStyle.TWO,
    sep=" ",
    sep2="</s>",
    version="v1",
)

conv_llama_2 = Conversation(
    system=(
        "You are a helpful language and vision assistant. You are able to "
        "understand the visual content that the user provides, and assist "
        "the user with a variety of tasks using natural language."
    ),
    roles=("USER", "ASSISTANT"),
    messages=[],
    sep_style=SeparatorStyle.LLAMA_2,
    sep="<s>",
    sep2="</s>",
    version="llama_2",
)

conv_mistral = Conversation(
    # Mistral-Instruct: same [INST] wire format, no system block and
    # sep="" (the single leading BOS is the tokenizer's job, never a
    # mid-sequence literal — the reference registry's
    # conv_mistral_instruct row).
    system="",
    roles=("USER", "ASSISTANT"),
    messages=[],
    sep_style=SeparatorStyle.LLAMA_2,
    sep="",
    sep2="</s>",
    version="mistral_instruct",
)

conv_llava_v1 = Conversation(
    # llava_v1's system differs from vicuna_v1 by two words
    # (human/human's vs user/user's) — checkpoints notice.
    system=(
        "A chat between a curious human and an artificial intelligence "
        "assistant. The assistant gives helpful, detailed, and polite "
        "answers to the human's questions."
    ),
    roles=("USER", "ASSISTANT"),
    messages=[],
    sep_style=SeparatorStyle.TWO,
    sep=" ",
    sep2="</s>",
    version="llava_v1",
)

conv_chatml_direct = Conversation(
    # ChatML with the short llava-v1.6-34b-style system. RECONSTRUCTED
    # (reference mount empty): the family's chatml_direct row carries
    # "Answer the questions." — revisit when the reference is readable.
    system="Answer the questions.",
    roles=("user", "assistant"),
    messages=[],
    sep_style=SeparatorStyle.CHATML,
    sep="<|im_end|>\n",
    version="chatml_direct",
)

conv_mpt = Conversation(
    # RECONSTRUCTED mpt-style system (reference mount empty).
    system=(
        "A conversation between a user and an LLM-based AI assistant. "
        "The assistant gives helpful and honest answers."
    ),
    roles=("user", "assistant"),
    messages=[],
    sep_style=SeparatorStyle.CHATML,
    sep="<|im_end|>\n",
    version="mpt",
)

conv_templates: dict[str, Conversation] = {
    "qwen": conv_qwen,
    "qwen_1_5": conv_qwen,
    "plain": conv_plain,
    "v1": conv_vicuna,
    # Reference-family (LLaVA-derived conversation registry) styles so
    # records/templates from sibling checkpoints load without surgery.
    # System strings are reconstructions where marked (empty mount) —
    # pinned by tests/test_goldens.py so any revision is a visible diff.
    "llava_v1": conv_llava_v1,
    "vicuna_v1": conv_vicuna,
    "llava_llama_2": conv_llama_2,
    "mistral_instruct": conv_mistral,
    "chatml_direct": conv_chatml_direct,
    "mpt": conv_mpt,
    # 34B (Yi backbone) template DECISION (reference mount empty, so the
    # real oryx_34b template is unverifiable): Yi-34B-Chat speaks ChatML
    # with the same <|im_start|>/<|im_end|> markers as Qwen, so oryx_34b
    # maps to the ChatML template. Pinned by tests/test_goldens.py and
    # documented in docs/MIGRATING.md; revisit the moment the reference
    # becomes readable.
    "yi_34b": conv_qwen,
}

default_conversation = conv_qwen
