"""Device-mesh construction (dp, fsdp, tp, sp axes).

Reference parity: the reference's "distributed backend" is NCCL process
groups set up by the deepspeed launcher (SURVEY.md §2c). The TPU-native
equivalent is a `jax.sharding.Mesh` whose axes carry all parallelism:

  dp    pure data parallelism (replicated params; gradients psum)
  fsdp  ZeRO-3-equivalent axis: params/optimizer state sharded, batch also
        sharded (so dp×fsdp is the total data-parallel width)
  tp    tensor parallelism (attention heads / MLP columns)
  sp    sequence/context parallelism (ring attention, ops/ring_attention.py)

Multi-slice pods: `build_hybrid_mesh` puts the slice-local axes on ICI and
the leading dp axis on DCN (SURVEY.md §5 "Distributed comm backend").
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from oryx_tpu.config import MeshConfig

AXES = ("dp", "fsdp", "tp", "sp")


def build_mesh(cfg: MeshConfig, devices=None) -> Mesh:
    """Dense single-slice mesh over ICI. Axis sizes must multiply to the
    device count; size-1 axes are kept (cheap, keeps PartitionSpecs stable).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if cfg.num_devices != n:
        raise ValueError(
            f"mesh {cfg.dp}x{cfg.fsdp}x{cfg.tp}x{cfg.sp}="
            f"{cfg.num_devices} != {n} devices"
        )
    arr = np.asarray(devices).reshape(cfg.dp, cfg.fsdp, cfg.tp, cfg.sp)
    return Mesh(arr, AXES)


def build_hybrid_mesh(cfg: MeshConfig, *, num_slices: int) -> Mesh:
    """Multi-slice (DCN×ICI) mesh: dp spans slices over DCN; fsdp/tp/sp stay
    inside each slice on ICI. Requires cfg.dp % num_slices == 0."""
    from jax.experimental import mesh_utils

    if cfg.dp % num_slices != 0:
        raise ValueError(f"dp={cfg.dp} not divisible by {num_slices} slices")
    per_slice_dp = cfg.dp // num_slices
    if jax.default_backend() == "cpu":
        # Forced-CPU test platform (no slice topology): build_mesh's
        # contiguous layout already IS the hybrid contract there — the
        # dp axis is slice-major, so indices s*per_slice_dp + d land on
        # "slice" s and fsdp/tp/sp collectives never cross a simulated
        # slice boundary. Real accelerators always go through
        # create_hybrid_device_mesh so genuine topology errors surface.
        return build_mesh(cfg)
    dev = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(per_slice_dp, cfg.fsdp, cfg.tp, cfg.sp),
        dcn_mesh_shape=(num_slices, 1, 1, 1),
    )
    return Mesh(dev, AXES)


def parse_shard_arg(arg: str | None) -> tuple[Mesh | None, str]:
    """CLI `--shard MODE=N` (e.g. "tp=8", "fsdp=8") → (mesh, mode) for
    multi-chip serving; (None, "tp") when arg is None. Shared by the
    serve and eval CLIs so validation lives once."""
    if arg is None:
        return None, "tp"
    mode, sep, n = arg.partition("=")
    if mode not in ("tp", "fsdp") or not sep or not n.isdigit() or int(n) < 1:
        raise ValueError(
            f"--shard expects tp=N or fsdp=N with N >= 1, got {arg!r}"
        )
    return build_mesh(MeshConfig(**{mode: int(n)})), mode


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    initialization_timeout: int = 300,
) -> None:
    """Multi-host rendezvous — the NCCL/env-var `init_process_group`
    equivalent (SURVEY.md §2c). On TPU pods arguments are auto-detected.
    initialization_timeout covers slow peers (a contended host importing
    jax can keep the coordinator waiting for minutes)."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        initialization_timeout=initialization_timeout,
    )


def process_batch_slice(global_batch: int) -> tuple[int, int]:
    """(start, size) of this host's slice of the global batch — host-side
    data sharding, one process per host (SURVEY.md §2c(c))."""
    n = jax.process_count()
    if global_batch % n != 0:
        raise ValueError(f"global batch {global_batch} % {n} processes != 0")
    per = global_batch // n
    return jax.process_index() * per, per
