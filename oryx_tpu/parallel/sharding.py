"""Parameter/activation sharding rules (GSPMD under jit).

Reference parity: DeepSpeed ZeRO partitioning + NCCL collectives
(SURVEY.md §2b). Here sharding is declarative: every param leaf gets a
logical-axis tuple from path-pattern rules, logical axes map to mesh axes,
and XLA inserts the all-gathers / reduce-scatters (the "kernels" the
reference gets from DeepSpeed's C++ runtime).

  ZeRO-3 / FSDP  → mode="fsdp":  params sharded on the fsdp axis
  ZeRO-2         → mode="zero2": params replicated, optimizer state sharded
  DDP            → mode="ddp":   everything replicated over dp

Tensor parallelism composes orthogonally: head/mlp/vocab logical axes map
to "tp" whenever cfg.mesh.tp > 1.
"""

from __future__ import annotations

import fnmatch
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = dict[str, Any]

# path-pattern → logical axes (matched with fnmatch on "/"-joined paths;
# first match wins; patterns cover llm/vit/compressor subtrees).
LOGICAL_RULES: tuple[tuple[str, tuple[str | None, ...]], ...] = (
    # LLM (stacked layers: leading "layer" axis)
    ("llm/embed/weight", ("vocab", "embed")),
    ("llm/layers/*_norm/weight", ("layer", None)),
    ("llm/layers/q_proj/kernel", ("layer", "embed", "heads")),
    ("llm/layers/k_proj/kernel", ("layer", "embed", "heads")),
    ("llm/layers/v_proj/kernel", ("layer", "embed", "heads")),
    ("llm/layers/o_proj/kernel", ("layer", "heads", "embed")),
    ("llm/layers/*_proj/bias", ("layer", "heads")),
    ("llm/layers/gate_proj/kernel", ("layer", "embed", "mlp")),
    ("llm/layers/up_proj/kernel", ("layer", "embed", "mlp")),
    ("llm/layers/down_proj/kernel", ("layer", "mlp", "embed")),
    ("llm/final_norm/weight", (None,)),
    ("llm/lm_head/kernel", ("embed", "vocab")),
    # Vision tower
    ("vit/patch_embed/kernel", (None, "embed")),
    ("vit/patch_embed/bias", ("embed",)),
    # Replicated on purpose: interp_pos_embed gathers 4 corners per patch
    # and its backward scatter-adds into the table; with the table
    # embed-sharded GSPMD pays involuntary-remat reshards between the
    # data-sharded patch axis and the sharded table on every step. The
    # table is ~3.4 MB fp32 at SigLIP scale — replication is free.
    ("vit/pos_embed/weight", (None, None)),
    ("vit/layers/norm*/weight", ("layer", None)),
    ("vit/layers/norm*/bias", ("layer", None)),
    ("vit/layers/?_proj/kernel", ("layer", "embed", "heads")),
    ("vit/layers/o_proj/kernel", ("layer", "heads", "embed")),
    ("vit/layers/?_proj/bias", ("layer", "heads")),
    ("vit/layers/o_proj/bias", ("layer", "embed")),
    ("vit/layers/fc1/kernel", ("layer", "embed", "mlp")),
    ("vit/layers/fc1/bias", ("layer", "mlp")),
    ("vit/layers/fc2/kernel", ("layer", "mlp", "embed")),
    ("vit/layers/fc2/bias", ("layer", "embed")),
    ("vit/post_norm/*", (None,)),
    # Compressor (small; shard the projector matmuls only)
    ("compressor/projector/fc1/kernel", ("embed", "mlp")),
    ("compressor/projector/fc2/kernel", ("mlp", "embed")),
    ("compressor/*/kernel", (None, None)),
    ("compressor/*/bias", (None,)),
    ("compressor/*/weight", (None,)),
)

# logical axis → mesh axis (or tuple of axes), per mode.
def mesh_rules(mode: str) -> dict[str, str | tuple[str, ...] | None]:
    base = {"layer": None, "vocab": None, "heads": "tp", "mlp": "tp",
            "embed": None}
    if mode == "fsdp":
        # ZeRO-3 shards over the COMBINED fsdp x sp width: sequence-
        # parallel devices hold param shards too (ring attention only
        # shard_maps activations; weights are use-site all-gathered
        # across both axes). On an sp=1 mesh this is plain fsdp; on a
        # long-video mesh like fsdp=16 x sp=4 it keeps the full 64-way
        # state sharding — fsdp-only sharding there quadruples per-chip
        # state (measured: the 34B/v5e-64 sp=4 compile OOMs without
        # this, TPU_VALIDATION round 5).
        base["embed"] = ("fsdp", "sp")
    elif mode not in ("zero2", "ddp"):
        raise ValueError(f"unknown sharding mode {mode!r}")
    return base


def _path_str(path) -> str:
    return "/".join(
        p.key if hasattr(p, "key") else str(getattr(p, "idx", p))
        for p in path
    )


def logical_axes(params: Params) -> Params:
    """Pytree of logical-axis tuples, same structure as params."""

    def lookup(path, leaf):
        s = _path_str(path)
        for pat, axes in LOGICAL_RULES:
            if fnmatch.fnmatch(s, pat):
                if len(axes) != leaf.ndim:
                    raise ValueError(
                        f"rule {pat} has {len(axes)} axes but {s} is "
                        f"rank {leaf.ndim}"
                    )
                return axes
        return (None,) * leaf.ndim  # replicate unknown leaves

    return jax.tree_util.tree_map_with_path(lookup, params)


def param_specs(params: Params, mode: str = "fsdp") -> Params:
    """Pytree of PartitionSpecs for params (also correct for same-shaped
    optimizer-state leaves)."""
    rules = mesh_rules(mode)

    def to_spec(axes):
        return P(*(rules.get(a) if a is not None else None for a in axes))

    return jax.tree.map(
        to_spec, logical_axes(params),
        is_leaf=lambda x: isinstance(x, tuple),
    )


def param_shardings(mesh: Mesh, params: Params, mode: str = "fsdp") -> Params:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_specs(params, mode),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Params, shardings: Params) -> Params:
    """Place (or re-place) a param pytree onto the mesh."""
    return jax.tree.map(jax.device_put, params, shardings)


def batch_spec() -> P:
    """Activations/batch shard over the full data-parallel width."""
    return P(("dp", "fsdp"))


# Packed visual buffer fields of the training batch (ops/packing +
# splice.query_slots layout): their second axis is the packing axis.
VISUAL_BATCH_FIELDS = (
    "patches", "segment_ids", "pos_coords", "region_ids", "q_region_ids",
)


def batch_field_spec(name: str) -> P:
    """Per-field placement for a [accum, ...] training batch leaf.

    Packed visual buffers ride the FULL (dp, fsdp, sp) width — their
    packing axis is pure data to the vision tower, which pins its
    intermediates to the same spec (oryx_vit/compressor), so sequence-
    parallel devices take patch shards instead of idling through the
    visual encode. Row-shaped token-stream fields ride the data width
    only (the decoder's sp axis splits the SEQUENCE dim, not rows).
    Must stay in lockstep with the AOT memory proofs
    (scripts/estimate_7b_mesh_memory.py) — the proven program's
    argument placement is the trainer's.
    """
    if name in VISUAL_BATCH_FIELDS:
        return P(None, ("dp", "fsdp", "sp"))
    return P(None, ("dp", "fsdp"))


def cast_params_for_compute(params: Params, dtype, mode: str = "fsdp"):
    """Cast float param leaves to the compute dtype, each cast output
    CONSTRAINED to the param's own sharding spec.

    The constraint is the point: without it GSPMD propagates the
    use-site "replicated" requirement back THROUGH the convert, so
    ZeRO-3's weight all-gathers move fp32 and convert afterwards —
    verified in the compiled 7B/16-mesh HLO (all-gathers of
    f32[3584,18944], f32[3584,152064], …). Pinning the convert output to
    the param's sharded spec makes every use-site all-gather (and the
    backward's grad reduce-scatter at the same boundary) move
    compute-dtype bytes: half the ICI traffic and half the gather temps
    of fp32. Gradients convert back to fp32 at this boundary (cast VJP)
    and are accumulated fp32 in train/step.py.

    No-op sharding-wise off-mesh (constrain passes through); numerically
    identical to the per-use `.astype(x.dtype)` casts in the model,
    which become no-ops on the cast tree.
    """
    specs = param_specs(params, mode)  # THE spec derivation, not a copy
    leaves, treedef = jax.tree.flatten(params)
    spec_leaves = treedef.flatten_up_to(specs)
    out = []
    for w, spec in zip(leaves, spec_leaves):
        if jnp.issubdtype(w.dtype, jnp.floating) and w.dtype != dtype:
            # A PartitionSpec unpacks into constrain's per-dim axes form;
            # constrain drops axes absent from the ambient mesh and
            # no-ops entirely off-mesh.
            w = constrain(w.astype(dtype), *spec)
        out.append(w)
    return jax.tree.unflatten(treedef, out)


def paged_kv_spec(mesh) -> P | None:
    """PartitionSpec for a paged KV pool leaf
    ([layers, pages, page_size, kv_heads, head_dim]) on `mesh`:
    sharded along KV HEADS over the tp axis, replicated otherwise.

    Heads is the one KV axis tensor parallelism can split without
    changing any reduction: each tp shard holds its own heads' pages
    end to end (write, gather, attention), and the only cross-shard
    collective is o_proj's existing contraction over heads — so paged
    decode on a tp mesh stays bit-identical per head to the
    single-device path. The packed RAGGED path inherits this for free:
    `write_pages_packed` scatters and `ragged_paged_attention` gathers
    along the (unsharded) page axis with the head axis untouched, and
    the reference pins its gathered per-row view to the same head
    split (ops/paged_kv.py) so one fused mixed prefill+decode dispatch
    partitions by heads exactly like the split dispatches did. Pages/page_size must NOT shard: block tables
    index pages globally and a page-axis split would turn every
    table-addressed write into a cross-device scatter. Returns None
    (replicate) when the mesh has no tp axis or tp == 1 — an fsdp-only
    serving mesh gathers weights but keeps the pool whole."""
    if mesh is None or "tp" not in mesh.axis_names:
        return None
    if mesh.shape["tp"] <= 1:
        return None
    return P(None, None, None, "tp", None)


def shard_paged_kv(kv_pages, mesh, *, num_kv_heads: int | None = None):
    """Place a paged KV pytree (qwen2.init_paged_kv_cache leaves) on
    `mesh` with heads sharded over tp (see `paged_kv_spec`). No-op —
    the same pytree back — when the mesh doesn't split heads or the
    head count doesn't divide (a 2-kv-head model on tp=4 serves with a
    replicated pool rather than failing)."""
    spec = paged_kv_spec(mesh)
    if spec is None:
        return kv_pages
    heads = num_kv_heads
    if heads is None:
        # A quantized pool carries 3-D per-page scale leaves next to
        # the 5-D code leaves; the head count lives on the 5-D ones.
        heads = next(
            leaf.shape[3]
            for leaf in jax.tree_util.tree_leaves(kv_pages)
            if getattr(leaf, "ndim", 0) == 5
        )
    if heads % mesh.shape["tp"]:
        return kv_pages
    sharding = NamedSharding(mesh, spec)
    replicated = NamedSharding(mesh, P())

    def place(a):
        # Only the [L, P, ps, Hk, D] code/value leaves split by heads;
        # per-page scale blocks ([L, P, ps]) have no head axis and
        # replicate — they are <1% of the pool's bytes.
        return jax.device_put(
            a, sharding if getattr(a, "ndim", 0) == 5 else replicated
        )

    return jax.tree.map(place, kv_pages)


def ambient_mesh():
    """The ambient named mesh, across JAX versions: the abstract mesh
    (jax >= 0.5, set via `jax.sharding.set_mesh`) or the thread-local
    physical mesh (older JAX, set via `with mesh:`). Returns None when
    no mesh is ambient."""
    get = getattr(jax.sharding, "get_abstract_mesh", None)
    if get is not None:
        return get()
    from jax._src import mesh as _mesh_lib

    m = _mesh_lib.thread_resources.env.physical_mesh
    return None if m.empty else m


def mesh_scope(mesh):
    """Context manager making `mesh` ambient for `constrain`/jit calls:
    `jax.sharding.set_mesh` on new JAX, the legacy `with mesh:` resource
    env on old. `mesh=None` is a no-op scope."""
    from contextlib import nullcontext

    if mesh is None:
        return nullcontext()
    set_mesh = getattr(jax.sharding, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    return mesh  # Mesh is itself a context manager on older JAX


def constrain(x, *axes):
    """`with_sharding_constraint` iff a named mesh is ambient, else no-op.

    Model code annotates its main activations with this so GSPMD stops
    guessing intermediate shardings (guessing shows up as "[SPMD]
    Involuntary full rematerialization" resharding warnings). Single-device
    jit (bench, tests without a mesh) passes through untouched. Axis names
    absent from the ambient mesh are dropped (e.g. calling with "sp" on a
    dp/fsdp-only mesh).
    """
    mesh = ambient_mesh()
    if mesh is None or mesh.empty or not mesh.axis_names:
        return x

    def keep(a):
        if a is None:
            return None
        if isinstance(a, tuple):
            kept = tuple(x_ for x_ in a if x_ in mesh.axis_names)
            return kept or None
        return a if a in mesh.axis_names else None

    spec = P(*(keep(a) for a in axes))
    return jax.lax.with_sharding_constraint(x, spec)


def opt_state_specs(opt_state, params: Params, mode: str = "fsdp"):
    """Shardings for optax state: leaves with a param-shaped counterpart
    inherit that param's spec; scalars/steps replicate.

    For ZeRO-2 the optimizer state shards over fsdp even though params
    replicate — pass mode="fsdp" here with mode="zero2" for params.
    """
    specs = param_specs(params, mode)
    flat_specs = {
        tuple(str(p) for p in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )[0]
    }

    def match(path, leaf):
        suffix = tuple(str(p) for p in path)
        for ppath, spec in flat_specs.items():
            if suffix[-len(ppath):] == ppath:
                if hasattr(leaf, "ndim") and leaf.ndim == len(spec):
                    return spec
        return P()

    return jax.tree_util.tree_map_with_path(match, opt_state)
