"""Ring attention — sequence/context parallelism over the `sp` mesh axis.

Long-context scaling beyond one chip's HBM (SURVEY.md §5 "Long-context"):
the sequence is sharded over `sp`; each device keeps its local Q block
resident and K/V blocks rotate around the ring via `lax.ppermute` (ICI
neighbor exchange), merging each visiting block into an online-softmax
accumulator. Peak memory is O(T/sp) per device while computing exact
(non-approximate) attention over the full sequence — the XLA-collective
equivalent of Ring Attention (Liu et al., 2023), built with shard_map so
the collective schedule is explicit.

Masking model matches ops/attention.py: causal on absolute positions
(positions travel with the K/V blocks), plus explicit kv validity.
Compute follows the same policy: fp32 logits/softmax state, input-dtype
probs·V matmuls.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # jax < 0.5 keeps shard_map under experimental
    from jax.experimental.shard_map import shard_map

NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _chunk_logits(q, k, qpos, kpos, kvalid, *, causal, scale):
    """[B,Tq,Hk,G,D] x [B,Tc,Hk,D] → masked fp32 logits [B,Hk,G,Tq,Tc]."""
    logits = jnp.einsum(
        "bqhgd,bkhd->bhgqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    mask = kvalid[:, None, :].astype(bool)  # [B, 1, Tc]
    if causal:
        mask = jnp.logical_and(
            mask, qpos[:, :, None] >= kpos[:, None, :]
        )
    return jnp.where(mask[:, None, None, :, :], logits, NEG)


def ring_attention_shard(
    q, k, v, q_pos, kv_pos, kv_valid,
    *,
    axis_name: str,
    causal: bool = False,
    scale: float | None = None,
    impl: str = "xla",
):
    """Per-shard body (call inside shard_map over `axis_name`).

    q/k/v: local blocks [B, Tl, H*, D] (GQA: Hq % Hk == 0);
    q_pos/kv_pos: absolute positions [B, Tl]; kv_valid: [B, Tl] int.
    Returns [B, Tl, Hq, D] in q.dtype — exact attention over the global
    sequence.

    impl: "xla" materializes [Tl, Tc] fp32 logits per visiting block;
    "flash" runs the Pallas flash kernel per block and merges the
    per-block normalized outputs via their logsumexp — O(tile) memory,
    which is what makes Tl in the tens-of-thousands feasible.
    """
    if impl == "flash":
        return _ring_shard_flash(
            q, k, v, q_pos, kv_pos, kv_valid,
            axis_name=axis_name, causal=causal, scale=scale,
        )
    B, Tl, Hq, D = q.shape
    _, _, Hk, _ = k.shape
    G = Hq // Hk
    if scale is None:
        scale = D**-0.5
    n = jax.lax.psum(1, axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]

    qg = q.reshape(B, Tl, Hk, G, D)
    acc = jnp.zeros((B, Hk, G, Tl, D), jnp.float32)
    m = jnp.full((B, Hk, G, Tl, 1), NEG, jnp.float32)
    l = jnp.zeros((B, Hk, G, Tl, 1), jnp.float32)

    def merge(acc, m, l, k_cur, v_cur, kpos_cur, kvalid_cur):
        s = _chunk_logits(
            qg, k_cur, q_pos, kpos_cur, kvalid_cur, causal=causal,
            scale=scale,
        )  # [B, Hk, G, Tl, Tc]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum(
            "bhgqk,bkhd->bhgqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32,
        )
        return acc * alpha + pv, m_new, l

    def body(_, carry):
        acc, m, l, k_cur, v_cur, kpos_cur, kvalid_cur = carry
        if causal:
            # Skip blocks that are entirely in this shard's causal future
            # (every kv position > every local q position): with causal
            # sharding, about half the ring steps merge nothing — cond
            # saves the logits+softmax compute (the ppermute still runs).
            live = jnp.min(kpos_cur) <= jnp.max(q_pos)
            acc, m, l = jax.lax.cond(
                live, merge, lambda a, mm, ll, *_: (a, mm, ll),
                acc, m, l, k_cur, v_cur, kpos_cur, kvalid_cur,
            )
        else:
            acc, m, l = merge(acc, m, l, k_cur, v_cur, kpos_cur, kvalid_cur)
        # Rotate the K/V block (and its metadata) one step around the ring.
        k_cur, v_cur, kpos_cur, kvalid_cur = jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm),
            (k_cur, v_cur, kpos_cur, kvalid_cur),
        )
        return acc, m, l, k_cur, v_cur, kpos_cur, kvalid_cur

    acc, m, l, *_ = jax.lax.fori_loop(
        0, n, body, (acc, m, l, k, v, kv_pos, kv_valid)
    )
    out = acc / jnp.where(l == 0.0, 1.0, l)
    out = jnp.moveaxis(out, 3, 1).reshape(B, Tl, Hq, D)  # [B,Tl,Hk,G,D]
    # Tag for the "attn"/"attn_qkv" remat policies (utils/remat.py): the
    # saved output spares the backward a full second ring pass for the
    # downstream (o_proj/MLP) gradients.
    return checkpoint_name(out.astype(q.dtype), "flash_out")


def _ring_shard_flash(
    q, k, v, q_pos, kv_pos, kv_valid,
    *,
    axis_name: str,
    causal: bool,
    scale: float | None,
):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _ring_flash_vjp(
        q, k, v, q_pos, kv_pos, kv_valid, axis_name, causal, float(scale)
    )


def _ring_flash_forward(
    q, k, v, q_pos, kv_pos, kv_valid, axis_name, causal, scale
):
    """Flash-inner ring forward: per visiting block, run the Pallas kernel
    (fp32 softmax inside, O(tile) memory) and fold its normalized output
    into a running LSE-weighted sum:

        LSE' = logaddexp(LSE, lse_i)
        out' = out·exp(LSE − LSE') + out_i·exp(lse_i − LSE')

    Returns (out [B,Tl,Hq,D] in q.dtype, global lse [B,Hq,Tl] fp32). The
    kernel marks fully-masked rows with lse = +FLT_MAX (a backward-pass
    convention); those are re-mapped to the NEG sentinel so empty blocks
    merge with weight 0 (NEG-NEG arithmetic stays finite, no NaNs).
    """
    from oryx_tpu.ops.pallas.flash_attention import _flash_attention_impl

    B, Tl, Hq, D = q.shape
    n = jax.lax.psum(1, axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]

    out = jnp.zeros((B, Tl, Hq, D), jnp.float32)
    lse = jnp.full((B, Hq, Tl), NEG, jnp.float32)

    def merge(out, lse, k_cur, v_cur, kpos_cur, kvalid_cur):
        o_i, lse_i = _flash_attention_impl(
            q, k_cur, v_cur, q_pos, kpos_cur, None, None, kvalid_cur,
            causal, scale, with_lse=True,
        )
        lse_i = lse_i[:, :, :Tl]  # kernel pads to block multiples
        lse_i = jnp.where(lse_i > -0.5 * NEG, NEG, lse_i)  # empty rows
        lse_new = jnp.logaddexp(lse, lse_i)
        w_old = jnp.exp(lse - lse_new)  # [B, Hq, Tl]
        w_new = jnp.exp(lse_i - lse_new)
        wo = jnp.moveaxis(w_old, 1, 2)[..., None]  # [B, Tl, Hq, 1]
        wn = jnp.moveaxis(w_new, 1, 2)[..., None]
        out = out * wo + o_i.astype(jnp.float32) * wn
        return out, lse_new

    def body(_, carry):
        out, lse, k_cur, v_cur, kpos_cur, kvalid_cur = carry
        if causal:
            live = jnp.min(kpos_cur) <= jnp.max(q_pos)
            out, lse = jax.lax.cond(
                live, merge, lambda o, s, *_: (o, s),
                out, lse, k_cur, v_cur, kpos_cur, kvalid_cur,
            )
        else:
            out, lse = merge(out, lse, k_cur, v_cur, kpos_cur, kvalid_cur)
        k_cur, v_cur, kpos_cur, kvalid_cur = jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm),
            (k_cur, v_cur, kpos_cur, kvalid_cur),
        )
        return out, lse, k_cur, v_cur, kpos_cur, kvalid_cur

    out, lse, *_ = jax.lax.fori_loop(
        0, n, body, (out, lse, k, v, kv_pos, kv_valid)
    )
    # Same tags as the Pallas kernel: with remat_policy="attn"/"attn_qkv"
    # these are saved, so the checkpointed backward reuses the ring
    # backward's residuals instead of re-running the forward ring pass.
    out = checkpoint_name(out.astype(q.dtype), "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, lse


@partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _ring_flash_vjp(
    q, k, v, q_pos, kv_pos, kv_valid, axis_name, causal, scale
):
    return _ring_flash_forward(
        q, k, v, q_pos, kv_pos, kv_valid, axis_name, causal, scale
    )[0]


def _ring_flash_fwd(q, k, v, q_pos, kv_pos, kv_valid, axis_name, causal,
                    scale):
    out, lse = _ring_flash_forward(
        q, k, v, q_pos, kv_pos, kv_valid, axis_name, causal, scale
    )
    return out, (q, k, v, q_pos, kv_pos, kv_valid, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, res, g):
    """Ring backward: a second pass around the ring. dq accumulates
    locally; each visiting block's dk/dv partials travel WITH the block
    (n rotations = full circle, so they arrive home at loop end). Per
    block, the Pallas flash backward kernels run against the GLOBAL
    logsumexp saved from the forward — the standard ring-attention
    backward, O(Tl) memory per device.
    """
    from oryx_tpu.ops.pallas.flash_attention import (
        _mha_backward, _pad_axis, _prepare,
    )

    q, k, v, q_pos, kv_pos, kv_valid, out, lse = res
    B, Tl, Hq, D = q.shape
    n = jax.lax.psum(1, axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]

    # Restore the kernel's empty-row convention (+MAX ⇒ p underflows to 0)
    # for rows that saw no valid key anywhere in the ring.
    lse_bwd = jnp.where(
        lse <= 0.5 * NEG, jnp.float32(jnp.finfo(jnp.float32).max), lse
    )
    delta = jnp.einsum(
        "bqhd,bqhd->bhq", g.astype(jnp.float32), out.astype(jnp.float32)
    )  # [B, Hq, Tl]

    dq0 = jnp.zeros((B, Tl, Hq, D), jnp.float32)
    dkv0 = jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32)

    def block_grads(dq, dk_t, dv_t, k_cur, v_cur, kpos_cur, kvalid_cur):
        padded, flags, _ = _prepare(
            q, k_cur, v_cur, q_pos, kpos_cur, None, None, kvalid_cur,
            causal, scale,
        )
        Tq_p = padded[0].shape[2]
        do = _pad_axis(g.swapaxes(1, 2), 2, Tq_p)
        lse_p = _pad_axis(lse_bwd, 2, Tq_p)
        delta_p = _pad_axis(delta, 2, Tq_p)
        dq_i, dk_i, dv_i = _mha_backward(
            padded[0], padded[1], padded[2], do, lse_p, delta_p,
            padded[3], padded[4], padded[5], padded[6], padded[7],
            **flags,
        )
        dq = dq + dq_i[:, :, :Tl].swapaxes(1, 2)
        dk_t = dk_t + dk_i[:, :, :Tl].swapaxes(1, 2)
        dv_t = dv_t + dv_i[:, :, :Tl].swapaxes(1, 2)
        return dq, dk_t, dv_t

    def body(_, carry):
        dq, k_cur, v_cur, kpos_cur, kvalid_cur, dk_t, dv_t = carry
        if causal:
            live = jnp.min(kpos_cur) <= jnp.max(q_pos)
            dq, dk_t, dv_t = jax.lax.cond(
                live, block_grads, lambda a, b, c, *_: (a, b, c),
                dq, dk_t, dv_t, k_cur, v_cur, kpos_cur, kvalid_cur,
            )
        else:
            dq, dk_t, dv_t = block_grads(
                dq, dk_t, dv_t, k_cur, v_cur, kpos_cur, kvalid_cur
            )
        k_cur, v_cur, kpos_cur, kvalid_cur, dk_t, dv_t = jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm),
            (k_cur, v_cur, kpos_cur, kvalid_cur, dk_t, dv_t),
        )
        return dq, k_cur, v_cur, kpos_cur, kvalid_cur, dk_t, dv_t

    dq, _, _, _, _, dk, dv = jax.lax.fori_loop(
        0, n, body, (dq0, k, v, kv_pos, kv_valid, *dkv0)
    )
    return (
        dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
        None, None, None,
    )


_ring_flash_vjp.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(
    q, k, v,
    *,
    mesh: Mesh | None = None,
    axis_name: str = "sp",
    batch_axes: tuple[str, ...] = (),
    causal: bool = False,
    positions=None,
    kv_mask=None,
    scale: float | None = None,
    impl: str = "xla",
):
    """Global-array entry: shards the sequence over `axis_name` and runs the
    ring. q/k/v: [B, T, H*, D] with T divisible by the axis size.
    mesh=None uses the ambient mesh (jax.sharding.use_mesh / jit context).
    impl="flash" uses the Pallas kernel per visiting block (O(tile) logits
    memory — required once per-shard T reaches the tens of thousands).

    batch_axes: mesh axes the batch dim is sharded over (e.g.
    ("dp", "fsdp") in the trainer) — carried through the shard_map so the
    surrounding layers' batch sharding survives instead of forcing an
    all-gather/re-scatter at the shard_map boundary. Axes not present on
    the mesh are dropped.
    """
    B, T, _, _ = q.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    positions = positions.astype(jnp.int32)
    kv_valid = (
        jnp.broadcast_to(kv_mask, (B, T)).astype(jnp.int32)
        if kv_mask is not None
        else jnp.ones((B, T), jnp.int32)
    )
    from oryx_tpu.parallel.sharding import ambient_mesh

    resolved = mesh or ambient_mesh()
    names = getattr(resolved, "axis_names", ()) or ()
    batch = tuple(a for a in batch_axes if a in names) or None
    seq = P(batch, axis_name, None, None)
    tok = P(batch, axis_name)
    import inspect

    # Replication checking is off (the accumulator update is manual);
    # the flag was renamed check_rep -> check_vma across JAX versions.
    check_kw = (
        "check_vma"
        if "check_vma" in inspect.signature(shard_map).parameters
        else "check_rep"
    )
    fn = shard_map(
        partial(
            ring_attention_shard, axis_name=axis_name, causal=causal,
            scale=scale, impl=impl,
        ),
        mesh=resolved,
        in_specs=(seq, seq, seq, tok, tok, tok),
        out_specs=seq,
        **{check_kw: False},
    )
    return fn(q, k, v, positions, positions, kv_valid)
