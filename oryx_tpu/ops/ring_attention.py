"""Ring attention — sequence/context parallelism over the `sp` mesh axis.

Long-context scaling beyond one chip's HBM (SURVEY.md §5 "Long-context"):
the sequence is sharded over `sp`; each device keeps its local Q block
resident and K/V blocks rotate around the ring via `lax.ppermute` (ICI
neighbor exchange), merging each visiting block into an online-softmax
accumulator. Peak memory is O(T/sp) per device while computing exact
(non-approximate) attention over the full sequence — the XLA-collective
equivalent of Ring Attention (Liu et al., 2023), built with shard_map so
the collective schedule is explicit.

Masking model matches ops/attention.py: causal on absolute positions
(positions travel with the K/V blocks), plus explicit kv validity.
Compute follows the same policy: fp32 logits/softmax state, input-dtype
probs·V matmuls.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _chunk_logits(q, k, qpos, kpos, kvalid, *, causal, scale):
    """[B,Tq,Hk,G,D] x [B,Tc,Hk,D] → masked fp32 logits [B,Hk,G,Tq,Tc]."""
    logits = jnp.einsum(
        "bqhgd,bkhd->bhgqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    mask = kvalid[:, None, :].astype(bool)  # [B, 1, Tc]
    if causal:
        mask = jnp.logical_and(
            mask, qpos[:, :, None] >= kpos[:, None, :]
        )
    return jnp.where(mask[:, None, None, :, :], logits, NEG)


def ring_attention_shard(
    q, k, v, q_pos, kv_pos, kv_valid,
    *,
    axis_name: str,
    causal: bool = False,
    scale: float | None = None,
):
    """Per-shard body (call inside shard_map over `axis_name`).

    q/k/v: local blocks [B, Tl, H*, D] (GQA: Hq % Hk == 0);
    q_pos/kv_pos: absolute positions [B, Tl]; kv_valid: [B, Tl] int.
    Returns [B, Tl, Hq, D] in q.dtype — exact attention over the global
    sequence.
    """
    B, Tl, Hq, D = q.shape
    _, _, Hk, _ = k.shape
    G = Hq // Hk
    if scale is None:
        scale = D**-0.5
    n = jax.lax.psum(1, axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]

    qg = q.reshape(B, Tl, Hk, G, D)
    acc = jnp.zeros((B, Hk, G, Tl, D), jnp.float32)
    m = jnp.full((B, Hk, G, Tl, 1), NEG, jnp.float32)
    l = jnp.zeros((B, Hk, G, Tl, 1), jnp.float32)

    def merge(acc, m, l, k_cur, v_cur, kpos_cur, kvalid_cur):
        s = _chunk_logits(
            qg, k_cur, q_pos, kpos_cur, kvalid_cur, causal=causal,
            scale=scale,
        )  # [B, Hk, G, Tl, Tc]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum(
            "bhgqk,bkhd->bhgqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32,
        )
        return acc * alpha + pv, m_new, l

    def body(_, carry):
        acc, m, l, k_cur, v_cur, kpos_cur, kvalid_cur = carry
        if causal:
            # Skip blocks that are entirely in this shard's causal future
            # (every kv position > every local q position): with causal
            # sharding, about half the ring steps merge nothing — cond
            # saves the logits+softmax compute (the ppermute still runs).
            live = jnp.min(kpos_cur) <= jnp.max(q_pos)
            acc, m, l = jax.lax.cond(
                live, merge, lambda a, mm, ll, *_: (a, mm, ll),
                acc, m, l, k_cur, v_cur, kpos_cur, kvalid_cur,
            )
        else:
            acc, m, l = merge(acc, m, l, k_cur, v_cur, kpos_cur, kvalid_cur)
        # Rotate the K/V block (and its metadata) one step around the ring.
        k_cur, v_cur, kpos_cur, kvalid_cur = jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm),
            (k_cur, v_cur, kpos_cur, kvalid_cur),
        )
        return acc, m, l, k_cur, v_cur, kpos_cur, kvalid_cur

    acc, m, l, *_ = jax.lax.fori_loop(
        0, n, body, (acc, m, l, k, v, kv_pos, kv_valid)
    )
    out = acc / jnp.where(l == 0.0, 1.0, l)
    out = jnp.moveaxis(out, 3, 1).reshape(B, Tl, Hq, D)  # [B,Tl,Hk,G,D]
    return out.astype(q.dtype)


def ring_attention(
    q, k, v,
    *,
    mesh: Mesh | None = None,
    axis_name: str = "sp",
    batch_axes: tuple[str, ...] = (),
    causal: bool = False,
    positions=None,
    kv_mask=None,
    scale: float | None = None,
):
    """Global-array entry: shards the sequence over `axis_name` and runs the
    ring. q/k/v: [B, T, H*, D] with T divisible by the axis size.
    mesh=None uses the ambient mesh (jax.sharding.use_mesh / jit context).

    batch_axes: mesh axes the batch dim is sharded over (e.g.
    ("dp", "fsdp") in the trainer) — carried through the shard_map so the
    surrounding layers' batch sharding survives instead of forcing an
    all-gather/re-scatter at the shard_map boundary. Axes not present on
    the mesh are dropped.
    """
    B, T, _, _ = q.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    positions = positions.astype(jnp.int32)
    kv_valid = (
        jnp.broadcast_to(kv_mask, (B, T)).astype(jnp.int32)
        if kv_mask is not None
        else jnp.ones((B, T), jnp.int32)
    )
    resolved = mesh or jax.sharding.get_abstract_mesh()
    names = getattr(resolved, "axis_names", ()) or ()
    batch = tuple(a for a in batch_axes if a in names) or None
    seq = P(batch, axis_name, None, None)
    tok = P(batch, axis_name)
    fn = shard_map(
        partial(
            ring_attention_shard, axis_name=axis_name, causal=causal,
            scale=scale,
        ),
        mesh=mesh,
        in_specs=(seq, seq, seq, tok, tok, tok),
        out_specs=seq,
        check_vma=False,
    )
    return fn(q, k, v, positions, positions, kv_valid)
