"""Rotary position embeddings (RoPE).

Reference parity: HF Qwen2 rotary embedding (`apply_rotary_pos_emb`,
half-rotation layout), fused into attention in the CUDA path (SURVEY.md §2a
"RoPE"). Here it is a pure jnp function — XLA fuses it into the surrounding
attention computation, so a dedicated Pallas kernel is unnecessary on TPU
(the op is bandwidth-trivial next to the matmuls).

Angles are always computed in float32 (bf16 position*inv_freq products lose
precision catastrophically past ~4k positions).
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float) -> jnp.ndarray:
    """inv_freq vector, shape [head_dim // 2], float32."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponents)


def rope_cos_sin(
    positions: jnp.ndarray, head_dim: int, theta: float
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for integer positions.

    positions: [...], int32. Returns (cos, sin) each [..., head_dim] in
    float32, with the HF "duplicated halves" layout: angles repeated as
    concat([freqs, freqs]) along the last dim.
    """
    inv_freq = rope_frequencies(head_dim, theta)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., hd/2]
    angles = jnp.concatenate([angles, angles], axis=-1)  # [..., hd]
    return jnp.cos(angles), jnp.sin(angles)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(
    q: jnp.ndarray,
    k: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Apply rotary embedding to q/k.

    q: [B, T, Hq, D], k: [B, T, Hk, D]; cos/sin: [B, T, D] (or broadcastable).
    Rotation computed in fp32, output cast back to the input dtype.
    """
    cos = cos[..., None, :]  # [B, T, 1, D] — broadcast over heads
    sin = sin[..., None, :]

    def rot(x):
        xf = x.astype(jnp.float32)
        out = xf * cos + _rotate_half(xf) * sin
        return out.astype(x.dtype)

    return rot(q), rot(k)
