"""Normalization ops with a strict fp32-accumulation policy.

Reference parity: Qwen2 RMSNorm and SigLIP LayerNorm (HF implementations;
SURVEY.md §2 "LLM wrapper" / "OryxViT"). Computation is always performed in
float32 regardless of input dtype, then cast back — this is the policy that
makes bf16 TPU runs track the fp32 CUDA reference closely (SURVEY.md §7 hard
part 2).
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """RMSNorm as in Qwen2/Llama: x / rms(x) * weight (no bias, no mean sub).

    Matches HF `Qwen2RMSNorm`: variance over the last dim in fp32, weight
    multiply after the cast back to input dtype.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    xf = xf * (1.0 / jnp.sqrt(var + eps))
    # HF casts the normalized activations back to input dtype *before* the
    # weight multiply; replicate for bit-closeness.
    return (weight * xf.astype(dtype)).astype(dtype)


def layer_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    bias: jnp.ndarray,
    eps: float = 1e-6,
) -> jnp.ndarray:
    """LayerNorm (SigLIP / ViT blocks): mean-subtracted, fp32 accumulation."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    xf = (xf - mean) * (1.0 / jnp.sqrt(var + eps))
    out = xf * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return out.astype(dtype)
