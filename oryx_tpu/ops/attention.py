"""Attention ops — XLA reference path.

This is the portable (CPU-testable) attention used for parity work; the
Pallas TPU kernels in `oryx_tpu/ops/pallas/` are drop-in replacements
selected by `OryxConfig.attn_impl` (SURVEY.md §2a: flash-attn CUDA →
Pallas flash attention; flash-attn varlen → segment-id attention).

Conventions:
  q: [B, Tq, Hq, D]   k/v: [B, Tk, Hk, D]   with Hq % Hk == 0 (GQA).
  Logits and softmax are computed in float32 regardless of input dtype
  (the bit-closeness policy, SURVEY.md §7 hard part 2); the probs·V matmul
  runs in the input dtype so the MXU stays in bf16 on TPU.

Masking model (all optional, combined by logical AND):
  * causal        — query position i attends to key positions <= i + offset.
  * segment ids   — packed varlen: token i attends to token j iff
                    q_segment_ids[b, i] == kv_segment_ids[b, j]. This is the
                    TPU-native replacement for cu_seqlens varlen attention:
                    many images packed into one sequence, each attending only
                    within itself. Padding uses segment id 0 by convention
                    (still self-consistent; pad outputs are discarded).
  * kv_mask       — explicit boolean key validity [B, Tk] (KV-cache length
                    masking during decode, padding masks).

Memory: the dense path materializes [B, Hq, Tq, Tk] fp32 logits. Above
MAX_LOGITS_ELEMS (256 MB fp32) the wrapper switches to a sequential
`lax.map` over query chunks so the largest packed-video buckets (e.g.
P=65536, which would need ~16 GB per head group dense) stay serviceable on
this path; the Pallas kernel is the fast path for those shapes.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

NEG_INF = float(jnp.finfo(jnp.float32).min)

# Cap on materialized fp32 logits elements (B * Hq * Tq_chunk * Tk).
MAX_LOGITS_ELEMS = 2**26  # 64M elems = 256 MB fp32


def _attention_dense(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool,
    q_positions: jnp.ndarray | None,
    kv_positions: jnp.ndarray | None,
    q_segment_ids: jnp.ndarray | None,
    kv_segment_ids: jnp.ndarray | None,
    kv_mask: jnp.ndarray | None,
    scale: float,
) -> jnp.ndarray:
    B, Tq, Hq, D = q.shape
    _, Tk, Hk, _ = k.shape
    G = Hq // Hk

    # [B, Tk, Hk, G, ...] grouped layout so k/v are never materialized
    # repeated (XLA keeps the broadcast virtual on TPU).
    qg = q.reshape(B, Tq, Hk, G, D)
    logits = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
    )
    logits = logits * scale  # [B, Hk, G, Tq, Tk] fp32

    mask = None  # [B, Tq, Tk] broadcastable

    def _and(m, new):
        return new if m is None else jnp.logical_and(m, new)

    if causal:
        mask = _and(
            mask, q_positions[:, :, None] >= kv_positions[:, None, :]
        )
    if q_segment_ids is not None:
        assert kv_segment_ids is not None
        mask = _and(
            mask, q_segment_ids[:, :, None] == kv_segment_ids[:, None, :]
        )
    if kv_mask is not None:
        mask = _and(mask, kv_mask[:, None, :].astype(bool))

    if mask is not None:
        logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)

    # fp32 softmax; rows that are fully masked (e.g. cache slots past the
    # current length for padded queries) produce uniform probs over masked
    # slots — harmless because those outputs are themselves discarded.
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    probs = probs.astype(v.dtype)

    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
    return out.reshape(B, Tq, Hq, D).astype(q.dtype)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    q_positions: jnp.ndarray | None = None,
    kv_positions: jnp.ndarray | None = None,
    q_segment_ids: jnp.ndarray | None = None,
    kv_segment_ids: jnp.ndarray | None = None,
    kv_mask: jnp.ndarray | None = None,
    scale: float | None = None,
) -> jnp.ndarray:
    """General GQA attention. Returns [B, Tq, Hq, D] in q.dtype.

    For causal masking with a KV cache, pass `q_positions`/`kv_positions`
    (absolute token positions, int32 [B, T*]); without them, positions
    default to arange (pure prefill).
    """
    B, Tq, Hq, D = q.shape
    _, Tk, Hk, _ = k.shape
    assert Hq % Hk == 0, f"GQA requires Hq % Hk == 0, got {Hq=} {Hk=}"
    if scale is None:
        scale = D**-0.5
    if causal:
        if q_positions is None:
            q_positions = jnp.arange(Tq, dtype=jnp.int32)[None, :]
        if kv_positions is None:
            kv_positions = jnp.arange(Tk, dtype=jnp.int32)[None, :]

    kwargs = dict(
        causal=causal, kv_positions=kv_positions,
        kv_segment_ids=kv_segment_ids, kv_mask=kv_mask, scale=scale,
    )

    # Pick the largest power-of-two query chunk that keeps the logits
    # buffer under MAX_LOGITS_ELEMS and divides Tq (buckets are powers of
    # two); chunk == Tq means one dense call.
    chunk = max(1, MAX_LOGITS_ELEMS // max(1, B * Hq * Tk))
    chunk = 2 ** int(math.floor(math.log2(chunk)))
    while Tq % chunk:
        chunk //= 2
    if chunk >= Tq:
        out = _attention_dense(
            q, k, v, q_positions=q_positions,
            q_segment_ids=q_segment_ids, **kwargs,
        )
        # Same tag the Pallas kernel gives its output, so the "attn"/
        # "attn_qkv" remat policies (utils/remat.py) save the attention
        # output on this path too. There is no explicit logsumexp here, so
        # dq/dk/dv still recompute the softmax internals under remat; the
        # saved output cuts the recompute tree for everything downstream
        # (o_proj and the MLP backward).
        return checkpoint_name(out, "flash_out")

    nc = Tq // chunk

    def split_q(x):  # [Bx, Tq, ...] → [nc, Bx, chunk, ...]
        if x is None:
            return None
        xs = x.reshape(x.shape[0], nc, chunk, *x.shape[2:])
        return jnp.moveaxis(xs, 1, 0)

    def body(args):
        qc, qp, qs = args
        return _attention_dense(
            qc, k, v, q_positions=qp, q_segment_ids=qs, **kwargs
        )

    # checkpoint: without it reverse-mode saves every chunk's probs —
    # O(Tq·Tk) residuals, exactly the memory this path exists to avoid
    # (451 GB at the 131072-patch long-video bucket). Recompute per chunk
    # in backward instead (flash-style tradeoff). prevent_cse barriers are
    # unnecessary under lax.map/scan.
    body = jax.checkpoint(body, prevent_cse=False)

    # Sequential over chunks: peak memory = one chunk's logits.
    outs = jax.lax.map(
        body, (split_q(q), split_q(q_positions), split_q(q_segment_ids))
    )  # [nc, B, chunk, Hq, D]
    out = jnp.moveaxis(outs, 0, 1).reshape(B, Tq, Hq, D)
    return checkpoint_name(out, "flash_out")
