"""Pallas TPU paged decode attention (ragged KV through block tables).

The TPU twin of `ops.paged_kv.ragged_decode_attention`: one decode step
attends over a sequence's pages IN PLACE — the block table is a
scalar-prefetch operand, so each kv tile's DMA source address is
computed from it before the tile runs, and no [B, max_len] contiguous
copy of the cache is ever materialized (the XLA reference gathers one
per layer per step; at 7B serving shapes that gather IS the decode
bandwidth bill).

Shares the flash-attention kernel skeleton (ops/pallas/
flash_attention.py): grid (B, Hk, num_pages_per_seq) with the page
dimension innermost and sequential, online-softmax (m, l, acc) state in
VMEM scratch, fp32 logits/softmax, probs·V in the value dtype. The GQA
group dimension rides INSIDE the tile (q is reshaped [B, Hk, G, D]), so
every grid step issues one [G, page_size] logit matmul per kv head —
the decode-shaped analogue of the prefill kernel's [block_q, block_k]
tiles.

Ragged handling, per row b with `kv_lengths[b] = n`:
  * tiles wholly past n skip their compute (`pl.when`) AND their DMA —
    the index map clamps dead page ids to the last live page, and
    Pallas elides a DMA whose source block repeats the previous step's.
  * the tail tile masks slots >= n to -inf before the softmax.
  * sentinel block-table entries (unallocated tails) clip into the pool
    for address safety; they are only reachable masked.

Interpret mode runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _decode_kernel(
    bt_ref,  # [B, maxp] SMEM (scalar prefetch)
    len_ref,  # [B] SMEM (scalar prefetch)
    q_ref,  # [1, 1, G, D]
    k_ref,  # [1, ps, 1, D]
    v_ref,
    o_ref,  # [1, 1, G, D]
    m_scr, l_scr, acc_scr,
    *,
    scale: float,
    page_size: int,
    num_groups: int,
):
    b, ik = pl.program_id(0), pl.program_id(2)
    nk = pl.num_programs(2)
    G = num_groups

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[b]
    run = ik * page_size < length

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]  # [G, D]
        k = k_ref[0, :, 0, :]  # [ps, D]
        v = v_ref[0, :, 0, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [G, ps] fp32

        slot = ik * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        s = jnp.where(slot < length, s, NEG)

        m_prev = m_scr[:G, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [G, ps] fp32
        l_new = l_scr[:G, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:G, :] = jnp.broadcast_to(m_new, (G, m_scr.shape[1]))
        l_scr[:G, :] = jnp.broadcast_to(l_new, (G, l_scr.shape[1]))
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:G, :] = acc_scr[:G, :] * alpha + pv

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:G, :1]
        out = acc_scr[:G, :] / jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "page_size", "interpret")
)
def _paged_decode(
    q,  # [B, Hk, G, D]
    k_pages,  # [P, ps, Hk, D]
    v_pages,
    block_tables,  # [B, maxp] int32
    kv_lengths,  # [B] int32
    *,
    scale: float,
    page_size: int,
    interpret: bool,
):
    B, Hk, G, D = q.shape
    P = k_pages.shape[0]
    maxp = block_tables.shape[1]

    def kv_map(b, hk, ik, bt_ref, len_ref):
        # Clamp dead tiles onto the last live page (DMA elision — see
        # module docstring) and sentinel entries into the pool.
        last = jnp.maximum(len_ref[b] - 1, 0) // page_size
        page = bt_ref[b, jnp.minimum(ik, last)]
        return (jnp.minimum(page, P - 1), 0, hk, 0)

    grid = (B, Hk, maxp)
    Gp = max(G, 8)  # scratch sublane floor
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=scale, page_size=page_size, num_groups=G
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, G, D), lambda b, hk, ik, *_: (b, hk, 0, 0)
                ),
                pl.BlockSpec((1, page_size, 1, D), kv_map),
                pl.BlockSpec((1, page_size, 1, D), kv_map),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, G, D), lambda b, hk, ik, *_: (b, hk, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((Gp, 128), jnp.float32),
                pltpu.VMEM((Gp, 128), jnp.float32),
                pltpu.VMEM((Gp, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hk, G, D), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), kv_lengths.astype(jnp.int32),
      q, k_pages, v_pages)
    return out


def ragged_decode_attention(
    q,  # [B, 1, Hq, D] or [B, Hq, D]
    k_pages,  # [P, page_size, Hk, D]
    v_pages,
    block_tables,  # [B, max_pages] int32 (sentinel >= P for unallocated)
    kv_lengths,  # [B] valid kv count INCLUDING the current token
    *,
    scale: float | None = None,
    interpret: bool | None = None,
):
    """Drop-in for ops.paged_kv.ragged_decode_attention (same contract);
    pages are read in place through the block table."""
    squeezed = q.ndim == 3
    if squeezed:
        q = q[:, None]
    B, Tq, Hq, D = q.shape
    assert Tq == 1, f"paged decode kernel is single-token (got Tq={Tq})"
    Hk = k_pages.shape[2]
    assert Hq % Hk == 0, f"GQA requires Hq % Hk == 0, got {Hq=} {Hk=}"
    G = Hq // Hk
    if scale is None:
        scale = D**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # h = hk * G + g (the repo's GQA head order: h // G == hk).
    qg = q[:, 0].reshape(B, Hk, G, D)
    out = _paged_decode(
        qg, k_pages, v_pages, block_tables, kv_lengths,
        scale=float(scale), page_size=int(k_pages.shape[1]),
        interpret=bool(interpret),
    )
    out = out.reshape(B, Hq, D)
    return out if squeezed else out[:, None]
