"""Pallas TPU paged attention (ragged KV through block tables).

Two kernels share one skeleton here:

  * `ragged_decode_attention` — the original single-token decode twin
    of `ops.paged_kv.ragged_decode_attention`: [B, 1] queries, one
    sequence per batch row.
  * `ragged_paged_attention` — the PACKED ragged kernel (arXiv
    2604.15464): R query rows drawn from many sequences with MIXED
    query lengths (decode steps and chunked-prefill suffix tokens side
    by side), each walking its OWN sequence's block table via
    scalar-prefetched (segment, position) metadata and causally masked
    at its own position. This is the kernel behind the serving
    engine's one-dispatch-per-step path
    (models/generate.paged_ragged_step); its grid/tile parameters come
    from a (head_dim, page_size)-keyed grid table that is autotuned
    once per shape class and cached (`ragged_grid_config`).

    Speculative decoding rides the SAME kernel unchanged: a slot's 1+k
    verify lanes (ops/paged_kv.spec_lane_metadata) are just 1+k more
    (segment, position) rows of the R-row grid — consecutive positions
    of one segment, exactly the shape a chunked-prefill suffix already
    exercises, so the R axis grows from S+pf to S*(1+k)+pf and nothing
    else moves. The grid stays static per (S, k, pf_width) class; the
    per-row page walk, dead-tile DMA elision and tail masking are
    position-driven and need no notion of "draft".

The TPU win in both: attention over a sequence's pages happens IN
PLACE — the block table is a scalar-prefetch operand, so each kv
tile's DMA source address is computed from it before the tile runs,
and no [B, max_len] contiguous copy of the cache is ever materialized
(the XLA reference gathers one per layer per step; at 7B serving
shapes that gather IS the decode bandwidth bill).

Shares the flash-attention kernel skeleton (ops/pallas/
flash_attention.py): grid (B, Hk, num_pages_per_seq) with the page
dimension innermost and sequential, online-softmax (m, l, acc) state in
VMEM scratch, fp32 logits/softmax, probs·V in the value dtype. The GQA
group dimension rides INSIDE the tile (q is reshaped [B, Hk, G, D]), so
every grid step issues one [G, page_size] logit matmul per kv head —
the decode-shaped analogue of the prefill kernel's [block_q, block_k]
tiles.

Ragged handling, per row b with `kv_lengths[b] = n`:
  * tiles wholly past n skip their compute (`pl.when`) AND their DMA —
    the index map clamps dead page ids to the last live page, and
    Pallas elides a DMA whose source block repeats the previous step's.
  * the tail tile masks slots >= n to -inf before the softmax.
  * sentinel block-table entries (unallocated tails) clip into the pool
    for address safety; they are only reachable masked.

Interpret mode runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _decode_kernel(
    bt_ref,  # [B, maxp] SMEM (scalar prefetch)
    len_ref,  # [B] SMEM (scalar prefetch)
    *refs,  # q, k, [k_scale], v, [v_scale], o, scratch x3
    scale: float,
    page_size: int,
    num_groups: int,
    dequant_dtype: str | None = None,
):
    # Quantized pool: each page tile arrives as storage-dtype codes
    # plus its [1, ps] scale block (fetched through the SAME
    # block-table-driven index map), and the dequant happens HERE, in
    # the page walk — int8 is what crossed HBM. The multiply matches
    # ops.paged_kv.gather_pages' dequant elementwise (same dtype, same
    # broadcast), preserving the kernels' bit-parity contract.
    if dequant_dtype is None:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
        ks_ref = vs_ref = None
    else:
        (q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    b, ik = pl.program_id(0), pl.program_id(2)
    nk = pl.num_programs(2)
    G = num_groups

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[b]
    run = ik * page_size < length

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]  # [G, D]
        k = k_ref[0, :, 0, :]  # [ps, D]
        v = v_ref[0, :, 0, :]
        if ks_ref is not None:
            dq = jnp.dtype(dequant_dtype)
            k = k.astype(dq) * ks_ref[0].astype(dq)[:, None]
            v = v.astype(dq) * vs_ref[0].astype(dq)[:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [G, ps] fp32

        slot = ik * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        s = jnp.where(slot < length, s, NEG)

        m_prev = m_scr[:G, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [G, ps] fp32
        l_new = l_scr[:G, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:G, :] = jnp.broadcast_to(m_new, (G, m_scr.shape[1]))
        l_scr[:G, :] = jnp.broadcast_to(l_new, (G, l_scr.shape[1]))
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:G, :] = acc_scr[:G, :] * alpha + pv

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:G, :1]
        out = acc_scr[:G, :] / jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "page_size", "interpret", "dequant_dtype"),
)
def _paged_decode(
    q,  # [B, Hk, G, D]
    k_pages,  # [P, ps, Hk, D] (codes when quantized)
    v_pages,
    block_tables,  # [B, maxp] int32
    kv_lengths,  # [B] int32
    k_scale=None,  # [P, ps] fp32 per-page scale blocks (quantized pool)
    v_scale=None,
    *,
    scale: float,
    page_size: int,
    interpret: bool,
    dequant_dtype: str | None = None,
):
    B, Hk, G, D = q.shape
    P = k_pages.shape[0]
    maxp = block_tables.shape[1]

    def _page(b, ik, bt_ref, len_ref):
        # Clamp dead tiles onto the last live page (DMA elision — see
        # module docstring) and sentinel entries into the pool.
        last = jnp.maximum(len_ref[b] - 1, 0) // page_size
        page = bt_ref[b, jnp.minimum(ik, last)]
        return jnp.minimum(page, P - 1)

    def kv_map(b, hk, ik, bt_ref, len_ref):
        return (_page(b, ik, bt_ref, len_ref), 0, hk, 0)

    def sc_map(b, hk, ik, bt_ref, len_ref):
        # The page's scale block rides the same block-table-driven
        # stream as its code tile (one address computation, two DMAs).
        return (_page(b, ik, bt_ref, len_ref), 0)

    grid = (B, Hk, maxp)
    Gp = max(G, 8)  # scratch sublane floor
    quant = dequant_dtype is not None
    in_specs = [
        pl.BlockSpec((1, 1, G, D), lambda b, hk, ik, *_: (b, hk, 0, 0)),
        pl.BlockSpec((1, page_size, 1, D), kv_map),
    ]
    operands = [q, k_pages]
    if quant:
        in_specs.append(pl.BlockSpec((1, page_size), sc_map))
        operands.append(k_scale)
    in_specs.append(pl.BlockSpec((1, page_size, 1, D), kv_map))
    operands.append(v_pages)
    if quant:
        in_specs.append(pl.BlockSpec((1, page_size), sc_map))
        operands.append(v_scale)
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=scale, page_size=page_size,
            num_groups=G, dequant_dtype=dequant_dtype,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, G, D), lambda b, hk, ik, *_: (b, hk, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((Gp, 128), jnp.float32),
                pltpu.VMEM((Gp, 128), jnp.float32),
                pltpu.VMEM((Gp, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hk, G, D), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), kv_lengths.astype(jnp.int32),
      *operands)
    return out


def ragged_decode_attention(
    q,  # [B, 1, Hq, D] or [B, Hq, D]
    k_pages,  # [P, page_size, Hk, D]
    v_pages,
    block_tables,  # [B, max_pages] int32 (sentinel >= P for unallocated)
    kv_lengths,  # [B] valid kv count INCLUDING the current token
    *,
    scale: float | None = None,
    interpret: bool | None = None,
):
    """Drop-in for ops.paged_kv.ragged_decode_attention (same contract);
    pages are read in place through the block table. A quantized pool
    (ops.paged_kv.QuantPages planes) is read as codes + per-page scale
    blocks and dequantized inside the page walk."""
    squeezed = q.ndim == 3
    if squeezed:
        q = q[:, None]
    B, Tq, Hq, D = q.shape
    assert Tq == 1, f"paged decode kernel is single-token (got Tq={Tq})"
    Hk = k_pages.shape[2]
    assert Hq % Hk == 0, f"GQA requires Hq % Hk == 0, got {Hq=} {Hk=}"
    G = Hq // Hk
    if scale is None:
        scale = D**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k_scale = v_scale = None
    dequant = None
    if _is_quant(k_pages):
        k_pages, k_scale, v_pages, v_scale, dequant = _split_quant(
            k_pages, v_pages
        )
    # h = hk * G + g (the repo's GQA head order: h // G == hk).
    qg = q[:, 0].reshape(B, Hk, G, D)
    out = _paged_decode(
        qg, k_pages, v_pages, block_tables, kv_lengths,
        k_scale, v_scale,
        scale=float(scale), page_size=int(k_pages.shape[1]),
        interpret=bool(interpret), dequant_dtype=dequant,
    )
    out = out.reshape(B, Hq, D)
    return out if squeezed else out[:, None]


def _is_quant(k_pages) -> bool:
    from oryx_tpu.ops import paged_kv as _pk

    return isinstance(k_pages, _pk.QuantPages)


def _split_quant(k_pages, v_pages):
    """(k_codes, k_scale, v_codes, v_scale, dequant_dtype_str) of a
    quantized pool pair — both planes must be quantized together (a
    mixed pool would silently misread one side's bytes)."""
    from oryx_tpu.ops import paged_kv as _pk

    if not isinstance(v_pages, _pk.QuantPages):
        raise ValueError(
            "quantized K pages with dense V pages: the pool must "
            "quantize both planes (qwen2.init_paged_kv_cache kv_dtype=)"
        )
    return (
        k_pages.q, k_pages.scale, v_pages.q, v_pages.scale,
        str(k_pages.dequant_dtype),
    )


# ---------------------------------------------------------------------------
# Packed ragged kernel: mixed query lengths, one grid, per-row block tables
# ---------------------------------------------------------------------------
#
# Grid (R, Hk // HB, maxp): packed row outermost, kv-head tile, pages
# innermost and sequential so the online-softmax scratch carries across
# a row's page walk. Each grid step DMAs ONE page tile of HB kv heads
# ([1, ps, HB, D], contiguous in the pool) and issues HB [G, ps] logit
# matmuls. Raggedness per packed row r (seg = q_segments[r],
# pos = q_positions[r]):
#   * tiles wholly past pos skip compute AND DMA (index map clamps dead
#     page ids onto the last live page; Pallas elides the repeat DMA);
#   * the tail tile masks slots > pos to -inf before the softmax —
#     the causal mask and the validity mask are the SAME mask here,
#     which is what lets decode rows (pos = len-1) and prefill-suffix
#     rows (consecutive pos) share the kernel;
#   * sentinel block-table entries clip into the pool for address
#     safety (only reachable masked).

# The grid table: (head_dim, page_size) -> tile parameters. HB
# (kv heads per tile) trades DMA count against VMEM residency:
# doubling HB halves page-walk DMAs but doubles the kv tile and the
# scratch footprint, so the sweet spot moves with head_dim x page_size
# bytes. Seeded with VMEM-budget defaults; `autotune_ragged_grid`
# measures the candidates once on real TPU and the winner is cached
# per shape class for the life of the process (the serving engine
# compiles one program per shape class, so the choice must be stable
# — autotune ONCE, never per call).
_RAGGED_GRID_CACHE: dict[tuple[int, int], dict] = {}

# Keep the double-buffered kv tile (2 * ps * HB * D * 4B fp32) within a
# conservative slice of VMEM alongside q/out/scratch.
_RAGGED_KV_TILE_BUDGET = 1 << 21  # 2 MiB


def _default_heads_per_block(head_dim: int, page_size: int) -> int:
    """VMEM-budget default, honoring the $ORYX_RPA_HEADS_PER_BLOCK
    operator pin (every cache-seeding path must route through this, or
    a pinned tile size would be silently discarded for the life of the
    process)."""
    import os

    env = os.environ.get("ORYX_RPA_HEADS_PER_BLOCK")
    if env:
        return max(1, int(env))
    hb = 1
    while (
        hb < 8
        and 2 * page_size * (hb * 2) * head_dim * 4
        <= _RAGGED_KV_TILE_BUDGET
    ):
        hb *= 2
    return hb


def ragged_grid_config(
    head_dim: int, page_size: int, num_kv_heads: int
) -> dict:
    """Tile parameters for the ragged kernel, keyed by shape class.

    Resolution order: process-lifetime cache (autotuned or first-use
    default) -> $ORYX_RPA_HEADS_PER_BLOCK override -> VMEM-budget
    default. The returned heads_per_block always divides num_kv_heads
    (clamped by gcd at use, so a cached choice from one model geometry
    stays safe for another)."""
    import math

    key = (int(head_dim), int(page_size))
    cfg = _RAGGED_GRID_CACHE.get(key)
    if cfg is None:
        cfg = {
            "heads_per_block": _default_heads_per_block(
                head_dim, page_size
            ),
            "autotuned": False,
        }
        _RAGGED_GRID_CACHE[key] = cfg
    hb = math.gcd(cfg["heads_per_block"], int(num_kv_heads))
    return {**cfg, "heads_per_block": max(1, hb)}


def autotune_ragged_grid(
    head_dim: int, page_size: int, num_kv_heads: int,
    *, candidates=(1, 2, 4, 8), trials: int = 3,
) -> dict:
    """Time the heads_per_block candidates once on the real backend and
    cache the winner for this (head_dim, page_size) shape class. On a
    non-TPU backend (or if timing fails) the VMEM-budget default is
    cached instead — the point is a STABLE choice per shape class, not
    a per-call search."""
    import math
    import time as _time

    key = (int(head_dim), int(page_size))
    cached = _RAGGED_GRID_CACHE.get(key)
    if cached is not None and cached.get("autotuned"):
        return ragged_grid_config(head_dim, page_size, num_kv_heads)
    if jax.default_backend() != "tpu":
        _RAGGED_GRID_CACHE[key] = {
            "heads_per_block": _default_heads_per_block(
                head_dim, page_size
            ),
            "autotuned": False,
        }
        return ragged_grid_config(head_dim, page_size, num_kv_heads)
    # Tiny synthetic problem in the target shape class.
    R, S, maxp, P = 16, 8, 8, 64
    Hk = int(num_kv_heads)
    # Independent subkeys: drawing q and the KV pages from one key
    # correlates the synthetic operands (identical leading random
    # stream), skewing the softmax mass the candidate grids are timed
    # against (found by oryxlint key-linearity self-application,
    # oryx_tpu/ops/pallas/paged_attention.py:395).
    kq, kk = jax.random.split(jax.random.key(0))
    q = jax.random.normal(kq, (R, Hk * 2, head_dim), jnp.float32)
    kp = jax.random.normal(kk, (P, page_size, Hk, head_dim), jnp.float32)
    bt = jnp.tile(jnp.arange(maxp, dtype=jnp.int32)[None], (S, 1))
    seg = jnp.arange(R, dtype=jnp.int32) % S
    pos = jnp.full((R,), maxp * page_size - 1, jnp.int32)
    best, best_dt, skipped = None, None, []
    for hb in candidates:
        if math.gcd(hb, Hk) != hb:
            continue
        try:
            fn = lambda: ragged_paged_attention(  # noqa: E731
                q, kp, kp, bt, seg, pos, heads_per_block=hb,
                interpret=False,
            ).block_until_ready()
            fn()  # compile
            t0 = _time.perf_counter()
            for _ in range(trials):
                fn()
            dt = _time.perf_counter() - t0
        except Exception as e:
            # An untunable candidate (VMEM overflow, lowering limit)
            # is a skipped data point, not a fatal error — but it is
            # recorded so the cached choice is explainable.
            skipped.append((hb, f"{type(e).__name__}: {e}"))
            continue
        if best_dt is None or dt < best_dt:
            best, best_dt = hb, dt
    _RAGGED_GRID_CACHE[key] = {
        "heads_per_block": best or _default_heads_per_block(
            head_dim, page_size
        ),
        "autotuned": best is not None,
        "skipped": skipped,
    }
    return ragged_grid_config(head_dim, page_size, num_kv_heads)


def _ragged_kernel(
    bt_ref,  # [S, maxp] SMEM (scalar prefetch)
    seg_ref,  # [R] SMEM
    pos_ref,  # [R] SMEM
    *refs,  # q, k, [k_scale], v, [v_scale], o, scratch x3
    scale: float,
    page_size: int,
    num_groups: int,
    heads_per_block: int,
    dequant_dtype: str | None = None,
):
    # Quantized pool: code tiles + their [1, ps] per-page scale blocks
    # arrive through the same scalar-prefetched block-table stream and
    # dequantize HERE (see _decode_kernel) — the page walk reads int8
    # off HBM and multiplies out to the logical dtype per tile.
    if dequant_dtype is None:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
        ks_ref = vs_ref = None
    else:
        (q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    r, ik = pl.program_id(0), pl.program_id(2)
    nk = pl.num_programs(2)
    G, HB = num_groups, heads_per_block
    Gp = m_scr.shape[0] // HB

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = pos_ref[r] + 1  # visible kv count for this packed row
    run = ik * page_size < length

    @pl.when(run)
    def _step():
        slot = ik * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        for h in range(HB):  # static unroll over the kv-head tile
            q = q_ref[0, h]  # [G, D]
            k = k_ref[0, :, h, :]  # [ps, D]
            v = v_ref[0, :, h, :]
            if ks_ref is not None:
                dq = jnp.dtype(dequant_dtype)
                k = k.astype(dq) * ks_ref[0].astype(dq)[:, None]
                v = v.astype(dq) * vs_ref[0].astype(dq)[:, None]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [G, ps] fp32
            # Causal == validity: slots past this row's own position
            # are invisible, whether they belong to its future tokens
            # (prefill-suffix packing) or to nobody yet (decode).
            s = jnp.where(slot < length, s, NEG)
            lo = h * Gp
            m_prev = m_scr[lo:lo + G, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)  # [G, ps] fp32
            l_new = l_scr[lo:lo + G, :1] * alpha + jnp.sum(
                p, axis=-1, keepdims=True
            )
            m_scr[lo:lo + G, :] = jnp.broadcast_to(
                m_new, (G, m_scr.shape[1])
            )
            l_scr[lo:lo + G, :] = jnp.broadcast_to(
                l_new, (G, l_scr.shape[1])
            )
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_scr[lo:lo + G, :] = acc_scr[lo:lo + G, :] * alpha + pv

    @pl.when(ik == nk - 1)
    def _finalize():
        for h in range(HB):
            lo = h * Gp
            l = l_scr[lo:lo + G, :1]
            out = acc_scr[lo:lo + G, :] / jnp.where(l == 0.0, 1.0, l)
            o_ref[0, h] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "page_size", "heads_per_block", "interpret",
        "dequant_dtype",
    ),
)
def _ragged_paged(
    q,  # [R, Hk, G, D]
    k_pages,  # [P, ps, Hk, D] (codes when quantized)
    v_pages,
    block_tables,  # [S, maxp] int32
    q_segments,  # [R] int32
    q_positions,  # [R] int32
    k_scale=None,  # [P, ps] fp32 per-page scale blocks (quantized pool)
    v_scale=None,
    *,
    scale: float,
    page_size: int,
    heads_per_block: int,
    interpret: bool,
    dequant_dtype: str | None = None,
):
    R, Hk, G, D = q.shape
    P = k_pages.shape[0]
    S, maxp = block_tables.shape
    HB = heads_per_block

    def _page(r, ik, bt_ref, seg_ref, pos_ref):
        # Clamp dead tiles onto the row's last live page (DMA elision)
        # and sentinel entries into the pool; the segment picks WHICH
        # sequence's table this row walks.
        s = jnp.clip(seg_ref[r], 0, S - 1)
        last = jnp.maximum(pos_ref[r], 0) // page_size
        page = bt_ref[s, jnp.minimum(ik, last)]
        return jnp.minimum(page, P - 1)

    def kv_map(r, hb, ik, bt_ref, seg_ref, pos_ref):
        return (_page(r, ik, bt_ref, seg_ref, pos_ref), 0, hb, 0)

    def sc_map(r, hb, ik, bt_ref, seg_ref, pos_ref):
        # The scale block rides the same block-table stream as its
        # code tile.
        return (_page(r, ik, bt_ref, seg_ref, pos_ref), 0)

    grid = (R, Hk // HB, maxp)
    Gp = max(G, 8)  # scratch sublane floor
    quant = dequant_dtype is not None
    in_specs = [
        pl.BlockSpec((1, HB, G, D), lambda r, hb, ik, *_: (r, hb, 0, 0)),
        pl.BlockSpec((1, page_size, HB, D), kv_map),
    ]
    operands = [q, k_pages]
    if quant:
        in_specs.append(pl.BlockSpec((1, page_size), sc_map))
        operands.append(k_scale)
    in_specs.append(pl.BlockSpec((1, page_size, HB, D), kv_map))
    operands.append(v_pages)
    if quant:
        in_specs.append(pl.BlockSpec((1, page_size), sc_map))
        operands.append(v_scale)
    out = pl.pallas_call(
        functools.partial(
            _ragged_kernel, scale=scale, page_size=page_size,
            num_groups=G, heads_per_block=HB,
            dequant_dtype=dequant_dtype,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, HB, G, D), lambda r, hb, ik, *_: (r, hb, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((HB * Gp, 128), jnp.float32),
                pltpu.VMEM((HB * Gp, 128), jnp.float32),
                pltpu.VMEM((HB * Gp, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((R, Hk, G, D), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), q_segments.astype(jnp.int32),
      q_positions.astype(jnp.int32), *operands)
    return out


def ragged_paged_attention(
    q,  # [R, Hq, D] packed query rows
    k_pages,  # [P, page_size, Hk, D]
    v_pages,
    block_tables,  # [S, max_pages] int32 (sentinel >= P for unallocated)
    q_segments,  # [R] owning slot per packed row
    q_positions,  # [R] absolute position per packed row
    *,
    scale: float | None = None,
    interpret: bool | None = None,
    heads_per_block: int | None = None,
):
    """Drop-in for ops.paged_kv.ragged_paged_attention (same contract):
    R packed query rows with mixed query lengths, each reading its own
    sequence's pages in place through the block table. Tile parameters
    come from the (head_dim, page_size) grid table unless pinned. A
    quantized pool (ops.paged_kv.QuantPages planes) is read as codes +
    per-page scale blocks and dequantized inside the page walk."""
    R, Hq, D = q.shape
    Hk = k_pages.shape[2]
    assert Hq % Hk == 0, f"GQA requires Hq % Hk == 0, got {Hq=} {Hk=}"
    G = Hq // Hk
    if scale is None:
        scale = D**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if heads_per_block is None:
        heads_per_block = ragged_grid_config(
            D, int(k_pages.shape[1]), Hk
        )["heads_per_block"]
    import math

    heads_per_block = max(1, math.gcd(int(heads_per_block), Hk))
    k_scale = v_scale = None
    dequant = None
    if _is_quant(k_pages):
        k_pages, k_scale, v_pages, v_scale, dequant = _split_quant(
            k_pages, v_pages
        )
    # h = hk * G + g (the repo's GQA head order: h // G == hk).
    qg = q.reshape(R, Hk, G, D)
    out = _ragged_paged(
        qg, k_pages, v_pages, block_tables, q_segments, q_positions,
        k_scale, v_scale,
        scale=float(scale), page_size=int(k_pages.shape[1]),
        heads_per_block=int(heads_per_block), interpret=bool(interpret),
        dequant_dtype=dequant,
    )
    return out.reshape(R, Hq, D)
