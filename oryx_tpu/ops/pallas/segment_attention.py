"""Packed varlen attention for OryxViT — segment ids over one flat buffer.

The TPU-native replacement for `flash_attn_varlen_func` + cu_seqlens
(SURVEY.md §2a): many arbitrary-resolution images packed into one bucketed
sequence, each attending only within its own segment. Thin front-end over
the unified Pallas flash kernel (flash_attention.py) with causal masking
off and segment masking on.
"""

from __future__ import annotations

from oryx_tpu.ops.pallas.flash_attention import flash_attention


def segment_attention(q, k, v, q_segment_ids, kv_segment_ids, scale=None):
    """q/k/v: [B, T, H, D]; segment ids [B, T] (0 = padding, which only
    attends to itself — outputs on pad rows are discarded by callers)."""
    return flash_attention(
        q, k, v,
        causal=False,
        q_segment_ids=q_segment_ids,
        kv_segment_ids=kv_segment_ids,
        scale=scale,
    )
