"""Pallas TPU flash attention (causal GQA + segments + KV masking).

The TPU-native replacement for the reference's flash-attn CUDA kernels
(SURVEY.md §2a): one kernel serves the decoder (causal, GQA, KV-cache
decode) and — via segment ids — the packed arbitrary-resolution ViT
(`flash_attn_varlen_func`-equivalent; see segment_attention.py).

Design:
  * Grid (B, Hq, nq, nk); the innermost kv dimension runs sequentially on
    the core, accumulating online-softmax state (m, l, acc) in VMEM
    scratch and finalizing the output block at the last kv step.
  * Logits/softmax in fp32 (matching ops/attention.py's bit-closeness
    policy); the probs·V matmul in the value dtype so the MXU runs bf16.
  * Masking is the same model as ops/attention.attention: causal on
    absolute positions, segment-id equality, explicit kv validity — all
    folded into one predicate per tile. With arange kv positions (the
    prefill and KV-cache layouts), causally-dead kv tiles are skipped.
  * Backward: Pallas flash backward (custom VJP). The forward saves the
    per-row logsumexp; `_dq_kernel` accumulates dq over kv tiles and
    `_dkv_kernel` accumulates dk/dv over (group-head, q-tile) steps with
    the GQA reduction in VMEM scratch — O(T) memory, no O(T²) recompute.
    Per-row lse/Δ scalars ride in an 8-sublane layout and are broadcast
    against logit tiles via a rank-1 MXU outer product (no relayouts).

Interpret mode runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -0.7 * float(jnp.finfo(jnp.float32).max)

# Tile sizes. 512×512 keeps the fp32 logits tile at 1 MB of VMEM while
# amortizing DMA and per-tile softmax state updates; q/k/v/acc tiles add
# ~0.8 MB — comfortably inside the ~16 MB VMEM budget with double
# buffering. Validated on-chip (v5e, see TPU_VALIDATION.md): 512x512 beat
# the 256/1024 variants on the bench shapes. Env-overridable for sweeps.
BLOCK_Q = int(os.environ.get("ORYX_FLASH_BLOCK_Q", "512"))
BLOCK_K = int(os.environ.get("ORYX_FLASH_BLOCK_K", "512"))
# Backward kernels take independent tile sizes: the dq/dkv kernels
# stream three extra operands (do, lse, Δ) per tile and accumulate into
# VMEM scratch, so their DMA/compute balance differs. On-chip (v5e,
# TPU_VALIDATION.md) 1024×1024 backward tiles beat the 512×512 forward
# tiling by ~2-3% of attention fwd+bwd at both T=2048 and T=4096;
# shorter/indivisible sequences fall back to the forward tiling
# (_bwd_block). Env: unset → the 1024 default; 0 → None = inherit the
# forward value AT CALL TIME; any other value → itself.
def _bwd_env(name: str) -> int | None:
    raw = os.environ.get(name)
    if raw is None:
        return 1024
    return int(raw) or None


BWD_BLOCK_Q = _bwd_env("ORYX_FLASH_BWD_BLOCK_Q")
BWD_BLOCK_K = _bwd_env("ORYX_FLASH_BWD_BLOCK_K")


def _bwd_block(pref: int | None, fwd: int, T: int) -> int:
    """Backward tile size: the preferred bwd block when set and dividing
    the padded length (which was padded to FORWARD-block multiples), else
    fall back to the forward choice (always a divisor)."""
    if pref is None:
        return min(fwd, T)
    b = min(pref, T)
    return b if T % b == 0 else min(fwd, T)


def _causal_kv_clamp(block_q: int, block_k: int, enabled: bool):
    """Grid-level kv skipping for causal PREFILL layouts (q AND kv
    positions both arange from 0 — `enabled` must encode that): map every
    causally-dead kv tile index to the LAST live tile for its q tile.
    Pallas elides the DMA when an input block's index map repeats the
    previous grid step's value, so dead tiles cost neither bandwidth nor
    compute (the kernels' `run` predicate — keyed on the unclamped
    program id — already skips their math). Invalid for the decode layout
    (arbitrary q positions): tile index no longer bounds position there."""
    if not enabled:
        return lambda iq, ik: ik

    def clamp(iq, ik):
        return jnp.minimum(ik, ((iq + 1) * block_q - 1) // block_k)

    return clamp


def _causal_q_clamp(block_q: int, block_k: int, enabled: bool):
    """dkv-kernel mirror of _causal_kv_clamp: q tiles entirely before a kv
    tile are dead; map them to the FIRST live q tile."""
    if not enabled:
        return lambda ik, iq: iq

    def clamp(ik, iq):
        return jnp.maximum(iq, (ik * block_k) // block_q)

    return clamp


def _kernel(
    qpos_ref, kpos_ref, qseg_ref, kseg_ref, kvalid_ref,
    q_ref, k_ref, v_ref,
    o_ref, lse_ref,  # lse_ref is None when with_lse=False (inference)
    m_scr, l_scr, acc_scr,
    *,
    scale: float,
    causal: bool,
    has_segments: bool,
    kv_arange: bool,
    block_k: int,
):
    ik, nk = pl.program_id(3), pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # q-side int refs are lane-broadcast [1, bq, LANES]; kv-side are
    # sublane-broadcast [1, SUBLANES, bk] (TPU tiling wants the last two
    # dims (8k, 128m)-aligned; a bare [1, bk] block is not lowerable).
    if causal and kv_arange:
        # kv positions are arange ⇒ tiles entirely after the largest query
        # position contribute nothing; skip their compute (data is still
        # prefetched — grid-level skipping is a later optimization).
        run = ik * block_k <= jnp.max(qpos_ref[0])
    else:
        run = True

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]  # [bq, D]
        k = k_ref[0, 0]  # [bk, D]
        v = v_ref[0, 0]  # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk] fp32

        mask = kvalid_ref[0, :1, :] > 0  # [1, bk]
        if causal:
            mask = jnp.logical_and(
                mask, qpos_ref[0, :, :1] >= kpos_ref[0, :1, :]
            )
        if has_segments:
            mask = jnp.logical_and(
                mask, qseg_ref[0, :, :1] == kseg_ref[0, :1, :]
            )
        s = jnp.where(mask, s, NEG)

        m_prev = m_scr[:, :1]  # [bq, 1] (m/l live lane-broadcast in VMEM)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [bq, bk] fp32
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        out = acc_scr[:] / jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = out.astype(o_ref.dtype)
        if lse_ref is not None:
            # logsumexp for the backward pass. Fully-masked rows (l == 0,
            # e.g. padding) get +inf so exp(s - lse) underflows to 0 there.
            lse = jnp.where(
                l == 0.0,
                jnp.float32(jnp.finfo(jnp.float32).max),
                m_scr[:, :1] + jnp.log(jnp.where(l == 0.0, 1.0, l)),
            )
            lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _pad_axis(x, axis: int, target: int, fill=0):
    pad = target - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=fill)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "has_segments", "kv_arange", "q_arange",
                     "scale", "interpret", "with_lse"),
)
def _mha_forward(
    q, k, v, q_pos, kv_pos, q_seg, kv_seg, kv_valid,
    *,
    causal: bool,
    has_segments: bool,
    kv_arange: bool,
    q_arange: bool,
    scale: float,
    interpret: bool,
    with_lse: bool = False,
):
    """Core pallas call. Layouts: q [B, Hq, Tq, D]; k/v [B, Hk, Tk, D];
    int arrays [B, T*] (already padded to block multiples). with_lse emits
    the logsumexp residual for the backward pass (skipped at inference —
    its lane-broadcast output buffer is the price of the grad path only).
    """
    B, Hq, Tq, D = q.shape
    _, Hk, Tk, _ = k.shape
    G = Hq // Hk
    block_q = min(BLOCK_Q, Tq)
    block_k = min(BLOCK_K, Tk)
    nq = Tq // block_q
    nk = Tk // block_k

    # Lane/sublane broadcast layouts for the per-token int arrays (see
    # kernel comment): q-side [B, Tq, LANES], kv-side [B, SUBLANES, Tk].
    LANES, SUB = 128, 8
    q_pos = jnp.broadcast_to(q_pos[:, :, None], (B, Tq, LANES))
    q_seg = jnp.broadcast_to(q_seg[:, :, None], (B, Tq, LANES))
    kv_pos = jnp.broadcast_to(kv_pos[:, None, :], (B, SUB, Tk))
    kv_seg = jnp.broadcast_to(kv_seg[:, None, :], (B, SUB, Tk))
    kv_valid = jnp.broadcast_to(kv_valid[:, None, :], (B, SUB, Tk))

    grid = (B, Hq, nq, nk)
    kern_full = functools.partial(
        _kernel, scale=scale, causal=causal, has_segments=has_segments,
        kv_arange=kv_arange, block_k=block_k,
    )
    if with_lse:
        kern = kern_full
    else:
        def kern(qp, kp, qs, ks, kvd, q_, k_, v_, o_, m_, l_, a_):
            kern_full(qp, kp, qs, ks, kvd, q_, k_, v_, o_, None, m_, l_, a_)

    ck = _causal_kv_clamp(block_q, block_k, causal and kv_arange and q_arange)

    o_spec = pl.BlockSpec(
        (1, 1, block_q, D), lambda b, h, iq, ik: (b, h, iq, 0)
    )
    o_shape = jax.ShapeDtypeStruct((B, Hq, Tq, D), q.dtype)
    lse_spec = pl.BlockSpec(
        (1, 1, block_q, LANES), lambda b, h, iq, ik: (b, h, iq, 0)
    )
    lse_shape = jax.ShapeDtypeStruct((B, Hq, Tq, LANES), jnp.float32)

    res = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, LANES), lambda b, h, iq, ik: (b, iq, 0)),
            pl.BlockSpec(
                (1, SUB, block_k), lambda b, h, iq, ik: (b, 0, ck(iq, ik))
            ),
            pl.BlockSpec((1, block_q, LANES), lambda b, h, iq, ik: (b, iq, 0)),
            pl.BlockSpec(
                (1, SUB, block_k), lambda b, h, iq, ik: (b, 0, ck(iq, ik))
            ),
            pl.BlockSpec(
                (1, SUB, block_k), lambda b, h, iq, ik: (b, 0, ck(iq, ik))
            ),
            pl.BlockSpec(
                (1, 1, block_q, D), lambda b, h, iq, ik: (b, h, iq, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, D),
                lambda b, h, iq, ik: (b, h // G, ck(iq, ik), 0),
            ),
            pl.BlockSpec(
                (1, 1, block_k, D),
                lambda b, h, iq, ik: (b, h // G, ck(iq, ik), 0),
            ),
        ],
        out_specs=[o_spec, lse_spec] if with_lse else [o_spec],
        out_shape=[o_shape, lse_shape] if with_lse else [o_shape],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q_pos, kv_pos, q_seg, kv_seg, kv_valid, q, k, v)
    if with_lse:
        return res[0], res[1][..., 0]
    return res[0], None


def _row_outer(row, n: int):
    """[1, bq] per-q-row scalars → [bq, n] tile with the scalar repeated
    along lanes: rank-1 outer product rowᵀ·1 on the MXU. Avoids a
    sublane↔lane relayout of the scalar vector."""
    ones = jnp.ones((1, n), jnp.float32)
    return jax.lax.dot_general(
        row, ones, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _dq_kernel(
    qpos_ref, kpos_ref, qseg_ref, kseg_ref, kvalid_ref,
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref,
    dq_scr,
    *,
    scale: float,
    causal: bool,
    has_segments: bool,
    kv_arange: bool,
    block_k: int,
):
    """dq = (p ∘ (do·vᵀ − Δ)) · k · scale, accumulated over kv tiles.
    Same grid/masking layout as the forward kernel."""
    ik, nk = pl.program_id(3), pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    if causal and kv_arange:
        run = ik * block_k <= jnp.max(qpos_ref[0])
    else:
        run = True

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        bk = k.shape[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

        mask = kvalid_ref[0, :1, :] > 0
        if causal:
            mask = jnp.logical_and(
                mask, qpos_ref[0, :, :1] >= kpos_ref[0, :1, :]
            )
        if has_segments:
            mask = jnp.logical_and(
                mask, qseg_ref[0, :, :1] == kseg_ref[0, :1, :]
            )
        s = jnp.where(mask, s, NEG)
        lse_mat = _row_outer(lse_ref[0, 0, :1, :], bk)  # [bq, bk]
        p = jnp.exp(s - lse_mat)  # [bq, bk] fp32

        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        ds = p * (dp - _row_outer(delta_ref[0, 0, :1, :], bk)) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    qpos_ref, kpos_ref, qseg_ref, kseg_ref, kvalid_ref,
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref,
    dk_scr, dv_scr,
    *,
    scale: float,
    causal: bool,
    has_segments: bool,
    kv_arange: bool,
    q_arange: bool,
    block_q: int,
    block_k: int,
):
    """dk/dv for one kv tile, accumulated over all (group-head, q-tile)
    steps. Grid (B, Hk, nk, G, nq): the two innermost dims revisit the same
    kv/output blocks, so GQA head-group reduction happens in VMEM scratch.
    """
    g, iq = pl.program_id(3), pl.program_id(4)
    nG, nq = pl.num_programs(3), pl.num_programs(4)

    @pl.when(jnp.logical_and(g == 0, iq == 0))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    ik = pl.program_id(2)
    if causal and kv_arange and q_arange:
        # Prefill: q tiles entirely before this kv tile contribute
        # nothing. Keyed on program ids (NOT qpos_ref — its index map
        # aliases dead q tiles onto live ones for the DMA skip). Padded q
        # rows past the real length still run but contribute zeros (do is
        # zero there).
        run = ik * block_k <= (iq + 1) * block_q - 1
    elif causal and kv_arange:
        # Arbitrary q positions (decode layout): no q-side aliasing, so
        # the actual positions bound the live kv range.
        run = ik * block_k <= jnp.max(qpos_ref[0])
    else:
        run = True

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]    # [bq, D]
        k = k_ref[0, 0]    # [bk, D]
        v = v_ref[0, 0]
        do = do_ref[0, 0]  # [bq, D]
        bk = k.shape[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]

        mask = kvalid_ref[0, :1, :] > 0
        if causal:
            mask = jnp.logical_and(
                mask, qpos_ref[0, :, :1] >= kpos_ref[0, :1, :]
            )
        if has_segments:
            mask = jnp.logical_and(
                mask, qseg_ref[0, :, :1] == kseg_ref[0, :1, :]
            )
        s = jnp.where(mask, s, NEG)
        p = jnp.exp(s - _row_outer(lse_ref[0, 0, :1, :], bk))  # [bq, bk]

        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        ds = p * (dp - _row_outer(delta_ref[0, 0, :1, :], bk)) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, D]

    @pl.when(jnp.logical_and(g == nG - 1, iq == nq - 1))
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "has_segments", "kv_arange", "q_arange",
                     "scale", "interpret"),
)
def _mha_backward(
    q, k, v, do, lse, delta, q_pos, kv_pos, q_seg, kv_seg, kv_valid,
    *,
    causal: bool,
    has_segments: bool,
    kv_arange: bool,
    q_arange: bool,
    scale: float,
    interpret: bool,
):
    """Layouts as _mha_forward, plus do [B, Hq, Tq, D] and lse/delta
    [B, Hq, Tq] (all padded to block multiples)."""
    B, Hq, Tq, D = q.shape
    _, Hk, Tk, _ = k.shape
    G = Hq // Hk
    block_q = _bwd_block(BWD_BLOCK_Q, BLOCK_Q, Tq)
    block_k = _bwd_block(BWD_BLOCK_K, BLOCK_K, Tk)
    nq = Tq // block_q
    nk = Tk // block_k

    LANES, SUB = 128, 8
    q_pos_l = jnp.broadcast_to(q_pos[:, :, None], (B, Tq, LANES))
    q_seg_l = jnp.broadcast_to(q_seg[:, :, None], (B, Tq, LANES))
    kv_pos_s = jnp.broadcast_to(kv_pos[:, None, :], (B, SUB, Tk))
    kv_seg_s = jnp.broadcast_to(kv_seg[:, None, :], (B, SUB, Tk))
    kv_valid_s = jnp.broadcast_to(kv_valid[:, None, :], (B, SUB, Tk))
    # Per-q-row scalars in the compact 8-sublane layout ([B, Hq, 8, Tq],
    # 16x smaller than lane-broadcast); kernels re-expand per tile with a
    # rank-1 outer product (_row_outer).
    lse_s = jnp.broadcast_to(lse[:, :, None, :], (B, Hq, SUB, Tq))
    delta_s = jnp.broadcast_to(delta[:, :, None, :], (B, Hq, SUB, Tq))

    common = dict(
        scale=scale, causal=causal, has_segments=has_segments,
        kv_arange=kv_arange,
    )

    ckv = _causal_kv_clamp(block_q, block_k, causal and kv_arange and q_arange)
    cq = _causal_q_clamp(block_q, block_k, causal and kv_arange and q_arange)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, **common),
        grid=(B, Hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, LANES), lambda b, h, iq, ik: (b, iq, 0)),
            pl.BlockSpec(
                (1, SUB, block_k), lambda b, h, iq, ik: (b, 0, ckv(iq, ik))
            ),
            pl.BlockSpec((1, block_q, LANES), lambda b, h, iq, ik: (b, iq, 0)),
            pl.BlockSpec(
                (1, SUB, block_k), lambda b, h, iq, ik: (b, 0, ckv(iq, ik))
            ),
            pl.BlockSpec(
                (1, SUB, block_k), lambda b, h, iq, ik: (b, 0, ckv(iq, ik))
            ),
            pl.BlockSpec(
                (1, 1, block_q, D), lambda b, h, iq, ik: (b, h, iq, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, D),
                lambda b, h, iq, ik: (b, h // G, ckv(iq, ik), 0),
            ),
            pl.BlockSpec(
                (1, 1, block_k, D),
                lambda b, h, iq, ik: (b, h // G, ckv(iq, ik), 0),
            ),
            pl.BlockSpec(
                (1, 1, block_q, D), lambda b, h, iq, ik: (b, h, iq, 0)
            ),
            pl.BlockSpec(
                (1, 1, SUB, block_q), lambda b, h, iq, ik: (b, h, 0, iq)
            ),
            pl.BlockSpec(
                (1, 1, SUB, block_q), lambda b, h, iq, ik: (b, h, 0, iq)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, D), lambda b, h, iq, ik: (b, h, iq, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, Tq, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q_pos_l, kv_pos_s, q_seg_l, kv_seg_s, kv_valid_s,
      q, k, v, do, lse_s, delta_s)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, block_q=block_q, block_k=block_k,
            q_arange=q_arange, **common
        ),
        grid=(B, Hk, nk, G, nq),
        in_specs=[
            pl.BlockSpec(
                (1, block_q, LANES),
                lambda b, hk, ik, g, iq: (b, cq(ik, iq), 0),
            ),
            pl.BlockSpec(
                (1, SUB, block_k), lambda b, hk, ik, g, iq: (b, 0, ik)
            ),
            pl.BlockSpec(
                (1, block_q, LANES),
                lambda b, hk, ik, g, iq: (b, cq(ik, iq), 0),
            ),
            pl.BlockSpec(
                (1, SUB, block_k), lambda b, hk, ik, g, iq: (b, 0, ik)
            ),
            pl.BlockSpec(
                (1, SUB, block_k), lambda b, hk, ik, g, iq: (b, 0, ik)
            ),
            pl.BlockSpec(
                (1, 1, block_q, D),
                lambda b, hk, ik, g, iq: (b, hk * G + g, cq(ik, iq), 0),
            ),
            pl.BlockSpec(
                (1, 1, block_k, D), lambda b, hk, ik, g, iq: (b, hk, ik, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, D), lambda b, hk, ik, g, iq: (b, hk, ik, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, D),
                lambda b, hk, ik, g, iq: (b, hk * G + g, cq(ik, iq), 0),
            ),
            pl.BlockSpec(
                (1, 1, SUB, block_q),
                lambda b, hk, ik, g, iq: (b, hk * G + g, 0, cq(ik, iq)),
            ),
            pl.BlockSpec(
                (1, 1, SUB, block_q),
                lambda b, hk, ik, g, iq: (b, hk * G + g, 0, cq(ik, iq)),
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, block_k, D), lambda b, hk, ik, g, iq: (b, hk, ik, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, D), lambda b, hk, ik, g, iq: (b, hk, ik, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hk, Tk, D), jnp.float32),
            jax.ShapeDtypeStruct((B, Hk, Tk, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(q_pos_l, kv_pos_s, q_seg_l, kv_seg_s, kv_valid_s,
      q, k, v, do, lse_s, delta_s)
    return dq, dk, dv


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def flash_attention(
    q, k, v,
    *,
    causal: bool = True,
    q_positions=None,
    kv_positions=None,
    q_segment_ids=None,
    kv_segment_ids=None,
    kv_mask=None,
    scale: float | None = None,
    slot_positions: bool = False,
):
    """Drop-in for ops.attention.attention with identical masking model.

    q: [B, Tq, Hq, D]; k/v: [B, Tk, Hk, D]. Returns [B, Tq, Hq, D].

    slot_positions: static caller promise that every VALID token's
    position equals its slot index (the right-padded prefill layout:
    positions are per-row arange with masked pads). Enables the causal
    tile skips (compute + DMA) that plain arange layouts get, while the
    mask math still uses the explicit position arrays.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_vjp(
        q, k, v, q_positions, kv_positions, q_segment_ids, kv_segment_ids,
        kv_mask, causal, float(scale), slot_positions,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10))
def _flash_vjp(
    q, k, v, q_positions, kv_positions, q_segment_ids, kv_segment_ids,
    kv_mask, causal, scale, slot_positions,
):
    return _flash_attention_impl(
        q, k, v, q_positions, kv_positions, q_segment_ids, kv_segment_ids,
        kv_mask, causal, scale, slot_positions=slot_positions,
    )[0]


def _prepare(q, k, v, q_positions, kv_positions, q_segment_ids,
             kv_segment_ids, kv_mask, causal, scale,
             slot_positions=False):
    """Normalize/pad every operand to the kernel layouts. Returns the
    padded tensors plus the static flags shared by forward and backward."""
    B, Tq, Hq, D = q.shape
    _, Tk, Hk, _ = k.shape
    if scale is None:
        scale = D**-0.5

    block_q = min(BLOCK_Q, _round_up(Tq, 16))
    block_k = min(BLOCK_K, _round_up(Tk, 16))
    Tq_p = _round_up(Tq, block_q)
    Tk_p = _round_up(Tk, block_k)

    kv_arange = kv_positions is None or slot_positions
    q_arange = q_positions is None or slot_positions
    if q_positions is None:
        q_positions = jnp.broadcast_to(jnp.arange(Tq, dtype=jnp.int32), (B, Tq))
    if kv_positions is None:
        kv_positions = jnp.broadcast_to(
            jnp.arange(Tk, dtype=jnp.int32), (B, Tk)
        )
    has_segments = q_segment_ids is not None
    if has_segments:
        assert kv_segment_ids is not None
        q_seg = jnp.broadcast_to(q_segment_ids, (B, Tq)).astype(jnp.int32)
        kv_seg = jnp.broadcast_to(kv_segment_ids, (B, Tk)).astype(jnp.int32)
    else:
        q_seg = jnp.zeros((B, Tq), jnp.int32)
        kv_seg = jnp.zeros((B, Tk), jnp.int32)
    kv_valid = (
        jnp.broadcast_to(kv_mask, (B, Tk)).astype(jnp.int32)
        if kv_mask is not None
        else jnp.ones((B, Tk), jnp.int32)
    )

    # Pad sequence dims to block multiples. Padded kv is invalid; padded q
    # rows produce garbage that is sliced off. Padded q positions stay 0 so
    # the causal-skip bound never extends the loop.
    qt = _pad_axis(q.swapaxes(1, 2), 2, Tq_p)  # [B, Hq, Tq_p, D]
    kt = _pad_axis(k.swapaxes(1, 2), 2, Tk_p)
    vt = _pad_axis(v.swapaxes(1, 2), 2, Tk_p)
    q_pos = _pad_axis(q_positions.astype(jnp.int32), 1, Tq_p)
    kv_pos = _pad_axis(kv_positions.astype(jnp.int32), 1, Tk_p)
    q_seg = _pad_axis(q_seg, 1, Tq_p, fill=-1)
    kv_seg = _pad_axis(kv_seg, 1, Tk_p, fill=-2)
    kv_valid = _pad_axis(kv_valid, 1, Tk_p)
    flags = dict(
        causal=causal, has_segments=has_segments, kv_arange=kv_arange,
        q_arange=q_arange, scale=float(scale), interpret=_use_interpret(),
    )
    return (qt, kt, vt, q_pos, kv_pos, q_seg, kv_seg, kv_valid), flags, Tq


def _flash_attention_impl(
    q, k, v, q_positions, kv_positions, q_segment_ids, kv_segment_ids,
    kv_mask, causal, scale, with_lse=False, slot_positions=False,
):
    padded, flags, Tq = _prepare(
        q, k, v, q_positions, kv_positions, q_segment_ids, kv_segment_ids,
        kv_mask, causal, scale, slot_positions=slot_positions,
    )
    out, lse = _mha_forward(*padded, with_lse=with_lse, **flags)
    return out[:, :, :Tq].swapaxes(1, 2), lse


def _fwd(q, k, v, q_positions, kv_positions, q_segment_ids, kv_segment_ids,
         kv_mask, causal, scale, slot_positions):
    out, lse = _flash_attention_impl(
        q, k, v, q_positions, kv_positions, q_segment_ids, kv_segment_ids,
        kv_mask, causal, scale, with_lse=True, slot_positions=slot_positions,
    )
    # Under block remat, a policy that saves these names (utils/remat.py
    # "attn") keeps the kernel output + softmax stats across the forward,
    # so the backward's block recompute reuses them instead of re-running
    # the forward kernel — the single most expensive recomputed op.
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    res = (q, k, v, out, lse, q_positions, kv_positions, q_segment_ids,
           kv_segment_ids, kv_mask)
    return out, res


def _bwd(causal, scale, slot_positions, res, g):
    """Flash backward: Pallas dq and dk/dv kernels using the saved
    logsumexp — O(T) memory (vs the O(T²) recompute fallback)."""
    (q, k, v, out, lse, q_positions, kv_positions, q_segment_ids,
     kv_segment_ids, kv_mask) = res
    B, Tq, Hq, D = q.shape

    padded, flags, _ = _prepare(
        q, k, v, q_positions, kv_positions, q_segment_ids, kv_segment_ids,
        kv_mask, causal, scale, slot_positions=slot_positions,
    )
    qt = padded[0]
    Tq_p = qt.shape[2]
    # Δ_i = Σ_d dOᵢ·Oᵢ in fp32, padded like q (zeros: padded do is zero).
    delta = jnp.einsum(
        "bqhd,bqhd->bhq", g.astype(jnp.float32), out.astype(jnp.float32)
    )
    delta = _pad_axis(delta, 2, Tq_p)
    do = _pad_axis(g.swapaxes(1, 2), 2, Tq_p)

    dq, dk, dv = _mha_backward(
        padded[0], padded[1], padded[2], do, lse, delta,
        padded[3], padded[4], padded[5], padded[6], padded[7],
        **flags,
    )
    Tk = k.shape[1]
    dq = dq[:, :, :Tq].swapaxes(1, 2).astype(q.dtype)
    dk = dk[:, :, :Tk].swapaxes(1, 2).astype(k.dtype)
    dv = dv[:, :, :Tk].swapaxes(1, 2).astype(v.dtype)
    return (dq, dk, dv, None, None, None, None, None)


_flash_vjp.defvjp(_fwd, _bwd)
