"""Pallas TPU kernels — drop-in replacements for the XLA reference ops.

Selected by `OryxConfig.attn_impl = "pallas"`. Every kernel here has an
XLA-path twin in `oryx_tpu/ops/` that defines the semantics; tests compare
the two in interpret mode on CPU (SURVEY.md §4 "Unit").
"""
