"""Paged KV cache: fixed-size pages, block tables, ragged decode attention.

The serving-side answer to "every (batch, seq) bucket owns a dense
[B, S, Hk, D] cache": K/V live in a single pool of fixed-size pages
([num_pages, page_size, Hk, D] per layer) and each sequence owns an
ordered list of page indices (its *block table*). Logical slot `s` of a
sequence lives at page `block_table[s // page_size]`, offset
`s % page_size`. Sequences of wildly different lengths then share one
pool — the HBM cost of a batch is the sum of its real lengths (rounded
up to pages), not num_slots × max_len — and a finished sequence's pages
return to the free list for the next admission (continuous batching,
arXiv 2604.15464 / 2605.25645).

Three pieces live here:
  * `PageAllocator` — the host-side free list. Pure Python; the device
    never sees it. Page 0..num_pages-1 are real; `allocator.sentinel`
    (== num_pages) marks unallocated block-table entries. Writes routed
    to the sentinel fall off the end of the pool and are DROPPED by
    XLA's out-of-bounds scatter rule; gathers CLIP to the last page and
    the garbage is masked out of attention. Both behaviors are load-
    bearing: masked rows need no branch on device.
  * `write_pages` / `gather_pages` — the device-side page I/O, plain
    scatter/gather in slot order. Shapes are static; the block table is
    a traced [B, max_pages] int32 operand, so growing a sequence never
    recompiles.
  * `ragged_decode_attention` — the pure-JAX reference decode path:
    gather each row's pages into a contiguous [B, K, Hk, D] view and
    run the stock fp32-softmax attention. Bit-identical to the dense
    cache path when the padded KV width matches (masked columns are
    exactly 0 probability either way). The Pallas twin
    (`ops/pallas/paged_attention.py`) reads pages in place through the
    block table instead of gathering.
"""

from __future__ import annotations

import jax.numpy as jnp

from oryx_tpu.ops.attention import attention


class OutOfPagesError(RuntimeError):
    """The free list cannot satisfy an allocation (caller should evict
    or defer admission — this is a scheduling signal, not a crash)."""


class PageAllocator:
    """Host-side free-list allocator over `num_pages` fixed-size pages.

    LIFO recycling: freshly freed pages are handed out first, which
    keeps the hot working set of pages small and stable (good for any
    cache layer under the pool). Allocation is all-or-nothing so a
    failed admission never leaks a partial block table.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 1 or page_size < 1:
            raise ValueError(f"need >= 1 page/slot, got {num_pages=} {page_size=}")
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: list[int] = list(range(num_pages - 1, -1, -1))

    @property
    def sentinel(self) -> int:
        """Block-table filler for unallocated entries: one past the pool
        (writes drop, gathers clip; see module docstring)."""
        return self.num_pages

    @property
    def num_free(self) -> int:
        return len(self._free)

    def pages_for(self, num_tokens: int) -> int:
        """Pages needed to hold `num_tokens` KV slots."""
        return max(0, -(-num_tokens // self.page_size))

    def alloc(self, n: int) -> list[int]:
        if n > len(self._free):
            raise OutOfPagesError(
                f"need {n} pages, {len(self._free)} free of {self.num_pages}"
            )
        if n <= 0:
            return []
        out = self._free[-n:][::-1]
        del self._free[-n:]
        return out

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if not 0 <= p < self.num_pages:
                raise ValueError(f"page {p} outside pool of {self.num_pages}")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
        self._free.extend(reversed(pages))


def write_pages(
    cache_layer: jnp.ndarray,  # [P, page_size, Hk, D]
    new: jnp.ndarray,  # [B, T, Hk, D]
    block_tables: jnp.ndarray,  # [B, max_pages] int32 (sentinel = P)
    start: jnp.ndarray,  # [B] int32 first logical slot per row
    *,
    write_mask: jnp.ndarray | None = None,  # [B] bool rows that may write
) -> jnp.ndarray:
    """Write T contiguous tokens per row into the page pool.

    Row b's token t lands at logical slot start[b] + t, i.e. page
    block_tables[b, slot // page_size] offset slot % page_size. Rows
    with write_mask False — and any slot routed through the sentinel —
    scatter out of bounds and are dropped (the masked-decode idiom:
    finished/empty slots cost no branch).
    """
    P, ps, Hk, D = cache_layer.shape
    B, T, _, _ = new.shape
    slots = start[:, None].astype(jnp.int32) + jnp.arange(T, dtype=jnp.int32)
    page = jnp.take_along_axis(block_tables, slots // ps, axis=1)  # [B, T]
    flat = page * ps + slots % ps  # sentinel page P -> index >= P*ps -> drop
    if write_mask is not None:
        flat = jnp.where(write_mask[:, None], flat, P * ps)
    pool = cache_layer.reshape(P * ps, Hk, D)
    pool = pool.at[flat.reshape(-1)].set(
        new.reshape(B * T, Hk, D).astype(pool.dtype), mode="drop"
    )
    return pool.reshape(P, ps, Hk, D)


def gather_pages(
    cache_layer: jnp.ndarray,  # [P, page_size, Hk, D]
    block_tables: jnp.ndarray,  # [B, max_pages]
) -> jnp.ndarray:
    """Materialize each row's logical KV stream: [B, max_pages*ps, Hk, D].

    Sentinel entries clip to the last real page; whatever they read is
    past every row's valid length and masked out of attention. This is
    the portable reference path — the Pallas kernel replaces it with
    in-place page reads on TPU.
    """
    B, maxp = block_tables.shape
    P, ps, Hk, D = cache_layer.shape
    out = cache_layer[block_tables]  # OOB gather clips
    return out.reshape(B, maxp * ps, Hk, D)


def ragged_decode_attention(
    q: jnp.ndarray,  # [B, 1, Hq, D] (or [B, Hq, D])
    k_pages: jnp.ndarray,  # [P, page_size, Hk, D]
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_pages]
    kv_lengths: jnp.ndarray,  # [B] valid kv count INCLUDING the current token
    *,
    scale: float | None = None,
) -> jnp.ndarray:
    """Pure-JAX reference for single-token paged decode attention.

    Each query attends to its own ragged KV prefix, addressed through
    its block table. Returns [B, 1, Hq, D] (or [B, Hq, D], matching q).
    """
    squeezed = q.ndim == 3
    if squeezed:
        q = q[:, None]
    B = q.shape[0]
    K = block_tables.shape[1] * k_pages.shape[1]
    k = gather_pages(k_pages, block_tables)
    v = gather_pages(v_pages, block_tables)
    kv_mask = (
        jnp.arange(K, dtype=jnp.int32)[None, :] < kv_lengths[:, None]
    ).astype(jnp.int32)
    out = attention(
        q, k, v,
        causal=True,
        q_positions=(kv_lengths - 1)[:, None].astype(jnp.int32),
        kv_positions=None,  # arange over logical slots == absolute positions
        kv_mask=kv_mask,
        scale=scale,
    )
    return out[:, 0] if squeezed else out
