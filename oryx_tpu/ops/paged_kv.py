"""Paged KV cache: fixed-size pages, block tables, ragged decode attention.

The serving-side answer to "every (batch, seq) bucket owns a dense
[B, S, Hk, D] cache": K/V live in a single pool of fixed-size pages
([num_pages, page_size, Hk, D] per layer) and each sequence owns an
ordered list of page indices (its *block table*). Logical slot `s` of a
sequence lives at page `block_table[s // page_size]`, offset
`s % page_size`. Sequences of wildly different lengths then share one
pool — the HBM cost of a batch is the sum of its real lengths (rounded
up to pages), not num_slots × max_len — and a finished sequence's pages
return to the free list for the next admission (continuous batching,
arXiv 2604.15464 / 2605.25645).

Three pieces live here:
  * `PageAllocator` — the host-side free list. Pure Python; the device
    never sees it. Page 0..num_pages-1 are real; `allocator.sentinel`
    (== num_pages) marks unallocated block-table entries. Writes routed
    to the sentinel fall off the end of the pool and are DROPPED by
    XLA's out-of-bounds scatter rule; gathers CLIP to the last page and
    the garbage is masked out of attention. Both behaviors are load-
    bearing: masked rows need no branch on device.
  * `write_pages` / `gather_pages` — the device-side page I/O, plain
    scatter/gather in slot order. Shapes are static; the block table is
    a traced [B, max_pages] int32 operand, so growing a sequence never
    recompiles.
  * `ragged_decode_attention` — the pure-JAX reference decode path:
    gather each row's pages into a contiguous [B, K, Hk, D] view and
    run the stock fp32-softmax attention. Bit-identical to the dense
    cache path when the padded KV width matches (masked columns are
    exactly 0 probability either way). The Pallas twin
    (`ops/pallas/paged_attention.py`) reads pages in place through the
    block table instead of gathering.
  * `write_pages_packed` / `ragged_paged_attention` — the PACKED
    (ragged) twins: one query buffer of R rows drawn from many
    sequences with MIXED query lengths (decode rows contribute one
    token, a chunked-prefill suffix contributes many), addressed per
    row by (segment, position) instead of per batch row by (start, T).
    This is what lets the serving engine run prefill suffixes and
    decode steps for every live slot in ONE dispatch
    (models/generate.paged_ragged_step; arXiv 2604.15464). The
    reference here is the CPU bit-parity anchor; the Pallas twin walks
    the block tables in place.
  * `spec_lane_metadata` — the SPECULATIVE extension of the same
    packing: each live slot contributes 1+k verify lanes (its fed
    token plus k drafted continuations at consecutive positions).
    Draft lanes need NO new kernel — a draft at position len+j is just
    one more (segment, position) row, causally masked at its own
    position, attending to the earlier lanes' K/V written in the same
    forward exactly as a chunked-prefill suffix already does
    (models/generate.paged_spec_step).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.ops.attention import attention
from oryx_tpu.utils import faults
from oryx_tpu.utils import quant as quant_lib


class OutOfPagesError(RuntimeError):
    """The free list cannot satisfy an allocation (caller should evict
    or defer admission — this is a scheduling signal, not a crash)."""


class PageAllocator:
    """Host-side free-list allocator over `num_pages` fixed-size pages,
    with per-page REFERENCE COUNTS so pages can be shared.

    LIFO recycling: freshly freed pages are handed out first, which
    keeps the hot working set of pages small and stable (good for any
    cache layer under the pool). Allocation is all-or-nothing so a
    failed admission never leaks a partial block table.

    Sharing (the prefix-cache contract, serve/prefix_cache.py): `alloc`
    hands out pages at refcount 1; `share` adds a holder; `free` /
    `release` drops one, and the page returns to the free list only at
    refcount 0. A shared page is IMMUTABLE by convention — a writer
    that owns only one of several references must copy-on-write first
    (`copy_pages` below); `refcount(p) > 1` is the "must COW" test.
    Freeing an unallocated page, or more references than a page holds,
    raises immediately with the page id (leak/double-free guard).

    Ownership observatory (docs/OBSERVABILITY.md "Memory & device
    time"): every reference carries an OWNER TAG stamped by the caller
    at the transition (`alloc`/`share`/`free` take `owner=`; the
    scheduler stamps `req:<request-id>`, the prefix cache `cache`), and
    every page records when its current tenancy began (`_born`, set at
    refcount 0→1) and when a reference last changed (`_touched`).
    `snapshot()` turns that into the live ownership map `/debug/pages`
    serves; an attached `observer` (utils/pagemap.PoolObservatory) is
    told the lifetime + idle time of every page returning to the free
    list, feeding the oryx_page_{lifetime,idle}_seconds histograms.
    Owner tags are accounting labels only — they never change what the
    allocator does, and an untagged transition stamps "?".
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 1 or page_size < 1:
            raise ValueError(f"need >= 1 page/slot, got {num_pages=} {page_size=}")
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        self._refs: list[int] = [0] * num_pages
        # Ownership map state (one tag per live reference, in grant
        # order) + tenancy clocks, all monotonic-clock based.
        self._owners: list[list[str]] = [[] for _ in range(num_pages)]
        self._born: list[float] = [0.0] * num_pages
        self._touched: list[float] = [0.0] * num_pages
        # Low-water mark of the free list since construction — the
        # peak-occupancy watermark the loadgen memory block reads.
        self.min_free: int = num_pages
        # utils/pagemap.PoolObservatory (or any object with a
        # page_freed(lifetime_s, idle_s) method); None = no telemetry.
        self.observer = None

    @property
    def sentinel(self) -> int:
        """Block-table filler for unallocated entries: one past the pool
        (writes drop, gathers clip; see module docstring)."""
        return self.num_pages

    @property
    def num_free(self) -> int:
        return len(self._free)

    def pages_for(self, num_tokens: int) -> int:
        """Pages needed to hold `num_tokens` KV slots."""
        return max(0, -(-num_tokens // self.page_size))

    def refcount(self, page: int) -> int:
        """Current holder count of `page` (0 = free)."""
        if not 0 <= page < self.num_pages:
            raise ValueError(f"page {page} outside pool of {self.num_pages}")
        return self._refs[page]

    def alloc(self, n: int, *, owner: str | None = None) -> list[int]:
        if n > 0:
            # Chaos site: simulated pool exhaustion. Every caller must
            # treat OutOfPagesError as a scheduling signal (defer /
            # evict / COW-fallback), never a crash — the chaos suite
            # proves refcounts stay exact through it.
            faults.fault_point(
                "page_alloc_oom",
                exc=lambda: OutOfPagesError(
                    f"injected pool exhaustion (asked {n} pages)"
                ),
            )
        if n > len(self._free):
            raise OutOfPagesError(
                f"need {n} pages, {len(self._free)} free of {self.num_pages}"
            )
        if n <= 0:
            return []
        out = self._free[-n:][::-1]
        del self._free[-n:]
        now = time.monotonic()
        tag = owner or "?"
        for p in out:
            self._refs[p] = 1
            self._owners[p] = [tag]
            self._born[p] = self._touched[p] = now
        self.min_free = min(self.min_free, len(self._free))
        return out

    def share(self, pages: list[int], *, owner: str | None = None) -> None:
        """Add one reference per page. All-or-nothing: sharing a FREE
        page is a bug (its contents are up for grabs) and raises with
        the page id before anything is mutated."""
        for p in pages:
            if not 0 <= p < self.num_pages:
                raise ValueError(f"page {p} outside pool of {self.num_pages}")
            if self._refs[p] <= 0:
                raise ValueError(f"share of unallocated page {p}")
        now = time.monotonic()
        tag = owner or "?"
        for p in pages:
            self._refs[p] += 1
            self._owners[p].append(tag)
            self._touched[p] = now

    def free(self, pages: list[int], *, owner: str | None = None) -> None:
        """Drop one reference per page; pages reaching refcount 0 return
        to the free list (in `pages` order, LIFO-recycled). Raises with
        the offending page id — before mutating anything — on a double
        free (refcount already 0) or when one call drops more references
        to a page than it holds. `owner` removes that holder's tag from
        the ownership map (falling back to the most recent tag when the
        caller's stamp is absent — accounting only, never a guard)."""
        from collections import Counter

        drops = Counter(pages)
        for p, n in drops.items():
            if not 0 <= p < self.num_pages:
                raise ValueError(f"page {p} outside pool of {self.num_pages}")
            if self._refs[p] <= 0:
                raise ValueError(f"double free of page {p}")
            if n > self._refs[p]:
                raise ValueError(
                    f"freeing {n} references to page {p}, which holds "
                    f"only {self._refs[p]}"
                )
        now = time.monotonic()
        released = []
        for p in pages:
            self._refs[p] -= 1
            tags = self._owners[p]
            if owner is not None and owner in tags:
                tags.remove(owner)
            elif tags:
                tags.pop()
            if self._refs[p] == 0:
                released.append(p)
                if self.observer is not None:
                    # Free-time telemetry: how long the page was
                    # resident, and how long since its last reference
                    # transition (the idle tail nobody was using it).
                    self.observer.page_freed(
                        now - self._born[p], now - self._touched[p]
                    )
            self._touched[p] = now
        self._free.extend(reversed(released))

    # `release` is `free` under its sharing-aware name: both drop one
    # reference; the page only leaves the pool's live set at refcount 0.
    release = free

    def check_invariant(self, holders=None) -> None:
        """Pool accounting invariant; raises RuntimeError on violation.

        Always checked: free list and refcounts partition the pool
        (num_free + pages-with-refcount > 0 == num_pages, no page in
        both sets, no negative refcount). With `holders` — an iterable
        of page lists, one per live holder (slots' block tables, the
        prefix cache's entries) — additionally checks that every page's
        refcount equals its holder count, i.e. nothing leaked and
        nothing is double-held. Callable from tests; the scheduler
        asserts it at `_reset_pool`."""
        from collections import Counter

        allocated = {p for p, r in enumerate(self._refs) if r > 0}
        if any(r < 0 for r in self._refs):
            raise RuntimeError(f"negative refcount: {self._refs}")
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            raise RuntimeError(f"duplicate pages in free list: {self._free}")
        if free_set & allocated:
            raise RuntimeError(
                f"pages both free and allocated: {sorted(free_set & allocated)}"
            )
        if len(self._free) + len(allocated) != self.num_pages:
            raise RuntimeError(
                f"pool accounting broken: {len(self._free)} free + "
                f"{len(allocated)} allocated != {self.num_pages} pages"
            )
        if holders is None:
            return
        held = Counter()
        for pages in holders:
            held.update(int(p) for p in pages)
        for p in range(self.num_pages):
            if held.get(p, 0) != self._refs[p]:
                raise RuntimeError(
                    f"page {p}: refcount {self._refs[p]} but "
                    f"{held.get(p, 0)} holders"
                )

    @staticmethod
    def classify(refcount: int, owners: list[str]) -> str:
        """Observatory state of one page — the four states partition
        the pool (free + slot + cache + shared == num_pages): free
        (refcount 0), shared (>= 2 holders, whoever they are), cache
        (exactly the prefix cache's own reference) or slot (exactly one
        request-held reference)."""
        if refcount <= 0:
            return "free"
        if refcount >= 2:
            return "shared"
        return "cache" if owners == ["cache"] else "slot"

    def snapshot(self) -> dict:
        """The live ownership map: one record per page (state, refcount,
        owner tags, tenancy age, idle time) plus the raw pool geometry.
        Pure read — derived summaries (state counts, fragmentation,
        age quantiles) live in utils/pagemap.summarize so the router
        and the bench harness share one implementation.

        Thread contract: the map is engine-owned state; a read from a
        debug-endpoint thread is best-effort (each page record is
        internally consistent, the map is exact on a quiesced engine —
        the reconciliation gate scrapes quiesced by design)."""
        now = time.monotonic()
        pages = []
        for p in range(self.num_pages):
            r = self._refs[p]
            owners = list(self._owners[p])
            pages.append({
                "page": p,
                "state": self.classify(r, owners),
                "refcount": r,
                "owners": owners,
                "age_s": round(now - self._born[p], 6) if r > 0 else None,
                "idle_s": (
                    round(now - self._touched[p], 6) if r > 0 else None
                ),
            })
        return {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "num_free": len(self._free),
            "min_free": self.min_free,
            "free_pages": sorted(self._free),
            "pages": pages,
        }


@jax.tree_util.register_pytree_node_class
class QuantPages:
    """A quantized paged KV pool (one plane — K or V — of the pool
    pytree): storage-dtype codes plus a PER-PAGE SCALE BLOCK.

      q:     [..., P, page_size, Hk, D] int8 (or fp8-e4m3) codes
      scale: [..., P, page_size] fp32 — one scale per token row,
             stored page-major so every page carries its own scale
             block: COW (`copy_pages`), host spill (`fetch_page`) and
             reload (`upload_page`) move q-bytes and scales together,
             verbatim, with zero special-casing.

    Scale granularity (docs/DESIGN.md "KV quantization & cache
    tiering"): the scale is per token ROW within the page block, not
    one scalar per page. A single per-page scalar would have to grow
    as later tokens land in the page (pages fill incrementally across
    prefill chunks and decode steps), forcing an in-place requantize
    of earlier rows — making the stored bytes depend on write
    GROUPING, which would break the cold-vs-cached, eviction-replay
    and spill/reload byte-parity contracts the serving engine leans
    on. Per-row scales make the encoding a pure function of the
    token's own value; the storage overhead is 4 bytes per Hk*D-byte
    row (<1%), and the layout is what rides the block-table stream
    into the Pallas kernel (scales are fetched per page tile alongside
    the code tile, addressed by the same scalar-prefetched table).

    Registered as a pytree node, so everything downstream — the layer
    scan in qwen2.forward, jit donation, `copy_pages`' tree_map, host
    fetch/upload — treats the pool transparently; `dequant_dtype` (the
    logical dtype consumers see, static aux data) is what the ops
    dequantize into."""

    def __init__(self, q, scale, dequant_dtype=jnp.float32):
        self.q = q
        self.scale = scale
        self.dequant_dtype = jnp.dtype(dequant_dtype)

    def tree_flatten(self):
        return (self.q, self.scale), str(self.dequant_dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    # Shape/dtype impersonation: callers read pool geometry off the
    # leaf (`kv_pages["k"].shape[2]` is the page size everywhere).
    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):  # the LOGICAL dtype consumers see after dequant
        return self.dequant_dtype

    @property
    def storage_dtype(self):
        return self.q.dtype

    def __repr__(self):
        return (
            f"QuantPages(q={self.q.shape}:{self.q.dtype}, "
            f"scale={self.scale.shape}, dequant={self.dequant_dtype})"
        )


def init_quant_pages(
    num_layers: int, num_pages: int, page_size: int, num_kv_heads: int,
    head_dim: int, *, fmt: str = "int8", dequant_dtype=jnp.float32,
) -> QuantPages:
    """A zeroed quantized pool plane (the int8 counterpart of one
    jnp.zeros leaf of qwen2.init_paged_kv_cache)."""
    storage, _ = quant_lib.kv_storage_dtype(fmt)
    return QuantPages(
        jnp.zeros(
            (num_layers, num_pages, page_size, num_kv_heads, head_dim),
            storage,
        ),
        jnp.zeros((num_layers, num_pages, page_size), jnp.float32),
        dequant_dtype=dequant_dtype,
    )


def kv_pool_dtype(kv_pages) -> str:
    """The pool's wire format: "int8" / "fp8_e4m3" for a quantized
    pool, else the dense leaf dtype's name (e.g. "float32")."""
    leaf = kv_pages["k"] if isinstance(kv_pages, dict) else kv_pages
    if isinstance(leaf, QuantPages):
        try:
            return _quant_fmt(leaf)
        except ValueError:
            return str(leaf.storage_dtype)
    return str(leaf.dtype)


@partial(jax.jit, donate_argnums=0)
def copy_pages(kv_pages, src: jnp.ndarray, dst: jnp.ndarray):
    """Copy page `src` onto page `dst` across every layer of a paged KV
    pytree ([L, P, page_size, Hk, D] leaves) — the device half of
    copy-on-write: a writer holding one of several references to a page
    allocates a fresh page, copies the shared contents here, and swaps
    the fresh page into its block table before writing. Donates the
    pool, so the copy is in place; src/dst are traced scalars (one
    compiled program per pool shape). On a QUANTIZED pool the tree_map
    descends into each plane's (codes, scales) children — both carry
    the page axis at position 1 — so COW moves the raw quantized bytes
    AND the page's scale block verbatim: share/splice/eviction-replay/
    spec-rollback semantics are untouched by the storage format."""
    return jax.tree_util.tree_map(
        lambda a: a.at[:, dst].set(a[:, src]), kv_pages
    )


def fetch_page(kv_pages, page: int):
    """Host-side byte-verbatim copy of ONE page across the whole pool
    pytree (every layer, K and V — and, on a quantized pool, the
    page's scale blocks): the spill half of the host-RAM prefix-cache
    tier. Returns a pytree of numpy arrays shaped [L, page_size, ...];
    `upload_page` is its exact inverse, so spill -> reload is lossless
    by construction (same dtype, same bytes, no re-encode)."""
    return jax.tree_util.tree_map(
        lambda a: np.asarray(a[:, page]), kv_pages
    )


def host_blob_bytes(blob) -> int:
    """Total host bytes of a `fetch_page` blob (the --host-cache-bytes
    accounting unit)."""
    return int(sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(blob)
    ))


@partial(jax.jit, donate_argnums=0)
def upload_page(kv_pages, dst: jnp.ndarray, blob):
    """Write a `fetch_page` host blob back into page `dst` of the pool
    (donated, in place; dst is a traced scalar — one compiled program
    per pool shape). The astype is a no-op by contract (same dtype
    both ways): the reload is byte-verbatim."""
    return jax.tree_util.tree_map(
        lambda a, b: a.at[:, dst].set(b.astype(a.dtype)), kv_pages, blob
    )


def write_pages(
    cache_layer: jnp.ndarray,  # [P, page_size, Hk, D]
    new: jnp.ndarray,  # [B, T, Hk, D]
    block_tables: jnp.ndarray,  # [B, max_pages] int32 (sentinel = P)
    start: jnp.ndarray,  # [B] int32 first logical slot per row
    *,
    write_mask: jnp.ndarray | None = None,  # [B] bool rows that may write
) -> jnp.ndarray:
    """Write T contiguous tokens per row into the page pool.

    Row b's token t lands at logical slot start[b] + t, i.e. page
    block_tables[b, slot // page_size] offset slot % page_size. Rows
    with write_mask False — and any slot routed through the sentinel —
    scatter out of bounds and are dropped (the masked-decode idiom:
    finished/empty slots cost no branch).

    Quantized pool (`cache_layer` a QuantPages plane): the incoming fp
    rows are quantized ON WRITE — per-token-row symmetric scales
    (utils/quant.quantize_kv_rows) — and the codes + scales scatter
    through the SAME flat slot indices, so masked/sentinel rows drop
    both identically and the scale blocks always describe exactly the
    codes that landed.
    """
    if isinstance(cache_layer, QuantPages):
        return _write_pages_quant(
            cache_layer, new, block_tables, start, write_mask=write_mask
        )
    P, ps, Hk, D = cache_layer.shape
    B, T, _, _ = new.shape
    slots = start[:, None].astype(jnp.int32) + jnp.arange(T, dtype=jnp.int32)
    page = jnp.take_along_axis(block_tables, slots // ps, axis=1)  # [B, T]
    flat = page * ps + slots % ps  # sentinel page P -> index >= P*ps -> drop
    if write_mask is not None:
        flat = jnp.where(write_mask[:, None], flat, P * ps)
    pool = cache_layer.reshape(P * ps, Hk, D)
    pool = pool.at[flat.reshape(-1)].set(
        new.reshape(B * T, Hk, D).astype(pool.dtype), mode="drop"
    )
    return pool.reshape(P, ps, Hk, D)


def _quant_fmt(pages: QuantPages) -> str:
    """The quant format name of a QuantPages plane (for the shared
    quantize helpers)."""
    for name, (dt, _) in quant_lib.KV_STORAGE_DTYPES.items():
        if pages.storage_dtype == jnp.dtype(dt):
            return name
    raise ValueError(
        f"QuantPages carries unknown storage dtype {pages.storage_dtype}"
    )


def _scatter_quant(
    pages: QuantPages, flat: jnp.ndarray, rows: jnp.ndarray
) -> QuantPages:
    """Scatter packed fp rows [N, Hk, D] into a quantized pool plane at
    flat slot indices [N] (one shared index stream for codes AND
    scales; OOB -> dropped for both)."""
    P, ps, Hk, D = pages.q.shape
    codes, scale = quant_lib.quantize_kv_rows(rows, _quant_fmt(pages))
    qpool = pages.q.reshape(P * ps, Hk, D)
    qpool = qpool.at[flat].set(codes, mode="drop")
    spool = pages.scale.reshape(P * ps)
    spool = spool.at[flat].set(scale, mode="drop")
    return QuantPages(
        qpool.reshape(P, ps, Hk, D), spool.reshape(P, ps),
        dequant_dtype=pages.dequant_dtype,
    )


def _write_pages_quant(
    pages: QuantPages,
    new: jnp.ndarray,  # [B, T, Hk, D]
    block_tables: jnp.ndarray,
    start: jnp.ndarray,
    *,
    write_mask: jnp.ndarray | None = None,
) -> QuantPages:
    """Quantize-on-write twin of the dense `write_pages` body: same
    slot routing, same drop semantics, codes + per-row scales written
    by one shared index stream."""
    P, ps, Hk, D = pages.q.shape
    B, T, _, _ = new.shape
    slots = start[:, None].astype(jnp.int32) + jnp.arange(T, dtype=jnp.int32)
    page = jnp.take_along_axis(block_tables, slots // ps, axis=1)  # [B, T]
    flat = page * ps + slots % ps
    if write_mask is not None:
        flat = jnp.where(write_mask[:, None], flat, P * ps)
    return _scatter_quant(
        pages, flat.reshape(-1), new.reshape(B * T, Hk, D)
    )


def gather_pages(
    cache_layer: jnp.ndarray,  # [P, page_size, Hk, D]
    block_tables: jnp.ndarray,  # [B, max_pages]
) -> jnp.ndarray:
    """Materialize each row's logical KV stream: [B, max_pages*ps, Hk, D].

    Sentinel entries clip to the last real page; whatever they read is
    past every row's valid length and masked out of attention. This is
    the portable reference path — the Pallas kernel replaces it with
    in-place page reads on TPU.

    Quantized pool: the gathered codes are DEQUANTIZED here — each
    page's scale block rides the same block-table gather — so every
    consumer downstream (the stock attention reference, the ragged
    reference) sees a plain fp stream. The Pallas kernels instead
    dequantize inside the page walk (the tile's scale block is fetched
    alongside its code tile), multiplying out identically.
    """
    B, maxp = block_tables.shape
    if isinstance(cache_layer, QuantPages):
        P, ps, Hk, D = cache_layer.q.shape
        dt = cache_layer.dequant_dtype
        codes = cache_layer.q[block_tables]  # OOB gather clips
        scale = cache_layer.scale[block_tables]  # [B, maxp, ps]
        out = codes.astype(dt) * scale[..., None, None].astype(dt)
        return out.reshape(B, maxp * ps, Hk, D)
    P, ps, Hk, D = cache_layer.shape
    out = cache_layer[block_tables]  # OOB gather clips
    return out.reshape(B, maxp * ps, Hk, D)


def ragged_decode_attention(
    q: jnp.ndarray,  # [B, 1, Hq, D] (or [B, Hq, D])
    k_pages: jnp.ndarray,  # [P, page_size, Hk, D]
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_pages]
    kv_lengths: jnp.ndarray,  # [B] valid kv count INCLUDING the current token
    *,
    scale: float | None = None,
) -> jnp.ndarray:
    """Pure-JAX reference for single-token paged decode attention.

    Each query attends to its own ragged KV prefix, addressed through
    its block table. Returns [B, 1, Hq, D] (or [B, Hq, D], matching q).
    """
    squeezed = q.ndim == 3
    if squeezed:
        q = q[:, None]
    B = q.shape[0]
    K = block_tables.shape[1] * k_pages.shape[1]
    k = gather_pages(k_pages, block_tables)
    v = gather_pages(v_pages, block_tables)
    kv_mask = (
        jnp.arange(K, dtype=jnp.int32)[None, :] < kv_lengths[:, None]
    ).astype(jnp.int32)
    out = attention(
        q, k, v,
        causal=True,
        q_positions=(kv_lengths - 1)[:, None].astype(jnp.int32),
        kv_positions=None,  # arange over logical slots == absolute positions
        kv_mask=kv_mask,
        scale=scale,
    )
    return out[:, 0] if squeezed else out


# ---------------------------------------------------------------------------
# Packed ragged mode: mixed query lengths, one buffer, one dispatch
# ---------------------------------------------------------------------------


def write_pages_packed(
    cache_layer: jnp.ndarray,  # [P, page_size, Hk, D]
    new: jnp.ndarray,  # [R, Hk, D] packed new K or V rows
    block_tables: jnp.ndarray,  # [S, max_pages] int32 (sentinel = P)
    q_segments: jnp.ndarray,  # [R] int32 owning slot per packed row
    q_positions: jnp.ndarray,  # [R] int32 logical slot index per row
    *,
    write_mask: jnp.ndarray | None = None,  # [R] bool rows that may write
) -> jnp.ndarray:
    """Write R packed tokens into the page pool, each routed through its
    OWN sequence's block table: row r lands at logical slot
    q_positions[r] of sequence q_segments[r]. The packed twin of
    `write_pages` (whose rows are per-sequence and contiguous): here a
    decode token and a prefill-chunk token of two different sequences
    sit side by side in one buffer and one scatter places both. Rows
    with write_mask False — and any slot routed through the sentinel —
    drop, exactly as in `write_pages` (quantized pools quantize on
    write with per-row scales, same routing — see `write_pages`)."""
    P, ps, Hk, D = cache_layer.q.shape if isinstance(
        cache_layer, QuantPages
    ) else cache_layer.shape
    S, maxp = block_tables.shape
    seg = jnp.clip(q_segments.astype(jnp.int32), 0, S - 1)
    pos = q_positions.astype(jnp.int32)
    # Page index clamps into the row's own table (matching the
    # take_along_axis OOB clamp of the per-sequence writer); the
    # sentinel page then routes the write off the pool end -> dropped.
    page = block_tables[seg, jnp.clip(pos // ps, 0, maxp - 1)]  # [R]
    flat = page * ps + pos % ps
    if write_mask is not None:
        flat = jnp.where(write_mask, flat, P * ps)
    if isinstance(cache_layer, QuantPages):
        return _scatter_quant(cache_layer, flat, new)
    pool = cache_layer.reshape(P * ps, Hk, D)
    pool = pool.at[flat].set(new.astype(pool.dtype), mode="drop")
    return pool.reshape(P, ps, Hk, D)


def spec_lane_metadata(
    lengths: jnp.ndarray,  # [S] int32 confirmed kv tokens per slot
    k: int,  # drafts per slot (static)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(q_segments, q_positions) for S slots x (1+k) speculative verify
    lanes, slot-major: lane j of slot s sits at logical position
    lengths[s] + j — lane 0 is the slot's fed decode token, lanes 1..k
    its drafted continuations. The packed writer and the ragged
    attention kernel consume this unchanged (a draft lane IS a
    chunked-prefill-suffix lane whose token happens to be proposed, not
    given): per-row causal masking at own position makes lane j attend
    to lanes < j of its own slot — freshly written this forward — and
    to nothing of any other slot's lanes. Returns ([S*(1+k)],
    [S*(1+k)]) int32."""
    S = lengths.shape[0]
    seg = jnp.repeat(jnp.arange(S, dtype=jnp.int32), k + 1)
    pos = (
        lengths[:, None].astype(jnp.int32)
        + jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    ).reshape(-1)
    return seg, pos


def stop_window_hit(
    recent: jnp.ndarray,  # [S, stop_L] rolling recent-token window
    stop_sequences: jnp.ndarray | None,  # [Sq, stop_L] (-1 = wildcard)
) -> jnp.ndarray:
    """In-scan stop mask over the per-slot recent-token windows: row s
    is True when its window's tail matches ANY template stop sequence
    (right-aligned; -1 template slots are wildcards, which is also how
    shorter sequences left-pad). This is the ONE device-side stop
    predicate — the per-step scan (`paged_ragged_step`) and the fused
    K-step megastep (`paged_fused_steps`) both call it, so multi-step
    fusion can never drift from the single-step stop semantics (the
    window initializes at -2, matching nothing, and carries across
    dispatches AND across the fused scan's iterations identically).
    Returns [S] bool."""
    if stop_sequences is None:
        return jnp.zeros((recent.shape[0],), bool)
    m = (stop_sequences[None] == -1) | (
        recent[:, None, :] == stop_sequences[None]
    )
    return jnp.any(jnp.all(m, axis=-1), axis=-1)


def ragged_paged_attention(
    q: jnp.ndarray,  # [R, Hq, D] packed query rows
    k_pages: jnp.ndarray,  # [P, page_size, Hk, D]
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [S, max_pages] int32
    q_segments: jnp.ndarray,  # [R] int32 owning slot per packed row
    q_positions: jnp.ndarray,  # [R] int32 absolute position per row
    *,
    scale: float | None = None,
) -> jnp.ndarray:
    """Pure-JAX reference for packed RAGGED paged attention — the one
    semantics both engine paths must agree on bit-for-bit.

    Each packed row r attends to the KV prefix of its own sequence
    q_segments[r], addressed through that sequence's block table,
    causally masked at its own position: logical slot j is visible iff
    j <= q_positions[r]. A decode step (one row at position len-1) and
    a chunked-prefill suffix (one row per suffix token, consecutive
    positions) are THE SAME case under this mask — which is exactly
    what makes one dispatch serve a mixed batch. Returns [R, Hq, D].

    Bit-parity contract (tests/test_ragged_attention.py): for a decode
    row this reproduces `ragged_decode_attention` exactly (the causal
    mask at position len-1 equals its kv_lengths mask), and for a
    prefill row it reproduces the row's logits from the per-sequence
    chunked prefill (same masked set, same fp32 reductions per row).
    """
    from oryx_tpu.parallel.sharding import constrain

    R = q.shape[0]
    S, maxp = block_tables.shape
    seg = jnp.clip(q_segments.astype(jnp.int32), 0, S - 1)
    k_all = gather_pages(k_pages, block_tables)  # [S, K, Hk, D]
    v_all = gather_pages(v_pages, block_tables)
    # On a tp mesh the pool is heads-sharded (sharding.paged_kv_spec);
    # pin the gathered per-row view to the same head split so GSPMD
    # never reshards the packed buffer's KV (no-op off-mesh).
    k_r = constrain(k_all[seg], None, None, "tp", None)  # [R, K, Hk, D]
    v_r = constrain(v_all[seg], None, None, "tp", None)
    out = attention(
        q[:, None], k_r, v_r,
        causal=True,
        q_positions=q_positions[:, None].astype(jnp.int32),
        kv_positions=None,  # arange over logical slots == absolute positions
        scale=scale,
    )
    return out[:, 0]
