"""Host-side packing of variable-resolution visual inputs into static shapes.

This is the TPU-native answer to the reference's `flash_attn_varlen_func` +
`cu_seqlens` pipeline (SURVEY.md §2a, §7 hard part 1): where CUDA varlen
kernels consume ragged sequences directly, XLA wants static shapes. We pack
all images/frames of a batch into ONE padded buffer with:

  * segment ids   — per-patch image membership; attention masks on equality,
                    so each image attends only within itself (ViT blocks).
  * region ids    — per-patch compressor-region membership; the Dynamic
                    Compressor's region cross-attention masks on these.
  * pos coords    — continuous source-space coordinates into the learned
                    position-embedding table (bilinear, align_corners=False
                    semantics), so arbitrary (h, w) grids reuse one table.

Buffer lengths are rounded up to a small set of buckets so XLA compiles a
bounded number of programs. All code here is numpy on the host; device code
(models/oryx_vit.py, models/compressor.py) sees only fixed-shape arrays.

Convention: id 0 is padding everywhere; real images/regions are numbered
from 1.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

# Default packed-length buckets (patches). Powers-of-two ladder keeps the
# number of distinct compiled programs small while bounding padding waste
# at <2x (typically ~25%).
DEFAULT_BUCKETS = (256, 512, 1024, 2048, 4096, 8192, 16384, 32768, 65536)


def round_up_bucket(n: int, buckets: tuple[int, ...] = DEFAULT_BUCKETS) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} patches exceed the largest bucket {buckets[-1]}")


def patchify(image: np.ndarray, patch_size: int) -> tuple[np.ndarray, tuple[int, int]]:
    """[H, W, C] (H, W multiples of patch_size) → ([h*w, p*p*C], (h, w)).

    Patch-internal pixel order is (py, px, c), matching the conv-kernel
    flattening in import_hf.import_siglip.
    """
    H, W, C = image.shape
    p = patch_size
    assert H % p == 0 and W % p == 0, f"image {H}x{W} not multiple of {p}"
    h, w = H // p, W // p
    x = image.reshape(h, p, w, p, C).transpose(0, 2, 1, 3, 4)
    return np.ascontiguousarray(x.reshape(h * w, p * p * C)), (h, w)


def posemb_source_coords(h: int, w: int, base_grid: int) -> np.ndarray:
    """Continuous coords [h*w, 2] into the base_grid×base_grid posemb table.

    Uses torch `F.interpolate(..., mode="bilinear", align_corners=False)`
    source-coordinate semantics: src = (dst + 0.5) * (G / size) - 0.5, edge
    clamped by the device-side gather.
    """
    ys = (np.arange(h, dtype=np.float32) + 0.5) * (base_grid / h) - 0.5
    xs = (np.arange(w, dtype=np.float32) + 0.5) * (base_grid / w) - 0.5
    yy, xx = np.meshgrid(ys, xs, indexing="ij")
    return np.stack([yy.reshape(-1), xx.reshape(-1)], axis=-1)


@dataclasses.dataclass
class PackedVisual:
    """One batch of packed visual inputs (all numpy, host-side).

    Patch stream (length P, bucketed):
      patches      [P, patch_dim] float32 — raw patch pixels (0 on padding)
      segment_ids  [P] int32 — image membership (0 = pad)
      region_ids   [P] int32 — compressor region membership (0 = pad)
      pos_coords   [P, 2] float32 — posemb table coords
    Query stream (length Q, bucketed) — one query per compressor region:
      q_segment_ids [Q] int32 — image membership of each query (0 = pad)
      q_region_ids  [Q] int32 — region id of each query (0 = pad)
    Bookkeeping:
      grids        per-image patch grids (h, w)
      q_grids      per-image query grids (hq, wq)
      side_factors per-image compressor side factor (1, 2, or 4)
      num_patches  real (unpadded) patch count
      num_queries  real (unpadded) query count
    """

    patches: np.ndarray
    segment_ids: np.ndarray
    region_ids: np.ndarray
    pos_coords: np.ndarray
    q_segment_ids: np.ndarray
    q_region_ids: np.ndarray
    grids: list[tuple[int, int]]
    q_grids: list[tuple[int, int]]
    side_factors: list[int]
    num_patches: int
    num_queries: int


def _pack_metadata(
    grids: list[tuple[int, int]],
    side_factors: list[int],
    base_grid: int,
    patch_dim: int,
    buckets: tuple[int, ...],
) -> tuple[PackedVisual, list[int]]:
    """Build all bookkeeping arrays for given per-image patch grids, with a
    zeroed patches buffer. Returns (packed, per-image patch-row offsets) —
    the caller fills packed.patches[off : off + h*w] per image."""
    if not grids:
        # Text-only batch: a minimal all-padding buffer (segment/region id 0
        # everywhere) — the ViT/compressor run over it and every consumer
        # masks it out; splice never points at it (is_visual all False).
        P = buckets[0]
        return PackedVisual(
            patches=np.zeros((P, patch_dim), np.float32),
            segment_ids=np.zeros(P, np.int32),
            region_ids=np.zeros(P, np.int32),
            pos_coords=np.zeros((P, 2), np.float32),
            q_segment_ids=np.zeros(P, np.int32),
            q_region_ids=np.zeros(P, np.int32),
            grids=[], q_grids=[], side_factors=[],
            num_patches=0, num_queries=0,
        ), []

    seg_list, reg_list, coord_list = [], [], []
    qseg_list, qreg_list = [], []
    q_grids: list[tuple[int, int]] = []
    offsets: list[int] = []
    next_region = 1
    off = 0
    for i, ((h, w), s) in enumerate(zip(grids, side_factors), start=1):
        offsets.append(off)
        off += h * w
        seg_list.append(np.full(h * w, i, np.int32))
        coord_list.append(posemb_source_coords(h, w, base_grid))

        hq, wq = math.ceil(h / s), math.ceil(w / s)
        q_grids.append((hq, wq))
        rows = np.arange(h)[:, None] // s  # [h, 1]
        cols = np.arange(w)[None, :] // s  # [1, w]
        rid = next_region + rows * wq + cols  # [h, w]
        reg_list.append(rid.reshape(-1).astype(np.int32))
        qseg_list.append(np.full(hq * wq, i, np.int32))
        qreg_list.append(
            np.arange(next_region, next_region + hq * wq, dtype=np.int32)
        )
        next_region += hq * wq

    P_real = off
    P = round_up_bucket(P_real, buckets)

    def pad_to(arr, length, fill=0):
        out = np.full((length, *arr.shape[1:]), fill, arr.dtype)
        out[: arr.shape[0]] = arr
        return out

    q_seg = np.concatenate(qseg_list)
    Q_real = q_seg.shape[0]
    Q = round_up_bucket(Q_real, buckets)

    packed = PackedVisual(
        patches=np.zeros((P, patch_dim), np.float32),
        segment_ids=pad_to(np.concatenate(seg_list), P),
        region_ids=pad_to(np.concatenate(reg_list), P),
        pos_coords=pad_to(np.concatenate(coord_list), P),
        q_segment_ids=pad_to(q_seg, Q),
        q_region_ids=pad_to(np.concatenate(qreg_list), Q),
        grids=list(grids),
        q_grids=q_grids,
        side_factors=list(side_factors),
        num_patches=P_real,
        num_queries=Q_real,
    )
    return packed, offsets


def _broadcast_factors(side_factors: list[int] | int, n: int) -> list[int]:
    if isinstance(side_factors, int):
        return [side_factors] * n
    assert len(side_factors) == n
    return list(side_factors)


def pack_images(
    images: list[np.ndarray],
    *,
    patch_size: int,
    base_grid: int,
    side_factors: list[int] | int = 1,
    buckets: tuple[int, ...] = DEFAULT_BUCKETS,
) -> PackedVisual:
    """Pack preprocessed images (pixel arrays, dims multiples of patch_size)
    into one static-shape buffer.

    side_factors: compressor downsample factor per spatial side for each
    image (scalar broadcast). Area compression is the square: 1→1x, 2→4x,
    4→16x (constants.COMPRESSOR_RATIO).
    """
    side_factors = _broadcast_factors(side_factors, len(images))
    rows_grids = [patchify(img, patch_size) for img in images]
    grids = [g for _, g in rows_grids]
    patch_dim = (
        rows_grids[0][0].shape[1] if rows_grids else patch_size * patch_size * 3
    )
    packed, offsets = _pack_metadata(
        grids, side_factors, base_grid, patch_dim, buckets
    )
    for (rows, (h, w)), off in zip(rows_grids, offsets):
        packed.patches[off : off + h * w] = rows
    return packed


def pack_raw_images(
    images: list[np.ndarray],
    *,
    patch_size: int,
    base_grid: int,
    side_factors: list[int] | int = 1,
    max_patches: list[int] | int = 4096,
    buckets: tuple[int, ...] = DEFAULT_BUCKETS,
) -> PackedVisual:
    """Pack RAW images (uint8/float HWC, any resolution): fused
    resize+normalize+patchify straight into the packed buffer.

    Uses the native thread-pool kernels (native/loader.cpp via
    data/native_loader.py) when built — each image's patch rows are written
    by a C++ worker directly into its slice of the packed buffer — with a
    numpy fallback (data/mm_utils.preprocess_image + patchify) otherwise.
    """
    from oryx_tpu.data import mm_utils, native_loader

    n = len(images)
    side_factors = _broadcast_factors(side_factors, n)
    caps = max_patches if isinstance(max_patches, list) else [max_patches] * n
    assert len(caps) == n

    out_hws = [
        mm_utils.resize_to_patch_grid(img.shape[:2], patch_size, cap)
        for img, cap in zip(images, caps)
    ]
    grids = [(oh // patch_size, ow // patch_size) for oh, ow in out_hws]
    C = images[0].shape[2] if n else 3
    # All images must share a channel count: patch_dim (and every slice
    # width below) is sized from it, and the native kernel writes each
    # image's own C floats per pixel — a mismatch would corrupt the buffer.
    for i, img in enumerate(images):
        if img.shape[2] != C:
            raise ValueError(
                f"image {i} has {img.shape[2]} channels, expected {C}; "
                "convert inputs to RGB first"
            )
    packed, offsets = _pack_metadata(
        grids, side_factors, base_grid, patch_size * patch_size * C, buckets
    )
    slices = [
        packed.patches[off : off + h * w] for (h, w), off in zip(grids, offsets)
    ]
    if native_loader.is_available():
        native_loader.batch_preprocess(
            images, out_hws, patch_size,
            mm_utils.IMAGE_MEAN, mm_utils.IMAGE_STD, outs=slices,
        )
    else:
        for img, cap, dst in zip(images, caps, slices):
            pre = mm_utils.preprocess_image(img, patch_size, cap)
            dst[:] = patchify(pre, patch_size)[0]
    return packed
