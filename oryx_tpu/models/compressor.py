"""Dynamic Compressor — on-demand visual token compression.

Reference parity: the compressor + projector built by
`build_vision_projector()` (SURVEY.md §1 L1b, §2 "Dynamic Compressor";
reference mount empty — behavior reconstructed): downsample each image's
(h, w) feature grid by a per-modality side factor s ∈ {1, 2, 4} (area 1×/
4×/16×), where each downsampled token is produced by average pooling its
s×s source region and then cross-attending to that region's tokens, followed
by an MLP projector into the LLM embedding space.

TPU-first formulation: no per-image loops. The packed feature buffer
(ops/packing.py) carries `region_ids`; pooling is one `segment_sum` and the
region cross-attention is the generic segment-id-masked attention with
query segments = region ids. Everything is static-shape over the bucketed
patch/query buffers, so one compiled program serves any mix of image /
multi-image / video inputs.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from oryx_tpu.config import CompressorConfig, LLMConfig, VisionConfig
from oryx_tpu.ops.attention import attention
from oryx_tpu.ops.norms import layer_norm
from oryx_tpu.parallel.sharding import constrain

Params = dict[str, Any]


def init_params(
    cfg: CompressorConfig,
    vision_cfg: VisionConfig,
    llm_cfg: LLMConfig,
    key: jax.Array,
    dtype: jnp.dtype = jnp.float32,
) -> Params:
    Hv, Hl = vision_cfg.hidden_size, llm_cfg.hidden_size
    keys = iter(jax.random.split(key, 8))

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dtype)

    def proj(din, dout):
        return {
            "kernel": dense(next(keys), (din, dout)),
            "bias": jnp.zeros((dout,), dtype),
        }

    def ln(dim):
        return {"weight": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}

    return {
        "norm_q": ln(Hv),
        "norm_kv": ln(Hv),
        "q_proj": proj(Hv, Hv),
        "k_proj": proj(Hv, Hv),
        "v_proj": proj(Hv, Hv),
        "o_proj": proj(Hv, Hv),
        "projector": {"fc1": proj(Hv, Hl), "fc2": proj(Hl, Hl)},
    }


def _linear(x, p):
    return x @ p["kernel"].astype(x.dtype) + p["bias"].astype(x.dtype)


def forward(
    params: Params,
    cfg: CompressorConfig,
    vision_cfg: VisionConfig,
    features: jnp.ndarray,
    region_ids: jnp.ndarray,
    q_region_ids: jnp.ndarray,
    *,
    attn_impl: str = "xla",
    eps: float = 1e-6,
) -> jnp.ndarray:
    """Compress packed ViT features into packed LLM-space visual embeddings.

    features:     [P, Hv] packed ViT output (pad rows garbage, region id 0).
    region_ids:   [P] int32 — compressor region per patch (0 = pad).
    q_region_ids: [Q] int32 — region served by each query slot (0 = pad).

    Returns [Q, H_llm] visual embeddings; pad rows (q_region_ids == 0) are
    zeros. Queries are ordered image-major, row-major within each image's
    downsampled grid (the order splice.py interleaves into the text stream).
    """
    P, Hv = features.shape
    Q = q_region_ids.shape[0]
    feat32 = features.astype(jnp.float32)
    valid_p = (region_ids > 0).astype(jnp.float32)[:, None]

    # Region average pooling via one segment-sum (region 0 collects pads).
    num_segments = Q + 1
    sums = jax.ops.segment_sum(
        feat32 * valid_p, region_ids, num_segments=num_segments
    )
    counts = jax.ops.segment_sum(
        valid_p[:, 0], region_ids, num_segments=num_segments
    )
    pooled = sums[q_region_ids] / jnp.maximum(counts[q_region_ids], 1.0)[:, None]
    pooled = pooled.astype(features.dtype)  # [Q, Hv]
    # The query axis shards over the data width exactly like the packing
    # axis upstream (oryx_vit pins [1, P, H], "sp" included — the query
    # axis is pure data to the compressor); without the pin GSPMD
    # guesses the [Q, Hv] intermediates' shardings on meshes that also
    # carry tp, and the backward pays involuntary-remat reshards.
    q_spec = (("dp", "fsdp", "sp"), None)
    pooled = constrain(pooled, *q_spec)

    # Region cross-attention: query = pooled token, keys/values = its s×s
    # source region (segment-id mask on region equality).
    nq = constrain(
        layer_norm(
            pooled, params["norm_q"]["weight"], params["norm_q"]["bias"], eps
        ),
        *q_spec,
    )
    nkv = layer_norm(
        features, params["norm_kv"]["weight"], params["norm_kv"]["bias"], eps
    )
    nh, hd = cfg.num_heads, Hv // cfg.num_heads
    q = _linear(nq, params["q_proj"]).reshape(1, Q, nh, hd)
    k = _linear(nkv, params["k_proj"]).reshape(1, P, nh, hd)
    v = _linear(nkv, params["v_proj"]).reshape(1, P, nh, hd)
    if attn_impl == "pallas":
        from oryx_tpu.ops.pallas import segment_attention as _sa

        o = _sa.segment_attention(
            q, k, v, q_region_ids[None], region_ids[None]
        ).reshape(Q, Hv)
    else:
        o = attention(
            q, k, v,
            q_segment_ids=q_region_ids[None],
            kv_segment_ids=region_ids[None],
        ).reshape(Q, Hv)
    x = constrain(pooled + _linear(o, params["o_proj"]), *q_spec)

    # MLP projector into LLM embedding space (mlp2x_gelu-equivalent).
    # fc1's kernel is P(('fsdp','sp'),'tp') under fsdp mode — pin the
    # intermediate to the tp column sharding the matmul produces so the
    # backward agrees.
    x = jax.nn.gelu(_linear(x, params["projector"]["fc1"]), approximate=True)
    x = constrain(x, ("dp", "fsdp", "sp"), "tp")
    x = _linear(x, params["projector"]["fc2"])

    valid_q = (q_region_ids > 0)[:, None]
    out = jnp.where(valid_q, x, 0).astype(features.dtype)
    return constrain(out, *q_spec)
