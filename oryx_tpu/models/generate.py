"""Autoregressive generation: jitted prefill + lax.scan decode loop.

Reference parity: HF `generate()` as driven by `OryxQwenForCausalLM`
(SURVEY.md §3.2): greedy or sampled decoding with a KV cache, stopping on
EOS. TPU-first: the whole decode loop is ONE compiled program with no
host round-trip per token — a `lax.while_loop` over the step body that
exits as soon as every row has finished (`_decode_while`; the streaming
path scans fixed-size chunks instead and exits between chunks);
right-padded batches advance with per-row positions, so mixed-length
multimodal prefills need no left-padding shuffle.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.config import GenerationConfig, LLMConfig
from oryx_tpu.models import qwen2


def sample_token(
    logits: jnp.ndarray,
    key: jax.Array,
    *,
    temperature: float,
    top_p: float,
    top_k: int,
) -> jnp.ndarray:
    """Sample next token ids from [B, V] logits. temperature==0 → greedy."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative prob >= top_p (always
        # keeps the top token).
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def make_stop_sequences(
    stop_strs: list[str], tokenizer
) -> jnp.ndarray | None:
    """Encode stop strings to a [S, L] int32 array, left-padded with -1.

    Reference parity: `KeywordsStoppingCriteria` in `oryx/mm_utils.py`
    (SURVEY.md §2 "MM utils") encodes each keyword once and compares the
    trailing generated ids — here the comparison happens inside the jitted
    decode scan so multi-token stops end rows without burning decode steps.

    Shapes are bucketed (S to a power of two, L to a multiple of 4) so
    per-request stop lists share compiled programs: -1 left-padding is a
    wildcard (matches any id), and filler ROWS are -3 throughout — -3
    equals neither real ids (>= 0), the -2 window init, nor the -1
    wildcard, so a filler row can never fire.
    """
    seqs = []
    for s in stop_strs:
        if not s:
            continue
        ids = tokenizer.encode(s, add_special_tokens=False)
        if ids:
            seqs.append(np.asarray(ids, np.int32))
    if not seqs:
        return None
    L = -(-max(len(s) for s in seqs) // 4) * 4
    S = 1 << (len(seqs) - 1).bit_length()
    out = np.full((S, L), -3, np.int32)
    for i, s in enumerate(seqs):
        out[i, : L - len(s)] = -1
        out[i, L - len(s):] = s
    return jnp.asarray(out)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "gen_cfg", "max_new_tokens", "cache_len", "attn_impl",
        "compute_dtype", "return_cache",
    ),
)
def generate(
    params,
    cfg: LLMConfig,
    gen_cfg: GenerationConfig,
    *,
    inputs_embeds: jnp.ndarray,  # [B, T, H] (pre-spliced; right-padded)
    lengths: jnp.ndarray,  # [B] real TOTAL lengths (incl. cached prefix)
    max_new_tokens: int,
    cache_len: int,
    key: jax.Array | None = None,
    attn_impl: str = "xla",
    compute_dtype=None,
    stop_sequences: jnp.ndarray | None = None,  # [S, L], left-pad -1
    kv_cache: dict | None = None,
    start: jnp.ndarray | None = None,  # [] int32 first slot to write
    return_cache: bool = False,
):
    """Returns (tokens [B, max_new_tokens] int32, num_generated [B] int32,
    finished [B] bool) — plus the KV cache when return_cache.

    Slots after EOS are filled with eos_token_id. cache_len must be a bucket
    >= T + max_new_tokens. A row also finishes when its trailing tokens
    match any stop sequence (num_generated then includes the stop tokens;
    the caller trims the decoded text). finished=False marks a row cut off
    by max_new_tokens (the OpenAI "length" finish reason) rather than by
    EOS/stop.

    kv_cache/start (prefix reuse, serve/pipeline.ChatSession): a cache
    whose slots [0, start) already hold a previous turn's K/V — only the
    suffix embeds are prefilled (written at `start`, positions absolute)
    and `lengths` counts prefix + suffix. The caller guarantees
    cache_len >= lengths + max_new_tokens.
    """
    if kv_cache is None:
        assert cache_len >= inputs_embeds.shape[1] + max_new_tokens, (
            cache_len, inputs_embeds.shape[1], max_new_tokens
        )
    if key is None:
        key = jax.random.key(0)
    carry, key = _prefill_carry(
        params, cfg, gen_cfg, inputs_embeds, lengths, key,
        cache_len=cache_len, attn_impl=attn_impl,
        compute_dtype=compute_dtype,
        stop_L=0 if stop_sequences is None else stop_sequences.shape[1],
        kv_cache=kv_cache, start=start,
    )
    step = _make_decode_step(
        params, cfg, gen_cfg, stop_sequences,
        cache_len=cache_len, attn_impl=attn_impl,
        compute_dtype=compute_dtype,
    )
    carry, toks, fin = _decode_while(
        step, carry, jax.random.split(key, max_new_tokens),
        max_new_tokens, gen_cfg.eos_token_id,
    )
    # num generated = tokens up to and including the finishing token (EOS
    # or the last token of a stop sequence).
    num = jnp.where(
        jnp.any(fin, axis=1), jnp.argmax(fin, axis=1) + 1, max_new_tokens
    )
    out = (toks, num.astype(jnp.int32), jnp.any(fin, axis=1))
    return out + (carry[0],) if return_cache else out


def _decode_while(step, carry, step_keys, max_new_tokens: int, eos: int):
    """Run the decode step to completion OR until every row finished —
    a `lax.while_loop` over the scan body, so a batch of short answers
    inside a long decode window (bucketed serving, MCQ eval) stops
    paying for the unused steps. Unexecuted slots keep the same values
    the scan would have produced (tokens: EOS fill; finished: True —
    the loop only exits early when ALL rows are finished).

    Returns (final carry, toks [B, max_new], fin [B, max_new])."""
    nB = carry[1].shape[0]  # carry = (cache, tok, lengths, finished, recent)
    toks0 = jnp.full((nB, max_new_tokens), eos, jnp.int32)
    fin0 = jnp.ones((nB, max_new_tokens), bool)

    def cond(state):
        i, c, _, _ = state
        return (i < max_new_tokens) & ~jnp.all(c[3])  # c[3] = finished

    def body(state):
        i, c, toks, fin = state
        c, (tok, f) = step(c, step_keys[i])
        toks = jax.lax.dynamic_update_index_in_dim(toks, tok, i, axis=1)
        fin = jax.lax.dynamic_update_index_in_dim(fin, f, i, axis=1)
        return i + 1, c, toks, fin

    _, carry, toks, fin = jax.lax.while_loop(
        cond, body, (jnp.zeros((), jnp.int32), carry, toks0, fin0)
    )
    return carry, toks, fin


def _prefill_carry(
    params, cfg: LLMConfig, gen_cfg: GenerationConfig, inputs_embeds,
    lengths, key, *, cache_len: int, attn_impl: str, compute_dtype,
    stop_L: int, kv_cache: dict | None = None,
    start: jnp.ndarray | None = None,
):
    """Prefill + first sampled token → the decode-scan carry
    (cache, next token, per-row lengths, finished flags, rolling
    stop-match window). Shared by `generate` and the streaming path.

    With kv_cache/start, only the suffix embeds are prefilled into an
    existing cache at slot `start` (absolute positions; `lengths` counts
    prefix + suffix) — the prefix-reuse path."""
    B, T, _ = inputs_embeds.shape
    start_vec = (
        jnp.zeros((B,), jnp.int32)
        if start is None
        else jnp.broadcast_to(start.astype(jnp.int32), (B,))
    )
    positions = start_vec[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    slot_ar = jnp.arange(cache_len, dtype=jnp.int32)[None, :]
    kv_mask = (slot_ar < lengths[:, None]).astype(jnp.int32)

    cache = kv_cache if kv_cache is not None else qwen2.init_kv_cache(
        cfg, B, cache_len, dtype=compute_dtype or jnp.float32
    )
    logits, cache = qwen2.forward(
        params, cfg,
        inputs_embeds=inputs_embeds, positions=positions,
        kv_cache=cache, write_slots=start_vec,
        kv_mask=kv_mask, attn_impl=attn_impl, compute_dtype=compute_dtype,
    )
    # Last real logit per row: suffix-local index of the final token.
    last = jnp.take_along_axis(
        logits, (lengths - 1 - start_vec)[:, None, None].astype(jnp.int32),
        axis=1,
    )[:, 0]
    key, sk = jax.random.split(key)
    tok0 = sample_token(
        last, sk, temperature=gen_cfg.temperature, top_p=gen_cfg.top_p,
        top_k=gen_cfg.top_k,
    )
    # Rolling last-L-token window per row for stop-sequence matching; -2
    # init can match neither real ids nor the -1 stop padding.
    recent0 = jnp.full((B, stop_L), -2, jnp.int32)
    return (cache, tok0, lengths, jnp.zeros((B,), bool), recent0), key


def _make_decode_step(
    params, cfg: LLMConfig, gen_cfg: GenerationConfig, stop_sequences,
    *, cache_len: int, attn_impl: str, compute_dtype,
):
    """One decode-scan step over the `_prefill_carry` state — the single
    definition both `generate` and `_stream_chunk` scan over."""
    slot_ar = jnp.arange(cache_len, dtype=jnp.int32)[None, :]

    def stop_hit(recent):
        if stop_sequences is None:
            return jnp.zeros((recent.shape[0],), bool)
        # [B, S, L]: pad positions (-1) match anything.
        m = (stop_sequences[None] == -1) | (
            recent[:, None, :] == stop_sequences[None]
        )
        return jnp.any(jnp.all(m, axis=-1), axis=-1)

    def step(carry, step_key):
        cache, tok, cur_len, finished, recent = carry
        pos = cur_len[:, None]  # [B, 1] absolute position of tok
        kv_mask = (slot_ar <= cur_len[:, None]).astype(jnp.int32)
        logits, cache = qwen2.forward(
            params, cfg,
            input_ids=tok[:, None], positions=pos,
            kv_cache=cache, write_slots=cur_len,
            kv_mask=kv_mask, attn_impl=attn_impl,
            compute_dtype=compute_dtype,
        )
        nxt = sample_token(
            logits[:, 0], step_key, temperature=gen_cfg.temperature,
            top_p=gen_cfg.top_p, top_k=gen_cfg.top_k,
        )
        if recent.shape[1]:
            recent = jnp.concatenate([recent[:, 1:], tok[:, None]], axis=1)
        finished = (
            finished | (tok == gen_cfg.eos_token_id) | stop_hit(recent)
        )
        nxt = jnp.where(finished, gen_cfg.eos_token_id, nxt)
        return (cache, nxt, cur_len + 1, finished, recent), (tok, finished)

    return step


# ---------------------------------------------------------------------------
# Streaming
# ---------------------------------------------------------------------------


# The streaming path jits the shared prefill directly (generate traces
# it inline inside its own jit).
_stream_prefill = partial(
    jax.jit,
    static_argnames=(
        "cfg", "gen_cfg", "cache_len", "attn_impl", "compute_dtype",
        "stop_L",
    ),
)(_prefill_carry)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "gen_cfg", "cache_len", "attn_impl", "compute_dtype",
    ),
    donate_argnames=("carry",),
)
def _stream_chunk(
    params, cfg: LLMConfig, gen_cfg: GenerationConfig, carry, step_keys,
    stop_sequences, *, cache_len: int, attn_impl: str, compute_dtype,
):
    step = _make_decode_step(
        params, cfg, gen_cfg, stop_sequences,
        cache_len=cache_len, attn_impl=attn_impl,
        compute_dtype=compute_dtype,
    )
    carry, (toks, fin) = jax.lax.scan(init=carry, f=step, xs=step_keys)
    return carry, jnp.moveaxis(toks, 0, 1), jnp.moveaxis(fin, 0, 1)


def generate_stream(
    params,
    cfg: LLMConfig,
    gen_cfg: GenerationConfig,
    *,
    inputs_embeds: jnp.ndarray,
    lengths: jnp.ndarray,
    max_new_tokens: int,
    cache_len: int,
    key: jax.Array | None = None,
    attn_impl: str = "xla",
    compute_dtype=None,
    stop_sequences: jnp.ndarray | None = None,
    chunk: int = 8,
    kv_cache: dict | None = None,
    start: jnp.ndarray | None = None,
    yield_cache: bool = False,
):
    """Streaming twin of `generate` (HF TextIteratorStreamer parity):
    yields np int32 token blocks [B, <=chunk] as they decode, with the
    same semantics (EOS fill after finish, stop sequences end rows) AND
    the same RNG stream — the post-prefill key is pre-split into one key
    per step (jax.random.split is prefix-stable), so sampled outputs
    match `generate` token-for-token at any temperature.
    The decode runs WHOLE `chunk`-token compiled dispatches (a shrunken
    final chunk would compile a second decode program); overshoot
    tokens past max_new_tokens are computed and dropped, so cache_len
    must cover T + ceil(max_new/chunk)*chunk. Larger chunks amortize
    host round-trips, smaller ones lower first-token latency.

    kv_cache/start: prefix reuse as in `generate`. With yield_cache the
    generator yields (block, cache) pairs — the cache reference is valid
    until the NEXT block is requested (the chunk dispatch donates it),
    so a consumer breaking out of the loop may keep the last one.
    """
    padded_new = -(-max_new_tokens // chunk) * chunk
    if kv_cache is None:
        assert cache_len >= inputs_embeds.shape[1] + padded_new, (
            cache_len, inputs_embeds.shape[1], padded_new
        )
    if key is None:
        key = jax.random.key(0)
    stop_L = 0 if stop_sequences is None else stop_sequences.shape[1]
    common = dict(
        cache_len=cache_len, attn_impl=attn_impl,
        compute_dtype=compute_dtype,
    )
    carry, key = _stream_prefill(
        params, cfg, gen_cfg, inputs_embeds, lengths, key,
        stop_L=stop_L, kv_cache=kv_cache, start=start, **common,
    )
    step_keys = jax.random.split(key, padded_new)
    done = 0
    while done < max_new_tokens:
        carry, toks, fin = _stream_chunk(
            params, cfg, gen_cfg, carry, step_keys[done:done + chunk],
            stop_sequences, **common,
        )
        n = min(chunk, max_new_tokens - done)
        toks, fin = np.asarray(toks)[:, :n], np.asarray(fin)[:, :n]
        yield (toks, carry[0]) if yield_cache else toks
        done += n
        if fin[:, -1].all():
            break
