"""Autoregressive generation: jitted prefill + lax.scan decode loop.

Reference parity: HF `generate()` as driven by `OryxQwenForCausalLM`
(SURVEY.md §3.2): greedy or sampled decoding with a KV cache, stopping on
EOS. TPU-first: the whole decode loop is ONE compiled program with no
host round-trip per token — a `lax.while_loop` over the step body that
exits as soon as every row has finished (`_decode_while`; the streaming
path scans fixed-size chunks instead and exits between chunks);
right-padded batches advance with per-row positions, so mixed-length
multimodal prefills need no left-padding shuffle.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.config import GenerationConfig, LLMConfig
from oryx_tpu.models import qwen2
from oryx_tpu.ops import paged_kv as paged_kv_lib
from oryx_tpu.utils import numerics as numerics_lib


def sample_token(
    logits: jnp.ndarray,
    key: jax.Array,
    *,
    temperature: float,
    top_p: float,
    top_k: int,
) -> jnp.ndarray:
    """Sample next token ids from [B, V] logits. temperature==0 → greedy."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        # Clamp to the vocab dimension: top_k >= V keeps everything (the
        # kth value is the row minimum); unclamped it would index out of
        # range on the sorted axis.
        kth = jnp.sort(logits, axis=-1)[
            :, -min(top_k, logits.shape[-1])
        ][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative prob >= top_p (always
        # keeps the top token).
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def truncate_logits_rows(
    logits: jnp.ndarray,  # [S, V]
    *,
    temperature: jnp.ndarray,  # [S] float (0 => greedy for that row)
    top_p: jnp.ndarray,  # [S] float
    top_k: jnp.ndarray,  # [S] int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row temperature scale + top-k + top-p truncation — the
    distribution-shaping half of `sample_token_rows`, factored out so
    speculative verification (`spec_verify_rows`) accepts and resamples
    against EXACTLY the distribution the non-speculative sampler draws
    from. Returns (truncated logits [S, V] with -inf outside the
    nucleus, is_greedy [S] bool). Greedy rows pass through at t=1 (the
    caller overrides them with argmax, as `sample_token_rows` does)."""
    V = logits.shape[-1]
    is_greedy = temperature <= 0.0
    t = jnp.where(is_greedy, 1.0, temperature)[:, None]
    l = logits / t
    tk = jnp.clip(top_k.astype(jnp.int32), 0, V)
    srt = jnp.sort(l, axis=-1)  # ascending
    kth = jnp.take_along_axis(
        srt, jnp.clip(V - tk, 0, V - 1)[:, None], axis=-1
    )
    l = jnp.where((tk > 0)[:, None] & (l < kth), -jnp.inf, l)
    srt_d = jnp.sort(l, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(srt_d, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Smallest prefix with cumulative prob >= top_p (keeps the top token).
    cutoff_idx = jnp.sum(cum < top_p[:, None], axis=-1)
    cutoff = jnp.take_along_axis(srt_d, cutoff_idx[:, None], axis=-1)
    l = jnp.where((top_p < 1.0)[:, None] & (l < cutoff), -jnp.inf, l)
    return l, is_greedy


def sample_token_rows(
    logits: jnp.ndarray,  # [S, V]
    keys: jax.Array,  # [S] per-row PRNG keys
    *,
    temperature: jnp.ndarray,  # [S] float (0 => greedy for that row)
    top_p: jnp.ndarray,  # [S] float
    top_k: jnp.ndarray,  # [S] int
) -> jnp.ndarray:
    """Per-ROW sampling for continuous batching (`sample_token` treats
    its knobs as batch-wide statics; one compiled program per distinct
    value). Every slot carries its own (temperature, top_p, top_k) as
    traced arrays and its own key, so a row's draw is a function of that
    row alone — admitting or finishing a neighbor never perturbs an
    in-flight request's sample stream, and mixed sampling configs share
    ONE compiled decode."""
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    l, is_greedy = truncate_logits_rows(
        logits, temperature=temperature, top_p=top_p, top_k=top_k
    )
    # Per-row Gumbel-max with per-row keys (categorical over one shared
    # key would couple a row's draw to its batch position).
    u = jax.vmap(lambda k: jax.random.uniform(k, (V,)))(keys)
    g = -jnp.log(-jnp.log(jnp.maximum(u, jnp.finfo(jnp.float32).tiny)))
    sampled = jnp.argmax(l + g, axis=-1).astype(jnp.int32)
    return jnp.where(is_greedy, greedy, sampled)


def make_stop_sequences(
    stop_strs: list[str], tokenizer
) -> jnp.ndarray | None:
    """Encode stop strings to a [S, L] int32 array, left-padded with -1.

    Reference parity: `KeywordsStoppingCriteria` in `oryx/mm_utils.py`
    (SURVEY.md §2 "MM utils") encodes each keyword once and compares the
    trailing generated ids — here the comparison happens inside the jitted
    decode scan so multi-token stops end rows without burning decode steps.

    Shapes are bucketed (S to a power of two, L to a multiple of 4) so
    per-request stop lists share compiled programs: -1 left-padding is a
    wildcard (matches any id), and filler ROWS are -3 throughout — -3
    equals neither real ids (>= 0), the -2 window init, nor the -1
    wildcard, so a filler row can never fire.
    """
    seqs = []
    for s in stop_strs:
        if not s:
            continue
        ids = tokenizer.encode(s, add_special_tokens=False)
        if ids:
            seqs.append(np.asarray(ids, np.int32))
    if not seqs:
        return None
    L = -(-max(len(s) for s in seqs) // 4) * 4
    S = 1 << (len(seqs) - 1).bit_length()
    out = np.full((S, L), -3, np.int32)
    for i, s in enumerate(seqs):
        out[i, : L - len(s)] = -1
        out[i, L - len(s):] = s
    return jnp.asarray(out)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "gen_cfg", "max_new_tokens", "cache_len", "attn_impl",
        "compute_dtype", "return_cache",
    ),
)
def generate(
    params,
    cfg: LLMConfig,
    gen_cfg: GenerationConfig,
    *,
    inputs_embeds: jnp.ndarray,  # [B, T, H] (pre-spliced; right-padded)
    lengths: jnp.ndarray,  # [B] real TOTAL lengths (incl. cached prefix)
    max_new_tokens: int,
    cache_len: int,
    key: jax.Array | None = None,
    attn_impl: str = "xla",
    compute_dtype=None,
    stop_sequences: jnp.ndarray | None = None,  # [S, L], left-pad -1
    kv_cache: dict | None = None,
    start: jnp.ndarray | None = None,  # [] int32 first slot to write
    return_cache: bool = False,
):
    """Returns (tokens [B, max_new_tokens] int32, num_generated [B] int32,
    finished [B] bool) — plus the KV cache when return_cache.

    Slots after EOS are filled with eos_token_id. cache_len must be a bucket
    >= T + max_new_tokens. A row also finishes when its trailing tokens
    match any stop sequence (num_generated then includes the stop tokens;
    the caller trims the decoded text). finished=False marks a row cut off
    by max_new_tokens (the OpenAI "length" finish reason) rather than by
    EOS/stop.

    kv_cache/start (prefix reuse, serve/pipeline.ChatSession): a cache
    whose slots [0, start) already hold a previous turn's K/V — only the
    suffix embeds are prefilled (written at `start`, positions absolute)
    and `lengths` counts prefix + suffix. The caller guarantees
    cache_len >= lengths + max_new_tokens.
    """
    if kv_cache is None:
        assert cache_len >= inputs_embeds.shape[1] + max_new_tokens, (
            cache_len, inputs_embeds.shape[1], max_new_tokens
        )
    if key is None:
        key = jax.random.key(0)
    carry, key = _prefill_carry(
        params, cfg, gen_cfg, inputs_embeds, lengths, key,
        cache_len=cache_len, attn_impl=attn_impl,
        compute_dtype=compute_dtype,
        stop_L=0 if stop_sequences is None else stop_sequences.shape[1],
        kv_cache=kv_cache, start=start,
    )
    step = _make_decode_step(
        params, cfg, gen_cfg, stop_sequences,
        cache_len=cache_len, attn_impl=attn_impl,
        compute_dtype=compute_dtype,
    )
    carry, toks, fin = _decode_while(
        step, carry, jax.random.split(key, max_new_tokens),
        max_new_tokens, gen_cfg.eos_token_id,
    )
    # num generated = tokens up to and including the finishing token (EOS
    # or the last token of a stop sequence).
    num = jnp.where(
        jnp.any(fin, axis=1), jnp.argmax(fin, axis=1) + 1, max_new_tokens
    )
    out = (toks, num.astype(jnp.int32), jnp.any(fin, axis=1))
    return out + (carry[0],) if return_cache else out


def _decode_while(step, carry, step_keys, max_new_tokens: int, eos: int):
    """Run the decode step to completion OR until every row finished —
    a `lax.while_loop` over the scan body, so a batch of short answers
    inside a long decode window (bucketed serving, MCQ eval) stops
    paying for the unused steps. Unexecuted slots keep the same values
    the scan would have produced (tokens: EOS fill; finished: True —
    the loop only exits early when ALL rows are finished).

    Returns (final carry, toks [B, max_new], fin [B, max_new])."""
    nB = carry[1].shape[0]  # carry = (cache, tok, lengths, finished, recent)
    toks0 = jnp.full((nB, max_new_tokens), eos, jnp.int32)
    fin0 = jnp.ones((nB, max_new_tokens), bool)

    def cond(state):
        i, c, _, _ = state
        return (i < max_new_tokens) & ~jnp.all(c[3])  # c[3] = finished

    def body(state):
        i, c, toks, fin = state
        c, (tok, f) = step(c, step_keys[i])
        toks = jax.lax.dynamic_update_index_in_dim(toks, tok, i, axis=1)
        fin = jax.lax.dynamic_update_index_in_dim(fin, f, i, axis=1)
        return i + 1, c, toks, fin

    _, carry, toks, fin = jax.lax.while_loop(
        cond, body, (jnp.zeros((), jnp.int32), carry, toks0, fin0)
    )
    return carry, toks, fin


def _prefill_carry(
    params, cfg: LLMConfig, gen_cfg: GenerationConfig, inputs_embeds,
    lengths, key, *, cache_len: int, attn_impl: str, compute_dtype,
    stop_L: int, kv_cache: dict | None = None,
    start: jnp.ndarray | None = None,
):
    """Prefill + first sampled token → the decode-scan carry
    (cache, next token, per-row lengths, finished flags, rolling
    stop-match window). Shared by `generate` and the streaming path.

    With kv_cache/start, only the suffix embeds are prefilled into an
    existing cache at slot `start` (absolute positions; `lengths` counts
    prefix + suffix) — the prefix-reuse path."""
    B, T, _ = inputs_embeds.shape
    start_vec = (
        jnp.zeros((B,), jnp.int32)
        if start is None
        else jnp.broadcast_to(start.astype(jnp.int32), (B,))
    )
    positions = start_vec[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    slot_ar = jnp.arange(cache_len, dtype=jnp.int32)[None, :]
    kv_mask = (slot_ar < lengths[:, None]).astype(jnp.int32)

    cache = kv_cache if kv_cache is not None else qwen2.init_kv_cache(
        cfg, B, cache_len, dtype=compute_dtype or jnp.float32
    )
    logits, cache = qwen2.forward(
        params, cfg,
        inputs_embeds=inputs_embeds, positions=positions,
        kv_cache=cache, write_slots=start_vec,
        kv_mask=kv_mask, attn_impl=attn_impl, compute_dtype=compute_dtype,
    )
    # Last real logit per row: suffix-local index of the final token.
    last = jnp.take_along_axis(
        logits, (lengths - 1 - start_vec)[:, None, None].astype(jnp.int32),
        axis=1,
    )[:, 0]
    key, sk = jax.random.split(key)
    tok0 = sample_token(
        last, sk, temperature=gen_cfg.temperature, top_p=gen_cfg.top_p,
        top_k=gen_cfg.top_k,
    )
    # Rolling last-L-token window per row for stop-sequence matching; -2
    # init can match neither real ids nor the -1 stop padding.
    recent0 = jnp.full((B, stop_L), -2, jnp.int32)
    return (cache, tok0, lengths, jnp.zeros((B,), bool), recent0), key


def _make_decode_step(
    params, cfg: LLMConfig, gen_cfg: GenerationConfig, stop_sequences,
    *, cache_len: int, attn_impl: str, compute_dtype,
):
    """One decode-scan step over the `_prefill_carry` state — the single
    definition both `generate` and `_stream_chunk` scan over."""
    slot_ar = jnp.arange(cache_len, dtype=jnp.int32)[None, :]

    def stop_hit(recent):
        if stop_sequences is None:
            return jnp.zeros((recent.shape[0],), bool)
        # [B, S, L]: pad positions (-1) match anything.
        m = (stop_sequences[None] == -1) | (
            recent[:, None, :] == stop_sequences[None]
        )
        return jnp.any(jnp.all(m, axis=-1), axis=-1)

    def step(carry, step_key):
        cache, tok, cur_len, finished, recent = carry
        pos = cur_len[:, None]  # [B, 1] absolute position of tok
        kv_mask = (slot_ar <= cur_len[:, None]).astype(jnp.int32)
        logits, cache = qwen2.forward(
            params, cfg,
            input_ids=tok[:, None], positions=pos,
            kv_cache=cache, write_slots=cur_len,
            kv_mask=kv_mask, attn_impl=attn_impl,
            compute_dtype=compute_dtype,
        )
        nxt = sample_token(
            logits[:, 0], step_key, temperature=gen_cfg.temperature,
            top_p=gen_cfg.top_p, top_k=gen_cfg.top_k,
        )
        if recent.shape[1]:
            recent = jnp.concatenate([recent[:, 1:], tok[:, None]], axis=1)
        finished = (
            finished | (tok == gen_cfg.eos_token_id) | stop_hit(recent)
        )
        nxt = jnp.where(finished, gen_cfg.eos_token_id, nxt)
        return (cache, nxt, cur_len + 1, finished, recent), (tok, finished)

    return step


# ---------------------------------------------------------------------------
# Streaming
# ---------------------------------------------------------------------------


# The streaming path jits the shared prefill directly (generate traces
# it inline inside its own jit).
_stream_prefill = partial(
    jax.jit,
    static_argnames=(
        "cfg", "gen_cfg", "cache_len", "attn_impl", "compute_dtype",
        "stop_L",
    ),
)(_prefill_carry)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "gen_cfg", "cache_len", "attn_impl", "compute_dtype",
    ),
    donate_argnames=("carry",),
)
def _stream_chunk(
    params, cfg: LLMConfig, gen_cfg: GenerationConfig, carry, step_keys,
    stop_sequences, *, cache_len: int, attn_impl: str, compute_dtype,
):
    step = _make_decode_step(
        params, cfg, gen_cfg, stop_sequences,
        cache_len=cache_len, attn_impl=attn_impl,
        compute_dtype=compute_dtype,
    )
    carry, (toks, fin) = jax.lax.scan(init=carry, f=step, xs=step_keys)
    return carry, jnp.moveaxis(toks, 0, 1), jnp.moveaxis(fin, 0, 1)


# hot-path
def generate_stream(
    params,
    cfg: LLMConfig,
    gen_cfg: GenerationConfig,
    *,
    inputs_embeds: jnp.ndarray,
    lengths: jnp.ndarray,
    max_new_tokens: int,
    cache_len: int,
    key: jax.Array | None = None,
    attn_impl: str = "xla",
    compute_dtype=None,
    stop_sequences: jnp.ndarray | None = None,
    chunk: int = 8,
    kv_cache: dict | None = None,
    start: jnp.ndarray | None = None,
    yield_cache: bool = False,
):
    """Streaming twin of `generate` (HF TextIteratorStreamer parity):
    yields np int32 token blocks [B, <=chunk] as they decode, with the
    same semantics (EOS fill after finish, stop sequences end rows) AND
    the same RNG stream — the post-prefill key is pre-split into one key
    per step (jax.random.split is prefix-stable), so sampled outputs
    match `generate` token-for-token at any temperature.
    The decode runs WHOLE `chunk`-token compiled dispatches (a shrunken
    final chunk would compile a second decode program); overshoot
    tokens past max_new_tokens are computed and dropped, so cache_len
    must cover T + ceil(max_new/chunk)*chunk. Larger chunks amortize
    host round-trips, smaller ones lower first-token latency.

    kv_cache/start: prefix reuse as in `generate`. With yield_cache the
    generator yields (block, cache) pairs — the cache reference is valid
    until the NEXT block is requested (the chunk dispatch donates it),
    so a consumer breaking out of the loop may keep the last one.
    """
    padded_new = -(-max_new_tokens // chunk) * chunk
    if kv_cache is None:
        assert cache_len >= inputs_embeds.shape[1] + padded_new, (
            cache_len, inputs_embeds.shape[1], padded_new
        )
    if key is None:
        key = jax.random.key(0)
    stop_L = 0 if stop_sequences is None else stop_sequences.shape[1]
    common = dict(
        cache_len=cache_len, attn_impl=attn_impl,
        compute_dtype=compute_dtype,
    )
    carry, key = _stream_prefill(
        params, cfg, gen_cfg, inputs_embeds, lengths, key,
        stop_L=stop_L, kv_cache=kv_cache, start=start, **common,
    )
    step_keys = jax.random.split(key, padded_new)
    done = 0
    while done < max_new_tokens:
        carry, toks, fin = _stream_chunk(
            params, cfg, gen_cfg, carry, step_keys[done:done + chunk],
            stop_sequences, **common,
        )
        n = min(chunk, max_new_tokens - done)
        # The per-chunk harvest IS the yield surface (and the early-exit
        # test below needs host booleans) — the one deliberate sync.
        toks, fin = np.asarray(toks)[:, :n], np.asarray(fin)[:, :n]  # oryxlint: disable=host-sync
        yield (toks, carry[0]) if yield_cache else toks
        done += n
        if fin[:, -1].all():
            break


# ---------------------------------------------------------------------------
# Paged chunked decode (continuous-batching serving path)
# ---------------------------------------------------------------------------


@partial(
    jax.jit,
    static_argnames=("cfg", "attn_impl", "compute_dtype"),
    donate_argnames=("kv_pages",),
)
def paged_prefill(
    params,
    cfg: LLMConfig,
    inputs_embeds: jnp.ndarray,  # [B, T, H] right-padded
    lengths: jnp.ndarray,  # [B] real TOTAL lengths (incl. cached prefix)
    block_tables: jnp.ndarray,  # [B, max_pages] int32
    kv_pages: dict,  # qwen2.init_paged_kv_cache pytree (donated)
    start: jnp.ndarray,  # [B] int32 first logical slot to write
    keys: jax.Array,  # [B] per-row PRNG keys
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B]
    *,
    attn_impl: str = "xla",
    compute_dtype=None,
):
    """Prompt prefill into a PAGED cache + first sampled token.

    The paged twin of `_prefill_carry`: K/V land in the rows' pages
    (through their block tables) instead of a dense per-batch buffer.
    With `start` > 0 only the suffix is prefilled at absolute positions
    (prefix KV reuse). Sampling is per-row (`sample_token_rows`) so one
    compiled prefill serves every sampling config at a given prompt
    bucket. Returns (kv_pages, tok0 [B], advanced keys [B])."""
    B, T, _ = inputs_embeds.shape
    start = jnp.broadcast_to(start.astype(jnp.int32), (B,))
    positions = start[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    page_size = kv_pages["k"].shape[2]
    K = block_tables.shape[1] * page_size
    kv_mask = (
        jnp.arange(K, dtype=jnp.int32)[None, :] < lengths[:, None]
    ).astype(jnp.int32)
    logits, kv_pages = qwen2.forward(
        params, cfg,
        inputs_embeds=inputs_embeds, positions=positions,
        kv_cache=kv_pages, write_slots=start, kv_mask=kv_mask,
        block_tables=block_tables, kv_lengths=lengths,
        attn_impl=attn_impl, compute_dtype=compute_dtype,
    )
    last = jnp.take_along_axis(
        logits, (lengths - 1 - start)[:, None, None].astype(jnp.int32),
        axis=1,
    )[:, 0]
    pair = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    tok0 = sample_token_rows(
        last, pair[:, 1], temperature=temperature, top_p=top_p, top_k=top_k
    )
    return kv_pages, tok0, pair[:, 0]


@partial(jax.jit, static_argnames=("width",))
def slice_embeds(embeds: jnp.ndarray, start, *, width: int) -> jnp.ndarray:
    """[B, T, H] → the [B, width, H] window at traced offset `start`.

    One compiled program per (T, width) pair — the chunked-prefill
    slicer (a host-side `embeds[:, a:b]` would compile one slice per
    distinct offset). dynamic_slice CLAMPS out-of-range starts, which
    would silently misalign tokens: callers pad `embeds` so that every
    chunk start satisfies start + width <= T (`pad_embeds_for_chunks`).
    """
    return jax.lax.dynamic_slice_in_dim(embeds, start, width, axis=1)


def pad_embeds_for_chunks(embeds: jnp.ndarray, chunk: int) -> jnp.ndarray:
    """Zero-pad [B, T, H] on the token axis so every `chunk`-wide window
    starting at an offset < T stays in bounds (see `slice_embeds`). The
    padded columns prefill garbage KV past each row's real length —
    slots the decode loop overwrites before reading or masks out,
    exactly like the right-padding of a bucketed single-shot prefill."""
    return jnp.pad(embeds, ((0, 0), (0, chunk), (0, 0)))


def paged_prefill_chunks(
    params,
    cfg: LLMConfig,
    inputs_embeds: jnp.ndarray,  # [B, T, H] right-padded
    lengths: jnp.ndarray,  # [B] real TOTAL lengths (incl. cached prefix)
    block_tables: jnp.ndarray,  # [B, max_pages] int32
    kv_pages: dict,  # donated through the per-chunk calls
    start: int,  # shared first logical slot to write (cached prefix end)
    keys: jax.Array,  # [B] per-row PRNG keys
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B]
    *,
    prefill_chunk: int,
    attn_impl: str = "xla",
    compute_dtype=None,
):
    """`paged_prefill` in bounded windows: a host loop dispatching the
    SAME compiled program over `prefill_chunk`-token embed slices, so a
    long prompt never occupies the device in one monolithic dispatch
    (the admission path interleaves these with decode chunks).

    Bit-parity with the single-shot call: valid-slot KV and the sampled
    first token are identical — chunk grouping only changes the masked
    garbage past each row's length, and every chunk is seeded with the
    ORIGINAL per-row key (only the final real chunk's sample and
    advanced key are kept, which is exactly the single-shot RNG
    contract: tok0 ~ split(key)[1], key' = split(key)[0]).

    Returns (kv_pages, tok0 [B], advanced keys [B])."""
    B, T, _ = inputs_embeds.shape
    host_len = [int(x) for x in np.asarray(lengths)]
    max_len = max(host_len)
    embeds = pad_embeds_for_chunks(inputs_embeds, prefill_chunk)
    tok0 = np.zeros((B,), np.int32)
    out_keys = list(keys)
    lengths = jnp.asarray(lengths, jnp.int32)
    off = start
    while off < max_len:
        end = off + prefill_chunk
        sl = slice_embeds(
            embeds, jnp.asarray(off - start, jnp.int32),
            width=prefill_chunk,
        )
        # Every chunk DELIBERATELY consumes the same original per-row
        # keys: only the final real chunk's sample + advanced key are
        # kept (see docstring), which is exactly the single-shot RNG
        # contract. Re-deriving per chunk would make tok0 depend on
        # prefill_chunk — a replay-breaking divergence.
        kv_pages, tok, nkeys = paged_prefill(  # oryxlint: disable=key-linearity
            params, cfg, sl, jnp.minimum(lengths, end), block_tables,
            kv_pages, jnp.asarray([off], np.int32), keys,
            temperature, top_p, top_k,
            attn_impl=attn_impl, compute_dtype=compute_dtype,
        )
        for b, L in enumerate(host_len):
            if off <= L - 1 < end:  # row b's final real chunk
                tok0[b] = int(np.asarray(tok)[b])
                out_keys[b] = nkeys[b]
        off = end
    return kv_pages, jnp.asarray(tok0), jnp.stack(out_keys)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "chunk", "eos", "attn_impl", "compute_dtype", "numerics",
    ),
    donate_argnames=("kv_pages",),
)
def paged_decode_chunk(
    params,
    cfg: LLMConfig,
    kv_pages: dict,  # donated
    block_tables: jnp.ndarray,  # [S, max_pages] int32
    tok: jnp.ndarray,  # [S] next token to feed per slot
    lengths: jnp.ndarray,  # [S] kv tokens held per slot (frozen on finish)
    finished: jnp.ndarray,  # [S] bool (True for finished AND empty slots)
    recent: jnp.ndarray,  # [S, stop_L] rolling stop window (-2 init)
    keys: jax.Array,  # [S] per-slot PRNG keys
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S]
    stop_sequences: jnp.ndarray | None = None,  # [Sq, L] (shared, static)
    *,
    chunk: int,
    eos: int,
    attn_impl: str = "xla",
    compute_dtype=None,
    numerics: bool = False,
):
    """`chunk` decode steps over a FIXED-SLOT batch with a paged cache —
    the continuous-batching inner loop. One compiled program per
    (num_slots, max_pages, chunk) regardless of which slots are live:
    finished/empty slots still flow through the math but their cache
    writes are dropped (write_mask) and their lengths freeze, so the
    scheduler can retire and admit requests BETWEEN chunks by editing
    the small host-side state arrays — never recompiling, never touching
    other rows' streams (per-row keys + per-row sampling).

    Step semantics mirror `_make_decode_step` exactly (greedy token ids
    are bit-identical to the dense path at equal logical KV width).
    Returns (kv_pages, tok, lengths, finished, recent, keys,
    toks [S, chunk], fin [S, chunk]).

    numerics=True (STATIC — one extra stable compiled program, never a
    per-step recompile) appends ONE more output: the [6] float32 logit
    -stat accumulator (utils/numerics.py) folded over the chunk's live
    rows inside this same dispatch — token streams and every other
    output are bit-identical to the numerics=False program (the probe
    only reads the logits the sampler already computed)."""
    page_size = kv_pages["k"].shape[2]
    K = block_tables.shape[1] * page_size
    slot_ar = jnp.arange(K, dtype=jnp.int32)[None, :]

    def stop_hit(recent):
        if stop_sequences is None:
            return jnp.zeros((recent.shape[0],), bool)
        m = (stop_sequences[None] == -1) | (
            recent[:, None, :] == stop_sequences[None]
        )
        return jnp.any(jnp.all(m, axis=-1), axis=-1)

    def step(carry, _):
        if numerics:
            kv_pages, tok, cur_len, finished, recent, keys, nstats = carry
        else:
            kv_pages, tok, cur_len, finished, recent, keys = carry
        pair = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
        pos = cur_len[:, None]
        kv_mask = (slot_ar <= cur_len[:, None]).astype(jnp.int32)
        logits, kv_pages = qwen2.forward(
            params, cfg,
            input_ids=tok[:, None], positions=pos,
            kv_cache=kv_pages, write_slots=cur_len, kv_mask=kv_mask,
            block_tables=block_tables, write_mask=~finished,
            kv_lengths=cur_len + 1,
            attn_impl=attn_impl, compute_dtype=compute_dtype,
        )
        if numerics:
            # Live-row logit probe on the logits the sampler is about
            # to consume — same dispatch, zero extra device calls.
            nstats = numerics_lib.accumulate_logit_stats(
                nstats, logits[:, 0], ~finished
            )
        nxt = sample_token_rows(
            logits[:, 0], pair[:, 1],
            temperature=temperature, top_p=top_p, top_k=top_k,
        )
        if recent.shape[1]:
            recent = jnp.concatenate([recent[:, 1:], tok[:, None]], axis=1)
        finished = finished | (tok == eos) | stop_hit(recent)
        nxt = jnp.where(finished, eos, nxt)
        cur_len = cur_len + (~finished).astype(jnp.int32)
        out = (kv_pages, nxt, cur_len, finished, recent, pair[:, 0])
        if numerics:
            out = out + (nstats,)
        return out, (tok, finished)

    carry0 = (kv_pages, tok, lengths, finished, recent, keys)
    if numerics:
        carry0 = carry0 + (numerics_lib.init_logit_stats(),)
    carry, (toks, fin) = jax.lax.scan(step, carry0, None, length=chunk)
    out = carry[:6] + (jnp.moveaxis(toks, 0, 1), jnp.moveaxis(fin, 0, 1))
    if numerics:
        out = out + (carry[6],)
    return out


# ---------------------------------------------------------------------------
# Ragged fused prefill+decode step (one dispatch per engine step)
# ---------------------------------------------------------------------------


def pack_prefill_window(
    embeds_np: "np.ndarray",  # [1, T, H] HOST prompt embeds
    off: int,
    width: int,
) -> "np.ndarray":
    """Host-side packing helper: the [1, width, H] prefill window at
    logical offset `off` of a prompt whose embeds live on the HOST,
    zero-padded past the prompt end. The window — not the whole prompt
    — is the ragged dispatch's operand, so the dispatch shape is STATIC
    regardless of prompt length (the split path's `slice_embeds`
    compiles one device slicer per (T, width) pair instead; here the
    slice is free numpy)."""
    T, H = embeds_np.shape[1], embeds_np.shape[2]
    out = np.zeros((1, width, H), embeds_np.dtype)
    n = max(0, min(width, T - off))
    if n:
        out[0, :n] = embeds_np[0, off:off + n]
    return out


def unpack_ragged_rows(
    toks: "np.ndarray",  # [S, chunk] harvested decode tokens
    live: list[int],
) -> dict[int, list[int]]:
    """Host-side unpacking helper: per-slot token streams from the
    ragged harvest, restricted to the slots that were live DURING the
    dispatch (a slot activated after harvest must not consume this
    dispatch's frozen rows)."""
    return {s: [int(t) for t in toks[s]] for s in live}


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "chunk", "pf_width", "eos", "attn_impl", "compute_dtype",
        "numerics",
    ),
    donate_argnames=("kv_pages",),
)
def paged_ragged_step(
    params,
    cfg: LLMConfig,
    kv_pages: dict,  # donated
    block_tables: jnp.ndarray,  # [S, max_pages] int32
    tok: jnp.ndarray,  # [S] next token to feed per slot
    lengths: jnp.ndarray,  # [S] kv tokens held per slot (frozen on finish)
    finished: jnp.ndarray,  # [S] bool (True for finished AND empty slots)
    recent: jnp.ndarray,  # [S, stop_L] rolling stop window (-2 init)
    keys: jax.Array,  # [S] per-slot PRNG keys
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S]
    stop_sequences: jnp.ndarray | None,  # [Sq, L] (shared, static)
    pf_embeds: jnp.ndarray,  # [1, chunk*pf_width, H] prefill window
    pf_slot: jnp.ndarray,  # [] int32 slot the prefill belongs to
    pf_off: jnp.ndarray,  # [] int32 logical offset of the window start
    pf_len: jnp.ndarray,  # [] int32 total prompt length (incl. prefix)
    pf_active: jnp.ndarray,  # [] bool — a prefill rides this dispatch
    pf_key: jax.Array,  # [1] the admitting request's key0
    pf_temp: jnp.ndarray,  # [1]
    pf_top_p: jnp.ndarray,  # [1]
    pf_top_k: jnp.ndarray,  # [1]
    *,
    chunk: int,
    pf_width: int,
    eos: int,
    attn_impl: str = "xla",
    compute_dtype=None,
    numerics: bool = False,
):
    """ONE device dispatch for a mixed prefill+decode engine step — the
    fusion of `paged_prefill` (chunked) and `paged_decode_chunk`.

    Each of the `chunk` scan iterations runs a single packed forward
    over R = S + pf_width query rows: rows 0..S-1 are the decode lanes
    (one token per slot, exactly `paged_decode_chunk`'s step semantics
    — finished/empty slots ride masked), rows S.. are `pf_width`
    consecutive suffix tokens of the one admitting slot's prompt, so a
    dispatch advances the prefill by chunk*pf_width tokens while every
    resident stream decodes `chunk` tokens. The packed buffer's shape
    is STATIC: which slot is admitting, where its window starts, and
    how much of it is real are all traced scalars
    (`recompile_watchdog`-proven — varying live/prefill mixes share one
    compiled program per pf_width shape class).

    Bit-parity contract: decode lanes reproduce `paged_decode_chunk`
    exactly (same per-row math, same RNG stream); the prefill lanes
    reproduce `paged_prefill_chunks` (every window implicitly seeded
    with the request's own key0, only the window containing the prompt
    's final token samples tok0 ~ split(key0)[1], advanced key
    split(key0)[0]) — so an engine step through this program emits the
    same tokens as the split prefill-then-decode step pair.

    Returns (kv_pages, tok, lengths, finished, recent, keys,
    toks [S, chunk], fin [S, chunk], pf_tok0 [] int32, pf_key_next [1]).
    With pf_width=0 this is a pure packed decode step (the shape class
    dispatched when no admission is in flight).

    numerics=True (STATIC) appends the [6] float32 logit-stat
    accumulator (utils/numerics.py) over the decode lanes' live rows —
    same contract as paged_decode_chunk: one extra stable compiled
    program, bit-identical tokens, zero extra dispatches."""
    from oryx_tpu.parallel.sharding import constrain

    S = tok.shape[0]
    W = pf_width

    def stop_hit(recent):
        # Shared device-side stop predicate (ops/paged_kv.py): the
        # fused megastep must match these semantics bit-for-bit.
        return paged_kv_lib.stop_window_hit(recent, stop_sequences)

    def embed(ids):
        # The exact lookup `forward(input_ids=...)` performs, so decode
        # lanes stay bit-identical to the split path's embeds.
        e = constrain(params["embed"]["weight"], None, None)[ids]
        return e.astype(compute_dtype) if compute_dtype is not None else e

    def step(carry, i):
        if numerics:
            (kv_pages, tok, cur_len, finished, recent, keys, pf_tok0,
             nstats) = carry
        else:
            kv_pages, tok, cur_len, finished, recent, keys, pf_tok0 = carry
        pair = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
        dec_emb = embed(tok)  # [S, H]
        seg = jnp.arange(S, dtype=jnp.int32)
        pos = cur_len
        wm = ~finished
        if W:
            pf_win = jax.lax.dynamic_slice_in_dim(
                pf_embeds, i * W, W, axis=1
            )[0]
            pf_pos = pf_off + i * W + jnp.arange(W, dtype=jnp.int32)
            emb = jnp.concatenate(
                [dec_emb, pf_win.astype(dec_emb.dtype)], axis=0
            )
            pos = jnp.concatenate([pos, pf_pos])
            seg = jnp.concatenate(
                [seg, jnp.full((W,), 1, jnp.int32) * pf_slot]
            )
            # Prefill lanes write whenever a prefill rides the dispatch
            # (window overshoot past the prompt writes the same
            # never-read-before-overwritten garbage the split chunked
            # prefill writes — parity includes the pool bytes).
            wm = jnp.concatenate(
                [wm, jnp.broadcast_to(pf_active, (W,))]
            )
        else:
            emb = dec_emb
        logits, kv_pages = qwen2.forward(
            params, cfg,
            inputs_embeds=emb[None], positions=pos[None],
            kv_cache=kv_pages, block_tables=block_tables,
            q_segments=seg[None], write_mask=wm[None],
            attn_impl=attn_impl, compute_dtype=compute_dtype,
        )
        lg = logits[0]  # [R, V]
        if numerics:
            # Decode lanes only: the prefill lanes' logits are
            # intermediate prompt positions, not sampling inputs.
            nstats = numerics_lib.accumulate_logit_stats(
                nstats, lg[:S], ~finished
            )
        nxt = sample_token_rows(
            lg[:S], pair[:, 1],
            temperature=temperature, top_p=top_p, top_k=top_k,
        )
        if recent.shape[1]:
            recent = jnp.concatenate([recent[:, 1:], tok[:, None]], axis=1)
        finished = finished | (tok == eos) | stop_hit(recent)
        nxt = jnp.where(finished, eos, nxt)
        cur_len = cur_len + (~finished).astype(jnp.int32)
        if W:
            # Did the prompt's final real token land in THIS window?
            pf_pair = jax.vmap(lambda k: jax.random.split(k, 2))(pf_key)
            j = pf_len - 1 - pf_off - i * W
            present = pf_active & (j >= 0) & (j < W)
            row = jax.lax.dynamic_index_in_dim(
                lg, S + jnp.clip(j, 0, W - 1), axis=0, keepdims=True
            )  # [1, V]
            cand = sample_token_rows(
                row, pf_pair[:, 1],
                temperature=pf_temp, top_p=pf_top_p, top_k=pf_top_k,
            )[0]
            pf_tok0 = jnp.where(present, cand, pf_tok0)
        out = (
            kv_pages, nxt, cur_len, finished, recent, pair[:, 0], pf_tok0
        )
        if numerics:
            out = out + (nstats,)
        return out, (tok, finished)

    carry0 = (
        kv_pages, tok, lengths, finished, recent, keys,
        jnp.zeros((), jnp.int32),
    )
    if numerics:
        carry0 = carry0 + (numerics_lib.init_logit_stats(),)
    carry, (toks, fin) = jax.lax.scan(
        step, carry0, jnp.arange(chunk, dtype=jnp.int32),
    )
    kv_pages, tok, lengths, finished, recent, keys, pf_tok0 = carry[:7]
    pf_key_next = jax.vmap(lambda k: jax.random.split(k, 2))(pf_key)[:, 0]
    out = (
        kv_pages, tok, lengths, finished, recent, keys,
        jnp.moveaxis(toks, 0, 1), jnp.moveaxis(fin, 0, 1),
        pf_tok0, pf_key_next,
    )
    if numerics:
        out = out + (carry[7],)
    return out


@partial(
    jax.jit,
    static_argnames=("cfg", "chunk", "k_steps", "eos", "attn_impl",
                     "compute_dtype"),
    donate_argnames=("kv_pages",),
)
def paged_fused_steps(
    params,
    cfg: LLMConfig,
    kv_pages: dict,  # donated
    block_tables: jnp.ndarray,  # [S, max_pages] int32
    tok: jnp.ndarray,  # [S] next token to feed per slot
    lengths: jnp.ndarray,  # [S] kv tokens held per slot (frozen on finish)
    finished: jnp.ndarray,  # [S] bool (True for finished AND empty slots)
    recent: jnp.ndarray,  # [S, stop_L] rolling stop window (-2 init)
    keys: jax.Array,  # [S] per-slot PRNG keys
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S]
    stop_sequences: jnp.ndarray | None,  # [Sq, L] (shared, static)
    *,
    chunk: int,
    k_steps: int,
    eos: int,
    attn_impl: str = "xla",
    compute_dtype=None,
):
    """ONE device dispatch for K=`k_steps` PURE-DECODE engine steps —
    the decode megastep (docs/DESIGN.md "Fused multi-step decode").

    The scan body is `paged_ragged_step`'s pure-decode iteration
    (pf_width=0), run k_steps*chunk times instead of chunk: sampling,
    packed KV writes, the per-iteration RNG pair split and the
    EOS/stop-window freeze all stay device-side, and the host harvests
    ONCE per K logical steps instead of once per step. Columns
    [j*chunk, (j+1)*chunk) of the returned toks are logical step j's
    chunk — the host processes them as K sequential harvests (billing,
    journal entries, stop-string detection all per LOGICAL step).

    Bit-parity contract: K dispatches of the pure-decode
    `paged_ragged_step` program and one dispatch of this program
    produce identical carries and identical toks, because the per-
    iteration math is the same expression — the K=1 path's host
    round-trip between steps copies values it uploads back unchanged.
    Rows the HOST would have frozen between steps (max_new cap,
    per-request stop strings — both invisible to the device) keep
    decoding inside the megastep; their later logical chunks are
    garbage the host discards after the finish point, exactly like the
    intra-chunk overshoot the K=1 path already discards, and their KV
    overshoot self-confines to the row's own pages (the sentinel
    routing of write_pages_packed drops anything past them).

    Dispatched only when no admission is in flight: the megastep is
    the idle-resident fast path, and the shape class is one compiled
    program per k_steps ladder value (the recompile watchdog's bounded
    -class contract).

    Returns (kv_pages, tok, lengths, finished, recent, keys,
    toks [S, k_steps*chunk], fin [S, k_steps*chunk])."""
    from oryx_tpu.parallel.sharding import constrain

    S = tok.shape[0]

    def embed(ids):
        e = constrain(params["embed"]["weight"], None, None)[ids]
        return e.astype(compute_dtype) if compute_dtype is not None else e

    def step(carry, _):
        kv_pages, tok, cur_len, finished, recent, keys = carry
        pair = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
        emb = embed(tok)  # [S, H]
        seg = jnp.arange(S, dtype=jnp.int32)
        logits, kv_pages = qwen2.forward(
            params, cfg,
            inputs_embeds=emb[None], positions=cur_len[None],
            kv_cache=kv_pages, block_tables=block_tables,
            q_segments=seg[None], write_mask=(~finished)[None],
            attn_impl=attn_impl, compute_dtype=compute_dtype,
        )
        lg = logits[0]  # [S, V]
        nxt = sample_token_rows(
            lg[:S], pair[:, 1],
            temperature=temperature, top_p=top_p, top_k=top_k,
        )
        if recent.shape[1]:
            recent = jnp.concatenate([recent[:, 1:], tok[:, None]], axis=1)
        finished = finished | (tok == eos) | paged_kv_lib.stop_window_hit(
            recent, stop_sequences
        )
        nxt = jnp.where(finished, eos, nxt)
        cur_len = cur_len + (~finished).astype(jnp.int32)
        return (
            kv_pages, nxt, cur_len, finished, recent, pair[:, 0]
        ), (tok, finished)

    carry, (toks, fin) = jax.lax.scan(
        step, (kv_pages, tok, lengths, finished, recent, keys),
        None, length=k_steps * chunk,
    )
    kv_pages, tok, lengths, finished, recent, keys = carry
    return (
        kv_pages, tok, lengths, finished, recent, keys,
        jnp.moveaxis(toks, 0, 1), jnp.moveaxis(fin, 0, 1),
    )


# ---------------------------------------------------------------------------
# Speculative decoding: self-drafted multi-token steps, verified in one
# packed dispatch (docs/DESIGN.md "Speculative decoding")
# ---------------------------------------------------------------------------


class Drafter:
    """Pluggable draft-token proposer for speculative decoding.

    `propose(context, k)` returns UP TO `k` token ids predicted to
    continue `context` (the request's own confirmed stream: prompt ids
    + device-confirmed reply tokens + the pending fed token). Fewer —
    or zero — proposals are always legal: unproposed lanes of the
    verify dispatch ride masked, and a zero-draft step degenerates to
    the plain one-token decode. Implementations MUST be deterministic
    functions of `context` (eviction replay re-proposes from the same
    context and must re-derive the same accept pattern, or the replayed
    sample stream diverges from what the client already saw).

    The reference implementation is `NgramDrafter` (self-drafting — no
    second model); a small draft MODEL slots in by implementing this
    same method (propose = draft-model decode of k tokens).

    `window` (None = unbounded) declares how much context TAIL the
    drafter actually reads: the scheduler then materializes only that
    suffix per step instead of concatenating the full prompt + reply
    history — without a bound, proposal cost grows O(context) per slot
    per engine step, eroding the sequential-latency win speculation
    exists to buy. A fixed tail is still a deterministic function of
    the context, so replay stability is unaffected."""

    window: int | None = None

    def propose(self, context, k: int) -> list[int]:
        raise NotImplementedError

    # Device-side contract (opt-in): a drafter that can run ON the
    # accelerator — inside `paged_fused_steps`' speculative scan —
    # exposes its parameters as a pytree plus a module-level
    # `device_apply(params, ctx, ctx_len, fed, k) -> (drafts, draft_len)`
    # pure function. device_params() returning None means host-only:
    # the drafter works on the per-step path but cannot ride a fused
    # megastep (the scheduler rejects --fuse-steps > 1 + --speculate
    # for such drafters rather than silently falling back).
    device_apply = None

    def device_params(self):
        return None


class NgramDrafter(Drafter):
    """Prompt-lookup / n-gram self-drafting (arXiv 2605.25645's cheap
    lever for repetitive serving workloads — code, RAG, chat with
    quoting): find the MOST RECENT earlier occurrence of the longest
    suffix n-gram of the context and propose the tokens that followed
    it. No second model, no extra device work — the proposal is a pure
    host-side lookup against the request's own tokens, and the packed
    verify dispatch prices every proposal at one extra lane."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 window: int | None = 2048):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"{min_ngram=} {max_ngram=}"
            )
        if window is not None and window < max_ngram + 1:
            raise ValueError(
                f"window must cover at least one n-gram + continuation "
                f"(>= max_ngram + 1), got {window=} {max_ngram=}"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        # Lookup window (tokens of context tail searched): bounds the
        # per-step host cost at O(window) regardless of prompt/reply
        # length. Deterministic — replay sees the same tail at the
        # same confirmed position.
        self.window = window

    def propose(self, context, k: int) -> list[int]:
        a = np.asarray(context, np.int64).reshape(-1)
        if self.window is not None and a.shape[0] > self.window:
            a = a[-self.window:]
        n_ctx = int(a.shape[0])
        if k <= 0 or n_ctx < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, n_ctx - 1), self.min_ngram - 1,
                       -1):
            suf = a[-n:]
            w = n_ctx - n  # candidate starts 0..w-1 (w == the suffix itself)
            m = np.ones(w, bool)
            for j in range(n):
                m &= a[j: j + w] == suf[j]
            idx = np.nonzero(m)[0]
            if idx.size:
                i = int(idx[-1])  # most recent earlier occurrence
                cont = a[i + n: i + n + k]
                if cont.size:
                    return [int(x) for x in cont]
        return []


def spec_verify_rows(
    lg: jnp.ndarray,  # [S, k+1, V] verify-lane logits
    tok: jnp.ndarray,  # [S] fed token per slot (lane 0's input)
    drafts: jnp.ndarray,  # [S, k] proposed tokens (garbage past draft_len)
    draft_len: jnp.ndarray,  # [S] real proposals per slot (0..k)
    keys: jax.Array,  # [S] per-slot PRNG keys
    *,
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S]
    eos: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jax.Array]:
    """Accept/resample core of speculative decoding over verify-lane
    logits — pure math, shared by `paged_spec_step` and the
    distribution tests. Returns (acc [S], cand [S], keys_next [S]).

    Lane j's logits lg[s, j] are the model's distribution for the token
    at position len_s+j+1 (after feeding [tok, d_0..d_{k-1}]); drafts
    [s, j] is the proposal for that same position. Acceptance is the
    longest matching prefix:

      * greedy rows (temperature <= 0): d_j accepted iff it EQUALS the
        raw argmax target — accepted tokens are bit-identical to what
        sequential decode would have produced, which is the whole
        byte-parity claim.
      * sampled rows: point-mass rejection sampling. The drafter is
        deterministic, so the proposal distribution is q = delta(d_j);
        accept d_j with probability p'(d_j) where p' is the TRUNCATED
        target (same temperature/top-k/top-p shaping as
        `sample_token_rows`, via `truncate_logits_rows`); on rejection
        the bonus token is drawn from the residual max(p' - q, 0)/Z —
        for a point mass that is p' with d_j masked out, renormalized —
        so the marginal of the emitted token at every position is
        EXACTLY p' (the spec-vs-plain distribution test pins this).

    `acc` counts accepted drafts, truncated at the first accepted EOS
    (tokens "accepted" past an EOS never existed — the sequential path
    would have frozen the row) and forced to 0 when the fed token is
    itself EOS. `cand` is the bonus token at lane `acc` — the model's
    own next token at the first mismatch (or after all accepts), which
    becomes the next step's fed token. Key consumption is a FIXED
    2k+3 split per slot per step regardless of the accept pattern, so
    a row's RNG stream depends only on its own step count — the same
    per-row independence contract as `sample_token_rows`."""
    S, k = drafts.shape
    lanes = k + 1
    V = lg.shape[-1]
    tgt = jnp.argmax(lg, axis=-1).astype(jnp.int32)  # [S, lanes] raw greedy
    rep = lambda x: jnp.repeat(x, lanes)  # noqa: E731 — slot-major repeat
    l_t, _ = truncate_logits_rows(
        lg.reshape(S * lanes, V),
        temperature=rep(temperature), top_p=rep(top_p), top_k=rep(top_k),
    )
    l_t = l_t.reshape(S, lanes, V)
    is_greedy = temperature <= 0.0
    ks = jax.vmap(lambda key: jax.random.split(key, 2 * k + 3))(keys)
    if k:
        # Accept draws: one uniform per draft lane (ks[:, 2j]).
        u = jax.vmap(
            jax.vmap(lambda key: jax.random.uniform(key, ()))
        )(ks[:, 0:2 * k:2])  # [S, k]
        p = jax.nn.softmax(l_t[:, :k], axis=-1)
        p_d = jnp.take_along_axis(
            p, drafts[..., None].astype(jnp.int32), axis=-1
        )[..., 0]  # [S, k]
        ok = jnp.where(
            is_greedy[:, None], drafts == tgt[:, :k], u < p_d
        )
        jr = jnp.arange(k, dtype=jnp.int32)[None, :]
        ok = ok & (jr < draft_len[:, None])
        cum = jnp.cumprod(ok.astype(jnp.int32), axis=1)  # leading accepts
        # Truncate at the first ACCEPTED eos (inclusive): lanes after it
        # would extend a row the sequential path already froze.
        hit_eos = cum * (drafts == eos).astype(jnp.int32)
        eos_before = jnp.cumsum(hit_eos, axis=1) - hit_eos
        acc = jnp.sum(cum * (eos_before == 0), axis=1).astype(jnp.int32)
    else:
        acc = jnp.zeros_like(tok)
    acc = jnp.where(tok == eos, 0, acc)
    # Bonus lane b = acc: the model's own token at the first mismatch
    # (or the free extra token after a full accept).
    b = acc
    l_sel = jnp.take_along_axis(l_t, b[:, None, None], axis=1)[:, 0]
    tgt_sel = jnp.take_along_axis(tgt, b[:, None], axis=1)[:, 0]
    # Residual for a point-mass rejection: mask the rejected draft out
    # of the bonus draw (only when lane b actually carried a proposal).
    d_pad = jnp.concatenate(
        [drafts.astype(jnp.int32), jnp.full((S, 1), -1, jnp.int32)], axis=1
    )
    d_b = jnp.take_along_axis(d_pad, b[:, None], axis=1)[:, 0]
    rejected = b < draft_len
    l_res = jnp.where(
        rejected[:, None]
        & (jnp.arange(V, dtype=jnp.int32)[None] == d_b[:, None]),
        -jnp.inf, l_sel,
    )
    key_sel = jax.vmap(lambda row, i: row[i])(ks, 2 * b + 1)
    u2 = jax.vmap(lambda key: jax.random.uniform(key, (V,)))(key_sel)
    g = -jnp.log(-jnp.log(jnp.maximum(u2, jnp.finfo(jnp.float32).tiny)))
    cand_sample = jnp.argmax(l_res + g, axis=-1).astype(jnp.int32)
    cand = jnp.where(is_greedy, tgt_sel, cand_sample)
    return acc, cand, ks[:, -1]


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "k", "pf_width", "eos", "attn_impl", "compute_dtype",
    ),
    donate_argnames=("kv_pages",),
)
def paged_spec_step(
    params,
    cfg: LLMConfig,
    kv_pages: dict,  # donated
    block_tables: jnp.ndarray,  # [S, max_pages] int32
    tok: jnp.ndarray,  # [S] next token to feed per slot
    lengths: jnp.ndarray,  # [S] kv tokens held per slot (frozen on finish)
    finished: jnp.ndarray,  # [S] bool (True for finished AND empty slots)
    keys: jax.Array,  # [S] per-slot PRNG keys
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S]
    drafts: jnp.ndarray,  # [S, k] proposed draft tokens
    draft_len: jnp.ndarray,  # [S] real proposals per slot
    pf_embeds: jnp.ndarray,  # [1, pf_width, H] prefill window
    pf_slot: jnp.ndarray,  # [] int32 slot the prefill belongs to
    pf_off: jnp.ndarray,  # [] int32 logical offset of the window start
    pf_len: jnp.ndarray,  # [] int32 total prompt length (incl. prefix)
    pf_active: jnp.ndarray,  # [] bool — a prefill rides this dispatch
    pf_key: jax.Array,  # [1] the admitting request's key0
    pf_temp: jnp.ndarray,  # [1]
    pf_top_p: jnp.ndarray,  # [1]
    pf_top_k: jnp.ndarray,  # [1]
    *,
    k: int,
    pf_width: int,
    eos: int,
    attn_impl: str = "xla",
    compute_dtype=None,
):
    """ONE device dispatch for a SPECULATIVE mixed prefill+decode
    engine step: every live slot contributes 1+k packed verify lanes
    (its fed token plus k self-drafted continuations at consecutive
    positions) and the one admitting slot contributes `pf_width`
    prefill-suffix lanes — the whole fleet's drafts verified in a
    single packed forward through the SAME (segment, position) ragged
    kernel as `paged_ragged_step` (drafts are just extra packed rows;
    ops/paged_kv.spec_lane_metadata builds the routing).

    Unlike `paged_ragged_step`'s chunk-iteration scan, this is a single
    forward: the drafter is HOST-side (it needs the token history the
    device never holds), so each engine step proposes, verifies in one
    dispatch, and harvests — a slot advances 1..k+1 tokens per
    sequential step instead of 1, which is the whole latency lever
    (arXiv 2605.25645: interactive SLOs are bound by sequential steps,
    not per-step cost).

    KV discipline: all 1+k lanes write KV at positions len..len+k —
    always into the slot's EXCLUSIVELY-OWNED pages (the COW-at-splice
    invariant: shared prefix pages end strictly below the prompt, the
    partial boundary page is copy-on-written at admission, and finish-
    time donation is capped at the device-confirmed length — so a
    "scratch" region past cur_len needs no extra pages). Accepted
    drafts splice by advancing cur_len over KV already written;
    rejected drafts leave dead bytes past cur_len that causal masking
    never reads and the next real token overwrites before its first
    read. Rollback therefore frees nothing and copies nothing.

    The dispatch shape is STATIC per (S, k, pf_width) class — two
    compiled programs total (prefill lanes present/absent), exactly the
    ragged engine's contract; drafts/draft_len are traced operands.

    Returns (kv_pages, nxt, lengths, finished, keys, toks [S, k+1],
    n_new [S], acc [S], pf_tok0, pf_key_next): toks[s, :n_new[s]] are
    the tokens slot s emitted this step (fed token + accepted drafts,
    EOS-fill past n_new); nxt is the bonus token each slot feeds next
    step. Greedy rows are bit-identical to running `paged_ragged_step`
    n_new times (accept == argmax match, bonus == the argmax the
    sequential path would sample); see `spec_verify_rows` for the
    temperature>0 rejection-sampling contract."""
    from oryx_tpu.parallel.sharding import constrain

    S = tok.shape[0]
    lanes = k + 1
    W = pf_width

    def embed(ids):
        e = constrain(params["embed"]["weight"], None, None)[ids]
        return e.astype(compute_dtype) if compute_dtype is not None else e

    ids = jnp.concatenate(
        [tok[:, None], drafts.astype(jnp.int32)], axis=1
    )  # [S, lanes]
    dec_emb = embed(ids.reshape(S * lanes))
    seg, pos = paged_kv_lib.spec_lane_metadata(lengths, k)
    lane_j = jnp.tile(jnp.arange(lanes, dtype=jnp.int32), (S,))
    wm = (
        jnp.repeat(~finished, lanes)
        & (lane_j <= jnp.repeat(draft_len.astype(jnp.int32), lanes))
    )
    if W:
        pf_pos = pf_off + jnp.arange(W, dtype=jnp.int32)
        emb = jnp.concatenate(
            [dec_emb, pf_embeds[0].astype(dec_emb.dtype)], axis=0
        )
        pos = jnp.concatenate([pos, pf_pos])
        seg = jnp.concatenate(
            [seg, jnp.full((W,), 1, jnp.int32) * pf_slot]
        )
        wm = jnp.concatenate([wm, jnp.broadcast_to(pf_active, (W,))])
    else:
        emb = dec_emb
    logits, kv_pages = qwen2.forward(
        params, cfg,
        inputs_embeds=emb[None], positions=pos[None],
        kv_cache=kv_pages, block_tables=block_tables,
        q_segments=seg[None], write_mask=wm[None],
        attn_impl=attn_impl, compute_dtype=compute_dtype,
    )
    lg_all = logits[0]
    lg = lg_all[: S * lanes].reshape(S, lanes, -1)
    acc, cand, keys_next = spec_verify_rows(
        lg, tok, drafts, draft_len, keys,
        temperature=temperature, top_p=top_p, top_k=top_k, eos=eos,
    )
    jr = jnp.arange(k, dtype=jnp.int32)[None, :]
    accepted = jr < acc[:, None]
    out_toks = jnp.concatenate(
        [tok[:, None], jnp.where(accepted, drafts, eos)], axis=1
    )
    acc_eos = jnp.any(accepted & (drafts == eos), axis=1)
    fed_eos = tok == eos
    new_finished = finished | fed_eos | acc_eos
    n_new = jnp.where(finished, 0, 1 + acc)
    # cur_len counts confirmed non-EOS KV tokens, mirroring the
    # sequential step's `cur_len + ~finished` (EOS never increments).
    inc = jnp.where(
        finished | fed_eos, 0, 1 + acc - acc_eos.astype(jnp.int32)
    )
    nxt = jnp.where(new_finished, eos, cand)
    if W:
        # Prefill-lane sampling: the exact `paged_ragged_step` contract
        # (window seeded with the request's key0; only the window
        # containing the prompt's final token samples tok0).
        pf_pair = jax.vmap(lambda key: jax.random.split(key, 2))(pf_key)
        j = pf_len - 1 - pf_off
        present = pf_active & (j >= 0) & (j < W)
        row = jax.lax.dynamic_index_in_dim(
            lg_all, S * lanes + jnp.clip(j, 0, W - 1), axis=0,
            keepdims=True,
        )
        pf_cand = sample_token_rows(
            row, pf_pair[:, 1],
            temperature=pf_temp, top_p=pf_top_p, top_k=pf_top_k,
        )[0]
        pf_tok0 = jnp.where(present, pf_cand, jnp.zeros((), jnp.int32))
    else:
        pf_tok0 = jnp.zeros((), jnp.int32)
    pf_key_next = jax.vmap(lambda key: jax.random.split(key, 2))(
        pf_key
    )[:, 0]
    return (
        kv_pages, nxt, lengths + inc, new_finished, keys_next,
        out_toks, n_new, acc, pf_tok0, pf_key_next,
    )


# ---------------------------------------------------------------------------
# Trained draft model: tiny device-resident proposer behind the Drafter
# seam (docs/DESIGN.md "Fused multi-step decode" — the draft chain runs
# INSIDE the fused scan so propose->verify never leaves the chip)
# ---------------------------------------------------------------------------

# Positional decay of the context-mixing weights: token at distance d
# from the window's right edge contributes DRAFT_DECAY**d. Part of the
# checkpoint contract — changing it invalidates trained drafters.
DRAFT_DECAY = 0.9


def _draft_logits(params, buf: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """Next-token logits of the decayed-bag draft model.

    `buf` [S, W] is a RIGHT-ALIGNED token window (left-padded with
    anything; `n` [S] counts the valid tail entries). The model embeds
    the window, mixes it with exponentially-decayed weights anchored at
    the right edge, and projects to the vocabulary — one matmul pair,
    cheap enough to run k times per verify lane inside the fused scan.
    Pure function of (params, valid tail), so host and device callers
    produce bit-identical proposals from the same window."""
    W = buf.shape[1]
    idx = jnp.arange(W, dtype=jnp.int32)[None, :]
    valid = idx >= (W - n[:, None].astype(jnp.int32))
    w = jnp.power(
        jnp.float32(DRAFT_DECAY), (W - 1 - idx).astype(jnp.float32)
    ) * valid.astype(jnp.float32)  # [S, W]
    emb = params["embed"][jnp.clip(buf, 0)]  # [S, W, D] f32
    h = jnp.sum(w[..., None] * emb, axis=1) / jnp.maximum(
        jnp.sum(w, axis=1, keepdims=True), 1e-6
    )
    return h @ params["proj"]  # [S, V]


def _draft_chain(params, buf: jnp.ndarray, n: jnp.ndarray, *, k: int):
    """Greedy k-token draft chain: argmax, shift-append, repeat.

    Greedy by design — a deterministic proposer is what the Drafter
    replay contract requires, and speculative acceptance treats the
    proposal as a point mass regardless of how it was picked.
    Returns [S, k] int32 drafts."""

    def step(carry, _):
        buf, n = carry
        nxt = jnp.argmax(_draft_logits(params, buf, n), axis=-1)
        nxt = nxt.astype(jnp.int32)
        buf = jnp.concatenate([buf[:, 1:], nxt[:, None]], axis=1)
        n = jnp.minimum(n + 1, buf.shape[1])
        return (buf, n), nxt

    _, drafts = jax.lax.scan(step, (buf, n), None, length=k)
    return jnp.moveaxis(drafts, 0, 1)  # [S, k]


_draft_chain_jit = jax.jit(_draft_chain, static_argnames=("k",))


def neural_draft_propose(
    draft_params,
    ctx: jnp.ndarray,  # [S, W] right-aligned confirmed tail, EXCLUDING fed
    ctx_len: jnp.ndarray,  # [S] valid entries in ctx (0..W)
    fed: jnp.ndarray,  # [S] the fed token (lane 0 of the verify dispatch)
    k: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Device-side Drafter.device_apply for `NeuralDrafter`: shift the
    fed token into the window (the host Drafter contract hands propose()
    the confirmed stream INCLUDING the pending fed token) and run the
    greedy chain. Module-level so it is hashable as a jit static arg in
    `paged_fused_spec_steps`. Returns (drafts [S, k], draft_len [S]) —
    the chain always emits exactly k proposals."""
    buf = jnp.concatenate(
        [ctx[:, 1:], fed[:, None].astype(jnp.int32)], axis=1
    )
    n = jnp.minimum(ctx_len.astype(jnp.int32) + 1, ctx.shape[1])
    drafts = _draft_chain(draft_params, buf, n, k=k)
    return drafts, jnp.full(fed.shape, k, jnp.int32)


class NeuralDrafter(Drafter):
    """Tiny trained draft model (decayed-bag-of-embeddings -> vocab
    projection) implementing BOTH halves of the Drafter seam: the
    host-side `propose()` used by the per-step speculative path, and
    the `device_params()`/`device_apply` contract that lets
    `paged_fused_spec_steps` run the same chain inside the fused scan.
    Host and device call the SAME jitted `_draft_chain` math on the
    same right-aligned window, so proposals are bit-identical — the
    fused-vs-K=1 byte-parity claim for speculative serving rests on
    exactly that.

    Checkpoints are .npz files (embed [V, D] f32, proj [D, V] f32,
    window). `from_spec` accepts either a checkpoint path or
    "init:V:D:W:SEED" for a randomly-initialized model (useful for
    parity tests and smoke benches; a random drafter just accepts
    ~never, which is slow but CORRECT)."""

    def __init__(self, params: dict, window: int = 16,
                 source: str | None = None):
        embed = np.asarray(params["embed"], np.float32)
        proj = np.asarray(params["proj"], np.float32)
        if embed.ndim != 2 or proj.ndim != 2 or embed.shape[1] != \
                proj.shape[0] or embed.shape[0] != proj.shape[1]:
            raise ValueError(
                f"drafter params must be embed [V, D] / proj [D, V], got "
                f"{embed.shape} / {proj.shape}"
            )
        if window < 1:
            raise ValueError(f"drafter window must be >= 1, got {window}")
        self.params = {"embed": embed, "proj": proj}
        self.window = int(window)
        self.source = source

    @classmethod
    def init(cls, vocab_size: int, dim: int = 16, *, window: int = 16,
             seed: int = 0) -> "NeuralDrafter":
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
        return cls(
            {
                "embed": 0.02 * jax.random.normal(
                    k1, (vocab_size, dim), jnp.float32
                ),
                "proj": 0.02 * jax.random.normal(
                    k2, (dim, vocab_size), jnp.float32
                ),
            },
            window=window,
            source=f"init:{vocab_size}:{dim}:{window}:{seed}",
        )

    @classmethod
    def load(cls, path: str) -> "NeuralDrafter":
        with np.load(path) as z:
            return cls(
                {"embed": z["embed"], "proj": z["proj"]},
                window=int(z["window"]), source=str(path),
            )

    def save(self, path: str) -> None:
        np.savez(
            path, embed=self.params["embed"], proj=self.params["proj"],
            window=np.int64(self.window),
        )

    @classmethod
    def from_spec(cls, spec: str) -> "NeuralDrafter":
        """"init:V:D:W:SEED" -> random init; anything else -> npz path.
        The spec string is what gets stamped into the journal header
        (`draft_model`), so replay can rebuild the identical drafter."""
        if spec.startswith("init:"):
            parts = spec.split(":")
            if len(parts) != 5:
                raise ValueError(
                    f"drafter init spec must be init:V:D:W:SEED, got "
                    f"{spec!r}"
                )
            v, d, w, s = (int(p) for p in parts[1:])
            return cls.init(v, d, window=w, seed=s)
        return cls.load(spec)

    def device_params(self) -> dict:
        return {
            "embed": jnp.asarray(self.params["embed"]),
            "proj": jnp.asarray(self.params["proj"]),
        }

    device_apply = staticmethod(neural_draft_propose)

    def propose(self, context, k: int) -> list[int]:
        a = np.asarray(context, np.int64).reshape(-1)[-self.window:]
        if k <= 0 or a.size == 0:
            return []
        buf = np.zeros((1, self.window), np.int32)
        buf[0, self.window - a.size:] = a
        drafts = _draft_chain_jit(
            self.device_params(), jnp.asarray(buf),
            jnp.asarray([a.size], jnp.int32), k=k,
        )
        return [int(x) for x in np.asarray(drafts)[0]]


def fit_neural_drafter(
    streams,
    vocab_size: int,
    *,
    dim: int = 16,
    window: int = 16,
    epochs: int = 30,
    lr: float = 0.5,
    seed: int = 0,
) -> tuple["NeuralDrafter", list[float]]:
    """Train a NeuralDrafter on token streams (next-token cross-entropy,
    full-batch gradient descent). Deliberately tiny — the draft model's
    job is to beat n-gram lookup on non-repetitive tails, not to be a
    language model. Returns (drafter, per-epoch losses)."""
    bufs, ns, tgts = [], [], []
    for stream in streams:
        a = np.asarray(stream, np.int64).reshape(-1)
        for t in range(1, a.size):
            ctx = a[max(0, t - window): t]
            row = np.zeros((window,), np.int32)
            row[window - ctx.size:] = ctx
            bufs.append(row)
            ns.append(ctx.size)
            tgts.append(a[t])
    if not bufs:
        raise ValueError("fit_neural_drafter needs at least one 2-token "
                         "stream")
    buf = jnp.asarray(np.stack(bufs))
    n = jnp.asarray(np.asarray(ns, np.int32))
    tgt = jnp.asarray(np.asarray(tgts, np.int32))
    drafter = NeuralDrafter.init(
        vocab_size, dim, window=window, seed=seed
    )
    params = drafter.device_params()

    def loss_fn(p):
        lg = _draft_logits(p, buf, n)
        return -jnp.mean(
            jnp.take_along_axis(
                jax.nn.log_softmax(lg, axis=-1), tgt[:, None], axis=1
            )
        )

    step = jax.jit(
        lambda p: (loss_fn(p), jax.grad(loss_fn)(p))
    )
    losses = []
    for _ in range(epochs):
        loss, g = step(params)
        params = {k: v - lr * g[k] for k, v in params.items()}
        losses.append(float(loss))
    out = NeuralDrafter(
        {k: np.asarray(v) for k, v in params.items()}, window=window,
        source=f"fit:{vocab_size}:{dim}:{window}:{seed}",
    )
    return out, losses


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "k", "k_steps", "eos", "attn_impl", "compute_dtype",
        "draft_apply",
    ),
    donate_argnames=("kv_pages",),
)
def paged_fused_spec_steps(
    params,
    cfg: LLMConfig,
    kv_pages: dict,  # donated
    block_tables: jnp.ndarray,  # [S, max_pages] int32
    tok: jnp.ndarray,  # [S] next token to feed per slot
    lengths: jnp.ndarray,  # [S] kv tokens held per slot
    finished: jnp.ndarray,  # [S] bool
    keys: jax.Array,  # [S] per-slot PRNG keys
    temperature: jnp.ndarray,  # [S]
    top_p: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,  # [S]
    draft_params,  # drafter.device_params() pytree
    draft_ctx: jnp.ndarray,  # [S, CW] right-aligned confirmed tail (no fed)
    draft_ctx_len: jnp.ndarray,  # [S] valid entries in draft_ctx
    *,
    k: int,
    k_steps: int,
    eos: int,
    attn_impl: str = "xla",
    compute_dtype=None,
    draft_apply,
):
    """ONE device dispatch for K=`k_steps` SPECULATIVE pure-decode
    engine steps: each scan iteration drafts k tokens on-device via
    `draft_apply` (the Drafter's device contract — same math as its
    host `propose()`), verifies them through the same packed forward
    as `paged_spec_step`'s pure-decode branch, splices accepts /
    rolls back rejects, and shifts the confirmed tokens into the
    draft-context carry. Propose->verify->rollback never touches the
    host until the K-step harvest.

    Parity contract: iteration j's math is `paged_spec_step` (W=0)
    verbatim — same spec_verify_rows key discipline (fixed 2k+3 split
    per slot per step), same accept/EOS-truncation/rollback algebra —
    and the in-scan context update reproduces exactly the confirmed
    stream the host-side `_propose_drafts` would have assembled
    between dispatches. So K fused speculative steps emit the same
    bytes as K sequential `paged_spec_step` dispatches with the same
    drafter. The context carry is NOT returned: the host rebuilds it
    from its own confirmed stream before the next megastep, which
    keeps the harvest surface identical to the per-step spec path.

    Returns (kv_pages, tok, lengths, finished, keys,
    toks [S, k_steps*(k+1)], n_new [S, k_steps], acc [S, k_steps]) —
    logical step j owns toks[:, j*(k+1):(j+1)*(k+1)], of which the
    first n_new[:, j] are real emissions."""
    from oryx_tpu.parallel.sharding import constrain

    S = tok.shape[0]
    lanes = k + 1
    CW = draft_ctx.shape[1]

    def embed(ids):
        e = constrain(params["embed"]["weight"], None, None)[ids]
        return e.astype(compute_dtype) if compute_dtype is not None else e

    def step(carry, _):
        kv_pages, tok, lengths, finished, keys, ctx, clen = carry
        drafts, dlen = draft_apply(draft_params, ctx, clen, tok, k)
        ids = jnp.concatenate(
            [tok[:, None], drafts.astype(jnp.int32)], axis=1
        )
        dec_emb = embed(ids.reshape(S * lanes))
        seg, pos = paged_kv_lib.spec_lane_metadata(lengths, k)
        lane_j = jnp.tile(jnp.arange(lanes, dtype=jnp.int32), (S,))
        wm = (
            jnp.repeat(~finished, lanes)
            & (lane_j <= jnp.repeat(dlen.astype(jnp.int32), lanes))
        )
        logits, kv_pages = qwen2.forward(
            params, cfg,
            inputs_embeds=dec_emb[None], positions=pos[None],
            kv_cache=kv_pages, block_tables=block_tables,
            q_segments=seg[None], write_mask=wm[None],
            attn_impl=attn_impl, compute_dtype=compute_dtype,
        )
        lg = logits[0][: S * lanes].reshape(S, lanes, -1)
        acc, cand, keys_next = spec_verify_rows(
            lg, tok, drafts, dlen, keys,
            temperature=temperature, top_p=top_p, top_k=top_k, eos=eos,
        )
        jr = jnp.arange(k, dtype=jnp.int32)[None, :]
        accepted = jr < acc[:, None]
        out_toks = jnp.concatenate(
            [tok[:, None], jnp.where(accepted, drafts, eos)], axis=1
        )
        acc_eos = jnp.any(accepted & (drafts == eos), axis=1)
        fed_eos = tok == eos
        new_finished = finished | fed_eos | acc_eos
        n_new = jnp.where(finished, 0, 1 + acc)
        inc = jnp.where(
            finished | fed_eos, 0, 1 + acc - acc_eos.astype(jnp.int32)
        )
        nxt = jnp.where(new_finished, eos, cand)
        # Shift this step's confirmed tokens (fed + accepted drafts)
        # into the right-aligned window — what the host would have fed
        # the drafter next step. Frozen rows have n_new == 0: no shift.
        ext = jnp.concatenate([ctx, out_toks.astype(jnp.int32)], axis=1)
        ctx = jnp.take_along_axis(
            ext,
            n_new[:, None] + jnp.arange(CW, dtype=jnp.int32)[None, :],
            axis=1,
        )
        clen = jnp.minimum(clen + n_new, CW)
        return (
            kv_pages, nxt, lengths + inc, new_finished, keys_next, ctx,
            clen,
        ), (out_toks, n_new, acc)

    carry, (toks, n_new, acc) = jax.lax.scan(
        step,
        (kv_pages, tok, lengths, finished, keys, draft_ctx,
         draft_ctx_len.astype(jnp.int32)),
        None, length=k_steps,
    )
    kv_pages, tok, lengths, finished, keys, _, _ = carry
    return (
        kv_pages, tok, lengths, finished, keys,
        jnp.moveaxis(toks, 0, 1).reshape(S, k_steps * lanes),
        jnp.moveaxis(n_new, 0, 1), jnp.moveaxis(acc, 0, 1),
    )


@dataclasses.dataclass
class PagedState:
    """Host half of a paged decode: the device page pool plus the
    block tables and free-list that address it. Returned by
    `generate_paged(return_state=True)` for cross-turn prefix reuse;
    owned by serve/scheduler.py for continuous batching."""

    kv_pages: dict
    block_tables: np.ndarray  # [B, max_pages] int32 (sentinel-padded)
    allocator: "paged_kv_lib.PageAllocator"

    @property
    def page_size(self) -> int:
        return self.allocator.page_size


def _grow_block_tables(
    state: PagedState, row_tokens: list[int], max_pages: int
) -> np.ndarray:
    """Ensure each row's block table covers row_tokens[b] logical slots,
    allocating from the state's free list; widens the table to
    `max_pages` columns (sentinel-padded). Raises OutOfPagesError with
    nothing allocated if the pool cannot satisfy the TOTAL ask."""
    alloc = state.allocator
    bt = state.block_tables
    B, old = bt.shape
    out = np.full((B, max_pages), alloc.sentinel, np.int32)
    out[:, : min(old, max_pages)] = bt[:, : min(old, max_pages)]
    if old > max_pages:
        # Narrowing (a later turn with a smaller window): pages past the
        # new width would silently vanish from the table — return them
        # to the free list instead of leaking them.
        dropped = [
            int(p) for b in range(B) for p in bt[b, max_pages:]
            if p != alloc.sentinel
        ]
        if dropped:
            alloc.free(dropped)
    held = [int((out[b] != alloc.sentinel).sum()) for b in range(B)]
    need = [
        max(0, alloc.pages_for(row_tokens[b]) - held[b]) for b in range(B)
    ]
    if sum(need) > alloc.num_free:
        raise paged_kv_lib.OutOfPagesError(
            f"need {sum(need)} pages, {alloc.num_free} free"
        )
    for b in range(B):
        pages = alloc.alloc(need[b])
        out[b, held[b]: held[b] + need[b]] = pages
    state.block_tables = out
    return out


# hot-path
def generate_paged(
    params,
    cfg: LLMConfig,
    gen_cfg: GenerationConfig,
    *,
    inputs_embeds: jnp.ndarray,  # [B, T, H] (suffix only when `start`)
    lengths: jnp.ndarray,  # [B] real TOTAL lengths (incl. cached prefix)
    max_new_tokens: int,
    page_size: int = 64,
    chunk: int = 8,
    kv_capacity: int | None = None,
    num_pages: int | None = None,
    key: jax.Array | None = None,
    attn_impl: str = "xla",
    compute_dtype=None,
    stop_sequences: jnp.ndarray | None = None,
    state: PagedState | None = None,
    start: jnp.ndarray | None = None,
    return_state: bool = False,
    prefill_chunk: int | None = None,
    mesh=None,
    ragged: bool = False,
    kv_dtype: str | None = None,
):
    """`generate`, but over a paged KV cache in `chunk`-step compiled
    dispatches — the reference driver for the continuous-batching path
    (the scheduler runs the same `paged_prefill`/`paged_decode_chunk`
    programs with slots owned by different requests).

    kv_dtype: None/"bf16" = dense pages in the compute dtype (today's
    byte-exact path); "int8" = quantized pool with per-page scale
    blocks (qwen2.init_paged_kv_cache kv_dtype=) — quantize on page
    write, dequantize in the page walk; replies drift within the
    utils/quant.roundtrip_error_stats envelope instead of matching the
    dense path bit-for-bit. Ignored when a prior `state` is passed
    (the pool already exists).

    ragged: route every decode chunk through `paged_ragged_step` — the
    PACKED one-dispatch program (all rows ride one [1, B] query buffer
    with per-token segments instead of a [B, 1] batch) the continuous
    engine uses to fuse prefill and decode. Greedy token ids are
    bit-identical to ragged=False (per-row math is batch-layout
    independent); this is the standalone parity hook for the fused
    serving path (tests/test_ragged_attention.py).

    Greedy token ids are bit-identical to `generate` when `kv_capacity`
    matches the dense call's `cache_len` (identical fp32 reductions;
    masked kv columns contribute exact zeros either way). Sampled
    streams draw from per-row keys and so differ from the dense batch
    sampler by construction.

    kv_capacity: logical KV width per row (max_pages = kv_capacity /
    page_size); defaults to the bucket of max(lengths) + the chunk-
    padded decode window. num_pages: pool size; defaults to the exact
    ragged need — sum over rows of ceil((length + window) / page_size),
    which is the whole point: a short row costs its own pages, not the
    batch max. state/start: prefix KV reuse as in `generate`
    (kv_cache/start); pass the state from the previous turn and prefill
    only the suffix embeds. prefill_chunk: prefill in bounded windows
    via `paged_prefill_chunks` (bit-identical to single-shot; requires a
    uniform `start` across rows).

    mesh: tensor-parallel decode. A fresh page pool is placed with KV
    heads sharded over the mesh's tp axis
    (parallel/sharding.shard_paged_kv) and every dispatch runs inside
    the mesh scope, so GSPMD partitions attention by heads against
    tp-sharded params (builder.serving_param_shardings). Greedy token
    ids stay bit-identical to the single-device paged path: each shard
    computes its own heads' attention exactly as before, and the only
    cross-shard reduction (o_proj over heads) is the contraction the
    sharded dense path already proves. Callers passing a prior `state`
    own its placement."""
    from oryx_tpu.parallel.sharding import mesh_scope, shard_paged_kv

    def scope():
        return mesh_scope(mesh)  # fresh context manager per dispatch

    B, T, _ = inputs_embeds.shape
    if key is None:
        key = jax.random.key(0)
    padded_new = -(-max_new_tokens // chunk) * chunk
    lengths = jnp.asarray(lengths, jnp.int32)
    # Page-geometry decisions (block-table growth) are host-side by
    # design; one pre-loop copy of the row lengths, not a per-step sync.
    host_len = [int(x) for x in np.asarray(lengths)]  # oryxlint: disable=host-sync
    row_tokens = [n + padded_new for n in host_len]
    if kv_capacity is None:
        from oryx_tpu.ops.packing import round_up_bucket

        kv_capacity = round_up_bucket(max(row_tokens))
    if kv_capacity % page_size:
        raise ValueError(f"{kv_capacity=} not a multiple of {page_size=}")
    max_pages = kv_capacity // page_size
    dtype = compute_dtype or jnp.float32

    if state is None:
        if num_pages is None:
            alloc_probe = paged_kv_lib.PageAllocator(1, page_size)
            num_pages = sum(alloc_probe.pages_for(n) for n in row_tokens)
        allocator = paged_kv_lib.PageAllocator(num_pages, page_size)
        kv_pages = qwen2.init_paged_kv_cache(
            cfg, num_pages, page_size, dtype=dtype, kv_dtype=kv_dtype
        )
        if mesh is not None:
            kv_pages = shard_paged_kv(kv_pages, mesh)
        state = PagedState(
            kv_pages=kv_pages,
            block_tables=np.full((B, max_pages), allocator.sentinel,
                                 np.int32),
            allocator=allocator,
        )
    elif state.block_tables.shape[0] != B:
        raise ValueError(
            f"state holds {state.block_tables.shape[0]} rows, batch has {B}"
        )
    bt_host = _grow_block_tables(state, row_tokens, max_pages)
    bt = jnp.asarray(bt_host)

    start_vec = (
        jnp.zeros((B,), jnp.int32)
        if start is None
        else jnp.broadcast_to(jnp.asarray(start, jnp.int32), (B,))
    )
    temp = jnp.full((B,), gen_cfg.temperature, jnp.float32)
    top_p = jnp.full((B,), gen_cfg.top_p, jnp.float32)
    top_k = jnp.full((B,), gen_cfg.top_k, jnp.int32)
    key, sk = jax.random.split(key)
    row_keys = jax.random.split(sk, B)
    if prefill_chunk:
        # One admission-time validation read, outside the decode loop.
        starts = set(int(x) for x in np.asarray(start_vec))  # oryxlint: disable=host-sync
        if len(starts) != 1:
            raise ValueError(
                f"prefill_chunk needs one shared start, got {sorted(starts)}"
            )
        with scope():
            state.kv_pages, tok, row_keys = paged_prefill_chunks(
                params, cfg, inputs_embeds, lengths, bt, state.kv_pages,
                starts.pop(), row_keys, temp, top_p, top_k,
                prefill_chunk=prefill_chunk, attn_impl=attn_impl,
                compute_dtype=compute_dtype,
            )
    else:
        with scope():
            state.kv_pages, tok, row_keys = paged_prefill(
                params, cfg, inputs_embeds, lengths, bt, state.kv_pages,
                start_vec, row_keys, temp, top_p, top_k,
                attn_impl=attn_impl, compute_dtype=compute_dtype,
            )
    stop_L = 0 if stop_sequences is None else stop_sequences.shape[1]
    recent = jnp.full((B, stop_L), -2, jnp.int32)
    finished = jnp.zeros((B,), bool)
    cur_len = lengths
    eos = gen_cfg.eos_token_id
    toks_out = np.full((B, padded_new), eos, np.int32)
    fin_out = np.ones((B, padded_new), bool)
    H = inputs_embeds.shape[2]
    ragged_blanks = dict(
        pf_embeds=jnp.zeros((1, 0, H), inputs_embeds.dtype),
        pf_slot=jnp.asarray(0, jnp.int32),
        pf_off=jnp.asarray(0, jnp.int32),
        pf_len=jnp.asarray(0, jnp.int32),
        pf_active=jnp.asarray(False),
        pf_temp=jnp.zeros((1,), jnp.float32),
        pf_top_p=jnp.ones((1,), jnp.float32),
        pf_top_k=jnp.zeros((1,), jnp.int32),
    )
    done = 0
    while done < max_new_tokens:
        with scope():
            if ragged:
                (state.kv_pages, tok, cur_len, finished, recent,
                 row_keys, toks, fin, _, _) = paged_ragged_step(
                    params, cfg, state.kv_pages, bt, tok, cur_len,
                    finished, recent, row_keys, temp, top_p, top_k,
                    stop_sequences, pf_key=row_keys[:1],
                    **ragged_blanks,
                    chunk=chunk, pf_width=0, eos=eos,
                    attn_impl=attn_impl, compute_dtype=compute_dtype,
                )
            else:
                (state.kv_pages, tok, cur_len, finished, recent,
                 row_keys, toks, fin) = paged_decode_chunk(
                    params, cfg, state.kv_pages, bt, tok, cur_len,
                    finished, recent, row_keys, temp, top_p, top_k,
                    stop_sequences,
                    chunk=chunk, eos=eos, attn_impl=attn_impl,
                    compute_dtype=compute_dtype,
                )
        # The once-per-chunk harvest this loop exists to amortize (and
        # the early-exit below needs host booleans).
        # oryxlint: off=host-sync
        toks_out[:, done:done + chunk] = np.asarray(toks)
        fin_out[:, done:done + chunk] = np.asarray(fin)
        # oryxlint: on=host-sync
        done += chunk
        if fin_out[:, done - 1].all():
            break
    toks_out = toks_out[:, :max_new_tokens]
    fin_out = fin_out[:, :max_new_tokens]
    any_fin = fin_out.any(axis=1)
    num = np.where(
        any_fin, fin_out.argmax(axis=1) + 1, max_new_tokens
    ).astype(np.int32)
    out = (jnp.asarray(toks_out), jnp.asarray(num), jnp.asarray(any_fin))
    return out + (state,) if return_state else out
