"""Autoregressive generation: jitted prefill + lax.scan decode loop.

Reference parity: HF `generate()` as driven by `OryxQwenForCausalLM`
(SURVEY.md §3.2): greedy or sampled decoding with a KV cache, stopping on
EOS. TPU-first: the whole decode loop is ONE compiled program (`lax.scan`
over steps, no host round-trip per token); right-padded batches advance
with per-row positions, so mixed-length multimodal prefills need no
left-padding shuffle.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from oryx_tpu.config import GenerationConfig, LLMConfig
from oryx_tpu.models import qwen2


def sample_token(
    logits: jnp.ndarray,
    key: jax.Array,
    *,
    temperature: float,
    top_p: float,
    top_k: int,
) -> jnp.ndarray:
    """Sample next token ids from [B, V] logits. temperature==0 → greedy."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative prob >= top_p (always
        # keeps the top token).
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "gen_cfg", "max_new_tokens", "cache_len", "attn_impl",
        "compute_dtype",
    ),
)
def generate(
    params,
    cfg: LLMConfig,
    gen_cfg: GenerationConfig,
    *,
    inputs_embeds: jnp.ndarray,  # [B, T, H] (pre-spliced; right-padded)
    lengths: jnp.ndarray,  # [B] real prompt lengths
    max_new_tokens: int,
    cache_len: int,
    key: jax.Array | None = None,
    attn_impl: str = "xla",
    compute_dtype=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (tokens [B, max_new_tokens] int32, num_generated [B] int32).

    Slots after EOS are filled with eos_token_id. cache_len must be a bucket
    >= T + max_new_tokens.
    """
    B, T, _ = inputs_embeds.shape
    assert cache_len >= T + max_new_tokens, (cache_len, T, max_new_tokens)
    if key is None:
        key = jax.random.key(0)

    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    slot_ar = jnp.arange(cache_len, dtype=jnp.int32)[None, :]
    kv_mask = (slot_ar < lengths[:, None]).astype(jnp.int32)

    cache = qwen2.init_kv_cache(
        cfg, B, cache_len,
        dtype=compute_dtype or jnp.float32,
    )
    logits, cache = qwen2.forward(
        params, cfg,
        inputs_embeds=inputs_embeds, positions=positions,
        kv_cache=cache, write_slots=jnp.zeros((B,), jnp.int32),
        kv_mask=kv_mask, attn_impl=attn_impl, compute_dtype=compute_dtype,
    )
    # Last real logit per row (right padding ⇒ index lengths-1).
    last = jnp.take_along_axis(
        logits, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]

    key, sk = jax.random.split(key)
    tok0 = sample_token(
        last, sk, temperature=gen_cfg.temperature, top_p=gen_cfg.top_p,
        top_k=gen_cfg.top_k,
    )

    def step(carry, step_key):
        cache, tok, cur_len, finished = carry
        pos = cur_len[:, None]  # [B, 1] absolute position of tok
        kv_mask = (slot_ar <= cur_len[:, None]).astype(jnp.int32)
        logits, cache = qwen2.forward(
            params, cfg,
            input_ids=tok[:, None], positions=pos,
            kv_cache=cache, write_slots=cur_len,
            kv_mask=kv_mask, attn_impl=attn_impl,
            compute_dtype=compute_dtype,
        )
        nxt = sample_token(
            logits[:, 0], step_key, temperature=gen_cfg.temperature,
            top_p=gen_cfg.top_p, top_k=gen_cfg.top_k,
        )
        finished = jnp.logical_or(finished, tok == gen_cfg.eos_token_id)
        nxt = jnp.where(finished, gen_cfg.eos_token_id, nxt)
        return (cache, nxt, cur_len + 1, finished), tok

    init = (cache, tok0, lengths, jnp.zeros((B,), bool))
    step_keys = jax.random.split(key, max_new_tokens)
    (_, _, _, finished), toks = jax.lax.scan(init=init, f=step, xs=step_keys)
    toks = jnp.moveaxis(toks, 0, 1)  # [B, max_new_tokens]
    # num generated = tokens up to and including first EOS.
    is_eos = toks == gen_cfg.eos_token_id
    first_eos = jnp.argmax(is_eos, axis=1)
    any_eos = jnp.any(is_eos, axis=1)
    num = jnp.where(any_eos, first_eos + 1, max_new_tokens)
    return toks, num.astype(jnp.int32)
