"""Multimodal splicing: interleave visual embeddings into the token stream.

Reference parity: `prepare_inputs_labels_for_multimodal` in
`oryx/model/oryx_arch.py` (SURVEY.md §2 "Multimodal arch / splicing", §3.4)
— the reference's single biggest function, a per-sample Python loop that
splits `input_ids` at IMAGE_TOKEN_INDEX sentinels and concatenates text and
visual embeddings. That formulation is shape-dynamic and cannot jit.

TPU-first formulation (SURVEY.md §7 hard part 4): the *host* computes an
index map once per batch (cheap numpy bookkeeping — visual token counts are
known from packing metadata before any model runs), and the *device* builds
`inputs_embeds` with a single static-shape select-gather:

    embeds[b, t] = is_visual[b, t] ? visual_buffer[visual_idx[b, t]]
                                   : embed_table[token_ids[b, t]]

The visual buffer is the Dynamic Compressor's packed output [Q, H_llm] for
the whole batch (one ViT + one compressor call for all images of all
samples — the same batching win the reference gets from varlen flash-attn).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax.numpy as jnp

from oryx_tpu.constants import IGNORE_INDEX, IMAGE_TOKEN_INDEX
from oryx_tpu.ops.packing import DEFAULT_BUCKETS, PackedVisual, round_up_bucket
from oryx_tpu.parallel.sharding import constrain


def frame_separator_ids(tokenizer, frame_separator: str | None) -> tuple[int, ...]:
    """Tokenize OryxConfig.frame_separator into the sep_ids tuple for
    expand_video_sentinels. The ONE tokenization policy for the hook —
    serving (pipeline) and training (train/cli) both call this, so a
    policy tweak can never skew train vs serve layout."""
    if not frame_separator:
        return ()
    return tuple(
        int(t)
        for t in tokenizer.encode(frame_separator, add_special_tokens=False)
    )


def expand_video_sentinels(
    ids: np.ndarray,
    n_frames: int,
    *,
    labels: np.ndarray | None = None,
    sep_ids: tuple[int, ...] = (),
) -> tuple[np.ndarray, np.ndarray | None]:
    """Expand a video's single IMAGE_TOKEN_INDEX placeholder into one
    sentinel per frame, optionally followed by separator token ids after
    EACH frame (the LLaVA-NeXT image-newline convention).

    Reference parity hook (SURVEY.md §3.4 "optional per-frame
    separators/newlines", exp `oryx/model/oryx_arch.py`): default OFF
    (`sep_ids=()` reproduces the plain contiguous-sentinel layout). The
    flag is `OryxConfig.frame_separator` — a string tokenized by the
    caller — so reference behavior can be matched without surgery once
    the real checkpoint/template is readable.

    Inserted positions get IGNORE_INDEX labels. Shared by the serving
    path (pipeline._prepare_request) and the training collator
    (train/data.collate) so train and serve always agree on layout.
    """
    ids = np.asarray(ids)
    idx = int(np.where(ids == IMAGE_TOKEN_INDEX)[0][0])
    per_frame = [IMAGE_TOKEN_INDEX, *sep_ids]
    mid = np.asarray(per_frame * n_frames, ids.dtype)
    out = np.concatenate([ids[:idx], mid, ids[idx + 1:]])
    out_labels = None
    if labels is not None:
        labels = np.asarray(labels)
        out_labels = np.concatenate(
            [labels[:idx],
             np.full(len(mid), IGNORE_INDEX, labels.dtype),
             labels[idx + 1:]]
        )
    return out, out_labels


def query_slots(packed: PackedVisual) -> list[tuple[int, int]]:
    """Per-image (start, count) slots in the packed query buffer, in pack
    order. Derived from q_grids (queries are image-major, contiguous)."""
    slots = []
    start = 0
    for hq, wq in packed.q_grids:
        slots.append((start, hq * wq))
        start += hq * wq
    return slots


@dataclasses.dataclass
class MMBatch:
    """Static-shape spliced batch (host numpy; feed to device as-is).

    token_ids  [B, T] int32 — text token id per slot (0 at visual/pad slots)
    visual_idx [B, T] int32 — index into the packed visual buffer (0 if n/a)
    is_visual  [B, T] bool
    attn_mask  [B, T] int32 — 1 on real (text or visual) slots
    positions  [B, T] int32 — 0..len-1 per row (0 on pads)
    labels     [B, T] int32 — next-token targets aligned to slots
                               (IGNORE_INDEX on visual spans, prompt & pads)
    lengths    [B] int32 — real length per row
    """

    token_ids: np.ndarray
    visual_idx: np.ndarray
    is_visual: np.ndarray
    attn_mask: np.ndarray
    positions: np.ndarray
    labels: np.ndarray
    lengths: np.ndarray


def build_mm_batch(
    input_ids: list[np.ndarray],
    image_slots: list[tuple[int, int]],
    *,
    labels: list[np.ndarray] | None = None,
    max_len: int | None = None,
    buckets: tuple[int, ...] = DEFAULT_BUCKETS,
) -> MMBatch:
    """Build the spliced index map for a batch.

    input_ids: per-sample int arrays containing IMAGE_TOKEN_INDEX sentinels;
      sentinels are consumed left-to-right against `image_slots` (the global
      per-image (start, count) ranges from `query_slots`, ordered across the
      whole batch: sample 0's images first, then sample 1's, ...).
    labels: optional per-sample arrays aligned with input_ids (sentinel
      positions ignored); visual spans and pads become IGNORE_INDEX.
    max_len: truncate rows to this many slots (model_max_length-equivalent).
    """
    img_iter = iter(image_slots)
    rows = []
    for si, ids in enumerate(input_ids):
        ids = np.asarray(ids)
        lab = None if labels is None else np.asarray(labels[si])
        tok, vidx, isv, lb = [], [], [], []
        for j, t in enumerate(ids):
            if t == IMAGE_TOKEN_INDEX:
                start, count = next(img_iter)
                tok.extend([0] * count)
                vidx.extend(range(start, start + count))
                isv.extend([True] * count)
                lb.extend([IGNORE_INDEX] * count)
            else:
                tok.append(int(t))
                vidx.append(0)
                isv.append(False)
                lb.append(IGNORE_INDEX if lab is None else int(lab[j]))
        if max_len is not None:
            tok, vidx, isv, lb = (x[:max_len] for x in (tok, vidx, isv, lb))
        rows.append((tok, vidx, isv, lb))

    remaining = sum(1 for _ in img_iter)
    if remaining:
        raise ValueError(f"{remaining} image slot(s) had no sentinel consumer")

    B = len(rows)
    T = round_up_bucket(max(len(r[0]) for r in rows), buckets)
    out = MMBatch(
        token_ids=np.zeros((B, T), np.int32),
        visual_idx=np.zeros((B, T), np.int32),
        is_visual=np.zeros((B, T), bool),
        attn_mask=np.zeros((B, T), np.int32),
        positions=np.zeros((B, T), np.int32),
        labels=np.full((B, T), IGNORE_INDEX, np.int32),
        lengths=np.zeros((B,), np.int32),
    )
    for b, (tok, vidx, isv, lb) in enumerate(rows):
        n = len(tok)
        out.token_ids[b, :n] = tok
        out.visual_idx[b, :n] = vidx
        out.is_visual[b, :n] = isv
        out.attn_mask[b, :n] = 1
        out.positions[b, :n] = np.arange(n)
        out.labels[b, :n] = lb
        out.lengths[b] = n
    # Shift labels: label[t] supervises the prediction made AT slot t for
    # slot t+1 (standard causal LM shift, done once here so the loss is a
    # plain masked CE with no further shifting).
    out.labels = np.concatenate(
        [out.labels[:, 1:], np.full((B, 1), IGNORE_INDEX, np.int32)], axis=1
    )
    return out


def embed_spliced(
    embed_table: jnp.ndarray,
    visual_buffer: jnp.ndarray,
    token_ids: jnp.ndarray,
    visual_idx: jnp.ndarray,
    is_visual: jnp.ndarray,
) -> jnp.ndarray:
    """Device-side: build [B, T, H] inputs_embeds with one select-gather.

    embed_table: [V, H]; visual_buffer: [Q, H] (compressor output).

    The gathers read from replicated tables: without the constraints GSPMD
    lets the gather output inherit the fsdp/tp-sharded table layout and
    then full-rematerializes it to the batch-sharded activation spec
    ("[SPMD] Involuntary full rematerialization"). All-gathering the
    tables first (standard FSDP use-site gather) makes the downstream
    reshard a local slice.
    """
    text = constrain(embed_table, None, None)[token_ids]
    vis = constrain(visual_buffer, None, None)[visual_idx].astype(text.dtype)
    out = jnp.where(is_visual[..., None], vis, text)
    return constrain(out, ("dp", "fsdp"), None, None)
