"""Oryx multimodal model: OryxViT + Dynamic Compressor + Qwen2/Yi decoder.

Reference parity: `OryxQwenForCausalLM` + `OryxMetaForCausalLM`
(`oryx/model/language_model/oryx_qwen.py`, `oryx/model/oryx_arch.py`;
SURVEY.md §1 L1c/L1d). The reference threads `images=` kwargs through HF
`forward`/`generate`; here the visual encode, splice, decoder forward and
decode loop are separate pure functions composed under one jit, all
operating on the static-shape packed buffers from ops/packing.py +
models/splice.py.

Param tree: {"llm": qwen2 params, "vit": oryx_vit params,
             "compressor": compressor params}.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.config import OryxConfig
from oryx_tpu.models import compressor as compressor_lib
from oryx_tpu.models import generate as generate_lib
from oryx_tpu.models import oryx_vit, qwen2, splice
from oryx_tpu.ops.packing import PackedVisual, round_up_bucket

Params = dict[str, Any]


def init_params(cfg: OryxConfig, key: jax.Array, dtype=jnp.float32) -> Params:
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "llm": qwen2.init_params(cfg.llm, k1, dtype),
        "vit": oryx_vit.init_params(cfg.vision, k2, dtype),
        "compressor": compressor_lib.init_params(
            cfg.compressor, cfg.vision, cfg.llm, k3, dtype
        ),
    }


def enable_lora(params: Params, cfg: OryxConfig, key: jax.Array) -> Params:
    """Attach LoRA adapters to the decoder (reference `lora_enable`)."""
    return {
        **params,
        "llm": qwen2.add_lora_params(
            params["llm"], cfg.llm, cfg.train.lora, key
        ),
    }


def merge_lora(params: Params) -> Params:
    """Fold trained adapters into the decoder kernels for serving."""
    return {**params, "llm": qwen2.merge_lora_params(params["llm"])}


def encode_visual(
    params: Params,
    cfg: OryxConfig,
    patches: jnp.ndarray,
    segment_ids: jnp.ndarray,
    pos_coords: jnp.ndarray,
    region_ids: jnp.ndarray,
    q_region_ids: jnp.ndarray,
    *,
    remat: bool | str = False,
    compute_dtype=None,
) -> jnp.ndarray:
    """Packed patches → packed LLM-space visual embeddings [Q, H_llm].

    The reference's `encode_images` (SURVEY.md §3.4): one ViT pass over all
    images/frames of the batch, then the Dynamic Compressor.
    """
    # The vision tower keeps Pallas ONLY for single-program ("pallas")
    # configs. Under the sequence-parallel decoder modes the packed
    # patch axis is sharded across the mesh, and a pallas_call is not
    # GSPMD-partitionable — XLA would all-gather the full packed q/k/v
    # and run the kernel replicated per chip (+3.1 GB/chip at the
    # 256-frame 34B/v5e-64 point, AOT-measured, round 5) — so the
    # partitionable XLA segment-attention path is the right kernel
    # there, not a fallback.
    feats = oryx_vit.forward(
        params["vit"], cfg.vision, patches, segment_ids, pos_coords,
        remat=remat, attn_impl=cfg.attn_impl, compute_dtype=compute_dtype,
    )
    return compressor_lib.forward(
        params["compressor"], cfg.compressor, cfg.vision,
        feats, region_ids, q_region_ids,
        attn_impl="pallas" if cfg.attn_impl == "pallas" else "xla",
    )


def forward(
    params: Params,
    cfg: OryxConfig,
    *,
    # Packed visual arrays (ops/packing.PackedVisual fields, device arrays):
    patches: jnp.ndarray,
    segment_ids: jnp.ndarray,
    pos_coords: jnp.ndarray,
    region_ids: jnp.ndarray,
    q_region_ids: jnp.ndarray,
    # Spliced text stream (models/splice.MMBatch fields, device arrays):
    token_ids: jnp.ndarray,
    visual_idx: jnp.ndarray,
    is_visual: jnp.ndarray,
    attn_mask: jnp.ndarray,
    positions: jnp.ndarray,
    remat: bool | str = False,
    mesh=None,
    compute_dtype=None,
    logits_dtype=jnp.float32,
    return_hidden: bool = False,
    text_segment_ids: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Training/prefill forward: visual encode → splice → decoder logits
    (or final hidden states when return_hidden, for the chunked-CE loss).

    mesh: only needed for attn_impl='ring' without an ambient mesh
    (jax.sharding.set_mesh) in scope.
    text_segment_ids: decoder-row sample ids for sequence-packed text
    training (train/data.collate_packed_text) — distinct from the
    VISUAL buffer's `segment_ids`."""
    vis = encode_visual(
        params, cfg, patches, segment_ids, pos_coords, region_ids,
        q_region_ids, remat=remat, compute_dtype=compute_dtype,
    )
    embeds = splice.embed_spliced(
        params["llm"]["embed"]["weight"], vis, token_ids, visual_idx, is_visual
    )
    out, _ = qwen2.forward(
        params["llm"], cfg.llm,
        inputs_embeds=embeds, positions=positions, kv_mask=attn_mask,
        remat=remat, attn_impl=cfg.attn_impl, mesh=mesh,
        compute_dtype=compute_dtype, logits_dtype=logits_dtype,
        return_hidden=return_hidden,
        segment_ids=text_segment_ids,
    )
    return out


@partial(jax.jit, static_argnames=("cfg",))
def mm_embeds(params, cfg: OryxConfig, arrays):
    """Visual encode + splice only → [B, T, H] decoder inputs (the
    prefill half of `mm_generate`; used by the streaming decode path)."""
    vis = encode_visual(
        params, cfg,
        arrays["patches"], arrays["segment_ids"], arrays["pos_coords"],
        arrays["region_ids"], arrays["q_region_ids"],
        compute_dtype=_dtype(cfg),
    )
    return splice.embed_spliced(
        params["llm"]["embed"]["weight"], vis,
        arrays["token_ids"], arrays["visual_idx"], arrays["is_visual"],
    )


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "cache_len"))
def _jit_mm_generate(
    params, cfg: OryxConfig, arrays, max_new_tokens: int, cache_len: int,
    key, stop_sequences=None,
):
    vis = encode_visual(
        params, cfg,
        arrays["patches"], arrays["segment_ids"], arrays["pos_coords"],
        arrays["region_ids"], arrays["q_region_ids"],
        compute_dtype=_dtype(cfg),
    )
    embeds = splice.embed_spliced(
        params["llm"]["embed"]["weight"], vis,
        arrays["token_ids"], arrays["visual_idx"], arrays["is_visual"],
    )
    return generate_lib.generate(
        params["llm"], cfg.llm, cfg.generation,
        inputs_embeds=embeds, lengths=arrays["lengths"],
        max_new_tokens=max_new_tokens, cache_len=cache_len, key=key,
        attn_impl=cfg.attn_impl, compute_dtype=_dtype(cfg),
        stop_sequences=stop_sequences,
    )


def compute_dtype(cfg: OryxConfig):
    """cfg.dtype string → jnp dtype for matmuls/activations."""
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[cfg.dtype]


_dtype = compute_dtype


def mm_generate(
    params: Params,
    cfg: OryxConfig,
    packed: PackedVisual,
    batch: splice.MMBatch,
    *,
    max_new_tokens: int | None = None,
    key: jax.Array | None = None,
    stop_sequences: jnp.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """End-to-end multimodal generation from host-side packed inputs.

    Returns (tokens [B, max_new_tokens], num_generated [B], finished [B]
    bool — False means cut off by max_new_tokens) as numpy.
    The reference equivalent is `model.generate(input_ids, images=...)`
    (SURVEY.md §3.2). stop_sequences: see generate.make_stop_sequences.
    """
    if max_new_tokens is None:
        max_new_tokens = cfg.generation.max_new_tokens
    if key is None:
        key = jax.random.key(0)
    T = batch.token_ids.shape[1]
    cache_len = round_up_bucket(T + max_new_tokens)
    arrays = stage_mm_arrays(packed, batch)
    toks, num, fin = _jit_mm_generate(
        params, cfg, arrays, max_new_tokens, cache_len, key, stop_sequences
    )
    return np.asarray(toks), np.asarray(num), np.asarray(fin)


def stage_mm_arrays(packed: PackedVisual, batch: splice.MMBatch) -> dict:
    """Host packed/batch structs → the device-array dict `_jit_mm_generate`
    consumes. Single owner of the staging layout — the latency bench times
    the jitted program over these same arrays, so it can never drift from
    what serving runs."""
    return {
        "patches": jnp.asarray(packed.patches),
        "segment_ids": jnp.asarray(packed.segment_ids),
        "pos_coords": jnp.asarray(packed.pos_coords),
        "region_ids": jnp.asarray(packed.region_ids),
        "q_region_ids": jnp.asarray(packed.q_region_ids),
        "token_ids": jnp.asarray(batch.token_ids),
        "visual_idx": jnp.asarray(batch.visual_idx),
        "is_visual": jnp.asarray(batch.is_visual),
        "lengths": jnp.asarray(batch.lengths),
    }
