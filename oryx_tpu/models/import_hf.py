"""HF-checkpoint ⇄ oryx_tpu weight conversion.

Reference parity: the reference loads `Qwen2ForCausalLM.from_pretrained` +
OryxViT safetensors (SURVEY.md §2 "Model builder", §5 "Checkpoint / resume").
This module is the interop path: import HF safetensors → stacked JAX pytrees,
and export back for users of the reference checkpoints.

Works from (a) an in-memory numpy state dict, or (b) a directory of
*.safetensors shards (with or without an index json). No torch required.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Iterable, Mapping

import jax.numpy as jnp
import numpy as np

from oryx_tpu.config import LLMConfig, VisionConfig

Params = dict[str, Any]
StateDict = Mapping[str, np.ndarray]


# ---------------------------------------------------------------------------
# Safetensors directory reading
# ---------------------------------------------------------------------------


def load_safetensors_dir(path: str) -> dict[str, np.ndarray]:
    """Load all tensors from a HF checkpoint directory into numpy."""
    from safetensors.numpy import load_file

    index = os.path.join(path, "model.safetensors.index.json")
    out: dict[str, np.ndarray] = {}
    if os.path.exists(index):
        with open(index) as f:
            shards = sorted(set(json.load(f)["weight_map"].values()))
        for shard in shards:
            out.update(load_file(os.path.join(path, shard)))
    else:
        for name in sorted(os.listdir(path)):
            if name.endswith(".safetensors"):
                out.update(load_file(os.path.join(path, name)))
    if not out:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    return out


def _get(sd: StateDict, key: str) -> np.ndarray:
    if key not in sd:
        raise KeyError(f"missing weight {key!r}; have e.g. "
                       f"{sorted(sd)[:5]}...")
    arr = np.asarray(sd[key])
    if arr.dtype == np.dtype("V2"):  # raw bf16 from safetensors.numpy
        import jax
        arr = np.asarray(jax.numpy.asarray(arr.view(jnp.bfloat16)))
    return arr


def _stack(
    sd: StateDict, n: int, fmt: str, post: Callable[[np.ndarray], np.ndarray]
) -> jnp.ndarray:
    return jnp.stack([jnp.asarray(post(_get(sd, fmt.format(i)))) for i in range(n)])


# ---------------------------------------------------------------------------
# Qwen2 / Yi decoder
# ---------------------------------------------------------------------------

_T = lambda w: np.ascontiguousarray(w.T)  # torch [out,in] -> jax [in,out]
_I = lambda w: w


def import_qwen2(
    sd: StateDict, cfg: LLMConfig, dtype: jnp.dtype = jnp.float32
) -> Params:
    """HF Qwen2/Llama-family state dict → stacked pytree (models/qwen2.py).

    Accepts either `model.`-prefixed names (full ForCausalLM dict) or the
    bare inner-model names; the bare form carries no `lm_head.weight`, so it
    requires `cfg.tie_word_embeddings` (a clear KeyError otherwise).
    """
    p = "model." if any(k.startswith("model.") for k in sd) else ""
    L = cfg.num_layers
    lyr = p + "layers.{}."

    def stacked(suffix: str, post=_I) -> jnp.ndarray:
        return _stack(sd, L, lyr + suffix, post)

    cast = lambda x: jnp.asarray(x).astype(dtype)
    layers: Params = {
        "input_norm": {"weight": stacked("input_layernorm.weight")},
        "post_attn_norm": {"weight": stacked("post_attention_layernorm.weight")},
        "q_proj": {"kernel": stacked("self_attn.q_proj.weight", _T)},
        "k_proj": {"kernel": stacked("self_attn.k_proj.weight", _T)},
        "v_proj": {"kernel": stacked("self_attn.v_proj.weight", _T)},
        "o_proj": {"kernel": stacked("self_attn.o_proj.weight", _T)},
        "gate_proj": {"kernel": stacked("mlp.gate_proj.weight", _T)},
        "up_proj": {"kernel": stacked("mlp.up_proj.weight", _T)},
        "down_proj": {"kernel": stacked("mlp.down_proj.weight", _T)},
    }
    if cfg.attention_bias:
        for proj in ("q_proj", "k_proj", "v_proj"):
            layers[proj]["bias"] = stacked(f"self_attn.{proj}.bias")
    params: Params = {
        "embed": {"weight": cast(_get(sd, p + "embed_tokens.weight"))},
        "layers": {k: {kk: cast(vv) for kk, vv in v.items()}
                   for k, v in layers.items()},
        "final_norm": {"weight": cast(_get(sd, p + "norm.weight"))},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": cast(_T(_get(sd, "lm_head.weight")))}
    return params


def export_qwen2(params: Params, cfg: LLMConfig) -> dict[str, np.ndarray]:
    """Stacked pytree → HF state-dict names (fp32 numpy)."""
    out: dict[str, np.ndarray] = {}
    f32 = lambda x: np.asarray(jnp.asarray(x, jnp.float32))
    out["model.embed_tokens.weight"] = f32(params["embed"]["weight"])
    out["model.norm.weight"] = f32(params["final_norm"]["weight"])
    if not cfg.tie_word_embeddings:
        out["lm_head.weight"] = _T(f32(params["lm_head"]["kernel"]))
    lp = params["layers"]
    names = {
        "input_layernorm.weight": (lp["input_norm"]["weight"], _I),
        "post_attention_layernorm.weight": (lp["post_attn_norm"]["weight"], _I),
        "self_attn.q_proj.weight": (lp["q_proj"]["kernel"], _T),
        "self_attn.k_proj.weight": (lp["k_proj"]["kernel"], _T),
        "self_attn.v_proj.weight": (lp["v_proj"]["kernel"], _T),
        "self_attn.o_proj.weight": (lp["o_proj"]["kernel"], _T),
        "mlp.gate_proj.weight": (lp["gate_proj"]["kernel"], _T),
        "mlp.up_proj.weight": (lp["up_proj"]["kernel"], _T),
        "mlp.down_proj.weight": (lp["down_proj"]["kernel"], _T),
    }
    if cfg.attention_bias:
        for proj in ("q_proj", "k_proj", "v_proj"):
            names[f"self_attn.{proj}.bias"] = (lp[proj]["bias"], _I)
    for suffix, (stacked, post) in names.items():
        arr = f32(stacked)
        for i in range(cfg.num_layers):
            out[f"model.layers.{i}.{suffix}"] = post(arr[i])
    return out


# ---------------------------------------------------------------------------
# SigLIP-family vision tower (OryxViT)
# ---------------------------------------------------------------------------


def import_siglip(
    sd: StateDict, cfg: VisionConfig, dtype: jnp.dtype = jnp.float32
) -> Params:
    """HF `SiglipVisionModel`-layout state dict → OryxViT pytree
    (models/oryx_vit.py). Accepts optional `vision_model.` prefix."""
    p = ""
    for cand in ("vision_model.", "vision_tower.vision_model.", ""):
        if any(k.startswith(cand + "encoder.layers.0.") for k in sd):
            p = cand
            break
    L = cfg.num_layers
    lyr = p + "encoder.layers.{}."
    cast = lambda x: jnp.asarray(x).astype(dtype)

    def stacked(suffix: str, post=_I) -> jnp.ndarray:
        return _stack(sd, L, lyr + suffix, post).astype(dtype)

    def ln(prefix: str) -> Params:
        return {"weight": stacked(prefix + ".weight"),
                "bias": stacked(prefix + ".bias")}

    def dense(prefix: str) -> Params:
        return {"kernel": stacked(prefix + ".weight", _T),
                "bias": stacked(prefix + ".bias")}

    # HF stores patch embedding as Conv2d [H, C, ph, pw]; our patchify is an
    # unfold + matmul, so flatten to [ph*pw*C, H] matching the host-side
    # patch extraction order (channel-last pixels within a patch).
    conv = _get(sd, p + "embeddings.patch_embedding.weight")
    Hd, C, ph, pw = conv.shape
    kernel = np.ascontiguousarray(
        conv.transpose(2, 3, 1, 0).reshape(ph * pw * C, Hd)
    )
    params: Params = {
        "patch_embed": {
            "kernel": cast(kernel),
            "bias": cast(_get(sd, p + "embeddings.patch_embedding.bias")),
        },
        "pos_embed": {
            # [P, H] learned table at base_grid**2 positions.
            "weight": cast(_get(sd, p + "embeddings.position_embedding.weight")),
        },
        "layers": {
            "norm1": ln("layer_norm1"),
            "norm2": ln("layer_norm2"),
            "q_proj": dense("self_attn.q_proj"),
            "k_proj": dense("self_attn.k_proj"),
            "v_proj": dense("self_attn.v_proj"),
            "o_proj": dense("self_attn.out_proj"),
            "fc1": dense("mlp.fc1"),
            "fc2": dense("mlp.fc2"),
        },
        "post_norm": {
            "weight": cast(_get(sd, p + "post_layernorm.weight")),
            "bias": cast(_get(sd, p + "post_layernorm.bias")),
        },
    }
    return params
