"""HF-checkpoint ⇄ oryx_tpu weight conversion.

Reference parity: the reference loads `Qwen2ForCausalLM.from_pretrained` +
OryxViT safetensors (SURVEY.md §2 "Model builder", §5 "Checkpoint / resume").
This module is the interop path: import HF safetensors → stacked JAX pytrees,
and export back for users of the reference checkpoints.

Works from (a) an in-memory numpy state dict, or (b) a directory of
*.safetensors shards (with or without an index json). No torch required.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Callable, Iterable, Mapping

import jax.numpy as jnp
import numpy as np

from oryx_tpu.config import LLMConfig, VisionConfig

Params = dict[str, Any]
StateDict = Mapping[str, np.ndarray]


# ---------------------------------------------------------------------------
# Safetensors directory reading
# ---------------------------------------------------------------------------


def load_safetensors_dir(path: str) -> dict[str, np.ndarray]:
    """Load all tensors from a HF checkpoint directory into numpy."""
    from safetensors.numpy import load_file

    index = os.path.join(path, "model.safetensors.index.json")
    out: dict[str, np.ndarray] = {}
    if os.path.exists(index):
        with open(index) as f:
            shards = sorted(set(json.load(f)["weight_map"].values()))
        for shard in shards:
            out.update(load_file(os.path.join(path, shard)))
    else:
        for name in sorted(os.listdir(path)):
            if name.endswith(".safetensors"):
                out.update(load_file(os.path.join(path, name)))
    if not out:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    return out


def _get(sd: StateDict, key: str) -> np.ndarray:
    if key not in sd:
        raise KeyError(f"missing weight {key!r}; have e.g. "
                       f"{sorted(sd)[:5]}...")
    arr = np.asarray(sd[key])
    if arr.dtype == np.dtype("V2"):  # raw bf16 from safetensors.numpy
        import jax
        arr = np.asarray(jax.numpy.asarray(arr.view(jnp.bfloat16)))
    return arr


def _stack(
    sd: StateDict, n: int, fmt: str, post: Callable[[np.ndarray], np.ndarray]
) -> jnp.ndarray:
    return jnp.stack([jnp.asarray(post(_get(sd, fmt.format(i)))) for i in range(n)])


# ---------------------------------------------------------------------------
# Qwen2 / Yi decoder
# ---------------------------------------------------------------------------

_T = lambda w: np.ascontiguousarray(w.T)  # torch [out,in] -> jax [in,out]
_I = lambda w: w


def import_qwen2(
    sd: StateDict, cfg: LLMConfig, dtype: jnp.dtype = jnp.float32
) -> Params:
    """HF Qwen2/Llama-family state dict → stacked pytree (models/qwen2.py).

    Accepts either `model.`-prefixed names (full ForCausalLM dict) or the
    bare inner-model names; the bare form carries no `lm_head.weight`, so it
    requires `cfg.tie_word_embeddings` (a clear KeyError otherwise).
    """
    p = "model." if any(k.startswith("model.") for k in sd) else ""
    L = cfg.num_layers
    lyr = p + "layers.{}."

    def stacked(suffix: str, post=_I) -> jnp.ndarray:
        return _stack(sd, L, lyr + suffix, post)

    cast = lambda x: jnp.asarray(x).astype(dtype)
    layers: Params = {
        "input_norm": {"weight": stacked("input_layernorm.weight")},
        "post_attn_norm": {"weight": stacked("post_attention_layernorm.weight")},
        "q_proj": {"kernel": stacked("self_attn.q_proj.weight", _T)},
        "k_proj": {"kernel": stacked("self_attn.k_proj.weight", _T)},
        "v_proj": {"kernel": stacked("self_attn.v_proj.weight", _T)},
        "o_proj": {"kernel": stacked("self_attn.o_proj.weight", _T)},
        "gate_proj": {"kernel": stacked("mlp.gate_proj.weight", _T)},
        "up_proj": {"kernel": stacked("mlp.up_proj.weight", _T)},
        "down_proj": {"kernel": stacked("mlp.down_proj.weight", _T)},
    }
    if cfg.attention_bias:
        for proj in ("q_proj", "k_proj", "v_proj"):
            layers[proj]["bias"] = stacked(f"self_attn.{proj}.bias")
    params: Params = {
        "embed": {"weight": cast(_get(sd, p + "embed_tokens.weight"))},
        "layers": {k: {kk: cast(vv) for kk, vv in v.items()}
                   for k, v in layers.items()},
        "final_norm": {"weight": cast(_get(sd, p + "norm.weight"))},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": cast(_T(_get(sd, "lm_head.weight")))}
    return params


def export_qwen2(params: Params, cfg: LLMConfig) -> dict[str, np.ndarray]:
    """Stacked pytree → HF state-dict names (fp32 numpy)."""
    out: dict[str, np.ndarray] = {}
    f32 = lambda x: np.asarray(jnp.asarray(x, jnp.float32))
    out["model.embed_tokens.weight"] = f32(params["embed"]["weight"])
    out["model.norm.weight"] = f32(params["final_norm"]["weight"])
    if not cfg.tie_word_embeddings:
        out["lm_head.weight"] = _T(f32(params["lm_head"]["kernel"]))
    lp = params["layers"]
    names = {
        "input_layernorm.weight": (lp["input_norm"]["weight"], _I),
        "post_attention_layernorm.weight": (lp["post_attn_norm"]["weight"], _I),
        "self_attn.q_proj.weight": (lp["q_proj"]["kernel"], _T),
        "self_attn.k_proj.weight": (lp["k_proj"]["kernel"], _T),
        "self_attn.v_proj.weight": (lp["v_proj"]["kernel"], _T),
        "self_attn.o_proj.weight": (lp["o_proj"]["kernel"], _T),
        "mlp.gate_proj.weight": (lp["gate_proj"]["kernel"], _T),
        "mlp.up_proj.weight": (lp["up_proj"]["kernel"], _T),
        "mlp.down_proj.weight": (lp["down_proj"]["kernel"], _T),
    }
    if cfg.attention_bias:
        for proj in ("q_proj", "k_proj", "v_proj"):
            names[f"self_attn.{proj}.bias"] = (lp[proj]["bias"], _I)
    for suffix, (stacked, post) in names.items():
        arr = f32(stacked)
        for i in range(cfg.num_layers):
            out[f"model.layers.{i}.{suffix}"] = post(arr[i])
    return out


# ---------------------------------------------------------------------------
# SigLIP-family vision tower (OryxViT)
# ---------------------------------------------------------------------------


def import_siglip(
    sd: StateDict, cfg: VisionConfig, dtype: jnp.dtype = jnp.float32
) -> Params:
    """HF `SiglipVisionModel`-layout state dict → OryxViT pytree
    (models/oryx_vit.py). Accepts optional `vision_model.` prefix."""
    p = ""
    for cand in ("vision_model.", "vision_tower.vision_model.", ""):
        if any(k.startswith(cand + "encoder.layers.0.") for k in sd):
            p = cand
            break
    L = cfg.num_layers
    lyr = p + "encoder.layers.{}."
    cast = lambda x: jnp.asarray(x).astype(dtype)

    def stacked(suffix: str, post=_I) -> jnp.ndarray:
        return _stack(sd, L, lyr + suffix, post).astype(dtype)

    def ln(prefix: str) -> Params:
        return {"weight": stacked(prefix + ".weight"),
                "bias": stacked(prefix + ".bias")}

    def dense(prefix: str) -> Params:
        return {"kernel": stacked(prefix + ".weight", _T),
                "bias": stacked(prefix + ".bias")}

    # HF stores patch embedding as Conv2d [H, C, ph, pw]; our patchify is an
    # unfold + matmul, so flatten to [ph*pw*C, H] matching the host-side
    # patch extraction order (channel-last pixels within a patch).
    conv = _get(sd, p + "embeddings.patch_embedding.weight")
    Hd, C, ph, pw = conv.shape
    kernel = np.ascontiguousarray(
        conv.transpose(2, 3, 1, 0).reshape(ph * pw * C, Hd)
    )
    params: Params = {
        "patch_embed": {
            "kernel": cast(kernel),
            "bias": cast(_get(sd, p + "embeddings.patch_embedding.bias")),
        },
        "pos_embed": {
            # [P, H] learned table at base_grid**2 positions.
            "weight": cast(_get(sd, p + "embeddings.position_embedding.weight")),
        },
        "layers": {
            "norm1": ln("layer_norm1"),
            "norm2": ln("layer_norm2"),
            "q_proj": dense("self_attn.q_proj"),
            "k_proj": dense("self_attn.k_proj"),
            "v_proj": dense("self_attn.v_proj"),
            "o_proj": dense("self_attn.out_proj"),
            "fc1": dense("mlp.fc1"),
            "fc2": dense("mlp.fc2"),
        },
        "post_norm": {
            "weight": cast(_get(sd, p + "post_layernorm.weight")),
            "bias": cast(_get(sd, p + "post_layernorm.bias")),
        },
    }
    return params


def export_siglip(params: Params, cfg: VisionConfig) -> dict[str, np.ndarray]:
    """OryxViT pytree → HF SiglipVisionModel-layout state dict (fp32,
    `vision_model.`-prefixed) — inverse of import_siglip."""
    out: dict[str, np.ndarray] = {}
    f32 = lambda x: np.asarray(jnp.asarray(x, jnp.float32))
    p = "vision_model."
    # [ph*pw*C, H] → Conv2d [H, C, ph, pw] (inverse of the import flatten).
    kern = f32(params["patch_embed"]["kernel"])
    ph = pw = cfg.patch_size
    C = cfg.num_channels
    out[p + "embeddings.patch_embedding.weight"] = np.ascontiguousarray(
        kern.reshape(ph, pw, C, -1).transpose(3, 2, 0, 1)
    )
    out[p + "embeddings.patch_embedding.bias"] = f32(
        params["patch_embed"]["bias"]
    )
    out[p + "embeddings.position_embedding.weight"] = f32(
        params["pos_embed"]["weight"]
    )
    out[p + "post_layernorm.weight"] = f32(params["post_norm"]["weight"])
    out[p + "post_layernorm.bias"] = f32(params["post_norm"]["bias"])
    lp = params["layers"]
    names = {
        "layer_norm1": ("norm1", _I), "layer_norm2": ("norm2", _I),
        "self_attn.q_proj": ("q_proj", _T), "self_attn.k_proj": ("k_proj", _T),
        "self_attn.v_proj": ("v_proj", _T),
        "self_attn.out_proj": ("o_proj", _T),
        "mlp.fc1": ("fc1", _T), "mlp.fc2": ("fc2", _T),
    }
    for hf_name, (key, post_kernel) in names.items():
        mod = lp[key]
        for leaf, arr in mod.items():
            post = post_kernel if leaf == "kernel" else _I
            suffix = "weight" if leaf in ("kernel", "weight") else "bias"
            stacked = f32(arr)
            for i in range(cfg.num_layers):
                out[f"{p}encoder.layers.{i}.{hf_name}.{suffix}"] = post(
                    stacked[i]
                )
    return out


def llm_hf_config(cfg: LLMConfig) -> dict[str, Any]:
    """HF config.json dict for an exported checkpoint.

    Qwen2 geometry (qkv biases) exports as Qwen2ForCausalLM; bias-free
    (Yi/Llama-class) geometry as LlamaForCausalLM with attention_bias
    false — HF's Qwen2 arch always expects qkv biases, so declaring it for
    a bias-free model would make from_pretrained fabricate random biases.
    """
    if cfg.attention_bias:
        arch: dict[str, Any] = {
            "architectures": ["Qwen2ForCausalLM"],
            "model_type": "qwen2",
        }
    else:
        arch = {
            "architectures": ["LlamaForCausalLM"],
            "model_type": "llama",
            "attention_bias": False,
            "mlp_bias": False,
        }
    return {
        **arch,
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "head_dim": cfg.head_dim,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_norm_eps,
        "max_position_embeddings": cfg.max_position_embeddings,
        "tie_word_embeddings": cfg.tie_word_embeddings,
        "hidden_act": "silu",
        "torch_dtype": "float32",
    }


def save_hf_checkpoint(params: Params, llm_cfg: LLMConfig,
                       vision_cfg: VisionConfig, directory: str) -> None:
    """Write a reference-layout checkpoint directory: LLM safetensors +
    config.json (HF Qwen2/Llama names), vision-tower safetensors (SigLIP
    names), and the compressor as a projector npz (the reference's
    `mm_projector.bin` analog) — the exporter half of SURVEY.md §5
    "Checkpoint / resume". Tokenizer files are NOT written (they belong to
    the source checkpoint; copy them alongside for HF `from_pretrained`).
    """
    from safetensors.numpy import save_file

    from oryx_tpu.utils import checkpoint as ckpt_lib

    os.makedirs(directory, exist_ok=True)
    save_file(
        export_qwen2(params["llm"], llm_cfg),
        os.path.join(directory, "model.safetensors"),
    )
    with open(os.path.join(directory, "config.json"), "w") as f:
        json.dump(llm_hf_config(llm_cfg), f, indent=2)
    save_file(
        export_siglip(params["vit"], vision_cfg),
        os.path.join(directory, "vision_tower.safetensors"),
    )
    ckpt_lib.save_projector_only(
        os.path.join(directory, "mm_projector"), params
    )


# ---------------------------------------------------------------------------
# LoRA adapter merge (PEFT layout)
# ---------------------------------------------------------------------------

# PEFT target-module name → our stacked-layer param key.
_LORA_TARGETS = {
    "q_proj": "q_proj", "k_proj": "k_proj", "v_proj": "v_proj",
    "o_proj": "o_proj", "gate_proj": "gate_proj", "up_proj": "up_proj",
    "down_proj": "down_proj",
}


def merge_lora(
    params: Params,
    adapter_sd: StateDict,
    cfg: LLMConfig,
    *,
    scaling: float,
) -> Params:
    # cfg validates adapter layer indices against the stacked param depth
    # (an out-of-range index would otherwise be an opaque numpy error).
    """Merge a PEFT LoRA adapter into full LLM weights: W += s·(B@A).

    The reference's builder merges `model_base` + LoRA checkpoints into one
    model (`load_pretrained_model(model_path, model_base, ...)`; SURVEY.md
    §2 "Model builder" LoRA-base merge path). Adapter keys look like
    `base_model.model.model.layers.{i}.self_attn.q_proj.lora_A.weight`
    (A: [r, in], B: [out, r], torch layout). Our kernels are [in, out], so
    the delta is A.T @ B.T. Returns a new params tree (llm subtree copied).
    """
    # Group adapter keys by (proj, layer).
    pat = re.compile(
        r"layers\.(\d+)\.(?:self_attn|mlp)\.(\w+)\.lora_(A|B)\.weight$"
    )
    found: dict[tuple[str, int], dict[str, np.ndarray]] = {}
    unhandled: list[str] = []
    for key in adapter_sd:
        m = pat.search(key)
        if not m:
            # Refuse rather than silently skip: modules_to_save full-weight
            # replacements, embedding/lm_head LoRA, DoRA magnitudes etc.
            # would otherwise merge to a model that quietly differs from
            # the reference merged model.
            unhandled.append(key)
            continue
        layer, proj, ab = int(m.group(1)), m.group(2), m.group(3)
        if proj not in _LORA_TARGETS:
            raise ValueError(f"unsupported LoRA target {proj!r} in {key}")
        if not 0 <= layer < cfg.num_layers:
            raise ValueError(
                f"adapter layer {layer} out of range for a "
                f"{cfg.num_layers}-layer model ({key})"
            )
        found.setdefault((proj, layer), {})[ab] = _get(adapter_sd, key)
    if unhandled:
        raise ValueError(
            "unsupported adapter weights (only decoder-proj lora_A/B "
            f"supported): {sorted(unhandled)[:5]}"
            f"{'...' if len(unhandled) > 5 else ''}"
        )
    if not found:
        raise ValueError("no LoRA weights found in adapter state dict")

    layers = dict(params["layers"])
    by_proj: dict[str, list[int]] = {}
    for proj, layer in found:
        by_proj.setdefault(proj, []).append(layer)
    for proj, idxs in by_proj.items():
        key = _LORA_TARGETS[proj]
        # np.array (copy): device-array views are read-only.
        kernel = np.array(jnp.asarray(layers[key]["kernel"], jnp.float32))
        for i in idxs:
            pair = found[(proj, i)]
            if set(pair) != {"A", "B"}:
                raise ValueError(f"layer {i} {proj}: incomplete LoRA pair")
            delta = (pair["A"].astype(np.float32).T
                     @ pair["B"].astype(np.float32).T) * scaling
            kernel[i] = kernel[i] + delta
        dtype = jnp.asarray(layers[key]["kernel"]).dtype
        layers[key] = {**layers[key], "kernel": jnp.asarray(kernel, dtype)}
    return {**params, "layers": layers}


def merge_lora_dir(params: Params, adapter_dir: str, cfg: LLMConfig) -> Params:
    """Merge a PEFT adapter directory (adapter_config.json +
    adapter_model.safetensors) into full LLM weights."""
    from safetensors.numpy import load_file

    with open(os.path.join(adapter_dir, "adapter_config.json")) as f:
        acfg = json.load(f)
    from oryx_tpu.config import LoraConfig

    r = int(acfg["r"])
    # Scaling formula (incl. rsLoRA's alpha/sqrt(r)) lives on LoraConfig.
    scaling = LoraConfig(
        r=r,
        alpha=float(acfg.get("lora_alpha", r)),
        use_rslora=bool(acfg.get("use_rslora")),
    ).scaling
    sd_path = os.path.join(adapter_dir, "adapter_model.safetensors")
    return merge_lora(params, load_file(sd_path), cfg, scaling=scaling)


# PEFT module scope per decoder projection (single source with
# _LORA_TARGETS for what is adaptable at all).
_LORA_SCOPE = {
    "q_proj": "self_attn", "k_proj": "self_attn", "v_proj": "self_attn",
    "o_proj": "self_attn", "gate_proj": "mlp", "up_proj": "mlp",
    "down_proj": "mlp",
}


def export_lora(params: Params, lora) -> tuple[StateDict, dict]:
    """Trained in-tree adapters → PEFT layout (the reverse of merge_lora):
    per-layer `base_model.model.model.layers.{i}.<scope>.<proj>.lora_A/
    lora_B.weight` in torch [r, in]/[out, r] orientation, plus an
    adapter_config.json dict. `lora` is config.LoraConfig and must be the
    config the adapters were created with — r and scaling are validated
    against the params so the recorded adapter_config can never disagree
    with the weights (a silent factor-of-sqrt(r) merge error otherwise)."""
    sd: StateDict = {}
    targets = []
    for name, p in params["layers"].items():
        if not (isinstance(p, dict) and "lora_a" in p):
            continue
        targets.append(name)
        scope = _LORA_SCOPE[name]
        a = np.asarray(jnp.asarray(p["lora_a"], jnp.float32))  # [L, in, r]
        b = np.asarray(jnp.asarray(p["lora_b"], jnp.float32))  # [L, r, out]
        if a.shape[2] != lora.r:
            raise ValueError(
                f"{name}: adapter rank {a.shape[2]} != lora.r {lora.r}"
            )
        scale_leaf = float(np.asarray(p["lora_scale"]).flat[0])
        if abs(scale_leaf - lora.scaling) > 1e-6 * max(1.0, abs(scale_leaf)):
            raise ValueError(
                f"{name}: params lora_scale {scale_leaf} != config scaling "
                f"{lora.scaling} (r/alpha/use_rslora mismatch)"
            )
        for i in range(a.shape[0]):
            base = f"base_model.model.model.layers.{i}.{scope}.{name}"
            # ascontiguousarray: safetensors serializes the raw buffer, so
            # a transposed VIEW would be written with the wrong layout.
            sd[f"{base}.lora_A.weight"] = np.ascontiguousarray(a[i].T)
            sd[f"{base}.lora_B.weight"] = np.ascontiguousarray(b[i].T)
    if not sd:
        raise ValueError("params contain no LoRA adapters")
    adapter_cfg = {
        "peft_type": "LORA",
        "r": int(lora.r),
        "lora_alpha": float(lora.alpha),
        "use_rslora": bool(lora.use_rslora),
        "target_modules": sorted(targets),
        "bias": "none",
    }
    return sd, adapter_cfg


def export_lora_dir(params: Params, lora, out_dir: str) -> None:
    """Write a PEFT adapter directory (adapter_config.json +
    adapter_model.safetensors) loadable by merge_lora_dir / PEFT."""
    from safetensors.numpy import save_file

    sd, acfg = export_lora(params, lora)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "adapter_config.json"), "w") as f:
        json.dump(acfg, f, indent=2)
    save_file(sd, os.path.join(out_dir, "adapter_model.safetensors"))
