"""Qwen2/Yi-class causal decoder, TPU-first functional implementation.

Reference parity: the HF `Qwen2ForCausalLM` backbone that Oryx wraps
(SURVEY.md §1 L1d, §2 "LLM wrapper"). Geometry covers both Oryx-7B
(Qwen2-7B, attention bias) and Oryx-34B (Yi-34B, no bias) via `LLMConfig`.

Design (deliberately not a torch translation):
  * Params are plain nested-dict pytrees; per-layer weights are STACKED along
    a leading layer axis and the block is applied with `lax.scan`. One block
    compiles once regardless of depth, remat applies per scan step, and FSDP
    all-gathers one layer at a time — the idiomatic XLA/TPU layout.
  * All matmuls take bf16 inputs with fp32 softmax/norm accumulation
    (ops/norms.py, ops/attention.py) so TPU runs track the CUDA reference.
  * KV cache is a pytree of [L, B, S, Hk, D] arrays written with per-row
    dynamic slices — static shapes throughout, decode step fully jittable.

Weight layout: linear kernels are [in, out] (x @ W); the HF importer
transposes torch's [out, in].
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from oryx_tpu.config import LLMConfig
from oryx_tpu.ops.attention import attention
from oryx_tpu.ops.norms import rms_norm
from oryx_tpu.ops.rope import apply_rope, rope_cos_sin
from oryx_tpu.parallel.sharding import constrain
from oryx_tpu.utils.remat import wrap_remat

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init_params(
    cfg: LLMConfig, key: jax.Array, dtype: jnp.dtype = jnp.float32
) -> Params:
    """Random-normal init (scale 0.02, zero biases) in the stacked layout."""
    L, H = cfg.num_layers, cfg.hidden_size
    Dq = cfg.num_heads * cfg.head_dim
    Dkv = cfg.num_kv_heads * cfg.head_dim
    I = cfg.intermediate_size
    keys = iter(jax.random.split(key, 16))

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dtype)

    def stack(shape):
        return dense(next(keys), (L, *shape))

    params: Params = {
        "embed": {"weight": dense(next(keys), (cfg.vocab_size, H))},
        "layers": {
            "input_norm": {"weight": jnp.ones((L, H), dtype)},
            "post_attn_norm": {"weight": jnp.ones((L, H), dtype)},
            "q_proj": {"kernel": stack((H, Dq))},
            "k_proj": {"kernel": stack((H, Dkv))},
            "v_proj": {"kernel": stack((H, Dkv))},
            "o_proj": {"kernel": stack((Dq, H))},
            "gate_proj": {"kernel": stack((H, I))},
            "up_proj": {"kernel": stack((H, I))},
            "down_proj": {"kernel": stack((I, H))},
        },
        "final_norm": {"weight": jnp.ones((H,), dtype)},
    }
    if cfg.attention_bias:
        params["layers"]["q_proj"]["bias"] = jnp.zeros((L, Dq), dtype)
        params["layers"]["k_proj"]["bias"] = jnp.zeros((L, Dkv), dtype)
        params["layers"]["v_proj"]["bias"] = jnp.zeros((L, Dkv), dtype)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense(next(keys), (H, cfg.vocab_size))}
    return params


# ---------------------------------------------------------------------------
# KV cache
# ---------------------------------------------------------------------------


def init_kv_cache(
    cfg: LLMConfig, batch: int, max_len: int, dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def init_paged_kv_cache(
    cfg: LLMConfig, num_pages: int, page_size: int,
    dtype: jnp.dtype = jnp.bfloat16,
    kv_dtype: str | None = None,
) -> Params:
    """Page-pool KV cache (ops/paged_kv.py): one pool of fixed-size
    pages shared by every sequence; rows address it through per-row
    block tables passed to `forward`. HBM cost is the POOL size, not
    batch × max_len.

    kv_dtype: None/"bf16" stores pages densely in `dtype` (the
    compute dtype — today's path, byte-for-byte). "int8" (or
    "fp8_e4m3") stores QUANTIZED pages — ops/paged_kv.QuantPages
    planes: codes + per-page scale blocks, quantize-on-write /
    dequantize-in-the-page-walk — roughly doubling resident KV tokens
    per HBM byte; `dtype` then names the dequant target the kernels
    multiply out into."""
    shape = (
        cfg.num_layers, num_pages, page_size, cfg.num_kv_heads, cfg.head_dim
    )
    if kv_dtype in (None, "bf16", "fp"):
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    from oryx_tpu.ops import paged_kv

    mk = lambda: paged_kv.init_quant_pages(  # noqa: E731
        cfg.num_layers, num_pages, page_size, cfg.num_kv_heads,
        cfg.head_dim, fmt=kv_dtype, dequant_dtype=dtype,
    )
    return {"k": mk(), "v": mk()}


def _cache_write(cache_layer: jnp.ndarray, new: jnp.ndarray, slots: jnp.ndarray):
    """Write new [B, T, Hk, D] into cache [B, S, Hk, D] at per-row start slots.

    slots: [B] int32 — index of the first written position per row. Assumes
    the T new entries occupy contiguous slots (true for prefill-from-0 and
    single-token decode).
    """

    def row(c, x, s):
        return jax.lax.dynamic_update_slice(c, x.astype(c.dtype), (s, 0, 0))

    return jax.vmap(row)(cache_layer, new, slots)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _linear(x, p):
    y = x @ p["kernel"].astype(x.dtype)
    if "lora_a" in p:
        # Low-rank residual (W + scale·A·B)x; scale rides as a [1, 1]
        # per-layer leaf so the stacked-layer scan slices it with the rest.
        delta = (x @ p["lora_a"].astype(x.dtype)) @ p["lora_b"].astype(x.dtype)
        y = y + delta * p["lora_scale"].astype(x.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def add_lora_params(
    params: Params, cfg: LLMConfig, lora, key: jax.Array,
    dtype: jnp.dtype = jnp.float32,
) -> Params:
    """Attach LoRA adapters to the stacked decoder projections.

    Reference parity: train.py's `lora_enable` (PEFT LoraConfig on the
    decoder projections). A ~ N(0, 0.02), B = 0 — the adapted model is
    exactly the base model at step 0. `lora` is config.LoraConfig.
    """
    import copy

    L = cfg.num_layers
    layers = dict(params["layers"])
    keys = iter(jax.random.split(key, len(lora.targets)))
    for name in lora.targets:
        if name not in layers:
            raise ValueError(f"unknown LoRA target {name!r}")
        p = dict(layers[name])
        d_in, d_out = p["kernel"].shape[1], p["kernel"].shape[2]
        p["lora_a"] = (
            jax.random.normal(next(keys), (L, d_in, lora.r), jnp.float32)
            * 0.02
        ).astype(dtype)
        p["lora_b"] = jnp.zeros((L, lora.r, d_out), dtype)
        p["lora_scale"] = jnp.full((L, 1, 1), lora.scaling, dtype)
        layers[name] = p
    out = copy.copy(params)
    out["layers"] = layers
    return out


def merge_lora_params(params: Params) -> Params:
    """Fold trained adapters into the base kernels (for serving/export):
    kernel += scale·A·B per layer; adapter leaves are dropped."""
    import copy

    layers = {}
    for name, p in params["layers"].items():
        if isinstance(p, dict) and "lora_a" in p:
            p = dict(p)
            delta = jnp.einsum(
                "lir,lro->lio", p["lora_a"].astype(jnp.float32),
                p["lora_b"].astype(jnp.float32),
            ) * p["lora_scale"].astype(jnp.float32)
            p["kernel"] = (
                p["kernel"].astype(jnp.float32) + delta
            ).astype(params["layers"][name]["kernel"].dtype)
            for k_ in ("lora_a", "lora_b", "lora_scale"):
                del p[k_]
        layers[name] = p
    out = copy.copy(params)
    out["layers"] = layers
    return out


def _block(
    cfg: LLMConfig,
    h: jnp.ndarray,
    lp: Params,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    *,
    positions: jnp.ndarray,
    cache_k: jnp.ndarray | None,
    cache_v: jnp.ndarray | None,
    write_slots: jnp.ndarray | None,
    kv_mask: jnp.ndarray | None,
    attn_fn,
    block_tables: jnp.ndarray | None = None,
    write_mask: jnp.ndarray | None = None,
    kv_lengths: jnp.ndarray | None = None,
    q_segments: jnp.ndarray | None = None,
    attn_impl: str = "xla",
):
    """One decoder block. h: [B, T, H]. Returns (h, new_k, new_v)."""
    B, T, _ = h.shape
    x = rms_norm(h, lp["input_norm"]["weight"], cfg.rms_norm_eps)
    q = _linear(x, lp["q_proj"]).reshape(B, T, cfg.num_heads, cfg.head_dim)
    k = _linear(x, lp["k_proj"]).reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    v = _linear(x, lp["v_proj"]).reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    q, k = apply_rope(q, k, cos, sin)
    # Post-rope tags for the "attn_qkv" remat policy (utils/remat.py):
    # saving here spares the backward both the projections and the rope.
    q = checkpoint_name(q, "attn_q")
    k = checkpoint_name(k, "attn_k")
    v = checkpoint_name(v, "attn_v")

    if cache_k is not None and block_tables is not None and (
        q_segments is not None
    ):
        # Packed RAGGED paged mode (one dispatch, mixed query lengths):
        # the T axis is a PACKED buffer of rows from many sequences —
        # block_tables is [num_slots, max_pages] (not per batch row) and
        # each token routes by (q_segments, positions). write_mask here
        # is PER TOKEN [B, T]. See ops/paged_kv.write_pages_packed /
        # ragged_paged_attention and models/generate.paged_ragged_step.
        from oryx_tpu.ops import paged_kv

        seg = q_segments[0]
        pos = positions[0]
        wm = None if write_mask is None else write_mask[0]
        cache_k = paged_kv.write_pages_packed(
            cache_k, k[0], block_tables, seg, pos, write_mask=wm
        )
        cache_v = paged_kv.write_pages_packed(
            cache_v, v[0], block_tables, seg, pos, write_mask=wm
        )
        if attn_impl == "pallas":
            from oryx_tpu.ops.pallas import paged_attention as _ppa

            attn_out = _ppa.ragged_paged_attention(
                q[0], cache_k, cache_v, block_tables, seg, pos
            )[None]
        else:
            attn_out = paged_kv.ragged_paged_attention(
                q[0], cache_k, cache_v, block_tables, seg, pos
            )[None]
    elif cache_k is not None and block_tables is not None:
        # Paged cache: this layer's K/V pool is [P, page, Hk, D] and the
        # row's logical stream is addressed through its block table.
        from oryx_tpu.ops import paged_kv

        cache_k = paged_kv.write_pages(
            cache_k, k, block_tables, write_slots, write_mask=write_mask
        )
        cache_v = paged_kv.write_pages(
            cache_v, v, block_tables, write_slots, write_mask=write_mask
        )
        if attn_impl == "pallas" and T == 1 and kv_lengths is not None:
            # In-place ragged decode: pages are read through the block
            # table, no contiguous gather.
            from oryx_tpu.ops.pallas import paged_attention as _ppa

            attn_out = _ppa.ragged_decode_attention(
                q, cache_k, cache_v, block_tables, kv_lengths
            )
        else:
            # Reference path (and any T > 1 paged prefill): materialize
            # the logical stream, then the stock cached-attention call —
            # bit-identical math to the dense cache at equal KV width.
            kc = paged_kv.gather_pages(cache_k, block_tables)
            vc = paged_kv.gather_pages(cache_v, block_tables)
            attn_out = attn_fn(
                q, kc, vc,
                q_positions=positions,
                kv_positions=None,
                kv_mask=kv_mask,
            )
    elif cache_k is not None:
        cache_k = _cache_write(cache_k, k, write_slots)
        cache_v = _cache_write(cache_v, v, write_slots)
        attn_out = attn_fn(
            q, cache_k, cache_v,
            q_positions=positions,
            kv_positions=None,  # arange over cache slots == absolute positions
            kv_mask=kv_mask,
        )
    else:
        # Right-padded prefill: every valid token's position equals its
        # slot index, which lets the Pallas kernel skip causally-dead kv
        # tiles (DMA + compute) despite the explicit position arrays.
        attn_out = attn_fn(
            q, k, v,
            q_positions=positions,
            kv_positions=positions,
            kv_mask=kv_mask,
            slot_positions=True,
        )
    attn_out = attn_out.reshape(B, T, -1)
    # "attn_o" tag: with remat_policy="attn_o" the residual-stream value
    # h_mid = h + o_out is rebuilt from this saved projection, so the
    # backward recomputes neither the attention nor o_proj.
    h = h + checkpoint_name(_linear(attn_out, lp["o_proj"]), "attn_o")

    x = rms_norm(h, lp["post_attn_norm"]["weight"], cfg.rms_norm_eps)
    gate = jax.nn.silu(_linear(x, lp["gate_proj"]))
    h = h + _linear(gate * _linear(x, lp["up_proj"]), lp["down_proj"])
    return h, cache_k, cache_v


def forward(
    params: Params,
    cfg: LLMConfig,
    *,
    input_ids: jnp.ndarray | None = None,
    inputs_embeds: jnp.ndarray | None = None,
    positions: jnp.ndarray | None = None,
    kv_cache: Params | None = None,
    write_slots: jnp.ndarray | None = None,
    kv_mask: jnp.ndarray | None = None,
    block_tables: jnp.ndarray | None = None,
    write_mask: jnp.ndarray | None = None,
    kv_lengths: jnp.ndarray | None = None,
    q_segments: jnp.ndarray | None = None,
    remat: bool | str = False,
    attn_impl: str = "xla",
    mesh=None,
    sp_axis: str = "sp",
    compute_dtype: jnp.dtype | None = None,
    logits_dtype: jnp.dtype = jnp.float32,
    return_hidden: bool = False,
    segment_ids: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, Params | None]:
    """Full decoder forward.

    Args:
      input_ids / inputs_embeds: exactly one; ids [B, T] or embeds [B, T, H].
        (Multimodal calls pass pre-spliced `inputs_embeds`; SURVEY.md §3.4.)
      positions: [B, T] absolute positions (RoPE + causal mask). Defaults to
        arange when no cache is used. CONSTRAINT (no-cache path): every
        valid token's position must equal its slot index (right-padded
        rows with per-row arange — the build_mm_batch layout). The Pallas
        path asserts this statically (slot_positions=True) to skip
        causally-dead kv tiles; left-padded or offset layouts would be
        silently mis-skipped. Use the kv_cache path for offset prefill.
      kv_cache: pytree from `init_kv_cache`; when present, k/v are written at
        `write_slots` ([B] first-slot indices, default positions[:, 0]) and
        attention runs over the whole cache with `kv_mask` [B, S] validity.
      kv_mask: with no cache, [B, T] padding mask; with cache, [B, S] slot
        validity — caller maintains it (see models/generate.py).
      block_tables: paged-cache mode — kv_cache is from `init_paged_kv_cache`
        ([L, P, page, Hk, D]) and each row's logical slots map through
        block_tables [B, max_pages] (ops/paged_kv.py). kv_mask then spans
        the LOGICAL stream [B, max_pages*page]. write_mask [B] gates rows'
        cache writes (finished/empty serving slots). kv_lengths [B] (valid
        kv count incl. the current token) enables the in-place Pallas
        ragged decode kernel for single-token steps under attn_impl=pallas.
      q_segments: packed RAGGED paged mode ([B=1, T] int32, requires
        block_tables): the T axis is a packed buffer of query rows from
        many sequences with MIXED query lengths — q_segments names each
        token's owning slot, `positions` its absolute position, and
        block_tables is [num_slots, max_pages]. Every token writes its
        K/V through its own slot's table and attends that slot's pages
        causally at its own position (ops/paged_kv.write_pages_packed /
        ragged_paged_attention; Pallas twin under attn_impl=pallas).
        write_mask is then PER TOKEN [1, T]; kv_mask/kv_lengths are
        unused (the causal mask at each row's position IS the validity
        mask). This is the one-dispatch mixed prefill+decode serving
        path (models/generate.paged_ragged_step).
      segment_ids: [B, T] int32 SAMPLE ids for sequence-packed training
        (0 = pad): attention is causal in SLOT order and masked on
        segment equality, so samples packed into one row never attend
        each other, while `positions` (restarting per sample) still
        drives RoPE. Training-only: incompatible with kv_cache and the
        ring impls.

    Returns (logits [B, T, V] in logits_dtype, updated kv_cache or None).
    """
    assert (input_ids is None) != (inputs_embeds is None)
    if inputs_embeds is None:
        # All-gather the (fsdp-sharded) table before the lookup so the
        # gather output doesn't inherit the table layout and force an
        # involuntary full rematerialization to hs_spec (see
        # splice.embed_spliced).
        inputs_embeds = constrain(
            params["embed"]["weight"], None, None
        )[input_ids]
    if compute_dtype is not None:
        inputs_embeds = inputs_embeds.astype(compute_dtype)
    # Pin the hidden-state sharding so GSPMD doesn't guess intermediates:
    # batch over the data axes, sequence over sp only in ring mode.
    seq_axis = "sp" if attn_impl.startswith("ring") else None
    hs_spec = (("dp", "fsdp"), seq_axis, None)
    h = constrain(inputs_embeds, *hs_spec)
    B, T, _ = h.shape

    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)  # [B,T,D]

    if kv_cache is not None and write_slots is None:
        write_slots = positions[:, 0]

    if segment_ids is not None and (
        kv_cache is not None or attn_impl not in ("xla", "pallas")
    ):
        raise ValueError(
            "segment_ids (packed training) requires attn_impl xla|pallas "
            "and no kv_cache"
        )
    if q_segments is not None:
        if block_tables is None or kv_cache is None:
            raise ValueError(
                "q_segments (packed ragged serving) requires a paged "
                "kv_cache with block_tables"
            )
        if B != 1:
            raise ValueError(
                f"q_segments packs many sequences into ONE row; got B={B}"
            )

    # NOTE for new attn impls: every branch's implementation must tag its
    # output `checkpoint_name(out, "flash_out")` (plus "flash_lse" where a
    # logsumexp residual exists) or the "attn"/"attn_qkv"/"attn_o" remat
    # policies (utils/remat.py) silently degrade for it — the attention
    # forward gets recomputed in the backward despite the policy.
    # Tagged per-impl rather than here so the custom-VJP kernels save the
    # exact residuals their backward needs without double-tagging.
    if attn_impl == "pallas":
        from oryx_tpu.ops.pallas import flash_attention as _fa

        def attn_fn(q, k, v, **kw):
            return _fa.flash_attention(q, k, v, causal=True, **kw)
    elif attn_impl == "xla":
        def attn_fn(q, k, v, slot_positions=False, **kw):
            return attention(q, k, v, causal=True, **kw)
    elif attn_impl in ("ring", "ring_flash"):
        # Sequence parallelism over the `sp` mesh axis (training/prefill;
        # decode with a KV cache is not sequence-sharded). "ring_flash"
        # runs the Pallas kernel per visiting block — O(tile) logits
        # memory, the long-context configuration.
        from oryx_tpu.ops.ring_attention import ring_attention

        if kv_cache is not None:
            raise ValueError(f"attn_impl={attn_impl!r} needs no kv_cache")
        ring_impl = "flash" if attn_impl == "ring_flash" else "xla"

        def attn_fn(q, k, v, *, q_positions, kv_positions, kv_mask,
                    slot_positions=False):
            return ring_attention(
                q, k, v, mesh=mesh, axis_name=sp_axis,
                batch_axes=("dp", "fsdp"), causal=True,
                positions=q_positions, kv_mask=kv_mask, impl=ring_impl,
            )
    else:
        raise ValueError(f"unknown attn_impl {attn_impl!r}")

    # Packed rows: causal order is the SLOT order (within a sample the
    # two coincide; across samples the segment mask rules) — which also
    # keeps the Pallas slot_positions DMA clamp valid despite the
    # restarting RoPE positions.
    attn_positions = positions
    if segment_ids is not None:
        attn_positions = jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32), (B, T)
        )
        base_attn_fn = attn_fn

        def attn_fn(q, k, v, **kw):  # noqa: F811 - deliberate wrap
            return base_attn_fn(
                q, k, v,
                q_segment_ids=segment_ids, kv_segment_ids=segment_ids,
                **kw,
            )

    def body(carry, xs):
        h = carry
        if kv_cache is not None:
            lp, ck, cv = xs
        else:
            lp, ck, cv = xs, None, None
        h, ck, cv = _block(
            cfg, h, lp, cos, sin,
            positions=attn_positions,
            cache_k=ck, cache_v=cv,
            write_slots=write_slots,
            kv_mask=kv_mask,
            attn_fn=attn_fn,
            block_tables=block_tables,
            write_mask=write_mask,
            kv_lengths=kv_lengths,
            q_segments=q_segments,
            attn_impl=attn_impl,
        )
        h = constrain(h, *hs_spec)
        return h, (ck, cv) if kv_cache is not None else None

    body = wrap_remat(body, remat)

    if kv_cache is not None:
        xs = (params["layers"], kv_cache["k"], kv_cache["v"])
    else:
        xs = params["layers"]
    h, ys = jax.lax.scan(body, h, xs)

    h = rms_norm(h, params["final_norm"]["weight"], cfg.rms_norm_eps)
    if return_hidden:
        # Final hidden states pre-lm_head: the chunked-CE training path
        # (train/loss.chunked_causal_lm_loss) projects to the vocab
        # per-chunk instead of materializing [B, T, V] logits.
        return h, ({"k": ys[0], "v": ys[1]} if kv_cache is not None else None)
    if cfg.tie_word_embeddings:
        logits = h @ params["embed"]["weight"].astype(h.dtype).T
    else:
        logits = h @ params["lm_head"]["kernel"].astype(h.dtype)
    logits = logits.astype(logits_dtype)

    new_cache = None
    if kv_cache is not None:
        new_cache = {"k": ys[0], "v": ys[1]}
    return logits, new_cache
