"""OryxViT — SigLIP-derived vision transformer at arbitrary resolution.

Reference parity: `oryx/model/multimodal_encoder/oryx_vit.py` (SURVEY.md §1
L1a, §2 "OryxViT"; reference mount empty — behavior reconstructed). The
reference packs variable-size images into one `flash_attn_varlen_func` call
with cu_seqlens; here the packing is segment-ids over a bucketed static
buffer (ops/packing.py) and attention masks on segment equality — the
Pallas splash-attention kernel consumes the same layout (SURVEY.md §2a).

Structure per block (SigLIP family): pre-LN → MHA (biased projections) →
residual; pre-LN → MLP (gelu tanh) → residual; final post-LN. Learned
position embeddings live at base_grid² and are bilinearly resampled to each
image's (h, w) patch grid via per-patch continuous coordinates — one gather,
no per-image dynamic shapes.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from oryx_tpu.config import VisionConfig
from oryx_tpu.ops.attention import attention
from jax.ad_checkpoint import checkpoint_name

from oryx_tpu.ops.norms import layer_norm
from oryx_tpu.parallel.sharding import constrain
from oryx_tpu.utils.remat import wrap_remat

Params = dict[str, Any]


def init_params(
    cfg: VisionConfig, key: jax.Array, dtype: jnp.dtype = jnp.float32
) -> Params:
    L, H, I = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    D = cfg.num_heads * cfg.head_dim
    patch_dim = cfg.patch_size * cfg.patch_size * cfg.num_channels
    keys = iter(jax.random.split(key, 12))

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dtype)

    def ln(shape=(L, H)):
        return {"weight": jnp.ones(shape, dtype), "bias": jnp.zeros(shape, dtype)}

    def proj(shape_in, shape_out):
        return {
            "kernel": dense(next(keys), (L, shape_in, shape_out)),
            "bias": jnp.zeros((L, shape_out), dtype),
        }

    return {
        "patch_embed": {
            "kernel": dense(next(keys), (patch_dim, H)),
            "bias": jnp.zeros((H,), dtype),
        },
        "pos_embed": {
            "weight": dense(next(keys), (cfg.base_grid * cfg.base_grid, H))
        },
        "layers": {
            "norm1": ln(),
            "norm2": ln(),
            "q_proj": proj(H, D),
            "k_proj": proj(H, D),
            "v_proj": proj(H, D),
            "o_proj": proj(D, H),
            "fc1": proj(H, I),
            "fc2": proj(I, H),
        },
        "post_norm": {"weight": jnp.ones((H,), dtype), "bias": jnp.zeros((H,), dtype)},
    }


def interp_pos_embed(
    table: jnp.ndarray, coords: jnp.ndarray, base_grid: int
) -> jnp.ndarray:
    """Bilinearly sample the posemb table at continuous coordinates.

    table: [G*G, H]; coords: [P, 2] source-space (sy, sx) from
    ops/packing.posemb_source_coords (align_corners=False semantics, edge
    clamped). Returns [P, H] float32.
    """
    G = base_grid
    grid = table.reshape(G, G, -1).astype(jnp.float32)
    sy, sx = coords[:, 0], coords[:, 1]
    y0f, x0f = jnp.floor(sy), jnp.floor(sx)
    ly, lx = sy - y0f, sx - x0f
    y0 = jnp.clip(y0f.astype(jnp.int32), 0, G - 1)
    y1 = jnp.clip(y0f.astype(jnp.int32) + 1, 0, G - 1)
    x0 = jnp.clip(x0f.astype(jnp.int32), 0, G - 1)
    x1 = jnp.clip(x0f.astype(jnp.int32) + 1, 0, G - 1)
    ly, lx = ly[:, None], lx[:, None]
    return (
        grid[y0, x0] * (1 - ly) * (1 - lx)
        + grid[y0, x1] * (1 - ly) * lx
        + grid[y1, x0] * ly * (1 - lx)
        + grid[y1, x1] * ly * lx
    )


def _linear(x, p):
    return x @ p["kernel"].astype(x.dtype) + p["bias"].astype(x.dtype)


def forward(
    params: Params,
    cfg: VisionConfig,
    patches: jnp.ndarray,
    segment_ids: jnp.ndarray,
    pos_coords: jnp.ndarray,
    *,
    remat: bool | str = False,
    attn_impl: str = "xla",
    compute_dtype: jnp.dtype | None = None,
) -> jnp.ndarray:
    """Encode a packed patch buffer.

    patches: [P, patch_dim]; segment_ids: [P] (0 = pad); pos_coords: [P, 2].
    Returns features [P, hidden] in compute dtype (pad rows are garbage;
    consumers mask on segment_ids).
    """
    H = cfg.hidden_size
    emb = patches.astype(jnp.float32) @ params["patch_embed"]["kernel"].astype(
        jnp.float32
    ) + params["patch_embed"]["bias"].astype(jnp.float32)
    emb = emb + interp_pos_embed(
        params["pos_embed"]["weight"], pos_coords, cfg.base_grid
    )
    if compute_dtype is not None:
        emb = emb.astype(compute_dtype)
    else:
        emb = emb.astype(patches.dtype)

    # Batch dim of 1: the packed buffer IS the batch; the packing axis
    # shards over the data width (Trainer._device_batch) — pin it so GSPMD
    # doesn't guess intermediates. "sp" rides along: to the vision tower
    # the patch axis is pure data, so sequence-parallel devices take
    # patch shards too — at the 256-frame long-video scale the 27-layer
    # residual stacks over 16k patches/chip are the memory (TPU_VALIDATION
    # round 5); an sp-less mesh drops the axis (constrain).
    pk_spec = (None, ("dp", "fsdp", "sp"), None)
    h = constrain(emb[None], *pk_spec)  # [1, P, H]
    seg = segment_ids[None]  # [1, P]

    if attn_impl == "pallas":
        from oryx_tpu.ops.pallas import segment_attention as _sa

        def attn_fn(q, k, v):
            return _sa.segment_attention(q, k, v, seg, seg)
    elif attn_impl in ("xla", "ring", "ring_flash"):
        # "ring"/"ring_flash" (decoder sequence parallelism) have no
        # meaning for the packed ViT buffer; its parallel story is
        # sharding the packing axis, which the XLA path handles under
        # GSPMD.
        def attn_fn(q, k, v):
            return attention(q, k, v, q_segment_ids=seg, kv_segment_ids=seg)
    else:
        raise ValueError(f"unknown attn_impl {attn_impl!r}")

    def body(carry, lp):
        h = carry
        x = layer_norm(
            h, lp["norm1"]["weight"], lp["norm1"]["bias"], cfg.layer_norm_eps
        )
        B, P, _ = x.shape
        q = _linear(x, lp["q_proj"]).reshape(B, P, cfg.num_heads, cfg.head_dim)
        k = _linear(x, lp["k_proj"]).reshape(B, P, cfg.num_heads, cfg.head_dim)
        v = _linear(x, lp["v_proj"]).reshape(B, P, cfg.num_heads, cfg.head_dim)
        # Same remat tags as the decoder block (models/qwen2._block) so the
        # "attn_qkv"/"attn_o" policies skip the encoder's projection and
        # attention recompute too; the attention output itself is tagged
        # "flash_out" inside attn_fn's implementation.
        q = checkpoint_name(q, "attn_q")
        k = checkpoint_name(k, "attn_k")
        v = checkpoint_name(v, "attn_v")
        o = attn_fn(q, k, v).reshape(B, P, -1)
        h = h + checkpoint_name(_linear(o, lp["o_proj"]), "attn_o")
        x = layer_norm(
            h, lp["norm2"]["weight"], lp["norm2"]["bias"], cfg.layer_norm_eps
        )
        x = jax.nn.gelu(_linear(x, lp["fc1"]), approximate=True)
        h = h + _linear(x, lp["fc2"])
        return constrain(h, *pk_spec), None

    body = wrap_remat(body, remat)
    h, _ = jax.lax.scan(body, h, params["layers"])

    h = layer_norm(
        h, params["post_norm"]["weight"], params["post_norm"]["bias"],
        cfg.layer_norm_eps,
    )
    return h[0]
