"""The serving stack's concurrency model, in one checkable place.

Threads
-------
  * **engine** (`oryx-engine`, one per ContinuousScheduler): owns the
    slot arrays, block tables, page allocator, KV pool and the prefix
    cache — everything device-adjacent is single-threaded by design,
    so the decode hot path never takes a lock.
  * **HTTP handlers** (one per in-flight request): touch the scheduler
    only through `submit()` / `RequestHandle` and the `_queue` +
    control flags under `_cond`.
  * **engine-supervisor**: watches the engine thread and calls
    `restart()` only after observing its death (thread death is the
    happens-before edge that makes touching engine-owned state legal).
  * **stall-watchdog / telemetry scrapes / debug endpoints**: read the
    tracer's flight recorder and the metrics registry under their own
    locks; they never touch engine-owned state.
  * **router handlers + router-prober** (serve/router.py, its own
    process in production): the replica table's mutable fields and
    the affinity trie are shared between the proxy handler threads
    and the prober, always under `router._lock` — which is held only
    for table/trie edits, never across network I/O. A router process
    holds no engine locks, ever.

Lock acquisition order
----------------------
The declared order below is enforced two ways: statically by
oryxlint's `lock-order` rule (the repo-wide may-acquire-while-holding
graph must not invert it or form a cycle) and at runtime by
`analysis.sanitizers.LockOrderSanitizer` (armed via
`ORYX_LOCK_SANITIZER=1`), which raises at the acquire that would
invert it. A lock earlier in the chain may be held while acquiring a
later one, never the reverse.

`LOCK_ORDER` is the same manifest as a runtime value; a unit test
(tests/test_lock_sanitizer.py) asserts the comment line and the tuple
can never drift apart. (The declaration below is a real comment, not
docstring text: oryxlint reads directives from tokenized comments
only, so quoted syntax can never declare anything.)
"""

from __future__ import annotations

# The manifest: one declaration, read by the static rule from this
# comment and by the runtime sanitizer from the tuple beneath it.
# lock-order: server.stream_lock < scheduler._cond < anomaly._lock < trace._lock < tracer._lock < request_log._lock < forensics._lock < audit._lock < watchdog._lock < router._lock < registry._lock < metrics.family
LOCK_ORDER: tuple[str, ...] = (
    "server.stream_lock",   # window-engine device lock (api_server)
    "scheduler._cond",      # admission queue + control flags
    "anomaly._lock",        # anomaly episode state + events.jsonl sink
    "trace._lock",          # one request's span list
    "tracer._lock",         # the flight recorder of traces
    "request_log._lock",    # wide-event ring + requests.jsonl sink
                            # (terminal paths emit after closing the
                            # trace, so it ranks after the trace locks)
    "forensics._lock",      # OOM forensic ring (utils/forensics.py;
                            # a leaf like the request log — captures
                            # hold no other lock while appending)
    "audit._lock",          # output-audit ring + verdict counts
                            # (serve/audit.py; same leaf contract as
                            # the forensic ring — held only for the
                            # ring/counter edit, never across a replay)
    "watchdog._lock",       # stall-watchdog beat state
    "router._lock",         # front-end router replica table + affinity
                            # trie (serve/router.py; a router process
                            # never holds engine locks, but its metric
                            # bumps nest under this)
    "registry._lock",       # metric family declaration/lookup
    "metrics.family",       # one family's children (innermost:
                            # metrics are bumped under everything)
)
