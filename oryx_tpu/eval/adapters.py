"""Benchmark-format adapters: VideoMME / MLVU / MVBench → harness records.

Reference parity: the reference evaluates through lmms-eval task configs
(SURVEY.md §1 L7, §3.5), each of which maps a benchmark's native record
layout onto the same prompt shape (question + lettered options + "answer
with the letter"). These adapters do that mapping onto
`eval.harness`'s record schema:

    {"id", "question", "options": [...], "answer": "B", "video"|"image"}

so `python -m oryx_tpu.eval.harness --task f.json --format videomme ...`
runs the benchmark directly from its published annotation file.
"""

from __future__ import annotations

import os
import re
import string
from typing import Any, Callable

LETTERS = string.ascii_uppercase

_OPT_PREFIX = re.compile(r"^\(?([A-Z])[.):]\s*")


def _strip_option(opt: str) -> str:
    """Drop a leading "A. " / "(B) " letter prefix from an option string."""
    return _OPT_PREFIX.sub("", str(opt).strip())


def _answer_letter(answer: Any, options: list[str]) -> str:
    """Normalize an answer (letter, index, or full option text) to a letter."""
    if isinstance(answer, int):
        return LETTERS[answer]
    a = str(answer).strip()
    if len(a) == 1 and a.upper() in LETTERS[: len(options)]:
        return a.upper()
    m = _OPT_PREFIX.match(a)
    if m and m.group(1) in LETTERS[: len(options)]:
        return m.group(1)
    stripped = [_strip_option(o).lower() for o in options]
    key = _strip_option(a).lower()
    if key in stripped:
        return LETTERS[stripped.index(key)]
    raise ValueError(f"cannot map answer {answer!r} onto options {options!r}")


def from_videomme(
    recs: list[dict[str, Any]], *, video_root: str = "", video_ext: str = ".mp4"
) -> list[dict[str, Any]]:
    """Video-MME annotations: lettered `options` strings, letter `answer`,
    videos addressed by `videoID`."""
    out = []
    for r in recs:
        opts = [_strip_option(o) for o in r["options"]]
        vid = r.get("videoID") or r.get("video_id") or r["video"]
        video = vid if vid.endswith(video_ext) else vid + video_ext
        out.append({
            "id": r.get("question_id", vid),
            "question": r["question"],
            "options": opts,
            "answer": _answer_letter(r["answer"], [str(o) for o in r["options"]]),
            "video": os.path.join(video_root, video) if video_root else video,
            "meta": {
                k: r[k]
                for k in ("duration", "domain", "sub_category", "task_type")
                if k in r
            },
        })
    return out


def from_mlvu(
    recs: list[dict[str, Any]], *, video_root: str = ""
) -> list[dict[str, Any]]:
    """MLVU annotations: `candidates` option texts, full-text `answer`,
    `video` relative path, `question_type` task tag."""
    out = []
    for i, r in enumerate(recs):
        opts = [str(c) for c in r["candidates"]]
        video = r["video"]
        out.append({
            "id": r.get("question_id", i),
            "question": r["question"],
            "options": opts,
            "answer": _answer_letter(r["answer"], opts),
            "video": os.path.join(video_root, video) if video_root else video,
            "meta": {
                k: r[k] for k in ("question_type", "duration") if k in r
            },
        })
    return out


# MVBench annotations are MLVU-shaped (`candidates` + full-text `answer`,
# `video` relative to the per-task video dir) — same mapping applies.
from_mvbench = from_mlvu


def from_nextqa(
    recs: list[dict[str, Any]], *, video_root: str = "", video_ext: str = ".mp4"
) -> list[dict[str, Any]]:
    """NExT-QA multiple-choice annotations (the CSV rows of val.csv /
    test.csv): option texts in columns a0..a4, integer `answer` index,
    videos addressed by numeric `video` id."""
    out = []
    for r in recs:
        opts = [str(r[f"a{i}"]) for i in range(5) if f"a{i}" in r]
        vid = str(r["video"])
        video = vid if vid.endswith(video_ext) else vid + video_ext
        out.append({
            "id": f"{vid}_{r.get('qid', len(out))}",
            "question": r["question"],
            "options": opts,
            # CSV rows arrive as strings; the answer column is the index.
            "answer": LETTERS[int(r["answer"])],
            "video": os.path.join(video_root, video) if video_root else video,
            "meta": {k: r[k] for k in ("type", "frame_count") if k in r},
        })
    return out


ADAPTERS: dict[str, Callable[..., list[dict[str, Any]]]] = {
    "videomme": from_videomme,
    "mlvu": from_mlvu,
    "mvbench": from_mvbench,
    "nextqa": from_nextqa,
}


def adapt(
    fmt: str, recs: list[dict[str, Any]], *, video_root: str = ""
) -> list[dict[str, Any]]:
    """Apply a named adapter; fmt="native" returns records unchanged."""
    if fmt in (None, "", "native"):
        return recs
    if fmt not in ADAPTERS:
        raise ValueError(f"unknown format {fmt!r}; have {sorted(ADAPTERS)}")
    return ADAPTERS[fmt](recs, video_root=video_root)
