from oryx_tpu.eval.harness import evaluate, load_task  # noqa: F401
