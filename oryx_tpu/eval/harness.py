"""Benchmark evaluation harness: multiple-choice / open-ended QA over media.

Reference parity: the reference evaluates through the external lmms-eval
harness (VideoMME, MLVU, MVBench, NextQA, ...; SURVEY.md §1 L7, §3.5) — an
adapter wraps the §3.2 inference stack and the harness aggregates accuracy,
optionally splitting the dataset across ranks with each rank running an
independent replica. This module is that harness, standalone: a task is a
JSON/JSONL (or CSV, e.g. NextQA's annotations) file of records

    {"id": ..., "question": ..., "options": ["...", ...] | null,
     "answer": "B" | "<free text>", "image": path|[paths] | "video": path}

multiple-choice records are scored by option-letter match (lmms-eval's MCQ
protocol: prompt lists lettered options, the reply's first letter in range
counts); open-ended records by normalized exact match.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import re
import string
import sys
import time
from typing import Any, Sequence

from oryx_tpu.data import media
from oryx_tpu.serve.pipeline import OryxInference

LETTERS = string.ascii_uppercase

MCQ_SUFFIX = "Answer with the option's letter from the given choices directly."


def load_task(path: str) -> list[dict[str, Any]]:
    """Load a task file: .jsonl (one record per line), .json (list), or
    .csv (header row → dict per row; NextQA ships its MC annotations as
    CSV)."""
    with open(path, newline="") as f:
        if path.endswith(".jsonl"):
            return [json.loads(line) for line in f if line.strip()]
        if path.endswith(".csv"):
            import csv

            return list(csv.DictReader(f))
        recs = json.load(f)
    if not isinstance(recs, list):
        raise ValueError(f"{path}: expected a list of records")
    return recs


def format_question(rec: dict[str, Any]) -> str:
    opts = rec.get("options")
    if not opts:
        return rec["question"]
    lines = [rec["question"]] + [
        f"{LETTERS[i]}. {o}" for i, o in enumerate(opts)
    ]
    lines.append(MCQ_SUFFIX)
    return "\n".join(lines)


def _norm(s: str) -> str:
    return re.sub(r"\s+", " ", s.strip().lower().strip(".,!?\"'"))


def parse_choice(
    reply: str, num_options: int, options: Sequence[str] | None = None
) -> str | None:
    """Extract the chosen option letter from a model reply.

    Ordered by confidence (the lmms-eval MCQ protocol shape): a bare
    letter reply; "answer is X" / "(X)" / "X." forms; unique option-text
    containment; finally a standalone letter — but never the bare English
    articles "A"/"I" inside prose, which are words, not choices."""
    up = reply.strip().upper()
    valid = LETTERS[:num_options]
    if re.fullmatch(rf"\(?([{valid}])\)?[.,:)]?", up):
        return re.fullmatch(rf"\(?([{valid}])\)?[.,:)]?", up).group(1)
    m = re.search(rf"ANSWER\s*(?:IS|:)?\s*\(?([{valid}])\b", up)
    if m:
        return m.group(1)
    m = re.search(rf"\(([{valid}])\)|\b([{valid}])[.,:)]", up)
    if m:
        return m.group(1) or m.group(2)
    if options:
        nr = _norm(reply)
        hits = [
            i for i, o in enumerate(options)
            if _norm(str(o))
            and re.search(rf"\b{re.escape(_norm(str(o)))}\b", nr)
        ]
        if len(hits) == 1:
            return LETTERS[hits[0]]
    # Standalone letter anywhere — excluding the article/pronoun words.
    for m in re.finditer(rf"\b([{valid}])\b", up):
        if m.group(1) not in ("A", "I"):
            return m.group(1)
    return None


def score_record(rec: dict[str, Any], reply: str) -> bool:
    opts = rec.get("options")
    ans = rec["answer"]
    if opts:
        if isinstance(ans, int):
            ans = LETTERS[ans]
        return parse_choice(reply, len(opts), opts) == str(ans).strip().upper()
    return _norm(reply) == _norm(str(ans))


def eval_length_proxy(rec: dict[str, Any]) -> int:
    """Cheap per-record length proxy WITHOUT loading media. Delegates to
    train/data.length_estimate (the single owner of the per-visual token
    allowances) over a synthesized training-shaped record, so eval batch
    grouping can never drift from the training sampler's notion of
    length."""
    from oryx_tpu.train.data import length_estimate

    return length_estimate({
        "conversations": [{"value": format_question(rec)}],
        "image": rec.get("image"),
        "video": rec.get("video"),
    })


def _modality_key(rec: dict[str, Any]) -> str:
    """Batch-composition key: video / multi-image / image / text rows
    have wildly different visual-buffer shapes — keeping them apart means
    batches share patch buckets, not just sequence buckets. Text-only
    gets its own bucket on top of train/data.record_modality (training
    records always carry media; eval ones may not)."""
    if not rec.get("video") and not rec.get("image"):
        return "text"
    from oryx_tpu.train.data import record_modality

    return record_modality(rec)


@dataclasses.dataclass
class EvalResult:
    accuracy: float
    num_correct: int
    num_total: int
    seconds: float
    records: list[dict[str, Any]]

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def evaluate(
    pipe: OryxInference,
    records: Sequence[dict[str, Any]],
    *,
    media_root: str = "",
    num_frames: int = 64,
    max_new_tokens: int = 16,
    process_index: int = 0,
    process_count: int = 1,
    log_every: int = 25,
    batch_size: int = 8,
    length_group: bool = True,
    scoring: str = "generate",
) -> EvalResult:
    """Run the inference stack over a record shard and score it.

    Dataset sharding mirrors the reference's accelerate-split eval
    (SURVEY.md §3.5): record i belongs to process i mod process_count; the
    caller merges per-process results (accuracy is weighted by num_total).
    Records are batched `batch_size` at a time through `pipe.chat_batch`
    (one ViT/compressor/decode program per batch). Host memory holds the
    whole batch's raw frames at once (batch_size × num_frames ×
    native-resolution); lower batch_size for high-res long-video tasks.

    length_group (default on) sorts the shard by (modality, length proxy)
    before batching — chat_batch pads every row to the batch-max bucket,
    so mixed-length batches otherwise pay worst-row padding (the
    training side's LengthGroupedSampler, applied to eval). Record
    ORDER in the output changes but ids/scoring don't.

    scoring="loglikelihood" (lmms-eval's second model API): MCQ records
    are scored by the option LETTER with the highest teacher-forced
    log-probability (`pipe.score_options` — one visual prefill + one
    tiny forward per option, no sampling variance); records without
    options still generate. "generate" (default) decodes a reply and
    parses the letter, the lmms-eval `generate_until` protocol.
    """
    if scoring not in ("generate", "loglikelihood"):
        raise ValueError(f"scoring={scoring!r}: generate|loglikelihood")
    t0 = time.perf_counter()
    out: list[dict[str, Any]] = []
    correct = 0
    # Fallback ids use the GLOBAL record index so merged per-process
    # results stay distinguishable.
    mine = [
        (i, r, eval_length_proxy(r)) for i, r in enumerate(records)
        if i % process_count == process_index
    ]
    if length_group:
        mine.sort(key=lambda t: (_modality_key(t[1]), t[2]))
    pad_waste = 0  # proxy tokens spent on per-batch padding
    batch_size = max(1, batch_size)
    for b0 in range(0, len(mine), batch_size):
        group = mine[b0 : b0 + batch_size]
        requests = []
        for gi, rec, _ in group:
            frames, is_video = media.load_record_media(
                rec, media_root=media_root, num_frames=num_frames
            )
            requests.append({
                "question": format_question(rec),
                "images": frames,
                "is_video": is_video,
            })
        if scoring == "loglikelihood":
            replies: list[str | None] = [None] * len(group)
            open_idx = [
                i for i, (_, rec, _) in enumerate(group)
                if not rec.get("options")
            ]
            # Only the decoded (optionless) rows pay batch padding here;
            # MCQ rows score per-record with no padded batch at all.
            open_prox = [group[i][2] for i in open_idx]
            if open_prox:
                pad_waste += sum(max(open_prox) - p for p in open_prox)
            if open_idx:  # optionless records still BATCH their decode
                open_replies = pipe.chat_batch(
                    [requests[i] for i in open_idx],
                    max_new_tokens=max_new_tokens,
                )
                for i, r in zip(open_idx, open_replies):
                    replies[i] = r
            for i, (req, (_, rec, _)) in enumerate(zip(requests, group)):
                opts = rec.get("options")
                if opts:
                    scores = pipe.score_options(
                        req["question"], LETTERS[: len(opts)],
                        images=req["images"], is_video=req["is_video"],
                    )
                    replies[i] = LETTERS[int(scores.argmax())]
        else:
            proxies = [p for _, _, p in group]
            pad_waste += sum(max(proxies) - p for p in proxies)
            replies = pipe.chat_batch(
                requests, max_new_tokens=max_new_tokens
            )
        for (gi, rec, _), reply in zip(group, replies):
            ok = score_record(rec, reply)
            correct += ok
            row = {"id": rec.get("id", gi), "reply": reply, "correct": ok}
            if rec.get("meta"):
                # Adapter-provided tags (duration, question_type, ...)
                # ride along for per-category accuracy breakdowns.
                row["meta"] = rec["meta"]
            out.append(row)
        n = len(out)
        if log_every and (n % log_every < len(group) or n == len(mine)):
            print(f"[eval] {n}/{len(mine)} acc={correct / n:.4f}", flush=True)
    dt = time.perf_counter() - t0
    if log_every and mine:
        print(f"[eval] pad_waste={pad_waste} proxy tokens "
              f"(length_group={'on' if length_group else 'off'})",
              flush=True)
    acc = correct / max(len(mine), 1)
    return EvalResult(acc, correct, len(mine), dt, out)


def merge_results(results: Sequence[EvalResult]) -> EvalResult:
    """Merge per-process shard results (the reference's accelerate-split
    eval aggregation): accuracy re-derived from summed counts, wall time =
    max over processes (they run concurrently), records concatenated."""
    if not results:
        raise ValueError("no results to merge")
    correct = sum(r.num_correct for r in results)
    total = sum(r.num_total for r in results)
    return EvalResult(
        accuracy=correct / max(total, 1),
        num_correct=correct,
        num_total=total,
        seconds=max(r.seconds for r in results),
        records=[rec for r in results for rec in r.records],
    )


def breakdown(result: EvalResult, key: str) -> dict[str, dict[str, Any]]:
    """Per-category accuracy over a meta tag (lmms-eval's per-split
    reporting: VideoMME by `duration`, MLVU/NextQA by question type).
    Records without the tag land under "<untagged>"."""
    groups: dict[str, list[int]] = {}
    for r in result.records:
        cat = str((r.get("meta") or {}).get(key, "<untagged>"))
        g = groups.setdefault(cat, [0, 0])
        g[0] += bool(r["correct"])
        g[1] += 1
    return {
        cat: {"accuracy": c / max(n, 1), "n": n}
        for cat, (c, n) in sorted(groups.items())
    }


def _print_summary(result: EvalResult, by: list[str] | None = None) -> None:
    rec: dict[str, Any] = {
        "accuracy": result.accuracy, "n": result.num_total,
        "seconds": round(result.seconds, 1),
    }
    for key in by or []:
        rec[f"by_{key}"] = breakdown(result, key)
    print(json.dumps(rec))


def _write_output(result: EvalResult, path: str) -> None:
    outdir = os.path.dirname(os.path.abspath(path))
    os.makedirs(outdir, exist_ok=True)
    with open(path, "w") as f:
        json.dump(result.to_dict(), f, indent=2)


def main(argv: list[str] | None = None) -> None:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    # Merge mode is parsed by a dedicated pre-parser so --merge=FILE and
    # abbreviations work, and any flag it doesn't know is an error rather
    # than silently dropped.
    pre = argparse.ArgumentParser(add_help=False)
    pre.add_argument("--merge", nargs="+", default=None)
    pre.add_argument("--output", default=None)
    pre.add_argument("--by", nargs="+", default=None)
    pre_args, rest = pre.parse_known_args(argv)
    if pre_args.merge is not None:
        if rest:
            raise SystemExit(
                f"unrecognized arguments with --merge: {rest}"
            )
        merged = merge_results([
            EvalResult(**json.load(open(p))) for p in pre_args.merge
        ])
        _print_summary(merged, by=pre_args.by)
        if pre_args.output:
            _write_output(merged, pre_args.output)
        return

    ap = argparse.ArgumentParser(description="Oryx-TPU benchmark eval")
    ap.add_argument(
        "--merge", nargs="+", default=None, metavar="RESULTS_JSON",
        help="merge per-process result files (from --output) and exit",
    )
    ap.add_argument("--model-path", required=True)
    ap.add_argument("--tokenizer-path", default=None)
    ap.add_argument(
        "--task", required=True, help="task .json/.jsonl/.csv file"
    )
    ap.add_argument(
        "--format", default="native",
        help="task record format: native|videomme|mlvu|mvbench|nextqa",
    )
    ap.add_argument("--media-root", default="")
    ap.add_argument(
        "--by", nargs="+", default=None, metavar="META_KEY",
        help="per-category accuracy breakdown over adapter meta tags "
        "(e.g. --by duration task_type)",
    )
    ap.add_argument("--num-frames", type=int, default=64)
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--output", default=None, help="results json path")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument(
        "--no-length-group", action="store_true",
        help="keep dataset order instead of sorting batches by "
        "(modality, length) — more padding, reproducible order",
    )
    ap.add_argument(
        "--scoring", default="generate",
        choices=["generate", "loglikelihood"],
        help="MCQ protocol: decode-and-parse the letter (generate) or "
        "pick the letter with the highest teacher-forced log-prob "
        "(loglikelihood; lmms-eval's second model API)",
    )
    ap.add_argument("--process-index", type=int, default=0)
    ap.add_argument("--process-count", type=int, default=1)
    ap.add_argument(
        "--shard", default=None, metavar="MODE=N",
        help="multi-chip serving (tp=8 / fsdp=8) for models that exceed "
        "one chip; combine with --process-* to also split the dataset "
        "across hosts",
    )
    ap.add_argument(
        "--quantize", default=None, choices=["int8"],
        help="weight-only int8 for single-chip serving",
    )
    args = ap.parse_args(argv)
    if args.quantize and args.shard:
        ap.error("--quantize is single-chip serving; drop --shard")

    from oryx_tpu.eval.adapters import adapt
    from oryx_tpu.parallel.mesh import parse_shard_arg
    from oryx_tpu.serve.builder import load_pipeline

    try:
        mesh, mode = parse_shard_arg(args.shard)
    except ValueError as e:
        ap.error(str(e))
    pipe = load_pipeline(
        args.model_path, tokenizer_path=args.tokenizer_path,
        mesh=mesh, sharding_mode=mode, quantize=args.quantize,
    )
    records = adapt(args.format, load_task(args.task))
    result = evaluate(
        pipe, records,
        media_root=args.media_root, num_frames=args.num_frames,
        max_new_tokens=args.max_new_tokens, batch_size=args.batch_size,
        process_index=args.process_index, process_count=args.process_count,
        length_group=not args.no_length_group,
        scoring=args.scoring,
    )
    _print_summary(result, by=args.by)
    if args.output:
        _write_output(result, args.output)


if __name__ == "__main__":
    main()
