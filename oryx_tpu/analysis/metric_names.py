"""metric-name: one naming discipline for every metric family.

PR 3 unified train and serve onto one Prometheus-model `Registry`, and
the contract that keeps dashboards and the runbook greppable is
lexical: every family renders as `oryx_<...>` in lowercase snake_case,
and a name means ONE thing — the registry enforces no-duplicate-family
at runtime, this rule enforces it at review time, across modules, for
both registries at once.

Checked call shapes (any receiver; the first argument must name the
family):

  declarations  reg.counter("x") / .gauge / .histogram / .info(...)
  usages        metrics.inc("x") / .set_gauge / .observe / .set_info

Rules:
  * literal names match `^[a-z][a-z0-9_]*$` (the registry prefix
    supplies the `oryx_` vendor prefix); with `raw_name=True` the
    literal IS the full family name and must match
    `^oryx_[a-z0-9_]+$`.
  * a family name must resolve to exactly one metric kind repo-wide:
    `inc("queue_depth")` in one file and `set_gauge("queue_depth")`
    in another is the split-brain this catches (the runtime error
    only fires when both code paths run in one process).
  * declaration names must be string literals — a computed name can't
    be checked, greped for, or pre-registered; tabulate the names and
    suppress the loop with a justification if you must.

`.info(...)` is only treated as a metric declaration when the receiver
looks like a registry (`...registry.info` / `reg.info`) so ordinary
`logger.info("...")` lines never match.

Wide-event schema (PR 12, extended PR 14): the same rule also checks
every wide-event builder call site (utils/request_log.py:
``build_request_event`` / ``build_oom_event`` / ``build_audit_event``;
serve/journal.py: ``build_journal_event``) — each literal keyword
field must be snake_case AND drawn from that builder's declared
registry in utils/metrics.py (``REQUEST_EVENT_KEYS`` — a superset of
``REQUEST_COST_KEYS`` — ``OOM_EVENT_KEYS``, ``AUDIT_EVENT_KEYS``,
``JOURNAL_EVENT_KEYS``; the builder->registry table is
``_EVENT_BUILDERS``). The registries are read from the canonical
metrics module's AST (never imported — metrics.py imports jax), so
the check works in single-file fixture runs too. A ``**splat`` passes
statically (runtime validation in the builders covers it); a literal
key outside the registry is exactly the silent-schema-drift this
catches.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from oryx_tpu.analysis.core import (
    Checker,
    Finding,
    ParsedModule,
    RepoContext,
    dotted_name,
)

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_RAW_NAME_RE = re.compile(r"^oryx_[a-z0-9_]+$")

# method -> metric kind it declares/uses.
_DECLARING = {"counter": "counter", "gauge": "gauge",
              "histogram": "histogram", "info": "info"}
_USING = {"inc": "counter", "set_gauge": "gauge",
          "observe": "histogram", "set_info": "info"}


# Every wide-event builder (utils/request_log.py) and the declared
# schema registry in utils/metrics.py its literal keyword fields must
# come from. One table, so adding an event kind means adding its
# builder + registry pair here and nothing else in the rule.
_EVENT_BUILDERS = {
    "build_request_event": "REQUEST_EVENT_KEYS",
    "build_oom_event": "OOM_EVENT_KEYS",
    "build_audit_event": "AUDIT_EVENT_KEYS",
    "build_journal_event": "JOURNAL_EVENT_KEYS",
}
_EVENT_KEYS_CACHE: tuple[dict[str, frozenset[str]] | None, bool] = (
    None, False,
)


def _event_keys() -> dict[str, frozenset[str]] | None:
    """The wide-event schema registries resolved from utils/metrics.py
    by AST ({registry name: keys}; REQUEST_EVENT_KEYS is
    REQUEST_COST_KEYS + a literal extension). None when the module or
    the assignments can't be found — the check then stays quiet rather
    than guessing a schema."""
    global _EVENT_KEYS_CACHE
    keys, loaded = _EVENT_KEYS_CACHE
    if loaded:
        return keys
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        os.pardir, "utils", "metrics.py",
    )
    wanted = {"REQUEST_COST_KEYS"} | set(_EVENT_BUILDERS.values())
    resolved: dict[str, tuple[str, ...]] = {}
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
        for node in tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id in wanted
            ):
                continue
            name = node.targets[0].id
            val = node.value
            parts: list[str] = []
            terms = (
                [val.left, val.right]
                if isinstance(val, ast.BinOp)
                and isinstance(val.op, ast.Add) else [val]
            )
            for term in terms:
                if isinstance(term, ast.Name):
                    parts += list(resolved.get(term.id, ()))
                elif isinstance(term, ast.Tuple):
                    parts += [
                        e.value for e in term.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    ]
            resolved[name] = tuple(parts)
        keys = {
            reg: frozenset(resolved[reg])
            for reg in _EVENT_BUILDERS.values()
            if resolved.get(reg)
        } or None
    except (OSError, SyntaxError):
        keys = None
    _EVENT_KEYS_CACHE = (keys, True)
    return keys


def _event_builder_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id in _EVENT_BUILDERS:
        return fn.id
    if isinstance(fn, ast.Attribute) and fn.attr in _EVENT_BUILDERS:
        return fn.attr
    return None


def _metric_call(call: ast.Call) -> tuple[str, str, bool] | None:
    """(kind, method, is_declaration) for metric-family call shapes."""
    if not isinstance(call.func, ast.Attribute):
        return None
    method = call.func.attr
    if method in _DECLARING:
        if method == "info":
            recv = dotted_name(call.func.value) or ""
            tail = recv.rsplit(".", 1)[-1]
            if not (tail in ("reg", "r") or "registr" in tail):
                return None
        return _DECLARING[method], method, True
    if method in _USING:
        return _USING[method], method, False
    return None


class MetricNameChecker(Checker):
    name = "metric-name"

    # ---- pass 1: gather every (name, kind) site --------------------------

    def scan(self, mod: ParsedModule, ctx: RepoContext) -> None:
        for call in mod.walk():
            if not isinstance(call, ast.Call):
                continue
            mk = _metric_call(call)
            if mk is None or not call.args:
                continue
            if mod.suppressed(call.lineno, self.name):
                # A suppressed site (a deliberate kind-clash test, the
                # registry plumbing) must not poison the cross-module
                # kind map and flag CORRECT usages elsewhere.
                continue
            arg0 = call.args[0]
            if not (
                isinstance(arg0, ast.Constant)
                and isinstance(arg0.value, str)
            ):
                continue
            kind, _, _ = mk
            ctx.metric_sites.setdefault(arg0.value, {}).setdefault(
                kind, []
            ).append((mod.path, call.lineno))

    # ---- pass 2 ----------------------------------------------------------

    def check(
        self, mod: ParsedModule, ctx: RepoContext
    ) -> Iterator[Finding | None]:
        for call in mod.walk():
            if not isinstance(call, ast.Call):
                continue
            builder = _event_builder_name(call)
            if builder is not None:
                yield from self._check_event_fields(mod, call, builder)
                continue
            mk = _metric_call(call)
            if mk is None or not call.args:
                continue
            kind, method, declares = mk
            arg0 = call.args[0]
            if not (
                isinstance(arg0, ast.Constant)
                and isinstance(arg0.value, str)
            ):
                if declares:
                    yield self.finding(
                        mod,
                        call,
                        f"metric family declared via .{method}() with "
                        "a computed name — declare family names as "
                        "string literals so they can be checked and "
                        "grepped",
                    )
                continue
            name = arg0.value
            raw = any(
                kw.arg == "raw_name"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in call.keywords
            )
            pattern = _RAW_NAME_RE if raw else _NAME_RE
            if not pattern.match(name):
                want = (
                    "oryx_<snake_case> (raw_name=True names are full "
                    "family names)" if raw else "lowercase snake_case "
                    "(the registry prefix supplies oryx_)"
                )
                yield self.finding(
                    mod,
                    call,
                    f"metric family name {name!r} does not match the "
                    f"naming discipline: expected {want}",
                )
                continue
            kinds = ctx.metric_sites.get(name, {})
            if len(kinds) > 1:
                others = sorted(k for k in kinds if k != kind)
                where = "; ".join(
                    f"{k} at {kinds[k][0][0]}:{kinds[k][0][1]}"
                    for k in others
                )
                yield self.finding(
                    mod,
                    call,
                    f"metric family {name!r} used as a {kind} here "
                    f"but declared/used elsewhere as: {where} — one "
                    "family, one kind",
                )

    # ---- wide-event schema (utils/request_log.build_request_event) -------

    def _check_event_fields(
        self, mod: ParsedModule, call: ast.Call, builder: str
    ) -> Iterator[Finding | None]:
        """Literal keyword fields of a wide-event builder call
        (build_request_event / build_oom_event / build_audit_event /
        build_journal_event)
        must be snake_case members of that builder's declared schema
        registry. `**splat` fields pass here (the builders re-validate
        at runtime); the defining module itself (utils/request_log.py,
        where the names are defs, not calls into the registry
        contract) contains no call sites, so no special-casing is
        needed."""
        registries = _event_keys()
        reg_name = _EVENT_BUILDERS[builder]
        registry = (registries or {}).get(reg_name)
        for kw in call.keywords:
            if kw.arg is None:
                continue  # **splat: runtime-validated
            if not _NAME_RE.match(kw.arg):
                yield self.finding(
                    mod,
                    call,
                    f"wide-event field {kw.arg!r} is not lowercase "
                    "snake_case (the wide-event schemas are "
                    "snake_case throughout)",
                )
            elif registry is not None and kw.arg not in registry:
                yield self.finding(
                    mod,
                    call,
                    f"wide-event field {kw.arg!r} is not declared in "
                    f"utils.metrics.{reg_name} — extend the "
                    "registry (and the docs) instead of letting the "
                    "JSONL schema drift",
                )
