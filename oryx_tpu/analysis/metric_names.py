"""metric-name: one naming discipline for every metric family.

PR 3 unified train and serve onto one Prometheus-model `Registry`, and
the contract that keeps dashboards and the runbook greppable is
lexical: every family renders as `oryx_<...>` in lowercase snake_case,
and a name means ONE thing — the registry enforces no-duplicate-family
at runtime, this rule enforces it at review time, across modules, for
both registries at once.

Checked call shapes (any receiver; the first argument must name the
family):

  declarations  reg.counter("x") / .gauge / .histogram / .info(...)
  usages        metrics.inc("x") / .set_gauge / .observe / .set_info

Rules:
  * literal names match `^[a-z][a-z0-9_]*$` (the registry prefix
    supplies the `oryx_` vendor prefix); with `raw_name=True` the
    literal IS the full family name and must match
    `^oryx_[a-z0-9_]+$`.
  * a family name must resolve to exactly one metric kind repo-wide:
    `inc("queue_depth")` in one file and `set_gauge("queue_depth")`
    in another is the split-brain this catches (the runtime error
    only fires when both code paths run in one process).
  * declaration names must be string literals — a computed name can't
    be checked, greped for, or pre-registered; tabulate the names and
    suppress the loop with a justification if you must.

`.info(...)` is only treated as a metric declaration when the receiver
looks like a registry (`...registry.info` / `reg.info`) so ordinary
`logger.info("...")` lines never match.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from oryx_tpu.analysis.core import (
    Checker,
    Finding,
    ParsedModule,
    RepoContext,
    dotted_name,
)

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_RAW_NAME_RE = re.compile(r"^oryx_[a-z0-9_]+$")

# method -> metric kind it declares/uses.
_DECLARING = {"counter": "counter", "gauge": "gauge",
              "histogram": "histogram", "info": "info"}
_USING = {"inc": "counter", "set_gauge": "gauge",
          "observe": "histogram", "set_info": "info"}


def _metric_call(call: ast.Call) -> tuple[str, str, bool] | None:
    """(kind, method, is_declaration) for metric-family call shapes."""
    if not isinstance(call.func, ast.Attribute):
        return None
    method = call.func.attr
    if method in _DECLARING:
        if method == "info":
            recv = dotted_name(call.func.value) or ""
            tail = recv.rsplit(".", 1)[-1]
            if not (tail in ("reg", "r") or "registr" in tail):
                return None
        return _DECLARING[method], method, True
    if method in _USING:
        return _USING[method], method, False
    return None


class MetricNameChecker(Checker):
    name = "metric-name"

    # ---- pass 1: gather every (name, kind) site --------------------------

    def scan(self, mod: ParsedModule, ctx: RepoContext) -> None:
        for call in ast.walk(mod.tree):
            if not isinstance(call, ast.Call):
                continue
            mk = _metric_call(call)
            if mk is None or not call.args:
                continue
            if mod.suppressed(call.lineno, self.name):
                # A suppressed site (a deliberate kind-clash test, the
                # registry plumbing) must not poison the cross-module
                # kind map and flag CORRECT usages elsewhere.
                continue
            arg0 = call.args[0]
            if not (
                isinstance(arg0, ast.Constant)
                and isinstance(arg0.value, str)
            ):
                continue
            kind, _, _ = mk
            ctx.metric_sites.setdefault(arg0.value, {}).setdefault(
                kind, []
            ).append((mod.path, call.lineno))

    # ---- pass 2 ----------------------------------------------------------

    def check(
        self, mod: ParsedModule, ctx: RepoContext
    ) -> Iterator[Finding | None]:
        for call in ast.walk(mod.tree):
            if not isinstance(call, ast.Call):
                continue
            mk = _metric_call(call)
            if mk is None or not call.args:
                continue
            kind, method, declares = mk
            arg0 = call.args[0]
            if not (
                isinstance(arg0, ast.Constant)
                and isinstance(arg0.value, str)
            ):
                if declares:
                    yield self.finding(
                        mod,
                        call,
                        f"metric family declared via .{method}() with "
                        "a computed name — declare family names as "
                        "string literals so they can be checked and "
                        "grepped",
                    )
                continue
            name = arg0.value
            raw = any(
                kw.arg == "raw_name"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in call.keywords
            )
            pattern = _RAW_NAME_RE if raw else _NAME_RE
            if not pattern.match(name):
                want = (
                    "oryx_<snake_case> (raw_name=True names are full "
                    "family names)" if raw else "lowercase snake_case "
                    "(the registry prefix supplies oryx_)"
                )
                yield self.finding(
                    mod,
                    call,
                    f"metric family name {name!r} does not match the "
                    f"naming discipline: expected {want}",
                )
                continue
            kinds = ctx.metric_sites.get(name, {})
            if len(kinds) > 1:
                others = sorted(k for k in kinds if k != kind)
                where = "; ".join(
                    f"{k} at {kinds[k][0][0]}:{kinds[k][0][1]}"
                    for k in others
                )
                yield self.finding(
                    mod,
                    call,
                    f"metric family {name!r} used as a {kind} here "
                    f"but declared/used elsewhere as: {where} — one "
                    "family, one kind",
                )
