"""lock-order / atomicity: ordering and atomicity BETWEEN locks.

The lock-discipline rule (locks.py) proves each guarded field is
touched under ITS lock; these two rules prove the locks compose:

  * `lock-order` — the repo declares its lock-acquisition order once
    (oryx_tpu/concurrency.py):

        # lock-order: scheduler._cond < trace._lock < registry._lock

    Locks are named at their creation site
    (`self._cond = named_lock("scheduler._cond", ...)`, or a
    `# lock-name: <name>` comment on the assignment). This checker
    builds the repo-wide may-acquire-while-holding graph from
    `with self.<lock>:` nesting — interprocedurally: a call made while
    holding a lock inherits the held set, and the callee's transitive
    may-acquire set lands as edges — and reports (a) any edge that
    inverts the declared order, (b) any cycle among locks the manifest
    doesn't rank, (c) a call to a `# hot-path` function made while
    holding any lock (a device dispatch under a lock serializes the
    whole stack on device latency), and (d) contradictory manifest
    declarations.

  * `atomicity` — check-then-act on a `# guarded-by:` field where the
    lock is RELEASED between the check and the dependent act (the
    exact shape of the queue-depth-gauge bugs PR 5 found by hand):

        with self._cond:
            if not self._queue:
                return              # checked under the lock...
        ...
        with self._cond:
            self._queue.popleft()   # ...acted on after releasing it

    Two shapes are flagged: an early-exit check (the guarded test's
    body ends in return/break/continue/raise) followed by a later
    same-lock block mutating the same field, and a value read under
    the lock that escapes to a local whose test guards a later
    same-lock mutation. Sites that are safe for a structural reason
    the checker can't see (single-consumer queues) carry a per-line
    `# oryxlint: disable=atomicity` with the reason — the suppression
    IS the documentation of the concurrency model.
"""

from __future__ import annotations

import ast
import re
import types
from typing import Iterator

from oryx_tpu.analysis.core import (
    Checker,
    Finding,
    ParsedModule,
    RepoContext,
    dotted_name,
    field_annotations,
)
from oryx_tpu.analysis.hostsync import is_hot

# The chain stops at a second '#' so a trailing comment (fixtures'
# `# expect:` markers) never becomes a lock name.
_LOCK_ORDER_RE = re.compile(r"#\s*lock-order:\s*([^#]+)")
_LOCK_NAME_RE = re.compile(r"#\s*lock-name:\s*([\w.\-]+)")
_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# Method names owned by stdlib containers/primitives: never resolved
# by bare name (a `self._queue.clear()` must not alias to a repo
# class's `clear`). Typed receivers (`self.prefix_cache.clear()`,
# where the attr's class is known) still resolve precisely.
_STDLIB_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "remove", "clear", "update", "add", "discard", "get", "keys",
    "values", "items", "setdefault", "put", "put_nowait", "get_nowait",
    "qsize", "join", "start", "run", "wait", "wait_for", "notify",
    "notify_all", "acquire", "release", "locked", "set", "is_set",
    "sort", "reverse", "count", "index", "copy", "split", "strip",
    "lower", "upper", "format", "encode", "decode", "read", "write",
    "flush", "close", "open", "seek", "tell", "search", "match",
    "finditer", "findall", "group", "sub", "replace", "startswith",
    "endswith", "is_alive", "item", "tolist", "tobytes", "astype",
    "reshape", "sum", "min", "max", "mean", "any", "all", "fill",
})


def _terminal_names(node: ast.AST) -> list[str]:
    """Candidate class-ish names mentioned in an annotation or value
    expression: `trace_lib.Tracer` -> Tracer, `X | None` -> X, ..."""
    out: list[str] = []
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute):
            out.append(n.attr)
        elif isinstance(n, ast.Name):
            out.append(n.id)
    return out


class _Fn:
    """One function's lock summary (scan pass)."""

    __slots__ = ("path", "cls", "name", "hot",
                 "acquires", "calls", "may_acquire")

    def __init__(self, path: str, cls: str | None, name: str, hot: bool):
        self.path = path
        self.cls = cls
        self.name = name
        self.hot = hot
        # (lock_name, frozenset(held), line)
        self.acquires: list[tuple[str, frozenset, int]] = []
        # (ref, frozenset(held), line); ref is ("self", m) /
        # ("selfattr", attr, m) / ("mod", alias, f) / ("any", m) /
        # ("bare", f)
        self.calls: list[tuple[tuple, frozenset, int]] = []
        self.may_acquire: set[str] = set()


class LockOrderChecker(Checker):
    name = "lock-order"

    def __init__(self) -> None:
        # Scan-pass accumulators (instance-scoped: runner builds fresh
        # checkers per run_lint call).
        self.methods: dict[tuple[str, str], list[_Fn]] = {}
        self.functions: dict[tuple[str, str], list[_Fn]] = {}
        self.class_locks: dict[tuple[str, str], str] = {}
        self.attr_ann: dict[tuple[str, str], set[str]] = {}
        self.known_classes: set[str] = set()
        self.name_locks: dict[tuple[str, str], str] = {}  # (path, var)
        self.manifest: list[tuple[str, int, list[str]]] = []
        self.imports: dict[str, dict[str, str]] = {}  # path -> alias->modtail
        self._analyzed: dict | None = None
        # Lazy name -> [_Fn] indexes (built once, first _resolve).
        self._name_index: dict[str, list[_Fn]] | None = None
        self._fn_name_index: dict[str, list[_Fn]] | None = None

    # ------------------------------------------------------------------
    # scan pass
    # ------------------------------------------------------------------

    def scan(self, mod: ParsedModule, ctx: RepoContext) -> None:
        path = mod.path
        for line, text in sorted(mod.comments().items()):
            m = _LOCK_ORDER_RE.search(text)
            if m:
                chain = [p.strip() for p in m.group(1).split("<")]
                chain = [p for p in chain if p]
                if len(chain) >= 2:
                    self.manifest.append((path, line, chain))
        imap = self.imports.setdefault(path, {})
        for node in mod.nodes_of(ast.Import, ast.ImportFrom):
            if isinstance(node, ast.Import):
                for a in node.names:
                    imap[a.asname or a.name.split(".")[0]] = \
                        a.name.rsplit(".", 1)[-1]
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    imap[a.asname or a.name] = a.name
        modtail = path.rsplit("/", 1)[-1].removesuffix(".py")
        # Pass A: declarations — name-lock bindings anywhere, class
        # field types, self.<attr> lock declarations — so the held-set
        # walk below resolves locks regardless of source order.
        for node in mod.nodes_of(
            ast.ClassDef, ast.Assign, ast.AnnAssign
        ):
            if isinstance(node, ast.ClassDef):
                self.known_classes.add(node.name)
                for item in node.body:
                    if isinstance(item, ast.AnnAssign) and isinstance(
                        item.target, ast.Name
                    ):
                        self.attr_ann.setdefault(
                            (node.name, item.target.id), set()
                        ).update(_terminal_names(item.annotation))
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        self._scan_attr_decls(mod, item, node.name)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                self._scan_lock_decl(
                    mod, node,
                    module_level=isinstance(
                        mod.parent(node), ast.Module
                    ),
                    modtail=modtail,
                )
        # Pass B: one summary per function, owner class = direct
        # parent ClassDef (nested closures register by bare name).
        for node in mod.nodes_of(
            ast.FunctionDef, ast.AsyncFunctionDef
        ):
            owner = mod.parent(node)
            cls = owner.name if isinstance(owner, ast.ClassDef) else None
            info = _Fn(path, cls, node.name, is_hot(mod, node))
            if cls is not None:
                self.methods.setdefault((cls, node.name), []).append(info)
            else:
                self.functions.setdefault(
                    (modtail, node.name), []
                ).append(info)
            self._walk_held(mod, info, node.body, frozenset(),
                            cls=cls, modtail=modtail)

    def _scan_lock_decl(self, mod, node, *, module_level, modtail) -> None:
        """Register `x = named_lock(...)` / `x = threading.Lock()` (and
        `# lock-name:` annotated) NAME assignments as known locks.
        Unannotated function-local plain locks are deliberately
        invisible: tests build throwaway lock pairs all the time, and
        only locks someone bothered to name participate in ordering."""
        targets = (
            node.targets if isinstance(node, ast.Assign)
            else [node.target]
        )
        value = node.value
        if value is None or len(targets) != 1:
            return
        target = targets[0]
        if not isinstance(target, ast.Name):
            return
        named, factory = self._lock_value(value)
        comment = _LOCK_NAME_RE.search(mod.comment_text(node.lineno))
        var = target.id
        if comment:
            self.name_locks[(mod.path, var)] = comment.group(1)
        elif named:
            self.name_locks[(mod.path, var)] = named
        elif factory and module_level:
            self.name_locks[(mod.path, var)] = f"{modtail}.{var}"

    def _lock_value(self, value: ast.AST) -> tuple[str | None, bool]:
        """(explicit name from a named_lock("...") call, any lock
        factory present) anywhere inside the value expression."""
        named = None
        factory = False
        for n in ast.walk(value):
            if not isinstance(n, ast.Call):
                continue
            fname = (
                n.func.id if isinstance(n.func, ast.Name)
                else n.func.attr if isinstance(n.func, ast.Attribute)
                else None
            )
            if fname == "named_lock":
                factory = True
                if n.args and isinstance(n.args[0], ast.Constant) \
                        and isinstance(n.args[0].value, str):
                    named = n.args[0].value
            elif fname in _LOCK_FACTORIES:
                factory = True
        return named, factory

    def _scan_attr_decls(self, mod, fn, cls: str) -> None:
        """self.<attr> assignments: lock declarations and attr types."""
        param_ann = {
            a.arg: set(_terminal_names(a.annotation))
            for a in list(fn.args.args) + list(fn.args.kwonlyargs)
            if a.annotation is not None
        }
        for node in mod.walk(fn):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            if len(targets) != 1 or node.value is None:
                continue
            t = targets[0]
            if not (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name) and t.value.id == "self"
            ):
                continue
            attr = t.attr
            named, factory = self._lock_value(node.value)
            comment = _LOCK_NAME_RE.search(mod.comment_text(node.lineno))
            if comment:
                self.class_locks[(cls, attr)] = comment.group(1)
            elif named:
                self.class_locks[(cls, attr)] = named
            elif factory:
                self.class_locks.setdefault(
                    (cls, attr), f"{cls}.{attr}"
                )
            ann = set(_terminal_names(node.value))
            if isinstance(node, ast.AnnAssign):
                ann |= set(_terminal_names(node.annotation))
            if isinstance(node.value, ast.Name) \
                    and node.value.id in param_ann:
                ann |= param_ann[node.value.id]
            if ann:
                self.attr_ann.setdefault((cls, attr), set()).update(ann)

    # ------------------------------------------------------------------
    # held-set walk
    # ------------------------------------------------------------------

    def _with_lock(self, mod, item: ast.withitem, cls, modtail
                   ) -> str | None:
        d = dotted_name(item.context_expr)
        if d is None:
            return None
        if d.startswith("self.") and d.count(".") == 1:
            attr = d.split(".", 1)[1]
            if cls is not None and (cls, attr) in self.class_locks:
                return self.class_locks[(cls, attr)]
            return None
        if "." not in d:
            return self.name_locks.get((mod.path, d))
        return None

    def _walk_held(self, mod, info: _Fn, body, held: frozenset,
                   *, cls, modtail) -> None:
        for node in body:
            self._walk_node(mod, info, node, held, cls=cls,
                            modtail=modtail)

    def _walk_node(self, mod, info, node, held, *, cls, modtail) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return  # nested scopes summarize separately
        if isinstance(node, ast.With):
            got = set(held)
            for item in node.items:
                self._walk_node(mod, info, item.context_expr, held,
                                cls=cls, modtail=modtail)
                lock = self._with_lock(mod, item, cls, modtail)
                if lock is not None:
                    info.acquires.append((lock, frozenset(got),
                                          node.lineno))
                    got.add(lock)
            inner = frozenset(got)
            self._walk_held(mod, info, node.body, inner,
                            cls=cls, modtail=modtail)
            return
        # Generic statement/expression: scan the preorder slice,
        # skipping nested-scope subtrees whole and handing With
        # subtrees back to the held-set logic (recursing node-by-node
        # costs a Python frame per AST node; this is the same
        # traversal over a precomputed list).
        sub = mod.walk(node)
        i, total = 0, len(sub)
        while i < total:
            n = sub[i]
            if n is not node:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                    i += mod.subtree_size(n)
                    continue
                if isinstance(n, ast.With):
                    self._walk_node(mod, info, n, held, cls=cls,
                                    modtail=modtail)
                    i += mod.subtree_size(n)
                    continue
            if isinstance(n, ast.Call):
                ref = self._call_ref(n)
                if ref is not None:
                    info.calls.append((ref, held, n.lineno))
            i += 1

    def _call_ref(self, call: ast.Call) -> tuple | None:
        func = call.func
        if isinstance(func, ast.Name):
            return ("bare", func.id)
        if not isinstance(func, ast.Attribute):
            return None
        base = func.value
        if isinstance(base, ast.Name):
            if base.id == "self":
                return ("self", func.attr)
            return ("mod", base.id, func.attr)
        if isinstance(base, ast.Attribute) and isinstance(
            base.value, ast.Name
        ) and base.value.id == "self":
            return ("selfattr", base.attr, func.attr)
        return ("any", func.attr)

    # ------------------------------------------------------------------
    # check pass (graph analysis runs once, findings emitted per module)
    # ------------------------------------------------------------------

    def _resolve(self, info: _Fn, ref: tuple, path: str) -> list[_Fn]:
        kind = ref[0]
        if kind == "self" and info.cls is not None:
            hit = self.methods.get((info.cls, ref[1]))
            if hit:
                return hit
            return self._by_name(ref[1])
        if kind == "selfattr" and info.cls is not None:
            out: list[_Fn] = []
            for t in self.attr_ann.get((info.cls, ref[1]), ()):
                out.extend(self.methods.get((t, ref[2]), ()))
            if out:
                return out
            return self._by_name(ref[2])
        if kind == "mod":
            alias, f = ref[1], ref[2]
            tail = self.imports.get(path, {}).get(alias, alias)
            hit = self.functions.get((tail, f))
            if hit:
                return hit
            return self._by_name(f)
        if kind == "bare":
            f = ref[1]
            tail = path.rsplit("/", 1)[-1].removesuffix(".py")
            hit = self.functions.get((tail, f))
            if hit:
                return hit
            ctor = self.methods.get((f, "__init__"))
            if ctor:
                return ctor
            if self._fn_name_index is None:
                idx: dict[str, list[_Fn]] = {}
                for (_, name), fns in self.functions.items():
                    idx.setdefault(name, []).extend(fns)
                self._fn_name_index = idx
            return self._fn_name_index.get(f, [])
        if kind in ("self", "selfattr", "any"):
            return self._by_name(ref[-1])
        return []

    def _by_name(self, m: str) -> list[_Fn]:
        if m in _STDLIB_METHODS:
            return []
        if self._name_index is None:
            idx: dict[str, list[_Fn]] = {}
            for (_, name), fns in self.methods.items():
                idx.setdefault(name, []).extend(fns)
            for (_, name), fns in self.functions.items():
                idx.setdefault(name, []).extend(fns)
            self._name_index = idx
        return self._name_index.get(m, [])

    def _analyze(self) -> dict:
        if self._analyzed is not None:
            return self._analyzed
        all_fns: list[_Fn] = [
            f for fns in list(self.methods.values())
            + list(self.functions.values()) for f in fns
        ]
        resolved: dict[int, list[list[_Fn]]] = {}
        for f in all_fns:
            resolved[id(f)] = [
                self._resolve(f, ref, f.path) for ref, _, _ in f.calls
            ]
        # may-acquire fixpoint over the call graph.
        for f in all_fns:
            f.may_acquire = {l for l, _, _ in f.acquires}
        changed = True
        while changed:
            changed = False
            for f in all_fns:
                for callees in resolved[id(f)]:
                    for g in callees:
                        extra = g.may_acquire - f.may_acquire
                        if extra:
                            f.may_acquire |= extra
                            changed = True
        # Observed edges (held -> acquired) with first witness.
        edges: dict[tuple[str, str], tuple[str, int, str]] = {}
        hot_sites: list[tuple[str, int, str, str]] = []
        for f in all_fns:
            for lock, heldset, line in f.acquires:
                for h in heldset:
                    if h != lock:
                        edges.setdefault(
                            (h, lock),
                            (f.path, line,
                             f"'with' nesting in {f.name}"),
                        )
            for (ref, heldset, line), callees in zip(
                f.calls, resolved[id(f)]
            ):
                if not heldset:
                    continue
                for g in callees:
                    if g.hot:
                        hot_sites.append(
                            (f.path, line, g.name,
                             ", ".join(sorted(heldset))),
                        )
                    for lock in g.may_acquire:
                        for h in heldset:
                            if h != lock:
                                edges.setdefault(
                                    (h, lock),
                                    (f.path, line,
                                     f"call to {g.name}() from "
                                     f"{f.name}"),
                                )
        # Declared order: consecutive pairs from every chain; conflicts
        # reported where the contradiction lands.
        declared: dict[str, set[str]] = {}
        conflicts: list[tuple[str, int, str]] = []

        def reaches(a: str, b: str) -> bool:
            seen, stack = set(), [a]
            while stack:
                n = stack.pop()
                if n == b:
                    return True
                if n in seen:
                    continue
                seen.add(n)
                stack.extend(declared.get(n, ()))
            return False

        for path, line, chain in sorted(self.manifest):
            for a, b in zip(chain, chain[1:]):
                if a == b or reaches(b, a):
                    conflicts.append((
                        path, line,
                        f"lock-order manifest declares '{a}' < '{b}' "
                        f"but '{b}' < '{a}' is already declared",
                    ))
                    continue
                declared.setdefault(a, set()).add(b)
        inversions: list[tuple[str, int, str]] = []
        inverted_edges: set[tuple[str, str]] = set()
        for (a, b), (path, line, how) in sorted(edges.items()):
            if reaches(b, a):
                inverted_edges.add((a, b))
                inversions.append((
                    path, line,
                    f"acquiring '{b}' while holding '{a}' inverts the "
                    f"declared lock order ('{b}' < '{a}'); via {how}",
                ))
        cycles = self._find_cycles(edges, inverted_edges)
        self._analyzed = {
            "inversions": inversions,
            "cycles": cycles,
            "conflicts": conflicts,
            "hot": [
                (path, line,
                 f"call to hot-path '{fn}()' while holding {held}: a "
                 "device dispatch under a lock serializes every other "
                 "thread on device latency")
                for path, line, fn, held in hot_sites
            ],
        }
        return self._analyzed

    def _find_cycles(self, edges, inverted_edges
                     ) -> list[tuple[str, int, str]]:
        """Cycles in the observed graph not already reported as
        declared-order inversions."""
        adj: dict[str, set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            work = [(v, iter(sorted(adj[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(sorted(adj[w]))))
                        advanced = True
                        break
                    if w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    sccs.append(scc)

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        out: list[tuple[str, int, str]] = []
        for scc in sccs:
            if len(scc) < 2:
                continue
            members = set(scc)
            scc_edges = sorted(
                (a, b) for (a, b) in edges
                if a in members and b in members
            )
            if any(e in inverted_edges for e in scc_edges):
                continue  # already reported as an inversion
            a, b = scc_edges[0]
            path, line, how = edges[(a, b)]
            out.append((
                path, line,
                "lock-order cycle among "
                f"{sorted(members)} (edge '{a}' -> '{b}' via {how}); "
                "declare an order in the lock-order manifest or break "
                "the nesting",
            ))
        return out

    def check(self, mod: ParsedModule, ctx: RepoContext
              ) -> Iterator[Finding | None]:
        res = self._analyze()
        for kind in ("conflicts", "inversions", "cycles", "hot"):
            for path, line, msg in res[kind]:
                if path != mod.path:
                    continue
                node = types.SimpleNamespace(lineno=line, col_offset=0)
                yield self.finding(mod, node, msg)


# ---------------------------------------------------------------------------
# atomicity
# ---------------------------------------------------------------------------

_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "remove", "clear", "update", "add", "discard", "setdefault",
    "reverse", "sort",
})


def _reads_field(node: ast.AST, field: str) -> bool:
    for n in ast.walk(node):
        if (
            isinstance(n, ast.Attribute)
            and isinstance(n.value, ast.Name)
            and n.value.id == "self"
            and n.attr == field
            and isinstance(n.ctx, ast.Load)
        ):
            return True
    return False


def _is_early_exit(body: list[ast.stmt]) -> bool:
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Break, ast.Continue, ast.Raise)
    )


class AtomicityChecker(Checker):
    name = "atomicity"

    def check(self, mod: ParsedModule, ctx: RepoContext
              ) -> Iterator[Finding | None]:
        for node in mod.nodes_of(ast.ClassDef):
            fields = {
                f: arg
                for f, (kind, arg) in
                field_annotations(mod, node).items()
                if kind == "guarded-by"
            }
            if not fields:
                continue
            for item in node.body:
                if isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and item.name != "__init__":
                    yield from self._check_method(mod, item, fields)

    def _lock_blocks(self, mod, fn, fields
                     ) -> list[tuple[str, ast.With]]:
        """(lock_attr, with_node) for every `with self.<lock>:` block
        over a lock that guards at least one annotated field."""
        locks = set(fields.values())
        out = []
        for node in mod.walk(fn):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                d = dotted_name(item.context_expr)
                if d and d.startswith("self.") \
                        and d[len("self."):] in locks:
                    out.append((d[len("self."):], node))
        out.sort(key=lambda p: p[1].lineno)
        return out

    def _mutations(self, block: ast.With, field: str) -> list[int]:
        lines = []
        for n in ast.walk(block):
            if isinstance(n, ast.Attribute) and isinstance(
                n.value, ast.Name
            ) and n.value.id == "self" and n.attr == field:
                if isinstance(n.ctx, (ast.Store, ast.Del)):
                    lines.append(n.lineno)
        for n in ast.walk(block):
            # self.F.<mutator>(...) and self.F[...] = ...
            if isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute
            ) and n.func.attr in _MUTATORS:
                base = n.func.value
                d = dotted_name(base)
                if d == f"self.{field}":
                    lines.append(n.lineno)
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = (
                    n.targets if isinstance(n, ast.Assign)
                    else [n.target]
                )
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        d = dotted_name(t.value)
                        if d == f"self.{field}":
                            lines.append(t.lineno)
        return sorted(set(lines))

    def _early_exit_checks(self, block: ast.With, fields, lock
                           ) -> dict[str, int]:
        """field -> line of a guarded early-exit test inside block."""
        out: dict[str, int] = {}
        for n in ast.walk(block):
            if isinstance(n, (ast.If, ast.While)) \
                    and _is_early_exit(n.body):
                for f, l in fields.items():
                    if l == lock and _reads_field(n.test, f):
                        out.setdefault(f, n.lineno)
        return out

    def _escapes(self, block: ast.With, fields, lock) -> dict[str, str]:
        """local var -> field it was derived from inside the block."""
        out: dict[str, str] = {}
        for n in ast.walk(block):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                for f, l in fields.items():
                    if l == lock and _reads_field(n.value, f):
                        out[n.targets[0].id] = f
        return out

    def _check_method(self, mod, fn, fields
                      ) -> Iterator[Finding | None]:
        blocks = self._lock_blocks(mod, fn, fields)
        if len(blocks) < 2:
            return
        reported: set[tuple[int, str]] = set()
        for i, (lock_a, a) in enumerate(blocks):
            checks = self._early_exit_checks(a, fields, lock_a)
            escapes = self._escapes(a, fields, lock_a)
            guarded_vars = set(escapes)
            # Escape form: the escaped value's test guards a later
            # same-lock block that mutates the field.
            guard_ranges: list[tuple[int, int, str]] = []
            for n in mod.walk(fn):
                if isinstance(n, (ast.If, ast.While)) \
                        and n.lineno > a.lineno:
                    used = {
                        x.id for x in ast.walk(n.test)
                        if isinstance(x, ast.Name)
                    } & guarded_vars
                    for v in used:
                        guard_ranges.append((
                            n.lineno,
                            getattr(n, "end_lineno", n.lineno),
                            escapes[v],
                        ))
            for lock_b, b in blocks[i + 1:]:
                if lock_b != lock_a or b is a:
                    continue
                for f, check_line in checks.items():
                    for line in self._mutations(b, f):
                        key = (line, f)
                        if key in reported:
                            continue
                        reported.add(key)
                        node = types.SimpleNamespace(
                            lineno=line, col_offset=0
                        )
                        yield self.finding(
                            mod, node,
                            f"check-then-act on 'self.{f}': checked "
                            f"under 'self.{lock_a}' at line "
                            f"{check_line}, but the lock was released "
                            "before this dependent mutation "
                            "re-acquired it (the check can go stale "
                            "in between)",
                        )
                for start, end, f in guard_ranges:
                    if not (start <= b.lineno <= end):
                        continue
                    for line in self._mutations(b, f):
                        key = (line, f)
                        if key in reported:
                            continue
                        reported.add(key)
                        node = types.SimpleNamespace(
                            lineno=line, col_offset=0
                        )
                        yield self.finding(
                            mod, node,
                            f"check-then-act on 'self.{f}': a value "
                            f"read under 'self.{lock_a}' guards this "
                            "mutation, but the lock was released "
                            "between the read and the act",
                        )
