"""use-after-donate: a buffer passed in a donated position must not be
read again until rebound.

`jax.jit(..., donate_argnames=...)` consumes its operand: after the
call the old array is deleted and any later read raises (TPU) or —
worse, with a warm persistent cache on some jax versions — silently
reads stale memory (see tests/conftest.py's donation-cache quirk).
The repo's donating callees are its hottest programs (`paged_prefill`,
`paged_decode_chunk`, `copy_pages`, `_stream_chunk`, the trainer's
`_step`), and the idiom that keeps them safe is rebinding in the same
statement:

    self.kv_pages = paged_kv.copy_pages(self.kv_pages, src, dst)

This checker scans the whole repo for jit-with-donation definitions
(decorator `@partial(jax.jit, donate_argnames=...)`, bare
`@jax.jit(...)` calls, and `name = jax.jit(fn, donate_argnames=...)`
assignments), resolves donated parameter names to positions via the
callee's def when it can see one, then walks every function body in
statement order: a Name or dotted attribute passed in a donated
position becomes DEAD at that statement; any later read of the same
dotted name before an assignment rebinds it is a finding. Branches
merge pessimistically (dead in either arm = possibly dead after) and
loop bodies run twice so a donation at the bottom of a loop is seen by
the read at the top of the next iteration.
"""

from __future__ import annotations

import ast
from typing import Iterator

from oryx_tpu.analysis.core import (
    Checker,
    Finding,
    ParsedModule,
    RepoContext,
    dotted_name,
)


def _tail(name: str | None) -> str | None:
    """`generate_lib.paged_prefill` -> `paged_prefill`; `self._step`
    -> `_step` (cross-module calls match by simple-name tail)."""
    return None if name is None else name.rsplit(".", 1)[-1]


def _const_strs(node: ast.AST) -> set[str]:
    out: set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            out |= _const_strs(elt)
    return out


def _const_ints(node: ast.AST) -> set[int]:
    out: set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            out |= _const_ints(elt)
    return out


def _jit_donations(call: ast.Call) -> tuple[set[str], set[int]] | None:
    """If `call` is jax.jit(...) or partial(jax.jit, ...), return its
    (donate_argnames, donate_argnums); None when it isn't a jit."""
    f = dotted_name(call.func)
    is_jit = _tail(f) == "jit" and (f or "").split(".")[0] in (
        "jax", "jit"
    )
    is_partial_jit = _tail(f) == "partial" and any(
        _tail(dotted_name(a)) == "jit" for a in call.args[:1]
    )
    if not (is_jit or is_partial_jit):
        return None
    names: set[str] = set()
    nums: set[int] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnames":
            names |= _const_strs(kw.value)
        elif kw.arg == "donate_argnums":
            nums |= _const_ints(kw.value)
    return names, nums


class UseAfterDonateChecker(Checker):
    name = "use-after-donate"

    # ---- pass 1: build the donation registry -----------------------------

    def scan(self, mod: ParsedModule, ctx: RepoContext) -> None:
        for node in mod.nodes_of(
            ast.FunctionDef, ast.AsyncFunctionDef, ast.Assign
        ):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = [a.arg for a in node.args.args]
                ctx.fn_params.setdefault(node.name, params)
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        don = _jit_donations(dec)
                        if don and (don[0] or don[1]):
                            self._register(ctx, node.name, params, *don)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                don = _jit_donations(node.value)
                if not don or not (don[0] or don[1]):
                    continue
                # `self._step = jax.jit(step_lib.train_step_fn, ...)`:
                # register under the bound name; the callee's def (if
                # scanned) provides positional resolution later.
                callee = None
                if node.value.args:
                    callee = _tail(dotted_name(node.value.args[0]))
                for target in node.targets:
                    t = _tail(dotted_name(target))
                    if t:
                        self._register(
                            ctx, t, None, *don, callee_name=callee
                        )

    def _register(
        self,
        ctx: RepoContext,
        name: str,
        params: list[str] | None,
        donate_names: set[str],
        donate_nums: set[int],
        callee_name: str | None = None,
    ) -> None:
        entry = ctx.donators.setdefault(
            name, {"names": set(), "positions": set(), "callee": set()}
        )
        entry["names"] |= donate_names
        entry["positions"] |= donate_nums
        if callee_name:
            entry["callee"].add(callee_name)
        if params is not None:
            ctx.fn_params[name] = params
            for i in donate_nums:
                if i < len(params):
                    entry["names"].add(params[i])
            for n in donate_names:
                if n in params:
                    entry["positions"].add(params.index(n))

    def _resolve_positions(self, ctx: RepoContext, name: str) -> set[int]:
        entry = ctx.donators[name]
        positions = set(entry["positions"])
        # Names registered without a visible def (assignment form)
        # resolve positions through the wrapped callee's params.
        for source in (name, *entry["callee"]):
            params = ctx.fn_params.get(source)
            if params:
                positions |= {
                    params.index(n)
                    for n in entry["names"]
                    if n in params
                }
        return positions

    # ---- pass 2: dead-name walk ------------------------------------------

    def check(
        self, mod: ParsedModule, ctx: RepoContext
    ) -> Iterator[Finding | None]:
        for node in mod.nodes_of(
            ast.FunctionDef, ast.AsyncFunctionDef
        ):
            yield from self._check_fn(mod, node, ctx)

    def _check_fn(
        self, mod: ParsedModule, fn: ast.FunctionDef, ctx: RepoContext
    ) -> Iterator[Finding | None]:
        findings: list[Finding | None] = []
        emitted: set[tuple[int, int, str]] = set()
        # dead: dotted name -> (callee, donation line)
        dead: dict[str, tuple[str, int]] = {}

        def kill(name: str, callee: str, line: int) -> None:
            dead[name] = (callee, line)

        def revive(target: ast.AST) -> None:
            if isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    revive(elt)
                return
            if isinstance(target, ast.Starred):
                revive(target.value)
                return
            d = dotted_name(target)
            if d is None:
                # Assignment through a subscript (`state["kv"] = ...`)
                # revives the container conservatively.
                if isinstance(target, ast.Subscript):
                    revive(target.value)
                return
            for k in list(dead):
                if k == d or k.startswith(d + "."):
                    del dead[k]

        def read(node: ast.AST) -> None:
            d = dotted_name(node)
            if d is None:
                return
            # `kv.shape` (or `self.kv["k"]`'s inner attribute) is a
            # read of dead `kv`: match the dead name or any dotted
            # extension of it.
            hit = next(
                (
                    k for k in dead
                    if d == k or d.startswith(k + ".")
                ),
                None,
            )
            if hit is None:
                return
            d = hit
            callee, line = dead[d]
            key = (node.lineno, node.col_offset, d)
            if key in emitted:
                return
            emitted.add(key)
            findings.append(
                self.finding(
                    mod,
                    node,
                    f"'{d}' was donated to '{callee}' on line {line} "
                    f"and is read again before being rebound",
                )
            )

        def eval_expr(node: ast.AST) -> None:
            """Post-order: donations of a call's operands happen after
            the operands (and any inner calls) are evaluated."""
            if isinstance(node, (ast.Name, ast.Attribute)):
                d = dotted_name(node)
                if d is not None:
                    read(node)
                    return  # don't double-count the chain's parts
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return  # nested defs execute later; out of scope
            for child in ast.iter_child_nodes(node):
                eval_expr(child)
            if isinstance(node, ast.Call):
                callee = _tail(dotted_name(node.func))
                if callee in ctx.donators:
                    entry = ctx.donators[callee]
                    positions = self._resolve_positions(ctx, callee)
                    for i, arg in enumerate(node.args):
                        if i in positions:
                            d = dotted_name(arg)
                            if d:
                                kill(d, callee, node.lineno)
                    for kw in node.keywords:
                        if kw.arg in entry["names"]:
                            d = dotted_name(kw.value)
                            if d:
                                kill(d, callee, node.lineno)

        def exec_stmts(stmts: list[ast.stmt]) -> None:
            for stmt in stmts:
                exec_stmt(stmt)

        def branch(bodies: list[list[ast.stmt]]) -> None:
            nonlocal dead
            entry_state = dict(dead)
            merged: dict[str, tuple[str, int]] = {}
            for body in bodies:
                dead = dict(entry_state)
                exec_stmts(body)
                merged.update(dead)
            dead = merged

        def exec_stmt(stmt: ast.stmt) -> None:
            nonlocal dead
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return
            if isinstance(stmt, ast.Assign):
                eval_expr(stmt.value)
                for t in stmt.targets:
                    revive(t)
            elif isinstance(stmt, ast.AnnAssign):
                if stmt.value is not None:
                    eval_expr(stmt.value)
                    revive(stmt.target)
            elif isinstance(stmt, ast.AugAssign):
                eval_expr(stmt.value)
                eval_expr(stmt.target)
                revive(stmt.target)
            elif isinstance(stmt, ast.If):
                eval_expr(stmt.test)
                branch([stmt.body, stmt.orelse])
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                eval_expr(stmt.iter)
                revive(stmt.target)
                # Twice: a donation at the bottom of the body must be
                # seen by the read at the top of the next iteration.
                entry_state = dict(dead)
                exec_stmts(stmt.body)
                exec_stmts(stmt.body)
                exec_stmts(stmt.orelse)
                dead = {**entry_state, **dead}
            elif isinstance(stmt, ast.While):
                eval_expr(stmt.test)
                entry_state = dict(dead)
                exec_stmts(stmt.body)
                eval_expr(stmt.test)
                exec_stmts(stmt.body)
                exec_stmts(stmt.orelse)
                dead = {**entry_state, **dead}
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    eval_expr(item.context_expr)
                    if item.optional_vars is not None:
                        revive(item.optional_vars)
                exec_stmts(stmt.body)
            elif isinstance(stmt, ast.Try):
                exec_stmts(stmt.body)
                for handler in stmt.handlers:
                    exec_stmts(handler.body)
                exec_stmts(stmt.orelse)
                exec_stmts(stmt.finalbody)
            elif isinstance(stmt, (ast.Return, ast.Expr)):
                if stmt.value is not None:
                    eval_expr(stmt.value)
            elif isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    revive(t)
            else:
                for child in ast.iter_child_nodes(stmt):
                    eval_expr(child)

        exec_stmts(fn.body)
        yield from findings
