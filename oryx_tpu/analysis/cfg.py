"""Intraprocedural control-flow graphs over the stdlib AST.

The AST-visitor rules (locks, hostsync, swallow, ...) see *syntax*; the
dataflow tier's rules (key-linearity, terminal-path, replay-taint) need
*paths*: which statements can execute before this one, which exits a
function has, what an `except` handler can observe. This module turns
one function body into basic blocks and edges — including the edges the
bug history cares about: early returns, `raise`, exception flow into
handlers, `finally` on every leaving path, loop back edges and
`continue`/`break`.

Design rules (shared with core.py): stdlib-only, source-level, small
enough to run over the whole tree inside the lint time budget.

Model:

  * A `Block` holds a straight-line list of *elements*: simple
    statements verbatim, the evaluated expression of compound-statement
    headers (`if`/`while` tests, `for` iterables), and `Bind` records
    for implicit assignments (`for` targets, `with ... as x`,
    `except E as e`). Compound statements NEVER appear whole — their
    bodies live in successor blocks — so a transfer function can walk
    every element wholesale without double-seeing nested code.
  * Exceptions: only explicit `raise` statements and try-body flow into
    handlers are modeled. Inside a `try` with handlers every element
    ends its block and edges to EVERY handler (any statement may raise,
    and static type matching is not attempted) — sound for both must-
    and may-analyses. Implicit raises outside a `try` (any call can
    throw) are deliberately NOT exits: modeling them would drown the
    terminal-path rule in noise. `assert` is treated as straight-line
    for the same reason.
  * `finally` bodies are INLINED (rebuilt) on every leaving edge —
    normal fall-through, `return`, `raise`, `break`, `continue` — the
    same duplication CPython's own compiler performs, so a discharge
    inside a `finally` proves every exit path.
  * Exits are virtual: `Exit(kind, node)` with kind one of `return`,
    `raise`, `implicit` (falling off the end), and — in `loop_body`
    mode, used by the terminal-path rule's per-iteration obligations —
    `continue`, `fallthrough` (reaching the next iteration) and
    `break`.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator


@dataclasses.dataclass(frozen=True)
class Bind:
    """An implicit assignment: `for TARGET in ...`, `with ... as
    TARGET`, `except E as name`. `value` is the source expression when
    the binding has one (`for`'s iterable, `with`'s context manager);
    None marks an opaque bind (the exception object)."""

    target: ast.expr | None
    value: ast.expr | None
    node: ast.AST  # anchor (lineno) — the owning compound statement
    kind: str  # "for" | "with" | "except"

    @property
    def lineno(self) -> int:
        return getattr(self.node, "lineno", 1)


# One block element: a simple statement, a header expression, or a Bind.
Element = object


class Block:
    __slots__ = ("id", "elems", "succs")

    def __init__(self, bid: int):
        self.id = bid
        self.elems: list[Element] = []
        self.succs: list["Block"] = []

    def edge(self, other: "Block") -> None:
        if other not in self.succs:
            self.succs.append(other)

    def __repr__(self) -> str:  # debugging aid only
        return f"Block({self.id}, elems={len(self.elems)}, " \
               f"succs={[b.id for b in self.succs]})"


@dataclasses.dataclass(frozen=True)
class Exit:
    """One way out: `block` is the (terminated) block whose out-state
    holds at the exit; `node` anchors the finding (the Return/Raise
    statement, the `continue`, or — for implicit/fallthrough — the last
    element executed, falling back to the owning body)."""

    block: Block
    kind: str  # return | raise | implicit | continue | fallthrough | break
    node: ast.AST


class CFG:
    def __init__(self) -> None:
        self.blocks: list[Block] = []
        self.entry: Block | None = None
        self.exits: list[Exit] = []

    def preds(self) -> dict[int, list[Block]]:
        out: dict[int, list[Block]] = {b.id: [] for b in self.blocks}
        for b in self.blocks:
            for s in b.succs:
                out[s.id].append(b)
        return out

    def elements(self) -> Iterator[Element]:
        for b in self.blocks:
            yield from b.elems


# Statements that run straight through (modeled as opaque elements).
_SIMPLE = (
    ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr, ast.Pass,
    ast.Delete, ast.Import, ast.ImportFrom, ast.Global, ast.Nonlocal,
    ast.Assert,
)


class _Ctx:
    """Build context: where `break`/`continue` go, which handler blocks
    an exception reaches, and the active `finally` stack (innermost
    last; each entry remembers the ctx to rebuild its body under)."""

    __slots__ = ("break_to", "continue_to", "handlers", "finallies",
                 "loop_depth")

    def __init__(self, break_to=None, continue_to=None, handlers=(),
                 finallies=(), loop_depth=0):
        self.break_to = break_to
        self.continue_to = continue_to
        self.handlers = handlers  # tuple[Block, ...]
        self.finallies = finallies  # tuple[(body, _Ctx), ...]
        self.loop_depth = loop_depth  # len(finallies) at loop entry

    def replace(self, **kw) -> "_Ctx":
        new = _Ctx(self.break_to, self.continue_to, self.handlers,
                   self.finallies, self.loop_depth)
        for k, v in kw.items():
            setattr(new, k, v)
        return new


class _Builder:
    def __init__(self, loop_body: bool):
        self.cfg = CFG()
        self.loop_body = loop_body
        self._n = 0

    def new_block(self) -> Block:
        b = Block(self._n)
        self._n += 1
        self.cfg.blocks.append(b)
        return b

    # -- elements ----------------------------------------------------------

    def _emit(self, cur: Block, elem: Element, ctx: _Ctx) -> Block:
        """Append one element; inside a try-with-handlers every element
        terminates its block and edges to each handler, so a handler's
        in-state joins every point the body could raise from."""
        cur.elems.append(elem)
        if ctx.handlers:
            nxt = self.new_block()
            cur.edge(nxt)
            for h in ctx.handlers:
                cur.edge(h)
            return nxt
        return cur

    # -- abrupt edges ------------------------------------------------------

    def _through_finallies(self, cur: Block, ctx: _Ctx,
                           upto: int = 0) -> Block:
        """Inline the active `finally` bodies, innermost first, down to
        stack depth `upto`; returns the block control leaves from."""
        for body, fctx in reversed(ctx.finallies[upto:]):
            entry = self.new_block()
            cur.edge(entry)
            nxt = self._seq(body, entry, fctx)
            if nxt is None:  # the finally itself never falls through
                return None
            cur = nxt
        return cur

    def _exit(self, cur: Block, node: ast.AST, kind: str,
              ctx: _Ctx) -> None:
        cur = self._through_finallies(cur, ctx, upto=0)
        if cur is not None:
            self.cfg.exits.append(Exit(cur, kind, node))

    def _jump(self, cur: Block, node: ast.AST, ctx: _Ctx,
              target: Block | None, kind: str) -> None:
        """break/continue: through finallies down to the loop's level,
        then to the loop-supplied target (or a loop_body-mode exit)."""
        cur = self._through_finallies(cur, ctx, upto=ctx.loop_depth)
        if cur is None:
            return
        if target is not None:
            cur.edge(target)
        else:
            self.cfg.exits.append(Exit(cur, kind, node))

    # -- statement sequencing ----------------------------------------------

    def _seq(self, stmts: list[ast.stmt], cur: Block,
             ctx: _Ctx) -> Block | None:
        """Build `stmts` from `cur`; returns the open fall-through
        block, or None when no path falls out the end."""
        for stmt in stmts:
            if cur is None:
                return None  # unreachable tail (after return/raise)
            cur = self._stmt(stmt, cur, ctx)
        return cur

    def _stmt(self, stmt: ast.stmt, cur: Block,
              ctx: _Ctx) -> Block | None:
        if isinstance(stmt, _SIMPLE):
            return self._emit(cur, stmt, ctx)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # Nested definitions are data here, not control flow; the
            # checkers analyze each function scope separately.
            return self._emit(cur, stmt, ctx)
        if isinstance(stmt, ast.Return):
            cur = self._emit(cur, stmt, ctx)
            self._exit(cur, stmt, "return", ctx)
            return None
        if isinstance(stmt, ast.Raise):
            cur = self._emit(cur, stmt, ctx)
            if ctx.handlers:
                return None  # _emit already edged into the handlers
            self._exit(cur, stmt, "raise", ctx)
            return None
        if isinstance(stmt, ast.Break):
            self._jump(cur, stmt, ctx, ctx.break_to,
                       "break" if self.loop_body else "return")
            return None
        if isinstance(stmt, ast.Continue):
            self._jump(cur, stmt, ctx, ctx.continue_to, "continue")
            return None
        if isinstance(stmt, ast.If):
            return self._if(stmt, cur, ctx)
        if isinstance(stmt, (ast.While,)):
            return self._while(stmt, cur, ctx)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._for(stmt, cur, ctx)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, cur, ctx)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, cur, ctx)
        if isinstance(stmt, ast.Match):
            return self._match(stmt, cur, ctx)
        # Unknown statement kind: treat as straight-line.
        return self._emit(cur, stmt, ctx)

    def _if(self, stmt: ast.If, cur, ctx):
        cur = self._emit(cur, stmt.test, ctx)
        join = self.new_block()
        then_entry = self.new_block()
        cur.edge(then_entry)
        then_out = self._seq(stmt.body, then_entry, ctx)
        if then_out is not None:
            then_out.edge(join)
        if stmt.orelse:
            else_entry = self.new_block()
            cur.edge(else_entry)
            else_out = self._seq(stmt.orelse, else_entry, ctx)
            if else_out is not None:
                else_out.edge(join)
        else:
            cur.edge(join)
        return join

    def _while(self, stmt: ast.While, cur, ctx):
        head = self.new_block()
        cur.edge(head)
        head2 = self._emit(head, stmt.test, ctx)
        body_entry = self.new_block()
        after = self.new_block()
        head2.edge(body_entry)
        # `while True:` never falls out of the loop on its own.
        infinite = (
            isinstance(stmt.test, ast.Constant) and stmt.test.value
        )
        inner = ctx.replace(
            break_to=after, continue_to=head,
            loop_depth=len(ctx.finallies),
        )
        body_out = self._seq(stmt.body, body_entry, inner)
        if body_out is not None:
            body_out.edge(head)  # back edge
        if not infinite:
            if stmt.orelse:
                else_entry = self.new_block()
                head2.edge(else_entry)
                else_out = self._seq(stmt.orelse, else_entry, ctx)
                if else_out is not None:
                    else_out.edge(after)
            else:
                head2.edge(after)
        return after

    def _for(self, stmt, cur, ctx):
        cur = self._emit(cur, stmt.iter, ctx)
        head = self.new_block()
        cur.edge(head)
        body_entry = self.new_block()
        after = self.new_block()
        head.edge(body_entry)
        bind = Bind(stmt.target, stmt.iter, stmt, "for")
        inner = ctx.replace(
            break_to=after, continue_to=head,
            loop_depth=len(ctx.finallies),
        )
        body_entry2 = self._emit(body_entry, bind, inner)
        body_out = self._seq(stmt.body, body_entry2, inner)
        if body_out is not None:
            body_out.edge(head)  # back edge
        if stmt.orelse:
            else_entry = self.new_block()
            head.edge(else_entry)
            else_out = self._seq(stmt.orelse, else_entry, ctx)
            if else_out is not None:
                else_out.edge(after)
        else:
            head.edge(after)
        return after

    def _with(self, stmt, cur, ctx):
        for item in stmt.items:
            bind = Bind(item.optional_vars, item.context_expr, stmt,
                        "with")
            cur = self._emit(cur, bind, ctx)
        return self._seq(stmt.body, cur, ctx)

    def _try(self, stmt: ast.Try, cur, ctx):
        after = self.new_block()
        body_ctx = ctx
        if stmt.finalbody:
            body_ctx = body_ctx.replace(
                finallies=ctx.finallies + ((stmt.finalbody, ctx),),
            )
        handler_entries: list[Block] = []
        handler_outs: list[Block] = []
        if stmt.handlers:
            for h in stmt.handlers:
                handler_entries.append(self.new_block())
            body_ctx = body_ctx.replace(
                handlers=tuple(handler_entries),
            )
        body_out = self._seq(stmt.body, cur, body_ctx)
        # else: runs only on normal body completion, OUTSIDE the
        # handlers' protection but inside the finally's.
        else_ctx = ctx if not stmt.finalbody else ctx.replace(
            finallies=ctx.finallies + ((stmt.finalbody, ctx),),
        )
        if body_out is not None and stmt.orelse:
            body_out = self._seq(stmt.orelse, body_out, else_ctx)
        # Handlers run with the try's context minus themselves (a raise
        # inside a handler escapes to the OUTER try), plus the finally.
        for h, entry in zip(stmt.handlers, handler_entries):
            hctx = else_ctx
            b = self._emit(
                entry, Bind(None, None, h, "except"), hctx
            )
            h_out = self._seq(h.body, b, hctx)
            if h_out is not None:
                handler_outs.append(h_out)
        outs = ([body_out] if body_out is not None else []) + \
            handler_outs
        if not outs:
            return None
        if stmt.finalbody:
            merged = self.new_block()
            for o in outs:
                o.edge(merged)
            return self._seq(stmt.finalbody, merged, ctx)
        for o in outs:
            o.edge(after)
        return after

    def _match(self, stmt, cur, ctx):
        cur = self._emit(cur, stmt.subject, ctx)
        join = self.new_block()
        exhaustive = False
        for case in stmt.cases:
            entry = self.new_block()
            cur.edge(entry)
            out = self._seq(case.body, entry, ctx)
            if out is not None:
                out.edge(join)
            if isinstance(case.pattern, ast.MatchAs) \
                    and case.pattern.pattern is None:
                exhaustive = True  # `case _:` — no fall-past edge
        if not exhaustive:
            cur.edge(join)
        return join


def _prune(cfg: CFG) -> CFG:
    """Drop blocks unreachable from entry (e.g. join blocks both of
    whose arms returned) so fixpoints never see them."""
    seen: set[int] = set()
    stack = [cfg.entry]
    while stack:
        b = stack.pop()
        if b is None or b.id in seen:
            continue
        seen.add(b.id)
        stack.extend(b.succs)
    cfg.blocks = [b for b in cfg.blocks if b.id in seen]
    cfg.exits = [e for e in cfg.exits if e.block.id in seen]
    return cfg


def _last_anchor(block: Block, fallback: ast.AST) -> ast.AST:
    for elem in reversed(block.elems):
        node = elem.node if isinstance(elem, Bind) else elem
        if getattr(node, "lineno", None):
            return node
    return fallback


def build_cfg(stmts: list[ast.stmt], *, loop_body: bool = False,
              anchor: ast.AST | None = None) -> CFG:
    """CFG of a statement list (a function body, or — loop_body=True —
    one loop iteration: `continue` and falling off the end become
    `continue`/`fallthrough` exits, `break` a `break` exit, and
    return/raise keep their own kinds)."""
    builder = _Builder(loop_body)
    cfg = builder.cfg
    cfg.entry = builder.new_block()
    ctx = _Ctx()
    out = builder._seq(stmts, cfg.entry, ctx)
    if out is not None:
        kind = "fallthrough" if loop_body else "implicit"
        node = _last_anchor(out, anchor or (stmts[-1] if stmts else
                                            ast.Pass()))
        cfg.exits.append(Exit(out, kind, node))
    return _prune(cfg)


def function_cfg(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> CFG:
    return build_cfg(fn.body, anchor=fn)


def loop_cfg(loop: ast.For | ast.While) -> CFG:
    """One iteration of `loop`'s body — the terminal-path rule's
    per-iteration obligation domain. `break` paths surface as `break`
    exits (reported or not is the rule's call)."""
    return build_cfg(loop.body, loop_body=True, anchor=loop)
