"""swallowed-exception: no silent broad catches outside fault
boundaries.

A `except Exception: pass` (or bare `except:`, or a handler that only
logs and drops) turns every future bug at that site into silence — the
engine keeps "serving" with a consumed pool, the trainer keeps
"training" with frozen params. This PR family's whole posture is that
failures are CONTAINED, not swallowed: containment sites are few,
deliberate, and documented.

Flagged: an `except` clause whose type is broad (bare, `Exception`,
or `BaseException`) and whose body does nothing but drop — every
statement is `pass`, `...`, `continue`, an `import`, or a logging-ish
expression call (`logging`/`log`/`_LOG`/`logger` methods, `print`,
`rank0_print`, `traceback.print_exc`, `warnings.warn`). Handlers that
bind state, return a fallback, re-raise, or call real code are
handling, not swallowing, and are not flagged.

The escape hatch is an explicit annotation — a `# fault-boundary:
<why>` comment on the `except` line or the line directly above it —
which is exactly the review conversation the rule forces: every
swallow must say what failure it bounds and why dropping is correct
(a broken metrics collector must never break the scrape; a crashed
restart attempt must not kill the supervisor). Ordinary per-line
`# oryxlint: disable=swallowed-exception` suppressions work too, but
the annotation is the idiom.

Narrow catches (`except OSError: pass`) are NOT flagged: naming the
exception type is itself the statement of what is expected to fail.
"""

from __future__ import annotations

import ast
from typing import Iterator

from oryx_tpu.analysis.core import (
    Checker,
    Finding,
    ParsedModule,
    RepoContext,
    dotted_name,
)

_BROAD = {"Exception", "BaseException"}
_LOG_CALL_BASES = {"logging", "log", "logger", "_LOG", "LOG", "traceback",
                   "warnings"}
_LOG_CALL_NAMES = {"print", "rank0_print", "print_exc"}


def _is_logging_call(call: ast.Call) -> bool:
    d = dotted_name(call.func)
    if d is None:
        return False
    parts = d.split(".")
    if parts[0] in _LOG_CALL_BASES:
        return True
    return parts[-1] in _LOG_CALL_NAMES


def _drops_silently(handler: ast.ExceptHandler) -> bool:
    """True when every statement in the handler body is a no-op or a
    log line — nothing is handled, returned, raised, or recorded."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Import,
                             ast.ImportFrom)):
            continue
        if isinstance(stmt, ast.Expr):
            v = stmt.value
            if isinstance(v, ast.Constant):  # bare `...` / docstring
                continue
            if isinstance(v, ast.Call) and _is_logging_call(v):
                continue
        return False
    return True


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare `except:`
        return True
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts
        )
    return isinstance(t, ast.Name) and t.id in _BROAD


def is_fault_boundary(mod: ParsedModule, handler: ast.ExceptHandler) -> bool:
    """`# fault-boundary` on the except line or in the contiguous
    comment block directly above it (tokenized comments only — a
    docstring quoting the marker can never annotate a handler)."""
    if "fault-boundary" in mod.comment_text(handler.lineno):
        return True
    line = handler.lineno - 1
    # Comment-ONLY lines: a trailing comment on a code line above must
    # not extend the annotation's reach.
    while line >= 1 and mod.line_text(line).strip().startswith("#"):
        if "fault-boundary" in mod.comment_text(line):
            return True
        line -= 1
    return False


class SwallowedExceptionChecker(Checker):
    name = "swallowed-exception"

    def check(
        self, mod: ParsedModule, ctx: RepoContext
    ) -> Iterator[Finding | None]:
        for node in mod.nodes_of(ast.ExceptHandler):
            if not _is_broad(node) or not _drops_silently(node):
                continue
            if is_fault_boundary(mod, node):
                continue
            kind = (
                "bare except" if node.type is None
                else "broad except"
            )
            yield self.finding(
                mod,
                node,
                f"{kind} swallows the exception (body only "
                "passes/logs); handle it, narrow the type, or annotate "
                "the line with `# fault-boundary: <why>` if dropping "
                "is the containment",
            )
