"""oryxlint core: dependency-free AST lint framework.

The stack's three concurrency- and compilation-sensitive hot paths —
the threaded continuous-batching scheduler over a shared refcounted
page pool, jitted prefill/decode with donated buffers, and the trainer
step loop — share a family of bug classes pytest can't see on CPU in
seconds: lock-discipline violations, use-after-donate, silent host
syncs in decode loops, recompile storms, metric-name drift. Each
checker here is a small AST visitor over one of those invariants; the
runner applies them to the whole repo and `scripts/check_tier1.sh`
gates on a clean self-lint.

Design rules:
  * stdlib only (`ast`, `re`) — the linter must run before jax
    imports, in CI images without the accelerator stack, and in <2 s
    over the whole tree.
  * never import the code under analysis — everything is source-level.
  * two passes: every checker first `scan()`s every module into a
    shared `RepoContext` (cross-module facts: which functions donate
    which params, which metric families exist where), then `check()`s
    each module against that context.
  * suppression is per-line and explicit:
        x = f(y)  # oryxlint: disable=use-after-donate
    or a region (for a deliberate block, e.g. the scheduler's harvest
    syncs):
        # oryxlint: off=host-sync
        ...
        # oryxlint: on=host-sync
    or whole-file:
        # oryxlint: disable-file=metric-name
    `disable=all` / `off=all` suppress every rule. Suppressions are
    counted and reported, so `--strict` output still shows where the
    escapes live.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from typing import Any, Callable, Iterable, Iterator


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One lint finding, anchored to a source line."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


_DISABLE_RE = re.compile(r"#\s*oryxlint:\s*disable=([a-z0-9_,\- ]+)")
_DISABLE_FILE_RE = re.compile(r"#\s*oryxlint:\s*disable-file=([a-z0-9_,\- ]+)")
_OFF_RE = re.compile(r"#\s*oryxlint:\s*off=([a-z0-9_,\- ]+)")
_ON_RE = re.compile(r"#\s*oryxlint:\s*on=([a-z0-9_,\- ]+)")


def _split_rules(spec: str) -> set[str]:
    return {r.strip() for r in spec.split(",") if r.strip()}


class ParsedModule:
    """One source file: text, AST, and its suppression map."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.file_disables: set[str] = set()
        # line (1-based) -> rules suppressed on that line.
        self.line_disables: dict[int, set[str]] = {}
        # Lazy shared walk index (walk()/parent()): built on first use.
        # Initialized BEFORE suppression parsing — the comment scanner
        # reads string-literal spans through nodes_of().
        self._preorder: list[ast.AST] | None = None
        self._spans: dict[int, tuple[int, int]] = {}
        self._parents: dict[int, ast.AST] = {}
        self._by_type: dict[type, list[ast.AST]] = {}
        self._parse_suppressions()

    def _build_walk_index(self) -> None:
        """One DFS over the tree: preorder list + per-node subtree
        spans + parent links. Every checker walks the same tree many
        times (whole-module scans, per-function passes, per-statement
        taint checks); `ast.walk` re-derives children through getattr
        reflection on every call, which dominates lint wall time on a
        big tree. Amortizing it here is what keeps the repo-wide run
        inside the CI `--time-budget`."""
        # Pass 1: iterative preorder + parent links, with child
        # discovery inlined (getattr over _fields — no per-node
        # generator frames, which dominate an ast.iter_child_nodes
        # formulation at this scale).
        parents = self._parents
        order: list[ast.AST] = []
        AST, append, pop = ast.AST, order.append, None
        stack: list[ast.AST] = [self.tree]
        pop = stack.pop
        push = stack.append
        while stack:
            node = pop()
            append(node)
            for name in reversed(node._fields):
                field = getattr(node, name, None)
                if field.__class__ is list:
                    for item in reversed(field):
                        if isinstance(item, AST):
                            parents[id(item)] = node
                            push(item)
                elif isinstance(field, AST):
                    parents[id(field)] = node
                    push(field)
        # Pass 2: subtree spans. In preorder every node precedes its
        # descendants, so a reverse sweep folding each node's end into
        # its parent yields [start, end) without tracking frames.
        n = len(order)
        index = {id(node): i for i, node in enumerate(order)}
        ends = list(range(1, n + 1))
        for i in range(n - 1, 0, -1):
            pi = index[id(parents[id(order[i])])]
            if ends[i] > ends[pi]:
                ends[pi] = ends[i]
        spans = self._spans
        by_type = self._by_type
        for i, node in enumerate(order):
            spans[id(node)] = (i, ends[i])
            cls = node.__class__
            bucket = by_type.get(cls)
            if bucket is None:
                bucket = by_type[cls] = []
            bucket.append(node)
        self._preorder = order

    def walk(self, node: ast.AST | None = None) -> list[ast.AST]:
        """All nodes of `node`'s subtree (default: the whole module),
        `node` included, in preorder. Amortized O(subtree): the index
        is one DFS per module, a subtree walk is a list slice. Falls
        back to `ast.walk` for nodes synthesized outside this tree."""
        if self._preorder is None:
            self._build_walk_index()
        if node is None or node is self.tree:
            return self._preorder
        span = self._spans.get(id(node))
        if span is None:
            return list(ast.walk(node))
        return self._preorder[span[0]:span[1]]

    def nodes_of(self, *types: type) -> list[ast.AST]:
        """Every node in the module whose class is exactly one of
        `types`, in preorder. The module-wide `for n in walk(): if
        isinstance(n, T)` scans are the bulk of lint time on a big
        tree; this is the same loop precomputed."""
        if self._preorder is None:
            self._build_walk_index()
        if len(types) == 1:
            return self._by_type.get(types[0], [])
        out: list[ast.AST] = []
        for t in types:
            out.extend(self._by_type.get(t, ()))
        if len(types) > 1 and out:
            spans = self._spans
            out.sort(key=lambda n: spans[id(n)][0])
        return out

    def subtree_size(self, node: ast.AST) -> int:
        """Node count of `node`'s subtree (itself included) — lets a
        preorder consumer skip a subtree in O(1)."""
        if self._preorder is None:
            self._build_walk_index()
        span = self._spans.get(id(node))
        if span is None:
            return 1
        return span[1] - span[0]

    def parent(self, node: ast.AST) -> ast.AST | None:
        """The AST parent of `node` (None for the root or for nodes
        not from this tree). Same shared index as walk()."""
        if self._preorder is None:
            self._build_walk_index()
        return self._parents.get(id(node))

    def _comments_by_line(self) -> dict[int, str]:
        """line (1-based) -> comment text. A `#` counts as a comment
        only OUTSIDE every string-literal span of the parsed tree, so a
        docstring or string literal QUOTING the directive syntax (this
        module's own docstring does) can never disable rules. The AST
        span mask replaces a full tokenize pass — same answer at a
        fraction of the cost, since only lines containing `#` are ever
        inspected."""
        out: dict[int, str] = {}
        lines = self.lines
        cand = [i for i, l in enumerate(lines, 1) if "#" in l]
        if not cand:
            return out
        big = 1 << 30
        masks: dict[int, list[tuple[int, int]]] = {}
        for node in self.nodes_of(ast.Constant, ast.JoinedStr):
            if isinstance(node, ast.Constant) and not isinstance(
                node.value, (str, bytes)
            ):
                continue
            sl, el = node.lineno, node.end_lineno
            sc, ec = node.col_offset, node.end_col_offset
            if sl == el:
                masks.setdefault(sl, []).append((sc, ec))
            else:
                masks.setdefault(sl, []).append((sc, big))
                for ln in range(sl + 1, el):
                    masks.setdefault(ln, []).append((0, big))
                masks.setdefault(el, []).append((0, ec))
        for ln in cand:
            text = lines[ln - 1]
            mask = masks.get(ln)
            if mask is not None and not text.isascii():
                # AST col offsets are UTF-8 byte offsets: compare in
                # byte space when the line mixes strings and non-ASCII.
                raw = text.encode("utf-8")
                pos = raw.find(b"#")
                while pos != -1:
                    if not any(s <= pos < e for s, e in mask):
                        out[ln] = raw[pos:].decode("utf-8")
                        break
                    pos = raw.find(b"#", pos + 1)
                continue
            pos = text.find("#")
            while pos != -1:
                if mask is None or not any(
                    s <= pos < e for s, e in mask
                ):
                    out[ln] = text[pos:]
                    break
                pos = text.find("#", pos + 1)
        return out

    def comments(self) -> dict[int, str]:
        """line (1-based) -> comment text, only lines that HAVE one —
        for checkers scanning every comment in a file (iterating this
        beats probing comment_text per source line)."""
        return self._comments

    def comment_text(self, line: int) -> str:
        """The comment on `line` ('' when none) — checkers read markers
        (`# guarded-by:`, `# hot-path`) through this, never through raw
        line text, for the same quoting-safety reason."""
        return self._comments.get(line, "")

    def _parse_suppressions(self) -> None:
        comments = self._comments = self._comments_by_line()
        region: set[str] = set()  # rules currently `off`
        for i in range(1, len(self.lines) + 1):
            text = comments.get(i, "")
            if "oryxlint" not in text:
                if region:
                    self.line_disables.setdefault(i, set()).update(region)
                continue
            m = _DISABLE_FILE_RE.search(text)
            if m:
                self.file_disables |= _split_rules(m.group(1))
            m = _OFF_RE.search(text)
            if m:
                region |= _split_rules(m.group(1))
            m = _ON_RE.search(text)
            if m:
                region -= _split_rules(m.group(1))
                if "all" in _split_rules(m.group(1)):
                    region.clear()
            per_line = set(region)
            m = _DISABLE_RE.search(text)
            if m:
                per_line |= _split_rules(m.group(1))
            if per_line:
                self.line_disables.setdefault(i, set()).update(per_line)

    def suppressed(self, line: int, rule: str) -> bool:
        if "all" in self.file_disables or rule in self.file_disables:
            return True
        rules = self.line_disables.get(line)
        return bool(rules) and ("all" in rules or rule in rules)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


class RepoContext:
    """Cross-module facts accumulated by checkers' scan() pass."""

    def __init__(self) -> None:
        # use-after-donate: simple fn name -> {"names": set[str],
        # "positions": set[int]} of donated parameters.
        self.donators: dict[str, dict[str, set]] = {}
        # fn name -> ordered param names (for positional resolution of
        # donated/static operands at call sites).
        self.fn_params: dict[str, list[str]] = {}
        # recompile-hazard: jitted fn name -> set of static param names;
        # aliases map `name = jax.jit(fn, ...)` bindings to the wrapped
        # fn whose def provides positional parameter order.
        self.jitted_static: dict[str, set[str]] = {}
        self.jit_aliases: dict[str, str] = {}
        # metric-name: family name -> kind -> [(path, line)].
        self.metric_sites: dict[str, dict[str, list[tuple[str, int]]]] = {}


class Checker:
    """Base checker: `scan` every module first, then `check` each one.

    Subclasses set `name` (the rule id used in findings and
    suppressions) and implement `check`; `scan` is optional."""

    name = "base"

    def scan(self, mod: ParsedModule, ctx: RepoContext) -> None:
        return None

    def check(self, mod: ParsedModule, ctx: RepoContext) -> Iterator[Finding]:
        raise NotImplementedError

    # Shared helper: build a Finding unless that line suppresses it.
    def finding(
        self, mod: ParsedModule, node: ast.AST, message: str
    ) -> Finding | None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        if mod.suppressed(line, self.name):
            return None
        return Finding(mod.path, line, col, self.name, message)


# Shared field-annotation syntax: a field declaration line carrying a
# concurrency marker in a REAL comment (comment_text — quoted syntax in
# docstrings never counts). Two markers:
#   self._queue: deque = deque()   # guarded-by: _cond
#   cost_decode_steps: int = 0     # thread-owned: engine
# The declaration form covers `self.x = ...`, `self.x: T = ...`, and
# bare dataclass / class-body fields (`x: T = ...`, `x: T`).
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(\w+)")
_THREAD_OWNED_RE = re.compile(r"#\s*thread-owned:\s*(\w+)")
_FIELD_DECL_RE = re.compile(
    r"^\s*(?:self\.)?([A-Za-z_]\w*)\s*(?::[^=#]+)?(?:=(?!=)|$)"
)


def class_line_span(cls: ast.ClassDef) -> tuple[int, int]:
    return cls.lineno, getattr(cls, "end_lineno", cls.lineno) or cls.lineno


def field_annotations(
    mod: "ParsedModule", cls: ast.ClassDef
) -> dict[str, tuple[str, str]]:
    """field -> ("guarded-by", lock_attr) | ("thread-owned", owner_tag)
    from marker comments on declaration lines inside the class body.
    Used by the lock-discipline / atomicity static rules AND by the
    runtime race detector (analysis.sanitizers), so the annotation
    language can never drift between the two halves."""
    start, end = class_line_span(cls)
    out: dict[str, tuple[str, str]] = {}
    for line in range(start, end + 1):
        comment = mod.comment_text(line)
        m = _GUARDED_RE.search(comment)
        kind = "guarded-by"
        if not m:
            m = _THREAD_OWNED_RE.search(comment)
            kind = "thread-owned"
        if not m:
            continue
        code = mod.line_text(line).split("#", 1)[0]
        decl = _FIELD_DECL_RE.match(code)
        if decl:
            out[decl.group(1)] = (kind, m.group(1))
    return out


def dotted_name(node: ast.AST) -> str | None:
    """`a`, `a.b.c`, `self.kv_pages` → dotted string; anything with a
    non-Name base (calls, subscripts) → None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]
    errors: list[tuple[str, str]]  # (path, parse error)
    files: int
    suppressed: int
    # rule -> suppression count; the per-rule ratchet
    # (`--max-suppressions-per-rule`) reads this so a NEW rule can be
    # pinned at 0 escapes while the global ratchet stays loose.
    suppressed_by_rule: dict[str, int] = dataclasses.field(
        default_factory=dict
    )

    def findings_by_rule(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def run_lint(
    paths_and_sources: Iterable[tuple[str, str]],
    checkers: Iterable[Checker],
    check_only: set[str] | None = None,
) -> LintResult:
    """Parse every file, run every checker's scan pass over ALL of
    them, then the check pass. Returns findings sorted by location.
    Files that fail to parse are reported as errors, not findings —
    a syntax error is the interpreter's job to explain.

    check_only: restrict the CHECK pass to these paths while the scan
    pass still sees everything — the `--changed-only` contract. The
    cross-module facts (donation registry, metric kind map) come from
    the whole tree, so editing one caller of a donating function
    defined elsewhere still lints correctly."""
    checkers = list(checkers)
    ctx = RepoContext()
    mods: list[ParsedModule] = []
    errors: list[tuple[str, str]] = []
    for path, source in paths_and_sources:
        try:
            mods.append(ParsedModule(path, source))
        except SyntaxError as e:
            errors.append((path, f"{type(e).__name__}: {e}"))
    for checker in checkers:
        for mod in mods:
            checker.scan(mod, ctx)
    findings: list[Finding] = []
    suppressed = 0
    suppressed_by_rule: dict[str, int] = {}
    checked = [
        m for m in mods
        if check_only is None or m.path in check_only
    ]
    for checker in checkers:
        for mod in checked:
            for f in checker.check(mod, ctx):
                if f is None:
                    suppressed += 1
                    suppressed_by_rule[checker.name] = (
                        suppressed_by_rule.get(checker.name, 0) + 1
                    )
                else:
                    findings.append(f)
    findings.sort()
    return LintResult(
        findings, errors, len(checked), suppressed,
        suppressed_by_rule,
    )


def render_text(result: LintResult) -> str:
    out = [f.format() for f in result.findings]
    for path, err in result.errors:
        out.append(f"{path}:1:0: [parse-error] {err}")
    by_rule: dict[str, int] = {}
    for f in result.findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    summary = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items()))
    out.append(
        f"oryxlint: {len(result.findings)} finding(s)"
        + (f" ({summary})" if summary else "")
        + f", {result.suppressed} suppressed, {result.files} file(s)"
        + (f", {len(result.errors)} parse error(s)" if result.errors else "")
    )
    return "\n".join(out)


def render_json(result: LintResult) -> str:
    findings_by_rule = result.findings_by_rule()
    rules = sorted(
        set(findings_by_rule) | set(result.suppressed_by_rule)
    )
    return json.dumps(
        {
            "findings": [f.to_dict() for f in result.findings],
            "errors": [
                {"path": p, "error": e} for p, e in result.errors
            ],
            "files": result.files,
            "suppressed": result.suppressed,
            # Per-rule breakdown: what the CI artifact diffs and the
            # per-rule suppression ratchet gates on.
            "by_rule": {
                r: {
                    "findings": findings_by_rule.get(r, 0),
                    "suppressed": result.suppressed_by_rule.get(r, 0),
                }
                for r in rules
            },
        },
        indent=2,
    )
