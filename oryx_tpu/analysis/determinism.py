"""replay-taint: nondeterminism may not flow into journaled decisions.

The PR 18 flight recorder's whole contract is that replaying the
decision journal byte-reproduces the incident: every journaled value
and every journal-consulted decision (fuse-plan K, eviction victim
order) must be a function of journal state, never of wall-clock time,
process-local identity, or iteration order. One `time.monotonic()`
laundered into a journal field silently breaks `replay_journal.py`
forever after.

This rule runs a may-taint dataflow over the function CFG:

  * **sources** — calls that read nondeterministic ambient state:
    `time.*` wall clocks, the stdlib `random` module (NOT
    `jax.random`, which is keyed and deterministic), `os.urandom`,
    `os.getpid`, `uuid.uuid1/uuid4`, `threading.get_ident`,
    `secrets.*`, bare `id()`/`hash()` (address- and seed-dependent),
    and iterating a `set` display/constructor (order taint);
  * **propagation** — assignment from a tainted expression taints the
    target; a subscript store of a tainted value taints the base
    (`entry["ts"] = time.time()` taints `entry`); an ATTRIBUTE store
    taints the field path, not the object (`req.pages_t =
    time.monotonic()` taints `req.pages_t` — journaling
    `req.trace.id` stays clean), and a constructor call
    (`_Request(submit_time=now)`) taints per keyword field the same
    way; nested function/lambda bodies are separate scopes;
  * **sinks** — the journal entry points: `build_journal_event(...)`
    arguments, `.append(...)`/`.stamp_header(...)` on a receiver whose
    name mentions `journal`, functions the scan pass discovered to
    forward parameters into those (the scheduler's
    `_journal_submit`/`_journal_fault`/`_finish_megastep` wrappers —
    found transitively and PER PARAMETER, the lockorder call-summary
    idiom: `_timeline_record(dur_s=...)` is clean because `dur_s`
    never reaches the journal entry it writes, while its `rows=` does
    and is checked), and `return`s from a function marked
    `# replay-decision` (fuse-plan / eviction-order choosers).

Escapes: a `# replay-exempt: <why>` comment (non-empty reason
required) on the sink line or the line above exempts a DELIBERATELY
non-replayed field — e.g. the journal's own `ts_unix_s` metadata
stamp, which replay never reads. Exemptions are annotations, not
suppressions — they don't count against the ratchet, mirroring
`# fault-boundary:`. `# oryxlint: disable=replay-taint` remains the
counted escape for everything else.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from .cfg import Bind, build_cfg
from .core import Checker, Finding, ParsedModule, RepoContext, dotted_name
from .dataflow import ForwardAnalysis

_EXEMPT_RE = re.compile(r"#\s*replay-exempt:\s*(\S.*)")
_DECISION_RE = re.compile(r"#\s*replay-decision\b")

# Exact dotted call names that read nondeterministic ambient state.
TAINT_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "time.clock_gettime", "time.process_time",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow", "datetime.date.today", "date.today",
    "os.urandom", "os.getpid", "os.getrandom",
    "uuid.uuid1", "uuid.uuid4",
    "threading.get_ident", "threading.get_native_id",
    "random.random", "random.randint", "random.uniform",
    "random.choice", "random.choices", "random.shuffle",
    "random.sample", "random.randrange", "random.getrandbits",
    "random.gauss", "random.normalvariate", "random.betavariate",
    "secrets.token_hex", "secrets.token_bytes", "secrets.token_urlsafe",
    "secrets.randbelow", "secrets.choice",
}
# Bare builtins whose value is process-local (CPython address / seeded
# string hashing).
TAINT_BUILTINS = {"id", "hash"}

_SOURCE_DESCR = {
    "time.": "wall-clock read",
    "datetime.": "wall-clock read",
    "date.": "wall-clock read",
    "random.": "stdlib random draw",
    "os.urandom": "os entropy read",
    "os.getrandom": "os entropy read",
    "os.getpid": "process-local id",
    "uuid.": "nondeterministic uuid",
    "threading.": "thread-identity read",
    "secrets.": "os entropy read",
}

# Journal entry points: free/attr function names whose ARGUMENTS are
# journaled, and methods on journal-named receivers.
SINK_FUNCS = {"build_journal_event"}
SINK_METHODS = {"append", "stamp_header", "extend"}


def _describe_source(name: str) -> str:
    for prefix, desc in _SOURCE_DESCR.items():
        if name.startswith(prefix):
            return desc
    if name in TAINT_BUILTINS:
        return f"process-local `{name}()`"
    return "nondeterministic read"


def _source_call(call: ast.Call) -> str | None:
    dn = dotted_name(call.func)
    if dn is None:
        return None
    if dn in TAINT_CALLS:
        return dn
    # `self._clock()`-style indirection is invisible; only direct
    # module reads are sources.
    if isinstance(call.func, ast.Name) and dn in TAINT_BUILTINS:
        return dn
    return None


def _is_set_expr(expr: ast.expr) -> bool:
    if isinstance(expr, ast.Set) or isinstance(expr, ast.SetComp):
        return True
    if isinstance(expr, ast.Call):
        dn = dotted_name(expr.func)
        return dn == "set" or dn == "frozenset"
    return False


class _TaintScan(ast.NodeVisitor):
    """Taint evidence inside one expression: direct source calls plus
    reads of already-tainted names or field paths. Skips nested
    function/lambda bodies (separate scopes)."""

    def __init__(self, tainted: dict[str, tuple]):
        # name-or-dotted-path -> (src_line, src_desc)
        self.tainted = tainted
        self.hits: list[tuple[int, str]] = []  # (src_line, desc)

    def visit_Call(self, node: ast.Call) -> None:
        src = _source_call(node)
        if src is not None:
            self.hits.append(
                (node.lineno, _describe_source(src))
            )
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        dn = dotted_name(node)
        if dn is not None and dn in self.tainted:
            self.hits.append(self.tainted[dn])
            return  # the field hit; don't re-hit through the base
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load) and node.id in self.tainted:
            self.hits.append(self.tainted[node.id])

    def visit_Lambda(self, node) -> None:
        return

    def visit_FunctionDef(self, node) -> None:
        return

    def visit_AsyncFunctionDef(self, node) -> None:
        return


def _receiver_mentions_journal(func: ast.expr) -> bool:
    if not isinstance(func, ast.Attribute):
        return False
    recv = dotted_name(func.value)
    return recv is not None and "journal" in recv.lower()


class _Taint(ForwardAnalysis):
    """Facts: ("taint", var, src_line, src_desc). May-analysis."""

    may = True

    def __init__(self, checker: "ReplayTaintChecker"):
        self.checker = checker

    def _tainted_map(self, state) -> dict[str, tuple]:
        out: dict[str, tuple] = {}
        for fact in state:
            if fact[0] == "taint" and fact[1] not in out:
                out[fact[1]] = (fact[2], fact[3])
        return out

    def _expr_taint(self, expr, state) -> list[tuple[int, str]]:
        scan = _TaintScan(self._tainted_map(state))
        scan.visit(expr)
        return scan.hits

    def _kill(self, state, var: str):
        return frozenset(
            f for f in state
            if not (f[0] == "taint" and f[1] == var)
        )

    def _base_name(self, target: ast.expr) -> str | None:
        while isinstance(target, (ast.Subscript, ast.Attribute,
                                  ast.Starred)):
            target = target.value
        if isinstance(target, ast.Name):
            return target.id
        return None

    def _assign(self, state, targets, value):
        hits = self._expr_taint(value, state) if value is not None \
            else []
        for target in targets:
            if isinstance(target, (ast.Tuple, ast.List)):
                state = self._assign(state, target.elts, value)
                continue
            if isinstance(target, ast.Attribute):
                # Field-granular: `req.pages_t = time.monotonic()`
                # taints `req.pages_t`, not every use of `req`.
                path = dotted_name(target)
                if path is None:
                    continue
                state = self._kill(state, path)
                if hits:
                    line, desc = hits[0]
                    state = state | {("taint", path, line, desc)}
                continue
            direct = isinstance(target, ast.Name)
            name = self._base_name(target)
            if name is None:
                continue
            if direct:
                state = self._kill_prefix(state, name)
                ctor = self._ctor_fields(value, state)
                if ctor is not None:
                    # Constructor call: taint per tainted keyword
                    # field (`_Request(submit_time=now)` taints
                    # `req.submit_time`), whole-object only for
                    # tainted positionals.
                    whole, fields = ctor
                    for field, (line, desc) in fields.items():
                        state = state | {
                            ("taint", f"{name}.{field}", line, desc)
                        }
                    if whole:
                        line, desc = whole
                        state = state | {("taint", name, line, desc)}
                    continue
            if hits:
                # A store through a subscript taints the base object
                # without clearing its other taints.
                line, desc = hits[0]
                state = state | {("taint", name, line, desc)}
        return state

    def _kill_prefix(self, state, name: str):
        """Re-binding a name clears the name AND its field facts."""
        prefix = name + "."
        return frozenset(
            f for f in state
            if not (
                f[0] == "taint"
                and (f[1] == name or f[1].startswith(prefix))
            )
        )

    def _ctor_fields(self, value, state):
        """(whole_taint | None, {field: (line, desc)}) when `value`
        is a constructor call (Capitalized final name — the repo's
        dataclass/class convention), else None."""
        if not isinstance(value, ast.Call):
            return None
        dn = dotted_name(value.func)
        if dn is None:
            return None
        last = dn.split(".")[-1].lstrip("_")
        if not last or not last[0].isupper():
            return None
        whole = None
        for arg in value.args:
            h = self._expr_taint(arg, state)
            if h:
                whole = h[0]
                break
        fields = {}
        for kw in value.keywords:
            h = self._expr_taint(kw.value, state)
            if h:
                if kw.arg is None:  # **kwargs splat: whole-object
                    whole = whole or h[0]
                else:
                    fields[kw.arg] = h[0]
        return whole, fields

    def transfer(self, elem, state):
        if isinstance(elem, Bind):
            if elem.kind == "for" and elem.target is not None \
                    and elem.value is not None:
                hits = self._expr_taint(elem.value, state)
                if _is_set_expr(elem.value):
                    hits = hits + [(
                        elem.value.lineno, "set iteration order"
                    )]
                name = self._base_name(elem.target)
                if name is not None:
                    state = self._kill(state, name)
                    if hits:
                        line, desc = hits[0]
                        state = state | {
                            ("taint", name, line, desc)
                        }
            return state
        if isinstance(elem, ast.Assign):
            return self._assign(state, elem.targets, elem.value)
        if isinstance(elem, ast.AnnAssign):
            return self._assign(state, [elem.target], elem.value)
        if isinstance(elem, ast.AugAssign):
            hits = self._expr_taint(elem.value, state)
            name = self._base_name(elem.target)
            if hits and name is not None:
                line, desc = hits[0]
                state = state | {("taint", name, line, desc)}
            return state
        return state


def _callee_last(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _direct_sink(call: ast.Call) -> str | None:
    last = _callee_last(call)
    if last in SINK_FUNCS:
        return "journal event build"
    if last in SINK_METHODS and _receiver_mentions_journal(call.func):
        return "journal write"
    return None


def _effective_params(params: tuple, call: ast.Call) -> tuple:
    if params and params[0] in ("self", "cls") \
            and isinstance(call.func, ast.Attribute):
        return params[1:]
    return params


class ReplayTaintChecker(Checker):
    name = "replay-taint"

    def __init__(self) -> None:
        # Scan-pass function summaries: simple name -> [param tuples]
        # (one per def; name collisions keep every signature and the
        # check stays conservative across them).
        self._sigs: dict[
            str, list[tuple[tuple, ast.AST, ParsedModule]]
        ] = {}
        # name -> frozenset of params that flow into a journal sink —
        # computed transitively (fixpoint) on first use.
        self._forwarded: dict[str, frozenset] | None = None

    # -- scan --------------------------------------------------------------

    def scan(self, mod: ParsedModule, ctx: RepoContext) -> None:
        for node in mod.nodes_of(
            ast.FunctionDef, ast.AsyncFunctionDef
        ):
            args = node.args
            params = tuple(
                a.arg for a in
                args.posonlyargs + args.args + args.kwonlyargs
            )
            self._sigs.setdefault(node.name, []).append(
                (params, node, mod)
            )

    def _registry(self) -> dict[str, frozenset]:
        """fn name -> params that reach a journal sink from inside it,
        found to a fixpoint: `_timeline_record` forwards `rows` (it
        lands in its `step` journal entry) but NOT `dur_s` (timeline
        only), so callers' wall-clock durations stay clean while
        anything feeding journaled fields is checked — per parameter,
        transitively through wrappers (the lockorder may-acquire
        idiom)."""
        if self._forwarded is not None:
            return self._forwarded
        forwarded: dict[str, frozenset] = {}
        # Call lists are re-read every fixpoint round — collect them
        # once per signature up front.
        cands = []
        for name, sigs in self._sigs.items():
            for params, node, smod in sigs:
                pset = set(params)
                if not pset:
                    continue
                calls = [
                    c for c in smod.walk(node)
                    if isinstance(c, ast.Call)
                ]
                cands.append((name, pset, calls))
        changed = True
        while changed:
            changed = False
            for name, pset, calls in cands:
                have = set(forwarded.get(name, frozenset()))
                for call in calls:
                    for value in self._sink_values(
                        call, forwarded
                    ):
                        for n in ast.walk(value):
                            if isinstance(n, ast.Name) \
                                    and n.id in pset:
                                have.add(n.id)
                if have != set(forwarded.get(name, frozenset())):
                    forwarded[name] = frozenset(have)
                    changed = True
        self._forwarded = forwarded
        return forwarded

    def _sink_values(
        self, call: ast.Call, forwarded: dict[str, frozenset]
    ) -> list[ast.expr]:
        """The argument expressions of `call` that reach a journal
        sink: every arg for direct sinks; only the args bound to
        forwarded parameters for discovered wrappers."""
        if _direct_sink(call) is not None:
            return list(call.args) + [
                kw.value for kw in call.keywords
            ]
        last = _callee_last(call)
        fparams = forwarded.get(last)
        if not fparams:
            return []
        out: list[ast.expr] = []
        for params, _node, _mod in self._sigs.get(last, ()):
            eff = _effective_params(params, call)
            for i, arg in enumerate(call.args):
                if i < len(eff) and eff[i] in fparams:
                    out.append(arg)
            for kw in call.keywords:
                if kw.arg is None or kw.arg in fparams:
                    out.append(kw.value)
        return out

    def _sink_what(self, call: ast.Call) -> str | None:
        direct = _direct_sink(call)
        if direct is not None:
            return direct
        last = _callee_last(call)
        if self._registry().get(last):
            return f"journal entry point `{last}`"
        return None

    # -- check -------------------------------------------------------------

    def _exempt(self, mod: ParsedModule, line: int) -> bool:
        for ln in (line, line - 1):
            m = _EXEMPT_RE.search(mod.comment_text(ln))
            if m and m.group(1).strip():
                return True
        return False

    def _is_decision_fn(self, mod: ParsedModule, fn) -> bool:
        first = min(
            [fn.lineno] + [d.lineno for d in fn.decorator_list]
        )
        if _DECISION_RE.search(mod.comment_text(fn.lineno)):
            return True
        line = first - 1
        while line >= 1:
            text = mod.comment_text(line)
            if not text:
                break
            if _DECISION_RE.search(text):
                return True
            line -= 1
        return False

    def check(
        self, mod: ParsedModule, ctx: RepoContext
    ) -> Iterator[Finding]:
        registry = self._registry()
        for node in mod.nodes_of(
            ast.FunctionDef, ast.AsyncFunctionDef
        ):
            if not (
                self._may_sink(mod, node, registry)
                or self._is_decision_fn(mod, node)
            ):
                continue
            yield from self._check_fn(mod, node)

    def _may_sink(self, mod, fn, registry) -> bool:
        """Cheap superset test: the taint pass can only report a
        function that contains a journal sink call (direct or via a
        discovered wrapper)."""
        for n in mod.walk(fn):
            if isinstance(n, ast.Call):
                if _direct_sink(n) is not None:
                    return True
                if registry.get(_callee_last(n)):
                    return True
        return False

    def _check_fn(self, mod, fn):
        flow = _Taint(self)
        cfg = build_cfg(fn.body, anchor=fn)
        flow.run(cfg)
        decision = self._is_decision_fn(mod, fn)
        reported: set = set()
        for block in cfg.blocks:
            for elem, state in flow.replay(block):
                node = elem.node if isinstance(elem, Bind) else elem
                root = elem.value if isinstance(elem, Bind) else elem
                if root is None:
                    continue
                yield from self._check_elem(
                    mod, fn, node, root, state, flow, decision,
                    reported,
                )

    def _check_elem(self, mod, fn, node, root, state, flow,
                    decision, reported):
        for call in mod.walk(root):
            if not isinstance(call, ast.Call):
                continue
            what = self._sink_what(call)
            if what is None:
                continue
            hits = []
            for v in self._sink_values(call, self._registry()):
                hits.extend(flow._expr_taint(v, state))
            if not hits:
                continue
            key = (call.lineno, call.col_offset)
            if key in reported:
                continue
            reported.add(key)
            if self._exempt(mod, call.lineno):
                continue
            line, desc = hits[0]
            yield self.finding(
                mod, call,
                f"nondeterministic value ({desc} at line {line}) "
                f"flows into {what}: journaled state must replay "
                "byte-identically — derive it from journal/ledger "
                "state, or mark a deliberately non-replayed field "
                "with `# replay-exempt: <why>`",
            )
        if decision and isinstance(root, ast.Return) \
                and root.value is not None:
            hits = flow._expr_taint(root.value, state)
            key = ("ret", root.lineno)
            if hits and key not in reported:
                reported.add(key)
                if not self._exempt(mod, root.lineno):
                    line, desc = hits[0]
                    yield self.finding(
                        mod, root,
                        f"`{fn.name}` is marked # replay-decision "
                        f"but returns a nondeterministic value "
                        f"({desc} at line {line}): replayed "
                        "decisions must be functions of journal "
                        "state only",
                    )
