"""recompile-hazard: patterns that silently multiply compiled programs.

Two hazard families, both of which burn TPU hours without failing a
single CPU test:

  * A Python `if` on a NON-static parameter inside a jitted function.
    At best it raises TracerBoolConversionError on the first real run;
    at worst (boolean-ish numpy input on some call paths) it traces
    one program per observed value. Identity tests (`x is None` /
    `is not None`) are fine — they branch on the Python structure, not
    the traced value — and attribute reads like `x.shape[0]` are
    static by construction; only direct value-dependent tests on the
    parameter name are flagged.

  * An unhashable or per-call-unique operand (dict/list/set literal,
    f-string, lambda, comprehension) passed in a STATIC position of a
    known jitted callee. Every call is a cache miss: the jit cache
    keys static operands by hash/equality, and a fresh literal never
    compares equal to the last one. (`api_server._parse_sampling`
    quantizes temperature for the same reason.)

The scan pass collects every jit-wrapped definition in the repo
(decorator `@partial(jax.jit, static_argnames=...)`, `@jax.jit(...)`,
or `name = jax.jit(fn, static_argnums=...)`) with its static parameter
names; call sites anywhere then resolve by simple-name tail.
"""

from __future__ import annotations

import ast
from typing import Iterator

from oryx_tpu.analysis.core import (
    Checker,
    Finding,
    ParsedModule,
    RepoContext,
    dotted_name,
)
from oryx_tpu.analysis.donation import (
    _const_ints,
    _const_strs,
    _jit_donations,
    _tail,
)

_UNHASHABLE = (
    ast.Dict, ast.List, ast.Set, ast.JoinedStr, ast.Lambda,
    ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp,
)


def _is_bare_jit(dec: ast.AST) -> bool:
    """`@jax.jit` with no argument list."""
    d = dotted_name(dec)
    return _tail(d) == "jit" and (d or "").split(".")[0] in ("jax", "jit")


def _jit_statics(call: ast.Call) -> tuple[set[str], set[int]] | None:
    """(static_argnames, static_argnums) when `call` is a jax.jit /
    partial(jax.jit, ...) wrapper; None otherwise."""
    if _jit_donations(call) is None:  # shares the jit-shape detection
        return None
    names: set[str] = set()
    nums: set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names |= _const_strs(kw.value)
        elif kw.arg == "static_argnums":
            nums |= _const_ints(kw.value)
    return names, nums


class RecompileHazardChecker(Checker):
    name = "recompile-hazard"

    # ---- pass 1: collect jitted defs -------------------------------------

    def scan(self, mod: ParsedModule, ctx: RepoContext) -> None:
        for node in mod.nodes_of(
            ast.FunctionDef, ast.AsyncFunctionDef, ast.Assign
        ):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = [a.arg for a in node.args.args] + [
                    a.arg for a in node.args.kwonlyargs
                ]
                ctx.fn_params.setdefault(node.name, params)
                for dec in node.decorator_list:
                    if _is_bare_jit(dec):
                        ctx.jitted_static.setdefault(node.name, set())
                        continue
                    if not isinstance(dec, ast.Call):
                        continue
                    statics = _jit_statics(dec)
                    if statics is None:
                        continue
                    names, nums = statics
                    pos = [a.arg for a in node.args.args]
                    names |= {
                        pos[i] for i in nums if i < len(pos)
                    }
                    ctx.jitted_static.setdefault(node.name, set()).update(
                        names
                    )
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                statics = _jit_statics(node.value)
                if statics is None or not (statics[0] | statics[1]):
                    continue
                names, nums = statics
                callee = None
                if node.value.args:
                    callee = _tail(dotted_name(node.value.args[0]))
                for target in node.targets:
                    t = _tail(dotted_name(target))
                    if t:
                        ctx.jitted_static.setdefault(t, set()).update(
                            names
                        )
                        # Param order + argnum resolution happen at
                        # check time through the alias (the wrapped
                        # fn's def may live in a module scanned later).
                        if callee:
                            ctx.jit_aliases[t] = callee
                        for i in nums:
                            ctx.jitted_static[t].add(f"#argnum:{i}")

    # ---- pass 2 ----------------------------------------------------------

    def check(
        self, mod: ParsedModule, ctx: RepoContext
    ) -> Iterator[Finding | None]:
        for node in mod.nodes_of(
            ast.FunctionDef, ast.AsyncFunctionDef, ast.Call
        ):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                if node.name in ctx.jitted_static and self._is_jitted(
                    node
                ):
                    yield from self._check_tracer_branches(
                        mod, node, ctx.jitted_static[node.name]
                    )
            else:
                yield from self._check_static_operands(mod, node, ctx)

    @staticmethod
    def _is_jitted(fn: ast.FunctionDef) -> bool:
        return any(
            _is_bare_jit(d)
            or (isinstance(d, ast.Call) and _jit_statics(d) is not None)
            for d in fn.decorator_list
        )

    @staticmethod
    def _dynamic_names(expr: ast.expr) -> list[ast.Name]:
        """Name loads in `expr` whose VALUE flows into the result —
        excluding occurrences that are static under trace: bases of
        .shape/.ndim/.dtype/.size attribute chains and len() operands
        (array lengths are shape components)."""
        static_ids: set[int] = set()
        for node in ast.walk(expr):
            sub = None
            if isinstance(node, ast.Attribute) and node.attr in (
                "shape", "ndim", "dtype", "size",
            ):
                sub = node.value
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "len"
            ):
                sub = node.args[0] if node.args else None
            if sub is not None:
                for n in ast.walk(sub):
                    if isinstance(n, ast.Name):
                        static_ids.add(id(n))
        return [
            n for n in ast.walk(expr)
            if isinstance(n, ast.Name) and id(n) not in static_ids
        ]

    def _tainted_locals(
        self, mod: ParsedModule, fn: ast.FunctionDef, traced: set[str]
    ) -> set[str]:
        """Locals DERIVED from traced parameters (the packed-buffer
        idiom hazard: `num_live = (~finished).sum()` then
        `if num_live:` branches Python on a tracer just as surely as
        branching on the parameter itself). Conservative dataflow:
        single-name assignments whose value reads a traced/tainted name
        outside a static (.shape/len) context taint the target; run to
        fixpoint so chains (`a = x; b = a`) and loop back-edges
        resolve."""
        tainted: set[str] = set()
        assigns = [
            (node.targets[0].id,
             {n.id for n in self._dynamic_names(node.value)})
            for node in mod.walk(fn)
            if isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ]
        changed = True
        while changed:
            changed = False
            for tgt, names in assigns:
                if tgt in tainted:
                    continue
                if names & (traced | tainted):
                    tainted.add(tgt)
                    changed = True
        return tainted

    def _check_tracer_branches(
        self, mod: ParsedModule, fn: ast.FunctionDef, statics: set[str]
    ) -> Iterator[Finding | None]:
        traced = {
            a.arg
            for a in list(fn.args.args) + list(fn.args.kwonlyargs)
            if a.arg not in statics and a.arg != "self"
        }
        tainted = self._tainted_locals(mod, fn, traced)

        def value_dependent_names(test: ast.expr) -> list[ast.Name]:
            """Direct value tests on a traced parameter name or a local
            derived from one."""
            if isinstance(test, ast.Name):
                return [test] if test.id in traced | tainted else []
            if isinstance(test, ast.UnaryOp) and isinstance(
                test.op, ast.Not
            ):
                return value_dependent_names(test.operand)
            if isinstance(test, ast.BoolOp):
                out = []
                for v in test.values:
                    out.extend(value_dependent_names(v))
                return out
            if isinstance(test, ast.Compare):
                if all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops
                ):
                    return []
                out = []
                for side in [test.left, *test.comparators]:
                    if (
                        isinstance(side, ast.Name)
                        and side.id in traced | tainted
                    ):
                        out.append(side)
                return out
            return []

        for node in mod.walk(fn):
            if not isinstance(node, (ast.If, ast.IfExp, ast.While)):
                continue
            for name in value_dependent_names(node.test):
                what = (
                    f"traced argument '{name.id}'"
                    if name.id in traced
                    else f"'{name.id}' (derived from a traced argument)"
                )
                yield self.finding(
                    mod,
                    name,
                    f"Python branch on {what} inside jitted "
                    f"'{fn.name}' — use jnp.where/lax.cond, mark it "
                    "static, or hoist the decision to host state (the "
                    "packed-buffer idiom: shape-class selection happens "
                    "OUTSIDE the jitted ragged step)",
                )

    def _check_static_operands(
        self, mod: ParsedModule, call: ast.Call, ctx: RepoContext
    ) -> Iterator[Finding | None]:
        callee = _tail(dotted_name(call.func))
        if callee not in ctx.jitted_static:
            return
        statics = set(ctx.jitted_static[callee])
        params = ctx.fn_params.get(callee) or ctx.fn_params.get(
            ctx.jit_aliases.get(callee, ""), []
        )
        for s in list(statics):
            if s.startswith("#argnum:"):
                statics.discard(s)
                i = int(s.split(":", 1)[1])
                if i < len(params):
                    statics.add(params[i])
        operands: list[tuple[str, ast.expr]] = []
        for i, arg in enumerate(call.args):
            if i < len(params) and params[i] in statics:
                operands.append((params[i], arg))
        for kw in call.keywords:
            if kw.arg in statics:
                operands.append((kw.arg, kw.value))
        for pname, arg in operands:
            if isinstance(arg, _UNHASHABLE):
                kind = (
                    "f-string" if isinstance(arg, ast.JoinedStr)
                    else type(arg).__name__.lower() + " literal"
                )
                yield self.finding(
                    mod,
                    arg,
                    f"{kind} passed as static argument '{pname}' of "
                    f"jitted '{callee}' — a fresh object every call "
                    "never hits the jit cache (recompiles per call)",
                )
