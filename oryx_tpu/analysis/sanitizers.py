"""Runtime sanitizers: the dynamic half of oryxlint.

Static checks catch the patterns; these catch the behaviors — in unit
tests and canary runs, on CPU, before a TPU fleet burns hours on them:

  * `recompile_watchdog()` — counts jax compilation-cache misses per
    traced function for the duration of a `with` block (via jax's own
    compilation logging, no private APIs), exports them as
    `oryx_recompiles_total{fn=...}` through the existing metrics
    registry, and raises `RecompileStormError` when any one function
    compiles more than `budget` times. A decode loop that recompiles
    per step because someone passed a fresh tuple as a static arg
    fails the test in seconds instead of showing up as a 10x TTFT
    regression.
  * `donation_guard()` — tracks the live jax arrays of one or more
    pytrees across a donating call: `assert_consumed()` proves the
    donation actually happened (an aliasing contract silently
    degrading to copies is an HBM regression), and `check(tree)`
    raises `UseAfterDonateError` naming the first deleted leaf — the
    runtime twin of the `use-after-donate` static rule.

jax imports are deferred into the functions so `oryx_tpu.analysis`
stays importable (and the static linter runnable) without the
accelerator stack.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Any, Iterator


class RecompileStormError(RuntimeError):
    """A traced function exceeded its compile budget inside a
    `recompile_watchdog` block."""


class UseAfterDonateError(RuntimeError):
    """A donated (deleted) buffer was about to be read."""


class RecompileStats:
    """Per-traced-function compile counts observed by the watchdog."""

    def __init__(self, budget: int):
        self.budget = budget
        self.counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def record(self, fn_name: str) -> int:
        with self._lock:
            self.counts[fn_name] = self.counts.get(fn_name, 0) + 1
            return self.counts[fn_name]

    @property
    def total(self) -> int:
        with self._lock:
            return sum(self.counts.values())

    def over_budget(self) -> dict[str, int]:
        with self._lock:
            return {
                k: v for k, v in self.counts.items() if v > self.budget
            }


class _CompileLogHandler(logging.Handler):
    """Captures jax's "Compiling <fn> ..." records (emitted on every
    tracing-cache miss when `jax_log_compiles` is on)."""

    def __init__(self, callback):
        super().__init__(level=logging.DEBUG)
        self._callback = callback

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.msg if isinstance(record.msg, str) else ""
            if not msg.startswith("Compiling"):
                return
            fn = "<unknown>"
            if record.args:
                fn = str(
                    record.args[0]
                    if isinstance(record.args, tuple)
                    else record.args
                )
            self._callback(fn)
        # fault-boundary: a broken sanitizer must never break the run
        except Exception:
            pass


@contextlib.contextmanager
def recompile_watchdog(
    budget: int = 1,
    *,
    registry=None,
    action: str = "raise",
    logger_name: str = "jax",
) -> Iterator[RecompileStats]:
    """Count per-function jax compiles inside the block; over-budget
    raises (action="raise") at exit or just records (action="record").

    budget: max compiles allowed PER traced function name — distinct
    shapes of one function share a name, which is exactly the point:
    a shape-unstable loop is a recompile storm no matter how "valid"
    each individual compile is. The first compile of a function is
    expected (that's a cold start, not a recompile); every compile
    beyond the first increments `oryx_recompiles_total{fn=...}` on
    `registry` (a `utils.metrics.Registry`; pass
    `serving_metrics.registry` from serving code).
    """
    if action not in ("raise", "record"):
        raise ValueError(f"action must be 'raise' or 'record', got {action!r}")
    import jax

    stats = RecompileStats(budget)
    counter = None
    if registry is not None:
        counter = registry.counter(
            "oryx_recompiles_total", ("fn",), raw_name=True
        )

    def on_compile(fn_name: str) -> None:
        n = stats.record(fn_name)
        if n > 1 and counter is not None:
            counter.labels(fn=fn_name).inc()

    handler = _CompileLogHandler(on_compile)
    jax_logger = logging.getLogger(logger_name)
    prev_log_compiles = jax.config.jax_log_compiles
    jax.config.update("jax_log_compiles", True)
    jax_logger.addHandler(handler)
    try:
        yield stats
    finally:
        jax_logger.removeHandler(handler)
        jax.config.update("jax_log_compiles", prev_log_compiles)
    over = stats.over_budget()
    if over and action == "raise":
        worst = max(over, key=over.get)
        raise RecompileStormError(
            f"recompile storm: {worst!r} compiled {over[worst]} times "
            f"(budget {stats.budget}) inside a recompile_watchdog block; "
            f"all over budget: {over}. A fresh unhashable static operand "
            "or an unbucketed shape is the usual cause."
        )


class DonationGuard:
    """Tracks the jax-array leaves of pytrees across donating calls."""

    def __init__(self, *trees: Any, label: str = ""):
        import jax

        self.label = label
        self._leaves = [
            leaf
            for tree in trees
            for leaf in jax.tree_util.tree_leaves(tree)
            if isinstance(leaf, jax.Array)
        ]

    def _deleted(self) -> list[int]:
        return [
            i for i, a in enumerate(self._leaves) if a.is_deleted()
        ]

    @property
    def consumed(self) -> bool:
        """True when every tracked buffer was donated (deleted)."""
        return bool(self._leaves) and len(self._deleted()) == len(
            self._leaves
        )

    def assert_consumed(self) -> None:
        """The donation contract held: every tracked buffer is gone.
        Failing means the aliasing silently degraded to a copy — an
        HBM-footprint regression on real hardware. Tracking zero
        jax-array leaves also fails: a guard over an all-host tree
        verifies nothing, which is its own refactor hazard."""
        if not self._leaves:
            raise AssertionError(
                f"donation_guard{f' [{self.label}]' if self.label else ''}: "
                "no jax-array leaves were tracked — the guarded tree has "
                "no device buffers, so consumption cannot be verified"
            )
        dead = self._deleted()
        if len(dead) != len(self._leaves):
            live = len(self._leaves) - len(dead)
            raise AssertionError(
                f"donation_guard{f' [{self.label}]' if self.label else ''}: "
                f"{live}/{len(self._leaves)} tracked buffers were NOT "
                "consumed by the donating call (donation degraded to a "
                "copy, or the call never donated)"
            )

    def check(self, tree: Any = None) -> None:
        """Raise `UseAfterDonateError` if any leaf of `tree` (default:
        the tracked trees) has been deleted — call this before a read
        that must not touch donated storage."""
        import jax

        leaves = (
            self._leaves
            if tree is None
            else [
                leaf
                for leaf in jax.tree_util.tree_leaves(tree)
                if isinstance(leaf, jax.Array)
            ]
        )
        for i, a in enumerate(leaves):
            if a.is_deleted():
                raise UseAfterDonateError(
                    f"donation_guard"
                    f"{f' [{self.label}]' if self.label else ''}: "
                    f"leaf {i} ({a.aval}) was donated and deleted; "
                    "reading it is use-after-donate"
                )


@contextlib.contextmanager
def donation_guard(
    *trees: Any, expect_consumed: bool = False, label: str = ""
) -> Iterator[DonationGuard]:
    """Context-manager sugar over `DonationGuard`. With
    `expect_consumed=True` the exit asserts every tracked buffer was
    donated (use in tests around a single donating call)."""
    guard = DonationGuard(*trees, label=label)
    yield guard
    if expect_consumed:
        guard.assert_consumed()


def backend_donates() -> bool:
    """Whether this backend actually consumes donated buffers (CPU on
    some jax versions silently ignores donation) — tests gate
    `assert_consumed` on this."""
    import jax
    import jax.numpy as jnp

    probe = jax.jit(lambda x: x + 1, donate_argnums=0)
    x = jnp.zeros((8,))
    probe(x).block_until_ready()
    # The read IS the probe: asking whether donation consumed it.
    return x.is_deleted()  # oryxlint: disable=use-after-donate
