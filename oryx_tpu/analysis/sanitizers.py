"""Runtime sanitizers: the dynamic half of oryxlint.

Static checks catch the patterns; these catch the behaviors — in unit
tests and canary runs, on CPU, before a TPU fleet burns hours on them:

  * `recompile_watchdog()` — counts jax compilation-cache misses per
    traced function for the duration of a `with` block (via jax's own
    compilation logging, no private APIs), exports them as
    `oryx_recompiles_total{fn=...}` through the existing metrics
    registry, and raises `RecompileStormError` when any one function
    compiles more than `budget` times. A decode loop that recompiles
    per step because someone passed a fresh tuple as a static arg
    fails the test in seconds instead of showing up as a 10x TTFT
    regression.
  * `donation_guard()` — tracks the live jax arrays of one or more
    pytrees across a donating call: `assert_consumed()` proves the
    donation actually happened (an aliasing contract silently
    degrading to copies is an HBM regression), and `check(tree)`
    raises `UseAfterDonateError` naming the first deleted leaf — the
    runtime twin of the `use-after-donate` static rule.
  * `LockOrderSanitizer` — the runtime twin of the `lock-order` static
    rule. Production code creates its locks through `named_lock(name,
    kind=...)`: disarmed (the default) that returns a plain
    `threading.Lock/RLock/Condition` at the cost of one global read;
    armed (`ORYX_LOCK_SANITIZER=1`, or `lock_sanitizer()` in tests) it
    returns an instrumented wrapper that keeps a per-thread held-lock
    stack, raises `LockOrderViolation` at the acquire that inverts the
    declared order (oryx_tpu/concurrency.py), forms a cycle, or
    re-enters a non-reentrant lock, counts re-entrant acquires per
    name, and exports `oryx_lock_wait_seconds{lock=}` /
    `oryx_lock_hold_seconds{lock=}` histograms through a bound
    Registry. `hot_dispatch(name)` flags a device dispatch entered
    while holding ANY instrumented lock.
  * `RaceDetector` — a lightweight LockSet/Eraser-style happens-before
    race detector over the `# guarded-by:` / `# thread-owned:`
    annotated fields (the SAME source annotations the static rules
    read, via analysis.core.field_annotations). Armed, it installs
    data descriptors on the annotated classes: per-field last-accessor
    tracking with ownership HANDOFF (A A B B is a legal transfer;
    A B A — a prior live accessor interleaving back — makes the field
    shared), after which a guarded field must be accessed under its
    declared lock and a thread-owned field must not be touched at all
    by a second live thread. Thread death is a happens-before edge:
    a dead owner's state hands off freely (what makes supervisor
    restart and drain-of-a-dead-engine legal).

jax imports are deferred into the functions so `oryx_tpu.analysis`
stays importable (and the static linter runnable) without the
accelerator stack.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Any, Iterator


class RecompileStormError(RuntimeError):
    """A traced function exceeded its compile budget inside a
    `recompile_watchdog` block."""


class UseAfterDonateError(RuntimeError):
    """A donated (deleted) buffer was about to be read."""


class RecompileStats:
    """Per-traced-function compile counts observed by the watchdog."""

    def __init__(self, budget: int):
        self.budget = budget
        self.counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def record(self, fn_name: str) -> int:
        with self._lock:
            self.counts[fn_name] = self.counts.get(fn_name, 0) + 1
            return self.counts[fn_name]

    @property
    def total(self) -> int:
        with self._lock:
            return sum(self.counts.values())

    def over_budget(self) -> dict[str, int]:
        with self._lock:
            return {
                k: v for k, v in self.counts.items() if v > self.budget
            }


class _CompileLogHandler(logging.Handler):
    """Captures jax's "Compiling <fn> ..." records (emitted on every
    tracing-cache miss when `jax_log_compiles` is on)."""

    def __init__(self, callback):
        super().__init__(level=logging.DEBUG)
        self._callback = callback

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.msg if isinstance(record.msg, str) else ""
            if not msg.startswith("Compiling"):
                return
            fn = "<unknown>"
            if record.args:
                fn = str(
                    record.args[0]
                    if isinstance(record.args, tuple)
                    else record.args
                )
            self._callback(fn)
        # fault-boundary: a broken sanitizer must never break the run
        except Exception:
            pass


@contextlib.contextmanager
def recompile_watchdog(
    budget: int = 1,
    *,
    registry=None,
    action: str = "raise",
    logger_name: str = "jax",
) -> Iterator[RecompileStats]:
    """Count per-function jax compiles inside the block; over-budget
    raises (action="raise") at exit or just records (action="record").

    budget: max compiles allowed PER traced function name — distinct
    shapes of one function share a name, which is exactly the point:
    a shape-unstable loop is a recompile storm no matter how "valid"
    each individual compile is. The first compile of a function is
    expected (that's a cold start, not a recompile); every compile
    beyond the first increments `oryx_recompiles_total{fn=...}` on
    `registry` (a `utils.metrics.Registry`; pass
    `serving_metrics.registry` from serving code).
    """
    if action not in ("raise", "record"):
        raise ValueError(f"action must be 'raise' or 'record', got {action!r}")
    import jax

    stats = RecompileStats(budget)
    counter = None
    if registry is not None:
        counter = registry.counter(
            "oryx_recompiles_total", ("fn",), raw_name=True
        )

    def on_compile(fn_name: str) -> None:
        n = stats.record(fn_name)
        if n > 1 and counter is not None:
            counter.labels(fn=fn_name).inc()

    handler = _CompileLogHandler(on_compile)
    jax_logger = logging.getLogger(logger_name)
    prev_log_compiles = jax.config.jax_log_compiles
    jax.config.update("jax_log_compiles", True)
    jax_logger.addHandler(handler)
    try:
        yield stats
    finally:
        jax_logger.removeHandler(handler)
        jax.config.update("jax_log_compiles", prev_log_compiles)
    over = stats.over_budget()
    if over and action == "raise":
        worst = max(over, key=over.get)
        raise RecompileStormError(
            f"recompile storm: {worst!r} compiled {over[worst]} times "
            f"(budget {stats.budget}) inside a recompile_watchdog block; "
            f"all over budget: {over}. A fresh unhashable static operand "
            "or an unbucketed shape is the usual cause."
        )


class DonationGuard:
    """Tracks the jax-array leaves of pytrees across donating calls."""

    def __init__(self, *trees: Any, label: str = ""):
        import jax

        self.label = label
        self._leaves = [
            leaf
            for tree in trees
            for leaf in jax.tree_util.tree_leaves(tree)
            if isinstance(leaf, jax.Array)
        ]

    def _deleted(self) -> list[int]:
        return [
            i for i, a in enumerate(self._leaves) if a.is_deleted()
        ]

    @property
    def consumed(self) -> bool:
        """True when every tracked buffer was donated (deleted)."""
        return bool(self._leaves) and len(self._deleted()) == len(
            self._leaves
        )

    def assert_consumed(self) -> None:
        """The donation contract held: every tracked buffer is gone.
        Failing means the aliasing silently degraded to a copy — an
        HBM-footprint regression on real hardware. Tracking zero
        jax-array leaves also fails: a guard over an all-host tree
        verifies nothing, which is its own refactor hazard."""
        if not self._leaves:
            raise AssertionError(
                f"donation_guard{f' [{self.label}]' if self.label else ''}: "
                "no jax-array leaves were tracked — the guarded tree has "
                "no device buffers, so consumption cannot be verified"
            )
        dead = self._deleted()
        if len(dead) != len(self._leaves):
            live = len(self._leaves) - len(dead)
            raise AssertionError(
                f"donation_guard{f' [{self.label}]' if self.label else ''}: "
                f"{live}/{len(self._leaves)} tracked buffers were NOT "
                "consumed by the donating call (donation degraded to a "
                "copy, or the call never donated)"
            )

    def check(self, tree: Any = None) -> None:
        """Raise `UseAfterDonateError` if any leaf of `tree` (default:
        the tracked trees) has been deleted — call this before a read
        that must not touch donated storage."""
        import jax

        leaves = (
            self._leaves
            if tree is None
            else [
                leaf
                for leaf in jax.tree_util.tree_leaves(tree)
                if isinstance(leaf, jax.Array)
            ]
        )
        for i, a in enumerate(leaves):
            if a.is_deleted():
                raise UseAfterDonateError(
                    f"donation_guard"
                    f"{f' [{self.label}]' if self.label else ''}: "
                    f"leaf {i} ({a.aval}) was donated and deleted; "
                    "reading it is use-after-donate"
                )


@contextlib.contextmanager
def donation_guard(
    *trees: Any, expect_consumed: bool = False, label: str = ""
) -> Iterator[DonationGuard]:
    """Context-manager sugar over `DonationGuard`. With
    `expect_consumed=True` the exit asserts every tracked buffer was
    donated (use in tests around a single donating call)."""
    guard = DonationGuard(*trees, label=label)
    yield guard
    if expect_consumed:
        guard.assert_consumed()


# ---------------------------------------------------------------------------
# Lock-order sanitizer + race detector (the runtime half of the
# concurrency-correctness suite; static twins live in lockorder.py)
# ---------------------------------------------------------------------------


class LockOrderViolation(RuntimeError):
    """An instrumented lock acquire inverted the declared order,
    formed a cycle, re-entered a non-reentrant lock, or a hot-path
    dispatch ran while a lock was held."""


class RaceViolation(RuntimeError):
    """An annotated field was touched off its declared lock (shared
    state) or by an interloping live thread (thread-owned state)."""


class LockStats:
    """What the sanitizer observed: violations (recorded even when
    action='record'), per-name acquire / re-entrant-acquire counts,
    and buffered wait/hold samples awaiting a registry flush."""

    def __init__(self) -> None:
        self.violations: list[str] = []
        self.acquires: dict[str, int] = {}
        self.reentrant: dict[str, int] = {}


class _Held:
    __slots__ = ("lock", "t0")

    def __init__(self, lock: "_InstrumentedLock", t0: float):
        self.lock = lock
        self.t0 = t0


class LockOrderSanitizer:
    """Per-thread held-lock stacks + declared-order / cycle checking
    for every lock created through `named_lock` while armed."""

    _SAMPLE_CAP = 100_000  # buffered (kind, name, seconds) samples

    def __init__(self, order: tuple[str, ...] | None = None,
                 action: str = "raise"):
        if action not in ("raise", "record"):
            raise ValueError(
                f"action must be 'raise' or 'record', got {action!r}"
            )
        if order is None:
            from oryx_tpu.concurrency import LOCK_ORDER

            order = LOCK_ORDER
        self.order = tuple(order)
        self.rank = {name: i for i, name in enumerate(self.order)}
        self.action = action
        self.stats = LockStats()
        # Internal state lock: a PLAIN lock, deliberately outside the
        # instrumented world (it is a leaf and must never recurse into
        # the sanitizer).
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._edges: dict[str, set[str]] = {}
        self._samples: list[tuple[str, str, float]] = []
        self._dropped_samples = 0
        # Newest bind_registry() call owns the sample stream; stale
        # bindings' collectors no-op against this token.
        self._bind_gen: object | None = None

    # ---- lock factory ----------------------------------------------------

    def make(self, name: str, kind: str = "lock") -> "_InstrumentedLock":
        return _InstrumentedLock(self, name, kind)

    # ---- held-stack bookkeeping ------------------------------------------

    def _held(self) -> list[_Held]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_names(self) -> list[str]:
        return [e.lock.name for e in self._held()]

    def _violation(self, msg: str) -> None:
        with self._mu:
            self.stats.violations.append(msg)
        if self.action == "raise":
            raise LockOrderViolation(msg)

    def before_acquire(self, lock: "_InstrumentedLock") -> bool:
        """Order/cycle check; returns True when this is a re-entrant
        acquire of the same (reentrant) instance."""
        held = self._held()
        if any(e.lock is lock for e in held):
            if lock.kind == "lock":
                self._violation(
                    f"re-entrant acquire of non-reentrant lock "
                    f"'{lock.name}': guaranteed self-deadlock"
                )
            with self._mu:
                self.stats.reentrant[lock.name] = (
                    self.stats.reentrant.get(lock.name, 0) + 1
                )
            return True
        flagged: set[str] = set()  # held-lock names already reported
        for e in held:
            h = e.lock
            if h.name == lock.name:
                flagged.add(h.name)
                self._violation(
                    f"acquiring '{lock.name}' while already holding a "
                    f"DIFFERENT lock of the same name: same-rank locks "
                    "must never nest (no order between instances)"
                )
                continue
            ra = self.rank.get(h.name)
            rb = self.rank.get(lock.name)
            if ra is not None and rb is not None and rb < ra:
                flagged.add(h.name)
                self._violation(
                    f"acquiring '{lock.name}' while holding '{h.name}' "
                    f"inverts the declared lock order "
                    f"('{lock.name}' < '{h.name}' in "
                    "oryx_tpu/concurrency.py)"
                )
        with self._mu:
            # Pairs already reported above (same-name, declared-order
            # inversion) are excluded from BOTH the cycle check and
            # the edge insert: in record mode a recorded inverted edge
            # would otherwise turn every later LEGAL nesting of the
            # same pair into a spurious "cycle" at the correct site.
            for e in held:
                if e.lock.name in flagged:
                    continue
                if self._reaches(lock.name, e.lock.name):
                    cycle = f"'{e.lock.name}' -> '{lock.name}'"
                    self.stats.violations.append(
                        f"lock-order cycle closed by acquiring "
                        f"'{lock.name}' while holding '{e.lock.name}' "
                        f"(the reverse path {cycle} was already "
                        "observed)"
                    )
                    if self.action == "raise":
                        raise LockOrderViolation(
                            self.stats.violations[-1]
                        )
            for e in held:
                if e.lock.name not in flagged \
                        and e.lock.name != lock.name:
                    self._edges.setdefault(
                        e.lock.name, set()
                    ).add(lock.name)
        return False

    def _reaches(self, a: str, b: str) -> bool:
        # Caller holds self._mu.
        seen: set[str] = set()
        stack = [a]
        while stack:
            n = stack.pop()
            if n == b:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self._edges.get(n, ()))
        return False

    def note_acquired(self, lock: "_InstrumentedLock",
                      waited_s: float) -> None:
        self._held().append(_Held(lock, time.perf_counter()))
        with self._mu:
            self.stats.acquires[lock.name] = (
                self.stats.acquires.get(lock.name, 0) + 1
            )
            self._sample("wait", lock.name, waited_s)

    def note_release(self, lock: "_InstrumentedLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is lock:
                e = held.pop(i)
                with self._mu:
                    self._sample(
                        "hold", lock.name,
                        time.perf_counter() - e.t0,
                    )
                return
        # Releasing a lock this thread never acquired through the
        # sanitizer (armed mid-flight): let the inner lock complain.

    def _sample(self, kind: str, name: str, seconds: float) -> None:
        # Caller holds self._mu. Buffered, flushed by the registry
        # collector at scrape time: observing directly from here would
        # take registry._lock inside lock bookkeeping — exactly the
        # kind of hidden nesting this sanitizer exists to forbid.
        if len(self._samples) >= self._SAMPLE_CAP:
            self._dropped_samples += 1
            return
        self._samples.append((kind, name, seconds))

    # ---- metrics ---------------------------------------------------------

    def bind_registry(self, registry) -> None:
        """Pre-register the oryx_lock_* histograms on `registry` and
        flush buffered samples into them at every scrape. Re-binding
        (chaos boots one server per scenario) moves the stream: the
        NEWEST binding owns all subsequently buffered samples, and a
        superseded registry's scrape no-ops instead of draining the
        shared buffer into the wrong server's series. Samples dropped
        at the buffer cap are surfaced as
        `oryx_lock_samples_dropped_total`, never silently."""
        from oryx_tpu.utils.metrics import LOCK_SECONDS_BUCKETS

        wait_hist = registry.histogram(
            "oryx_lock_wait_seconds", LOCK_SECONDS_BUCKETS, ("lock",),
            raw_name=True,
        )
        hold_hist = registry.histogram(
            "oryx_lock_hold_seconds", LOCK_SECONDS_BUCKETS, ("lock",),
            raw_name=True,
        )
        dropped = registry.counter(
            "oryx_lock_samples_dropped_total", raw_name=True
        )
        self._bind_gen = gen = object()

        def flush() -> None:
            if self._bind_gen is not gen:
                return  # superseded by a newer binding
            with self._mu:
                samples, self._samples = self._samples, []
                d, self._dropped_samples = self._dropped_samples, 0
            for kind, name, seconds in samples:
                hist = wait_hist if kind == "wait" else hold_hist
                hist.labels(lock=name).observe(seconds)
            if d:
                dropped.inc(d)

        self._flush = flush
        registry.register_collector(flush)

    def flush_metrics(self) -> None:
        """Flush into the current binding (no-op when never bound)."""
        flush = getattr(self, "_flush", None)
        if flush is not None:
            flush()


class _InstrumentedLock:
    """Wrapper over threading.Lock/RLock/Condition that reports to a
    LockOrderSanitizer. Same surface as the wrapped primitive (plus
    Condition's wait/notify family, which keeps the held stack honest
    across the wait's internal release/re-acquire)."""

    __slots__ = ("_san", "name", "kind", "_inner")

    def __init__(self, san: LockOrderSanitizer, name: str, kind: str):
        if kind not in ("lock", "rlock", "condition"):
            raise ValueError(f"unknown lock kind {kind!r}")
        self._san = san
        self.name = name
        self.kind = kind
        self._inner = (
            threading.Condition() if kind == "condition"
            else threading.RLock() if kind == "rlock"
            else threading.Lock()
        )

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        reentrant = self._san.before_acquire(self)
        t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok and not reentrant:
            self._san.note_acquired(self, time.perf_counter() - t0)
        elif ok and reentrant:
            self._san._held().append(_Held(self, time.perf_counter()))
        return ok

    def release(self) -> None:
        self._san.note_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner = self._inner
        if hasattr(inner, "locked"):
            return inner.locked()
        return bool(inner._is_owned())

    def held_by_current(self) -> bool:
        return any(e.lock is self for e in self._san._held())

    # ---- Condition surface ----------------------------------------------

    def _wait_around(self, fn, *args):
        # Condition.wait releases the underlying lock and re-acquires
        # it before returning — but the ENTRY STAYS on the held stack:
        # while blocked this thread executes nothing, so its stack is
        # unobservable to itself, and wait_for's PREDICATE runs with
        # the lock genuinely held (popping here made a guarded-field
        # read inside the predicate a false RaceViolation). Only the
        # hold-time metric honors the release: the segment up to the
        # wait is sampled now and the clock restarts at wake-up.
        san = self._san
        entry = next(
            (e for e in reversed(san._held()) if e.lock is self), None
        )
        if entry is not None:
            with san._mu:
                san._sample(
                    "hold", self.name,
                    time.perf_counter() - entry.t0,
                )
        try:
            return fn(*args)
        finally:
            if entry is not None:
                entry.t0 = time.perf_counter()

    def wait(self, timeout: float | None = None):
        return self._wait_around(self._inner.wait, timeout)

    def wait_for(self, predicate, timeout: float | None = None):
        return self._wait_around(self._inner.wait_for, predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


# ---------------------------------------------------------------------------
# Race detector over annotated fields
# ---------------------------------------------------------------------------

_MISSING = object()


class _FieldState:
    __slots__ = ("owner", "prior", "shared")

    def __init__(self, owner: threading.Thread):
        self.owner = owner
        self.prior: set[threading.Thread] = set()
        self.shared = False


class _RaceField:
    """Data descriptor installed over an annotated field. Shadows the
    class attribute, stores the live value in the instance __dict__
    (or delegates to the original slot descriptor) and runs the
    handoff/lockset state machine on every access."""

    __slots__ = ("det", "field", "kind", "arg", "orig", "skey")

    def __init__(self, det: "RaceDetector", field: str, kind: str,
                 arg: str, orig: Any):
        self.det = det
        self.field = field
        self.kind = kind  # "guarded-by" | "thread-owned"
        self.arg = arg    # lock attr name | owner tag
        self.orig = orig  # original slot/other descriptor, or _MISSING
        self.skey = f"__race_{field}"

    # -- state machine -----------------------------------------------------

    def _check(self, obj: Any, write: bool) -> None:
        det = self.det
        # Exemption is MODULE-global (thread-local), not per-detector:
        # descriptors can outlive the detector epoch that installed
        # them (build_server's maybe_arm_from_env arms process-wide
        # and a later re-arming skips already-instrumented fields), so
        # a per-detector flag would ignore race_exempt() taken under
        # the CURRENT detector — the pool-invariant check then raises
        # from a stale descriptor despite being declared exempt.
        if getattr(_EXEMPT, "depth", 0):
            return
        t = threading.current_thread()
        with det._mu:
            state = obj.__dict__.get(self.skey)
            if state is None:
                obj.__dict__[self.skey] = _FieldState(t)
                return
            if state.owner is t:
                if state.shared and self.kind == "guarded-by":
                    self._require_lock(obj, t)
                return
            if not state.owner.is_alive():
                # Happens-before via thread death: a fresh exclusive
                # epoch (supervisor touching a dead engine's state,
                # drain failing out a dead engine's queue).
                state.owner = t
                state.prior.clear()
                state.shared = False
                return
            state.prior = {p for p in state.prior if p.is_alive()}
            if state.shared or t in state.prior:
                # A PRIOR live accessor interleaved back: the field is
                # genuinely shared from here on.
                state.shared = True
                state.prior.add(state.owner)
                state.owner = t
                if self.kind == "thread-owned":
                    self.det._violation(
                        f"thread-owned field "
                        f"'{type(obj).__name__}.{self.field}' (owner: "
                        f"{self.arg}) touched by interleaving live "
                        f"threads ({t.name} while prior accessors are "
                        "alive) — ownership never transferred"
                    )
                else:
                    self._require_lock(obj, t)
            else:
                # Clean handoff: previous owner never came back.
                state.prior.add(state.owner)
                state.owner = t

    def _require_lock(self, obj: Any, t: threading.Thread) -> None:
        lock = getattr(obj, self.arg, None)
        if not _held_by_current(lock):
            self.det._violation(
                f"guarded field '{type(obj).__name__}.{self.field}' "
                f"accessed by {t.name} without holding its declared "
                f"lock 'self.{self.arg}' while the field is shared "
                "between live threads"
            )

    # -- descriptor protocol -----------------------------------------------

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        self._check(obj, write=False)
        if self.orig is not _MISSING and hasattr(self.orig, "__get__"):
            return self.orig.__get__(obj, objtype)
        try:
            return obj.__dict__[self.field]
        except KeyError:
            raise AttributeError(self.field) from None

    def __set__(self, obj, value) -> None:
        self._check(obj, write=True)
        if self.orig is not _MISSING and hasattr(self.orig, "__set__"):
            self.orig.__set__(obj, value)
        else:
            obj.__dict__[self.field] = value

    def __delete__(self, obj) -> None:
        self._check(obj, write=True)
        if self.orig is not _MISSING and hasattr(self.orig, "__delete__"):
            self.orig.__delete__(obj)
        else:
            del obj.__dict__[self.field]


def _held_by_current(lock: Any) -> bool:
    if lock is None:
        return False
    if isinstance(lock, _InstrumentedLock):
        return lock.held_by_current()
    if hasattr(lock, "_is_owned"):  # Condition / RLock
        try:
            return bool(lock._is_owned())
        # fault-boundary: an exotic lock type must degrade to
        # approximate checking, not break the run under test
        except Exception:
            return True
    if hasattr(lock, "locked"):
        # Plain Lock predates per-thread ownership: `locked()` is the
        # best approximation (someone holds it). Armed runs create
        # instrumented locks, so this path only covers stragglers
        # constructed before arming.
        return lock.locked()
    return True


class RaceDetector:
    """Installs _RaceField descriptors over every `# guarded-by:` /
    `# thread-owned:` annotated field of the classes in the target
    modules — the annotations are parsed from SOURCE with the same
    analysis.core machinery the static rules use."""

    def __init__(self, action: str = "raise",
                 stats_sink: LockStats | None = None):
        if action not in ("raise", "record"):
            raise ValueError(
                f"action must be 'raise' or 'record', got {action!r}"
            )
        self.action = action
        self.violations: list[str] = []
        # Mirror race findings into the paired sanitizer's stats so
        # one `lock_stats().violations` assertion covers both halves.
        self._sink = stats_sink
        self._mu = threading.Lock()
        self._installed: list[tuple[type, str, Any]] = []

    def _violation(self, msg: str) -> None:
        # Caller holds self._mu. (list.append is atomic under the GIL,
        # so the cross-object sink append needs no extra lock.)
        self.violations.append(msg)
        if self._sink is not None:
            self._sink.violations.append(msg)
        if self.action == "raise":
            raise RaceViolation(msg)

    def install_module(self, module) -> int:
        """Instrument every annotated field of `module`'s classes;
        returns the number of fields instrumented."""
        import ast as ast_mod
        import inspect

        from oryx_tpu.analysis.core import (
            ParsedModule,
            field_annotations,
        )

        try:
            source = inspect.getsource(module)
        except (OSError, TypeError):
            return 0
        mod = ParsedModule(getattr(module, "__file__", "<mem>"), source)
        count = 0
        for node in ast_mod.walk(mod.tree):
            if not isinstance(node, ast_mod.ClassDef):
                continue
            cls = getattr(module, node.name, None)
            if not isinstance(cls, type):
                continue
            for field, (kind, arg) in field_annotations(mod, node).items():
                orig = cls.__dict__.get(field, _MISSING)
                if isinstance(orig, _RaceField):
                    continue  # already instrumented
                setattr(
                    cls, field,
                    _RaceField(self, field, kind, arg, orig),
                )
                self._installed.append((cls, field, orig))
                count += 1
        return count

    def uninstall(self) -> None:
        for cls, field, orig in reversed(self._installed):
            if orig is _MISSING:
                try:
                    delattr(cls, field)
                except AttributeError:
                    pass
            else:
                setattr(cls, field, orig)
        self._installed.clear()


# ---------------------------------------------------------------------------
# Arming (module-global, same contract as utils.faults: one global
# read on the hot path when disarmed)
# ---------------------------------------------------------------------------

_SAN: LockOrderSanitizer | None = None
_RACE: RaceDetector | None = None
_ENV_VAR = "ORYX_LOCK_SANITIZER"

# Module paths whose annotated classes the race detector instruments
# when armed from the environment (the concurrency surface of serving).
_RACE_MODULES = (
    "oryx_tpu.serve.scheduler",
    "oryx_tpu.serve.prefix_cache",
    "oryx_tpu.serve.api_server",
    "oryx_tpu.utils.trace",
    "oryx_tpu.utils.metrics",
)


def named_lock(name: str, kind: str = "lock"):
    """Create the lock for a `with self.<lock>:` site. Disarmed: a
    plain threading primitive (one global read of overhead). Armed:
    an instrumented wrapper reporting to the active sanitizer. The
    name is BOTH the runtime identity (held stacks, metrics labels,
    violation messages) and the static one (oryxlint's lock-order
    rule reads it from this call's literal)."""
    san = _SAN
    if san is None:
        if kind == "condition":
            return threading.Condition()
        if kind == "rlock":
            return threading.RLock()
        return threading.Lock()
    return san.make(name, kind)


def hot_dispatch(name: str) -> None:
    """Marker call at the top of a `# hot-path` device dispatch: armed,
    it flags the dispatch running while the current thread holds any
    instrumented lock (which would serialize every other thread on
    device latency). Disarmed: one global read."""
    san = _SAN
    if san is None:
        return
    held = san.held_names()
    if held:
        san._violation(
            f"hot-path dispatch '{name}' entered while holding "
            f"{held}: a device dispatch must never run under a lock"
        )


# Thread-local race-exemption depth, shared by EVERY detector epoch's
# descriptors (see _RaceField._check: descriptors can outlive the
# detector that installed them, so the flag cannot live on a detector).
_EXEMPT = threading.local()


@contextlib.contextmanager
def race_exempt(reason: str = "") -> Iterator[None]:
    """Mark the current thread's annotated-field accesses as
    externally synchronized for the duration (e.g. the pool-invariant
    check, which callers only run quiesced). The mark applies to ANY
    installed race descriptor — including one from an earlier arming
    epoch still instrumenting a class (process-wide arming via
    $ORYX_LOCK_SANITIZER has no disarm point). No-op disarmed."""
    _EXEMPT.depth = getattr(_EXEMPT, "depth", 0) + 1
    try:
        yield
    finally:
        _EXEMPT.depth -= 1


def arm_lock_sanitizer(
    *,
    order: tuple[str, ...] | None = None,
    action: str = "raise",
    race_modules: Iterator | tuple | list | None = None,
    registry=None,
) -> LockOrderSanitizer:
    """Arm the global sanitizer (locks created through `named_lock`
    from now on are instrumented) and install the race detector over
    `race_modules` (imported module objects; default: the serving
    concurrency surface). Idempotent-ish: re-arming replaces the
    global but leaves existing instrumented locks reporting to their
    original sanitizer."""
    global _SAN, _RACE
    san = LockOrderSanitizer(order=order, action=action)
    det = RaceDetector(action=action, stats_sink=san.stats)
    if race_modules is None:
        import importlib

        race_modules = []
        for name in _RACE_MODULES:
            try:
                race_modules.append(importlib.import_module(name))
            # fault-boundary: a surface module that cannot import in
            # this environment simply is not instrumented
            except Exception:
                pass
    for module in race_modules:
        det.install_module(module)
    if registry is not None:
        san.bind_registry(registry)
    _SAN = san
    _RACE = det
    return san


def disarm_lock_sanitizer() -> None:
    global _SAN, _RACE
    if _RACE is not None:
        _RACE.uninstall()
    _SAN = None
    _RACE = None


@contextlib.contextmanager
def lock_sanitizer(
    *,
    order: tuple[str, ...] | None = None,
    action: str = "raise",
    race_modules=None,
    registry=None,
) -> Iterator[LockOrderSanitizer]:
    """Context-manager arming for tests — the recompile_watchdog
    contract: arm on entry, disarm (descriptors uninstalled, classes
    restored) on exit."""
    san = arm_lock_sanitizer(
        order=order, action=action, race_modules=race_modules,
        registry=registry,
    )
    try:
        yield san
    finally:
        disarm_lock_sanitizer()


def lock_sanitizer_armed() -> bool:
    return _SAN is not None


def lock_stats() -> LockStats | None:
    """The active sanitizer's stats (None disarmed). When armed via
    arm_lock_sanitizer/lock_sanitizer/maybe_arm_from_env, the paired
    race detector mirrors its findings into these violations too, so
    one `lock_stats().violations == []` assertion covers both halves
    (a standalone RaceDetector only mirrors if given a stats_sink)."""
    return _SAN.stats if _SAN is not None else None


def race_violations() -> list[str]:
    return list(_RACE.violations) if _RACE is not None else []


def bind_lock_metrics(registry) -> bool:
    """Attach the armed sanitizer's wait/hold histograms to `registry`
    (no-op disarmed). The API server calls this with its serving
    registry so armed runs surface oryx_lock_* on /metrics."""
    if _SAN is None:
        return False
    _SAN.bind_registry(registry)
    return True


def maybe_arm_from_env(registry=None) -> bool:
    """Arm from $ORYX_LOCK_SANITIZER unless empty/0/off/false (the
    ORYX_RECOMPILE_WATCHDOG convention). Called by tests/conftest.py,
    scripts/chaos_suite.py and the API server build — never at import
    (a library import must not mutate classes as a side effect)."""
    spec = os.environ.get(_ENV_VAR, "").strip().lower()
    if spec in ("", "0", "off", "false"):
        return False
    if _SAN is None:
        arm_lock_sanitizer(registry=registry)
    elif registry is not None:
        _SAN.bind_registry(registry)
    return True


def backend_donates() -> bool:
    """Whether this backend actually consumes donated buffers (CPU on
    some jax versions silently ignores donation) — tests gate
    `assert_consumed` on this."""
    import jax
    import jax.numpy as jnp

    probe = jax.jit(lambda x: x + 1, donate_argnums=0)
    x = jnp.zeros((8,))
    probe(x).block_until_ready()
    # The read IS the probe: asking whether donation consumed it.
    return x.is_deleted()  # oryxlint: disable=use-after-donate
