"""Forward dataflow fixpoints over cfg.py graphs.

One engine, two lattices:

  * **may** (union join): a fact holds if it holds on ANY path in —
    the shape for "this key was already consumed somewhere" and "this
    value is tainted". Missing facts are safe.
  * **must** (intersection join): a fact holds only if it holds on
    EVERY path in — the shape for "this obligation was discharged".
    Extra facts are unsafe, so unreached predecessors contribute
    nothing and the meet runs over reached predecessors only.

States are frozensets of checker-defined facts; both joins are
monotone over a finite fact universe (facts name syntax sites), so the
worklist terminates. Analyses implement one method:

    transfer(elem, state) -> state

applied to each block element in order (cfg.py guarantees elements are
simple statements / header expressions / Bind records — never whole
compound statements). After `run()`, `in_states[block.id]` holds the
join at block entry; `replay(block)` re-walks a block yielding
(elem, state_before_elem) so checkers can emit findings against the
converged solution instead of mid-iteration noise.
"""

from __future__ import annotations

from .cfg import CFG, Block, Element

State = frozenset


class ForwardAnalysis:
    """Subclass and implement `transfer`; pick the join with
    `may=True` (union) or `may=False` (intersection/must)."""

    may = True

    def initial(self) -> State:
        """State at function entry."""
        return frozenset()

    def transfer(self, elem: Element, state: State) -> State:
        raise NotImplementedError

    # -- engine ------------------------------------------------------------

    def _block_out(self, block: Block, state: State) -> State:
        for elem in block.elems:
            state = self.transfer(elem, state)
        return state

    def run(self, cfg: CFG) -> dict[int, State]:
        preds = cfg.preds()
        in_states: dict[int, State] = {}
        out_states: dict[int, State] = {}
        if cfg.entry is None:
            self.in_states = in_states
            return in_states
        in_states[cfg.entry.id] = self.initial()
        worklist = [cfg.entry]
        queued = {cfg.entry.id}
        while worklist:
            block = worklist.pop()
            queued.discard(block.id)
            if block.id not in in_states:
                # Reachable only through blocks not yet processed.
                continue
            out = self._block_out(block, in_states[block.id])
            if out_states.get(block.id) == out:
                continue
            out_states[block.id] = out
            for succ in block.succs:
                ins = [
                    out_states[p.id] for p in preds[succ.id]
                    if p.id in out_states
                ]
                if self.may:
                    joined = frozenset().union(*ins) if ins \
                        else frozenset()
                else:
                    joined = frozenset.intersection(*ins) if ins \
                        else frozenset()
                if in_states.get(succ.id) != joined:
                    in_states[succ.id] = joined
                    if succ.id not in queued:
                        worklist.append(succ)
                        queued.add(succ.id)
                elif succ.id not in out_states:
                    if succ.id not in queued:
                        worklist.append(succ)
                        queued.add(succ.id)
        self.in_states = in_states
        self.out_states = out_states
        return in_states

    def replay(self, block: Block):
        """Yield (elem, state_before_elem) under the converged
        solution — the reporting pass. Unreached blocks yield
        nothing."""
        state = self.in_states.get(block.id)
        if state is None:
            return
        for elem in block.elems:
            yield elem, state
            state = self.transfer(elem, state)

    def exit_state(self, block: Block) -> State | None:
        """Out-state of `block` (where an Exit's facts are read);
        None if the block was never reached."""
        state = self.in_states.get(block.id)
        if state is None:
            return None
        return self._block_out(block, state)


class GenKill(ForwardAnalysis):
    """Convenience for per-element gen/kill analyses: implement
    `gen(elem, state)` and `kill(elem, state)` returning iterables of
    facts; transfer is (state - kill) | gen, with gen computed against
    the PRE-kill state so a fact can observe what it replaces."""

    def gen(self, elem: Element, state: State):
        return ()

    def kill(self, elem: Element, state: State):
        return ()

    def transfer(self, elem: Element, state: State) -> State:
        gen = frozenset(self.gen(elem, state))
        kill = frozenset(self.kill(elem, state))
        return (state - kill) | gen
