"""oryxlint runner: file discovery + CLI (the body of
`scripts/run_oryxlint.py`).

Kept inside the package so tests drive `main()` in-process; kept free
of jax (and of the rest of oryx_tpu) so the script can stub the parent
package and lint the tree in well under a second.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import Iterable

from oryx_tpu.analysis.core import (
    Checker,
    render_json,
    render_text,
    run_lint,
)
from oryx_tpu.analysis.determinism import ReplayTaintChecker
from oryx_tpu.analysis.donation import UseAfterDonateChecker
from oryx_tpu.analysis.hostsync import HostSyncChecker
from oryx_tpu.analysis.keylin import KeyLinearityChecker
from oryx_tpu.analysis.lockorder import AtomicityChecker, LockOrderChecker
from oryx_tpu.analysis.locks import LockDisciplineChecker
from oryx_tpu.analysis.metric_names import MetricNameChecker
from oryx_tpu.analysis.obligations import ObligationChecker
from oryx_tpu.analysis.recompile import RecompileHazardChecker
from oryx_tpu.analysis.swallow import SwallowedExceptionChecker

ALL_CHECKERS: tuple[type[Checker], ...] = (
    LockDisciplineChecker,
    LockOrderChecker,
    AtomicityChecker,
    UseAfterDonateChecker,
    HostSyncChecker,
    RecompileHazardChecker,
    MetricNameChecker,
    SwallowedExceptionChecker,
    KeyLinearityChecker,
    ObligationChecker,
    ReplayTaintChecker,
)

# Seam for the --time-budget gate's unit test: tests monkeypatch this
# to a fake clock; production is the monotonic wall clock.
_monotonic = time.monotonic

# Fixture prefix -> the rule module whose behavior it pins. A change to
# EITHER invalidates the `--changed-only` fast path: a rule edit can
# introduce findings in files that did not change, and a fixture edit
# means the rule's contract moved — both must lint (and be tested
# against) the whole tree.
FIXTURE_RULE_MODULES: dict[str, str] = {
    "lock": "locks.py",
    "lockorder": "lockorder.py",
    "atomicity": "lockorder.py",
    "donate": "donation.py",
    "hostsync": "hostsync.py",
    "recompile": "recompile.py",
    "metric": "metric_names.py",
    "swallow": "swallow.py",
    "keylin": "keylin.py",
    "obligation": "obligations.py",
    "taint": "determinism.py",
}

# Directories that are not our python (vendored assets, fixtures that
# are DELIBERATELY dirty, caches, CI-dropped snapshots of older trees
# — linting a frozen copy double-counts every suppression against the
# ratchet).
_EXCLUDE_DIRS = {
    ".git", "__pycache__", ".claude", "native", "assets",
    "lint_fixtures", ".seedcheck",
}


def default_files(root: str) -> list[str]:
    out: list[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d not in _EXCLUDE_DIRS
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


def changed_files(root: str) -> list[str] | None:
    """Working-tree python files touched vs HEAD (plus untracked) —
    the `--changed-only` fast path for local pre-commit runs.

    Returns None ("check everything") when the change set invalidates
    per-file checking: an edit to the linter itself
    (oryx_tpu/analysis/*) or to a lint fixture (which pins a rule
    module's contract, per FIXTURE_RULE_MODULES) can change findings
    in files that did not change, so the fast path must widen to the
    full tree instead of silently passing."""
    files: set[str] = set()
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            res = subprocess.run(
                cmd, cwd=root, capture_output=True, text=True,
                timeout=30, check=True,
            )
        except (OSError, subprocess.SubprocessError):
            return None  # no git: fall back to full
        files.update(
            line.strip() for line in res.stdout.splitlines()
            if line.strip().endswith(".py")
        )
    rule_modules = set()
    for f in files:
        norm = f.replace(os.sep, "/")
        base = os.path.basename(norm)
        if "oryx_tpu/analysis/" in norm or norm.endswith(
            "scripts/run_oryxlint.py"
        ):
            return None
        if "lint_fixtures/" in norm:
            prefix = base.removesuffix(".py")
            for suffix in ("_pos", "_suppressed", "_clean"):
                prefix = prefix.removesuffix(suffix)
            rule_modules.add(
                FIXTURE_RULE_MODULES.get(prefix, base)
            )
    if rule_modules:
        # A fixture changed -> its rule module's contract changed ->
        # same blast radius as editing the rule module itself.
        return None
    allowed = set(default_files(root))
    return sorted(
        p
        for f in files
        if (p := os.path.join(root, f)) in allowed and os.path.exists(p)
    )


def _sources(paths: Iterable[str]):
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                yield path, f.read()
        except OSError as e:
            print(f"oryxlint: cannot read {path}: {e}", file=sys.stderr)


def make_checkers(rules: str | None = None) -> list[Checker]:
    selected = (
        {r.strip() for r in rules.split(",") if r.strip()}
        if rules
        else None
    )
    out = []
    for cls in ALL_CHECKERS:
        if selected is None or cls.name in selected:
            out.append(cls())
    if selected:
        known = {c.name for c in out}
        unknown = selected - known
        if unknown:
            raise SystemExit(
                f"oryxlint: unknown rule(s) {sorted(unknown)}; "
                f"known: {sorted(c.name for c in ALL_CHECKERS)}"
            )
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="run_oryxlint.py",
        description=(
            "oryxlint: JAX-aware static analysis (lock-discipline, "
            "lock-order, atomicity, use-after-donate, host-sync, "
            "recompile-hazard, metric-name, swallowed-exception, "
            "key-linearity, terminal-path, replay-taint). "
            "Exits 1 on any finding; --strict (the CI gate) "
            "additionally fails on files that don't parse; "
            "--max-suppressions N fails when justified suppressions "
            "exceed the recorded ratchet."
        ),
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/dirs to lint (default: the whole repo)",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root (default: two levels above this package)",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="CI gate mode: also exit 1 when a file fails to parse "
        "(findings exit 1 in every mode)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable JSON report on stdout",
    )
    parser.add_argument(
        "--changed-only", action="store_true",
        help="lint only files changed vs HEAD (+ untracked) — the "
        "fast local loop",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule subset (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print rule ids and exit",
    )
    parser.add_argument(
        "--max-suppressions", type=int, default=None, metavar="N",
        help="fail (exit 1) when more than N findings are suppressed "
        "via `# oryxlint: disable=` — the CI ratchet that keeps "
        "justified escapes from silently accumulating",
    )
    parser.add_argument(
        "--max-suppressions-per-rule", action="append", default=[],
        metavar="RULE=N", dest="per_rule_caps",
        help="fail when rule RULE has more than N suppressions "
        "(repeatable) — pins NEW rules at 0 escapes independently "
        "of the global --max-suppressions ratchet",
    )
    parser.add_argument(
        "--time-budget", type=float, default=None, metavar="SECONDS",
        help="fail when the lint run (parse + scan + check over the "
        "selected tree) exceeds this wall time — the CI gate that "
        "keeps the dataflow fixpoint passes from creeping",
    )
    parser.add_argument(
        "--json-out", default=None, metavar="PATH",
        help="also write the JSON report to PATH (the CI artifact; "
        "stdout keeps whichever format --json selects)",
    )
    args = parser.parse_args(argv)

    per_rule_caps: dict[str, int] = {}
    known_rules = {cls.name for cls in ALL_CHECKERS}
    for spec in args.per_rule_caps:
        rule, sep, cap = spec.partition("=")
        if not sep or not cap.strip().isdigit() \
                or rule.strip() not in known_rules:
            raise SystemExit(
                f"oryxlint: bad --max-suppressions-per-rule {spec!r} "
                f"(want RULE=N with RULE in {sorted(known_rules)})"
            )
        per_rule_caps[rule.strip()] = int(cap.strip())

    if args.list_rules:
        for cls in ALL_CHECKERS:
            doc = (sys.modules[cls.__module__].__doc__ or "").strip()
            first = doc.splitlines()[0] if doc else ""
            print(f"{cls.name}: {first}")
        return 0

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    check_only = None
    if args.paths:
        files = []
        for p in args.paths:
            if os.path.isdir(p):
                files.extend(default_files(p))
            else:
                files.append(p)
    elif args.changed_only:
        # Findings only for changed files, but the scan pass must see
        # the WHOLE tree: the donation registry and metric kind map are
        # cross-module, and a changed caller of an unchanged donating
        # callee must still lint correctly. changed_files returns None
        # when the linter or a fixture changed — then the fast path
        # widens to a full check.
        files = default_files(root)
        changed = changed_files(root)
        check_only = None if changed is None else set(changed)
    else:
        files = default_files(root)

    t0 = _monotonic()
    result = run_lint(
        _sources(files), make_checkers(args.rules), check_only=check_only
    )
    elapsed = _monotonic() - t0
    print(render_json(result) if args.as_json else render_text(result))
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            f.write(render_json(result) + "\n")
    rc = 0
    if result.findings:
        rc = 1
    if args.strict and result.errors:
        rc = 1
    if (
        args.max_suppressions is not None
        and result.suppressed > args.max_suppressions
    ):
        print(
            f"oryxlint: {result.suppressed} suppressions exceed the "
            f"--max-suppressions ratchet ({args.max_suppressions}); "
            "either fix the new site or consciously bump the ratchet "
            "in scripts/check_tier1.sh with a justification",
            file=sys.stderr,
        )
        rc = 1
    for rule, cap in sorted(per_rule_caps.items()):
        seen = result.suppressed_by_rule.get(rule, 0)
        if seen > cap:
            print(
                f"oryxlint: rule {rule} has {seen} suppression(s), "
                f"over its per-rule ratchet ({cap}); fix the site or "
                "consciously bump the pin in scripts/check_tier1.sh",
                file=sys.stderr,
            )
            rc = 1
    if args.time_budget is not None and elapsed > args.time_budget:
        print(
            f"oryxlint: run took {elapsed:.2f}s, over the "
            f"--time-budget gate ({args.time_budget:.2f}s); a "
            "fixpoint pass is creeping — profile the new rule before "
            "raising the budget",
            file=sys.stderr,
        )
        rc = 1
    return rc
