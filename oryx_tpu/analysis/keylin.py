"""key-linearity: JAX PRNG keys are linear — consume once, then re-bind.

Every byte-parity guarantee the engine sells (greedy parity, spec
rollback, fused replay) assumes PRNG keys are used linearly: a key is
split or sampled from exactly once, and fresh subkeys are re-bound
before the next consume. Reusing a consumed key is the classic silent
correctness bug — outputs correlate across sites that must be
independent, and nothing crashes.

The rule runs a may-dataflow over the function CFG (cfg.py):

  * a parameter with a key-ish name ({key, keys, rng, ...} or
    `*_key`/`*_keys`), or a local assigned from a producer
    (`jax.random.split`/`fold_in`/`PRNGKey`/..., including
    `jax.vmap(lambda k: jax.random.split(k, n))(keys)`), is tracked;
  * a *consume* is a tracked name passed BARE to a registered consumer:
    `jax.random` derive ops (split/fold_in — they retire the operand)
    and draw ops (uniform/categorical/...), the vmap-wrapped forms, and
    repo functions discovered by the scan pass (a function whose key-ish
    parameter it consumes — found transitively, the lockorder.py
    call-summary idiom — consumes its caller's key: `sample_token_rows`,
    `spec_verify_rows`, ...). Subscripts/slices (`ks[i]`, `pair[:, 1]`)
    are non-consuming projections of already-derived material;
  * assignment to a name KILLS its facts (the `key, sk =
    jax.random.split(key)` re-bind idiom), and `a = key` moves rather
    than copies;
  * two consumes reaching the same point (sequentially or on both arms
    of a join that later merges) is a finding — EXCEPT the lane-split
    contract generate.py is built on: two derives of the same op and
    width whose results are consumed through disjoint constant lanes
    (`split(k, 2)` used via `[:, 1]` here and `[:, 0]` there) partition
    the key material and are legal. Draws never partition.

Nested `def`s and lambdas are separate scopes (closure reuse inside a
`lax.scan` body is that scope's contract, analyzed separately), so the
fused-scan key chain validates instead of needing suppression.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

from .cfg import Bind, build_cfg
from .core import Checker, Finding, ParsedModule, RepoContext, dotted_name
from .dataflow import ForwardAnalysis

# jax.random ops that retire their key operand and hand back fresh key
# material (derives) vs ops that draw samples (draws). Matched as
# `<prefix>.<op>` where the prefix's last component is `random` (so
# `jax.random.split` and `jrandom.split` match; the stdlib `random`
# module has no `split`/`fold_in` and its draw names are claimed by
# replay-taint, not this rule).
DERIVE_OPS = {"split", "fold_in", "clone"}
DRAW_OPS = {
    "uniform", "normal", "bernoulli", "categorical", "gumbel", "bits",
    "randint", "truncated_normal", "exponential", "beta", "gamma",
    "poisson", "choice", "permutation", "ball", "cauchy", "dirichlet",
    "laplace", "logistic", "loggamma", "maxwell", "multivariate_normal",
    "orthogonal", "rademacher", "rayleigh", "t", "weibull_min",
}
# Ops that CREATE keys from seeds (producers that consume nothing).
CREATE_OPS = {"key", "PRNGKey", "wrap_key_data"}

KEYISH_NAMES = {"key", "keys", "rng", "prng_key", "rng_key", "subkey",
                "subkeys"}


def is_keyish(name: str) -> bool:
    return (
        name in KEYISH_NAMES
        or name.endswith("_key")
        or name.endswith("_keys")
    )


def _random_op(call: ast.Call) -> str | None:
    """`jax.random.split(...)` → "split"; None for anything else."""
    dn = dotted_name(call.func)
    if not dn or "." not in dn:
        return None
    prefix, op = dn.rsplit(".", 1)
    if prefix.split(".")[-1] != "random" or prefix == "random":
        return None
    return op


def _const_int(node: ast.AST | None) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    return None


@dataclasses.dataclass(frozen=True)
class ConsumeSite:
    """One consume of a key operand: `kind` is "derive" | "draw" |
    "call"; `width` the constant split width (derives only); `lanes`
    the constant final-axis lanes the result is consumed through
    (frozenset, or None = unknown/whole)."""

    line: int
    col: int
    kind: str
    op: str
    width: int | None
    lanes: frozenset | None

    def compatible(self, other: "ConsumeSite") -> bool:
        """May these two consumes of the SAME key coexist? Only the
        lane-split contract qualifies: same derive op, same known
        width, disjoint known lanes."""
        if self.kind != "derive" or other.kind != "derive":
            return False
        if self.op != other.op or self.width is None \
                or self.width != other.width:
            return False
        if self.lanes is None or other.lanes is None:
            return False
        return not (self.lanes & other.lanes)


class _SkipNested(ast.NodeVisitor):
    """Collect Call nodes in evaluation order, not descending into
    nested function/lambda bodies (separate scopes) or into a
    comprehension's element parts beyond their iterables."""

    def __init__(self):
        self.calls: list[ast.Call] = []

    def visit_Call(self, node: ast.Call) -> None:
        self.calls.append(node)
        self.generic_visit(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return  # separate scope

    def visit_FunctionDef(self, node) -> None:
        return

    def visit_AsyncFunctionDef(self, node) -> None:
        return


def _calls_in(node: ast.AST) -> list[ast.Call]:
    v = _SkipNested()
    v.visit(node)
    return v.calls


def _is_vmap(call: ast.Call) -> bool:
    dn = dotted_name(call.func)
    return dn is not None and dn.split(".")[-1] == "vmap"


class _Classifier:
    """Maps a Call to the key operands it consumes. `repo_consumers`
    is the scan pass's registry: simple fn name -> set of (position,
    param name) key parameters."""

    def __init__(self, repo_consumers: dict[str, set] | None = None):
        self.repo_consumers = repo_consumers or {}

    def consumed_operands(
        self, call: ast.Call
    ) -> list[tuple[ast.expr, str, str, int | None]]:
        """[(operand expr, kind, op, width)] — operands may be any
        expression; the caller filters for bare tracked Names."""
        op = _random_op(call)
        if op is not None:
            if op in DERIVE_OPS:
                operand = self._key_arg(call)
                if operand is not None:
                    width = _const_int(
                        call.args[1] if len(call.args) > 1 else
                        self._kwarg(call, "num")
                    )
                    return [(operand, "derive", op, width)]
                return []
            if op in DRAW_OPS:
                operand = self._key_arg(call)
                if operand is not None:
                    return [(operand, "draw", op, None)]
                return []
            return []
        # jax.vmap(lambda k: <consume of k>)(keys): the outer call
        # consumes `keys` with the lambda body's kind/op/width.
        if isinstance(call.func, ast.Call) and _is_vmap(call.func) \
                and call.func.args:
            mapped = call.func.args[0]
            if isinstance(mapped, ast.Lambda):
                params = [a.arg for a in mapped.args.args]
                out = []
                for inner in _calls_in_lambda(mapped.body):
                    for operand, kind, iop, width in \
                            self.consumed_operands(inner):
                        if isinstance(operand, ast.Name) \
                                and operand.id in params:
                            idx = params.index(operand.id)
                            if idx < len(call.args):
                                out.append(
                                    (call.args[idx], kind, iop, width)
                                )
                return out
            name = dotted_name(mapped)
            if name:
                return self._repo_call(call, name.split(".")[-1])
        dn = dotted_name(call.func)
        if dn:
            return self._repo_call(call, dn.split(".")[-1])
        return []

    def _repo_call(self, call: ast.Call, fname: str):
        out = []
        for pos, pname in self.repo_consumers.get(fname, ()):
            operand = None
            if pos is not None and pos < len(call.args):
                operand = call.args[pos]
            else:
                operand = self._kwarg(call, pname)
            if operand is not None:
                out.append((operand, "call", fname, None))
        return out

    @staticmethod
    def _key_arg(call: ast.Call) -> ast.expr | None:
        if call.args:
            return call.args[0]
        for kw in call.keywords:
            if kw.arg in ("key", "keys"):
                return kw.value
        return None

    @staticmethod
    def _kwarg(call: ast.Call, name: str) -> ast.expr | None:
        for kw in call.keywords:
            if kw.arg == name:
                return kw.value
        return None

    def produces_keys(self, expr: ast.expr) -> bool:
        """Does evaluating `expr` yield fresh key material?"""
        while isinstance(expr, ast.Subscript):
            expr = expr.value  # projections of key material are keys
        if not isinstance(expr, ast.Call):
            return False
        op = _random_op(expr)
        if op is not None:
            return op in DERIVE_OPS or op in CREATE_OPS
        if isinstance(expr.func, ast.Call) and _is_vmap(expr.func) \
                and expr.func.args:
            mapped = expr.func.args[0]
            if isinstance(mapped, ast.Lambda):
                return any(
                    (_random_op(c) or "") in (DERIVE_OPS | CREATE_OPS)
                    for c in _calls_in_lambda(mapped.body)
                )
        return False


def _calls_in_lambda(body: ast.expr) -> list[ast.Call]:
    # The one place we DO look inside a lambda: classifying the
    # vmap-mapped body itself.
    return [n for n in ast.walk(body) if isinstance(n, ast.Call)]


def _lanes_for_site(
    call: ast.Call, mod: ParsedModule,
    subscript_index: dict[str, object],
) -> frozenset | None:
    """Which constant final-axis lanes is this derive's result consumed
    through? `vmap(split)(k)[:, 1]` → {1}; `pair = ...` where `pair`
    only ever appears as `pair[:, c]` → the set of cs; anything used
    whole → None."""
    parent = mod.parent(call)
    if isinstance(parent, ast.Subscript) and parent.value is call:
        lane = _final_lane(parent)
        return frozenset((lane,)) if lane is not None else None
    if isinstance(parent, (ast.Assign, ast.AnnAssign)):
        targets = parent.targets if isinstance(parent, ast.Assign) \
            else [parent.target]
        if len(targets) == 1 and isinstance(targets[0], ast.Name):
            lanes = subscript_index.get(targets[0].id)
            if isinstance(lanes, frozenset):
                return lanes
    return None


def _final_lane(sub: ast.Subscript) -> int | None:
    idx = sub.slice
    if isinstance(idx, ast.Tuple) and idx.elts:
        idx = idx.elts[-1]
    return _const_int(idx)


def _subscript_index(mod: ParsedModule, fn: ast.AST) -> dict[str, object]:
    """name -> frozenset of constant final lanes, for names ONLY ever
    read through constant-lane subscripts; any whole/non-constant use
    maps the name to None."""
    lanes: dict[str, set] = {}
    poisoned: set[str] = set()
    sub_values: set[int] = set()
    nodes = mod.walk(fn)
    for node in nodes:
        if isinstance(node, ast.Subscript) and isinstance(
            node.value, ast.Name
        ):
            sub_values.add(id(node.value))
            lane = _final_lane(node)
            if lane is None:
                poisoned.add(node.value.id)
            else:
                lanes.setdefault(node.value.id, set()).add(lane)
    for node in nodes:
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, ast.Load
        ) and id(node) not in sub_values:
            poisoned.add(node.id)
    out: dict[str, object] = {}
    for name, ls in lanes.items():
        out[name] = None if name in poisoned else frozenset(ls)
    return out


@dataclasses.dataclass(frozen=True)
class _Fn:
    """Scan-pass summary: one function definition."""

    name: str
    params: tuple
    node: ast.AST
    mod: ParsedModule


class _KeyFlow(ForwardAnalysis):
    """Facts: ("key", var) — var holds live key material;
    ("used", var, ConsumeSite) — var was consumed at that site on some
    path. May-analysis (union join)."""

    may = True

    def __init__(self, mod: ParsedModule, fn, classifier: _Classifier):
        self.mod = mod
        self.fn = fn
        self.classifier = classifier
        self.sub_index = _subscript_index(mod, fn)
        # (line, col, var) -> conflicting prior site — filled during
        # transfer; the reporting pass reads it after convergence.
        self.conflicts: dict[tuple, ConsumeSite] = {}

    def initial(self):
        args = self.fn.args
        params = [
            a.arg for a in
            args.posonlyargs + args.args + args.kwonlyargs
        ]
        return frozenset(
            ("key", p) for p in params if is_keyish(p)
        )

    # -- helpers -----------------------------------------------------------

    def _consume(self, state, call: ast.Call):
        for operand, kind, op, width in \
                self.classifier.consumed_operands(call):
            if not isinstance(operand, ast.Name):
                continue  # projections / expressions: not a bare key
            var = operand.id
            if ("key", var) not in state:
                continue
            site = ConsumeSite(
                call.lineno, call.col_offset, kind, op, width,
                _lanes_for_site(call, self.mod, self.sub_index),
            )
            for fact in state:
                if fact[0] == "used" and fact[1] == var:
                    prior = fact[2]
                    if not prior.compatible(site):
                        key = (site.line, site.col, var)
                        old = self.conflicts.get(key)
                        if old is None or prior.line < old.line:
                            self.conflicts[key] = prior
            state = state | {("used", var, site)}
        return state

    def _kill(self, state, var: str):
        return frozenset(
            f for f in state
            if not (f[0] in ("key", "used") and f[1] == var)
        )

    def _target_names(self, target: ast.expr) -> list[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out = []
            for elt in target.elts:
                if isinstance(elt, ast.Starred):
                    elt = elt.value
                out.extend(self._target_names(elt))
            return out
        return []

    def _bind(self, state, targets: list[ast.expr],
              value: ast.expr | None):
        names = [n for t in targets for n in self._target_names(t)]
        produces = value is not None and (
            self.classifier.produces_keys(value)
        )
        moved = (
            value.id if isinstance(value, ast.Name)
            and ("key", value.id) in state else None
        )
        for n in names:
            state = self._kill(state, n)
        if produces or moved:
            for n in names:
                state = state | {("key", n)}
        if moved is not None and moved not in names:
            state = self._kill(state, moved)  # linear move, not copy
        return state

    # -- transfer ----------------------------------------------------------

    def transfer(self, elem, state):
        if isinstance(elem, Bind):
            # A for-loop's iterable was already consumed when its
            # header element ran (once, before the first iteration);
            # the per-iteration Bind must not re-consume it.
            if elem.value is not None and elem.kind != "for":
                for call in _calls_in(elem.value):
                    state = self._consume(state, call)
            if elem.kind == "for" and elem.target is not None:
                state = self._bind(state, [elem.target], elem.value)
            elif elem.target is not None:
                state = self._bind(state, [elem.target], None)
            return state
        for call in _calls_in(elem):
            state = self._consume(state, call)
        if isinstance(elem, ast.Assign):
            return self._bind(state, elem.targets, elem.value)
        if isinstance(elem, ast.AnnAssign) and elem.value is not None:
            return self._bind(state, [elem.target], elem.value)
        if isinstance(elem, ast.AugAssign):
            for n in self._target_names(elem.target):
                state = self._kill(state, n)
        return state


class KeyLinearityChecker(Checker):
    name = "key-linearity"

    def __init__(self) -> None:
        self._fns: list[_Fn] = []
        self._consumers: dict[str, set] | None = None

    # -- scan: build the repo consumer registry (transitively) -------------

    def scan(self, mod: ParsedModule, ctx: RepoContext) -> None:
        for node in mod.nodes_of(
            ast.FunctionDef, ast.AsyncFunctionDef
        ):
            args = node.args
            params = tuple(
                a.arg for a in
                args.posonlyargs + args.args + args.kwonlyargs
            )
            self._fns.append(_Fn(node.name, params, node, mod))

    def _registry(self) -> dict[str, set]:
        """Fixpoint over function summaries: f consumes its key-ish
        param p if ANY call in f's body (nested scopes included — a
        closure consuming the param still consumes it from the
        caller's view) passes bare `p` to a known consumer. Seeded by
        the jax.random registry, grown until stable (the lockorder
        may-acquire idiom)."""
        if self._consumers is not None:
            return self._consumers
        consumers: dict[str, set] = {}
        # Candidate call lists are re-read every fixpoint round —
        # compute them once up front.
        cands = []
        for fn in self._fns:
            keyish = {
                p: i for i, p in enumerate(fn.params)
                if is_keyish(p)
            }
            if not keyish:
                continue
            calls = [
                n for n in fn.mod.walk(fn.node)
                if isinstance(n, ast.Call)
            ]
            cands.append((fn, keyish, calls))
        changed = True
        while changed:
            changed = False
            clf = _Classifier(consumers)
            for fn, keyish, calls in cands:
                have = consumers.get(fn.name, set())
                for call in calls:
                    for operand, _k, _o, _w in \
                            clf.consumed_operands(call):
                        if isinstance(operand, ast.Name) \
                                and operand.id in keyish:
                            entry = (
                                keyish[operand.id], operand.id
                            )
                            if entry not in have:
                                have = have | {entry}
                                changed = True
                if have:
                    consumers[fn.name] = have
        self._consumers = consumers
        return consumers

    # -- check -------------------------------------------------------------

    def check(
        self, mod: ParsedModule, ctx: RepoContext
    ) -> Iterator[Finding]:
        registry = self._registry()
        classifier = _Classifier(registry)
        for node in mod.nodes_of(
            ast.FunctionDef, ast.AsyncFunctionDef
        ):
            if not self._may_consume(mod, node, registry):
                continue
            yield from self._check_fn(mod, node, classifier)

    @staticmethod
    def _may_consume(mod, fn, registry) -> bool:
        """Cheap superset test: the dataflow can only ever report a
        function that CONTAINS a consume site (a jax.random derive/draw
        or a call reaching a registry consumer, dotted or bare)."""
        for n in mod.walk(fn):
            if isinstance(n, ast.Call):
                op = _random_op(n)
                if op is not None and (
                    op in DERIVE_OPS or op in DRAW_OPS
                ):
                    return True
            elif isinstance(n, ast.Name):
                if n.id in registry:
                    return True
            elif isinstance(n, ast.Attribute):
                if n.attr in registry:
                    return True
        return False

    def _check_fn(self, mod, fn, classifier):
        flow = _KeyFlow(mod, fn, classifier)
        cfg = build_cfg(fn.body, anchor=fn)
        flow.run(cfg)
        # Reporting pass: replay each reachable block under the
        # converged states so conflicts carry final path facts.
        flow.conflicts.clear()
        for block in cfg.blocks:
            for _ in flow.replay(block):
                pass
        reported: set = set()
        for (line, col, var), prior in sorted(flow.conflicts.items()):
            if (line, col, var) in reported:
                continue
            reported.add((line, col, var))
            anchor = ast.Name(id=var)
            anchor.lineno, anchor.col_offset = line, col
            yield self.finding(
                mod, anchor,
                f"PRNG key `{var}` is consumed again here but was "
                f"already consumed at line {prior.line} "
                f"({prior.kind} {prior.op}): keys are linear — "
                "re-bind first (`key, sk = jax.random.split(key)`) "
                "or consume disjoint constant lanes of one equal-"
                "width split",
            )
