"""terminal-path: every exit of an annotated scope discharges its
declared obligations.

The scheduler's bug history (queue-depth gauge leaked on the
containment path, a rejected request's cost ledger never finalized,
SLO gauge re-arm starved by an early `continue` — all hand-found in
PRs 5-7) is one shape: a terminal path that forgets a resource. This
rule makes the contract declarative:

    # obligations: _finalize_cost, _emit_request_event
    def _finish(self, s, slot, h): ...

Every exit — `return`s, `raise`s, exits out of `except` handlers,
falling off the end — must *discharge* each named obligation. A loop
may be annotated too (`# obligations:` on/above a `for`/`while`
header): then every path to the next iteration — early `continue` and
normal fall-through — must discharge per iteration (the gauge re-arm
shape). `break`/`return` paths leave the loop's domain and are the
function-level annotation's business.

Discharge grammar (per path, any one of):
  * a call whose final name component equals the obligation token
    (`self._finalize_cost(...)` discharges `_finalize_cost`);
  * a call whose first positional argument is the token as a string
    literal (`self.metrics.set_gauge("queue_depth", n)` discharges
    `queue_depth` — how gauge re-arms are named);
  * an explicit `# discharges: <token>` comment on a statement line
    (for indirect discharges the checker cannot see).

Verification is a must-dataflow (intersection join) over the cfg.py
graph: a fact survives a join only if EVERY path in established it,
and an `except` handler's entry state is the try-entry state (any
statement in the body may raise before discharging). `finally` bodies
are inlined on every leaving edge, so a discharge there proves all
paths.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from .cfg import Bind, Exit, build_cfg, function_cfg, loop_cfg
from .core import Checker, Finding, ParsedModule, RepoContext
from .dataflow import ForwardAnalysis

_OBLIGATIONS_RE = re.compile(r"#\s*obligations:\s*([\w\., ]+)")
_DISCHARGES_RE = re.compile(r"#\s*discharges:\s*([\w\., ]+)")

# Exit kinds verified per annotation domain.
_FN_EXIT_KINDS = {"return", "raise", "implicit"}
_LOOP_EXIT_KINDS = {"continue", "fallthrough"}


def _tokens(spec: str) -> list[str]:
    return [t.strip() for t in spec.split(",") if t.strip()]


def declared_obligations(
    mod: ParsedModule, node: ast.stmt
) -> list[str]:
    """Tokens from `# obligations:` on the header line or on the
    contiguous comment block immediately above it (above decorators
    for a def). Real comments only (comment_text), so quoting the
    syntax in a docstring is inert."""
    first = min(
        [node.lineno]
        + [d.lineno for d in getattr(node, "decorator_list", [])]
    )
    m = _OBLIGATIONS_RE.search(mod.comment_text(node.lineno))
    if m:
        return _tokens(m.group(1))
    line = first - 1
    while line >= 1:
        text = mod.comment_text(line)
        if not text:
            break
        m = _OBLIGATIONS_RE.search(text)
        if m:
            return _tokens(m.group(1))
        line -= 1
    return []


def _call_discharges(call: ast.Call) -> set[str]:
    out: set[str] = set()
    func = call.func
    name = None
    if isinstance(func, ast.Attribute):
        name = func.attr
    elif isinstance(func, ast.Name):
        name = func.id
    if name:
        out.add(name)
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        out.add(call.args[0].value)
    return out


class _SkipNestedCalls(ast.NodeVisitor):
    def __init__(self):
        self.names: set[str] = set()

    def visit_Call(self, node: ast.Call) -> None:
        self.names |= _call_discharges(node)
        self.generic_visit(node)

    def visit_Lambda(self, node) -> None:
        return

    def visit_FunctionDef(self, node) -> None:
        return

    def visit_AsyncFunctionDef(self, node) -> None:
        return


class _Obligations(ForwardAnalysis):
    """Facts: ("done", token). Must-analysis — a discharge counts only
    when every path in performed it."""

    may = False

    def __init__(self, mod: ParsedModule, declared: list[str]):
        self.mod = mod
        self.declared = declared

    def transfer(self, elem, state):
        node = elem.node if isinstance(elem, Bind) else elem
        walk_root = elem.value if isinstance(elem, Bind) else elem
        names: set[str] = set()
        if walk_root is not None:
            v = _SkipNestedCalls()
            v.visit(walk_root)
            names = v.names
        line = getattr(node, "lineno", None)
        if line is not None:
            m = _DISCHARGES_RE.search(self.mod.comment_text(line))
            if m:
                names |= set(_tokens(m.group(1)))
        done = {
            ("done", t) for t in self.declared if t in names
        }
        return state | done if done else state


class ObligationChecker(Checker):
    name = "terminal-path"

    def check(
        self, mod: ParsedModule, ctx: RepoContext
    ) -> Iterator[Finding]:
        for node in mod.nodes_of(
            ast.FunctionDef, ast.AsyncFunctionDef, ast.For, ast.While
        ):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                declared = declared_obligations(mod, node)
                if declared:
                    yield from self._verify(
                        mod, node.name, declared,
                        function_cfg(node), _FN_EXIT_KINDS,
                        "terminal path",
                    )
            else:
                declared = declared_obligations(mod, node)
                if declared:
                    yield from self._verify(
                        mod, f"loop at line {node.lineno}", declared,
                        loop_cfg(node), _LOOP_EXIT_KINDS,
                        "iteration path",
                    )

    def _verify(self, mod, scope, declared, cfg, exit_kinds, what):
        flow = _Obligations(mod, declared)
        flow.run(cfg)
        seen: set[tuple] = set()
        for ex in cfg.exits:
            if ex.kind not in exit_kinds:
                continue
            state = flow.exit_state(ex.block)
            if state is None:
                continue  # unreachable exit
            missing = [
                t for t in declared if ("done", t) not in state
            ]
            if not missing:
                continue
            key = (getattr(ex.node, "lineno", 0), tuple(missing))
            if key in seen:
                continue
            seen.add(key)
            yield self.finding(
                mod, ex.node,
                f"{what} ({ex.kind}) out of `{scope}` leaves "
                f"obligation(s) {', '.join(missing)} undischarged: "
                "every exit must call each declared obligation (or "
                "carry `# discharges: <token>` where the call is "
                "indirect)",
            )
