"""host-sync: no implicit device→host syncs inside `# hot-path` code.

A `.item()`, `float(arr)`, `np.asarray(arr)` or `jax.device_get(...)`
inside the decode loop blocks the host on the device queue and
serializes dispatch — the classic "TPU at 40% because the scheduler
reads one scalar per token" regression. Tests never see it (CPU,
tiny shapes); production sees it as a throughput cliff.

Functions that ARE the hot path — the scheduler's decode-chunk loop,
the paged/streaming generate loops, the trainer step loop — carry a
`# hot-path` marker on (or immediately above) their `def` line. Inside
them, every flagged call must either go away or carry a per-line
`# oryxlint: disable=host-sync` (or an off/on region for a deliberate
harvest block) with a justification — which is exactly the review
conversation the rule exists to force.

Flagged forms:
  * `<expr>.item()`
  * `float(x)` where x is a name/attribute/subscript (a cast of an
    array-like; `float("1e-3")` and `float(fn())` are not flagged)
  * `np.asarray(...)` / `numpy.asarray(...)`
  * `jax.device_get(...)`
"""

from __future__ import annotations

import ast
from typing import Iterator

from oryx_tpu.analysis.core import (
    Checker,
    Finding,
    ParsedModule,
    RepoContext,
    dotted_name,
)

_SYNC_CALLS = {"np.asarray", "numpy.asarray", "jax.device_get"}


def is_hot(mod: ParsedModule, fn: ast.FunctionDef) -> bool:
    """True when `# hot-path` appears on the def line, above the
    decorator stack, or anywhere in between — a marker placed between
    the decorators and `def` (the natural spot when a decorator is
    added later) must keep the rule applying."""
    first = min(
        [fn.lineno] + [d.lineno for d in fn.decorator_list]
    )
    return any(
        "hot-path" in mod.comment_text(line)
        for line in range(first - 1, fn.lineno + 1)
    )


class HostSyncChecker(Checker):
    name = "host-sync"

    def check(
        self, mod: ParsedModule, ctx: RepoContext
    ) -> Iterator[Finding | None]:
        for node in mod.nodes_of(
            ast.FunctionDef, ast.AsyncFunctionDef
        ):
            if is_hot(mod, node):
                yield from self._check_fn(mod, node)

    def _check_fn(
        self, mod: ParsedModule, fn: ast.FunctionDef
    ) -> Iterator[Finding | None]:
        for node in mod.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            msg = self._sync_reason(node)
            if msg:
                yield self.finding(
                    mod,
                    node,
                    f"{msg} inside hot-path '{fn.name}' blocks the "
                    "host on the device queue; hoist it out of the "
                    "loop or justify with a suppression",
                )

    @staticmethod
    def _sync_reason(call: ast.Call) -> str | None:
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "item"
            and not call.args
        ):
            return "'.item()' host sync"
        d = dotted_name(call.func)
        if d in _SYNC_CALLS:
            return f"'{d}(...)' host transfer"
        if (
            d == "float"
            and len(call.args) == 1
            and isinstance(
                call.args[0], (ast.Name, ast.Attribute, ast.Subscript)
            )
        ):
            return "'float(...)' cast of an array-like"
        return None
