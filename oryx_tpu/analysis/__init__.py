"""oryxlint: JAX-aware static analysis + runtime sanitizers.

Static side (dependency-free, AST-only — see `core.py`):

  rule id           what it catches
  ----------------  ---------------------------------------------------
  lock-discipline   `# guarded-by:` fields touched outside their lock
  lock-order        with-nesting that inverts the declared lock order
                    (oryx_tpu/concurrency.py), cycles, locks held
                    across `# hot-path` dispatches — interprocedural
  atomicity         check-then-act on a guarded field across a lock
                    release
  use-after-donate  buffers read after a donating jit call consumed them
  host-sync         implicit device→host syncs inside `# hot-path` code
  recompile-hazard  tracer branches / unhashable static operands
  metric-name       family naming + one-kind-per-name, repo-wide
  swallowed-exception  broad excepts that only pass/log, un-annotated

Run it: `python scripts/run_oryxlint.py [--strict] [--changed-only]
[--max-suppressions N] [--json-out PATH]`.
Suppress a finding: `# oryxlint: disable=<rule>` on its line (regions:
`# oryxlint: off=<rule>` … `# oryxlint: on=<rule>`).

Runtime side (`sanitizers.py`, imports jax lazily except the lock
tooling, which is stdlib-only):
`recompile_watchdog()` (compile-storm budget + `oryx_recompiles_total`),
`donation_guard()` (donation actually happened / use-after-donate
tripwire), and the concurrency half armed by `ORYX_LOCK_SANITIZER=1`:
`named_lock()` + `LockOrderSanitizer` (held stacks, order/cycle/
re-entrancy checks, `oryx_lock_{wait,hold}_seconds{lock=}`),
`hot_dispatch()` and the `RaceDetector` over `# guarded-by:` /
`# thread-owned:` annotated fields.
"""

from oryx_tpu.analysis.core import (  # noqa: F401
    Checker,
    Finding,
    LintResult,
    ParsedModule,
    RepoContext,
    render_json,
    render_text,
    run_lint,
)
from oryx_tpu.analysis.runner import (  # noqa: F401
    ALL_CHECKERS,
    default_files,
    main,
    make_checkers,
)
from oryx_tpu.analysis.sanitizers import (  # noqa: F401
    DonationGuard,
    LockOrderSanitizer,
    LockOrderViolation,
    RaceDetector,
    RaceViolation,
    RecompileStats,
    RecompileStormError,
    UseAfterDonateError,
    arm_lock_sanitizer,
    backend_donates,
    bind_lock_metrics,
    disarm_lock_sanitizer,
    donation_guard,
    hot_dispatch,
    lock_sanitizer,
    lock_sanitizer_armed,
    lock_stats,
    maybe_arm_from_env,
    named_lock,
    race_exempt,
    race_violations,
    recompile_watchdog,
)
