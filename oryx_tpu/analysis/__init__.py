"""oryxlint: JAX-aware static analysis + runtime sanitizers.

Static side (dependency-free, AST-only — see `core.py`):

  rule id           what it catches
  ----------------  ---------------------------------------------------
  lock-discipline   `# guarded-by:` fields touched outside their lock
  use-after-donate  buffers read after a donating jit call consumed them
  host-sync         implicit device→host syncs inside `# hot-path` code
  recompile-hazard  tracer branches / unhashable static operands
  metric-name       family naming + one-kind-per-name, repo-wide

Run it: `python scripts/run_oryxlint.py [--strict] [--changed-only]`.
Suppress a finding: `# oryxlint: disable=<rule>` on its line (regions:
`# oryxlint: off=<rule>` … `# oryxlint: on=<rule>`).

Runtime side (`sanitizers.py`, imports jax lazily):
`recompile_watchdog()` (compile-storm budget + `oryx_recompiles_total`)
and `donation_guard()` (donation actually happened / use-after-donate
tripwire).
"""

from oryx_tpu.analysis.core import (  # noqa: F401
    Checker,
    Finding,
    LintResult,
    ParsedModule,
    RepoContext,
    render_json,
    render_text,
    run_lint,
)
from oryx_tpu.analysis.runner import (  # noqa: F401
    ALL_CHECKERS,
    default_files,
    main,
    make_checkers,
)
from oryx_tpu.analysis.sanitizers import (  # noqa: F401
    DonationGuard,
    RecompileStats,
    RecompileStormError,
    UseAfterDonateError,
    backend_donates,
    donation_guard,
    recompile_watchdog,
)
