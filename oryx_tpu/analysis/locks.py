"""lock-discipline: `# guarded-by:` annotated fields must be touched
under their lock.

The continuous scheduler shares exactly two pieces of state between
the HTTP threads and the engine thread (`_queue`, `_shutdown`), both
guarded by `self._cond`; the tracer's flight recorder and every Trace
share their span lists under `_lock`. A forgotten `with self._cond:`
is invisible to tests (CPython's GIL makes the race a once-a-week
production artifact) — so the discipline is declared in the source and
enforced statically:

    self._queue: deque[_Request] = deque()  # guarded-by: _cond

Every `self._queue` read or write in that class (outside `__init__`,
which runs before publication) must then sit lexically inside
`with self._<lock>:` (any `with` whose context expression is
`self.<lock>`, possibly among other items). Accesses that are safe for
a structural reason the checker can't see carry a per-line
`# oryxlint: disable=lock-discipline` with a justification comment.
"""

from __future__ import annotations

import ast
from typing import Iterator

from oryx_tpu.analysis.core import (
    Checker,
    Finding,
    ParsedModule,
    RepoContext,
    dotted_name,
    field_annotations,
)


class LockDisciplineChecker(Checker):
    name = "lock-discipline"

    def check(
        self, mod: ParsedModule, ctx: RepoContext
    ) -> Iterator[Finding | None]:
        for node in mod.nodes_of(ast.ClassDef):
            yield from self._check_class(mod, node)

    def _guarded_fields(
        self, mod: ParsedModule, cls: ast.ClassDef
    ) -> dict[str, str]:
        """field -> lock, from `# guarded-by:` comments on declaration
        lines inside the class body (the shared annotation parser in
        core.py; `# thread-owned:` fields are the runtime race
        detector's, not this rule's)."""
        return {
            field: arg
            for field, (kind, arg) in field_annotations(mod, cls).items()
            if kind == "guarded-by"
        }

    def _check_class(
        self, mod: ParsedModule, cls: ast.ClassDef
    ) -> Iterator[Finding | None]:
        fields = self._guarded_fields(mod, cls)
        if not fields:
            return
        for item in cls.body:
            if not isinstance(
                item, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if item.name == "__init__":
                # Construction happens-before publication: the fields
                # (and often the lock itself) don't exist yet.
                continue
            yield from self._check_method(mod, item, fields)

    def _check_method(
        self,
        mod: ParsedModule,
        fn: ast.FunctionDef,
        fields: dict[str, str],
    ) -> Iterator[Finding | None]:
        def visit(node: ast.AST, held: frozenset[str]):
            if isinstance(node, ast.With):
                got = set(held)
                for item in node.items:
                    d = dotted_name(item.context_expr)
                    if d and d.startswith("self."):
                        got.add(d[len("self."):])
                for expr in node.items:
                    yield from visit(expr, held)
                inner = frozenset(got)
                for stmt in node.body:
                    yield from visit(stmt, inner)
                return
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in fields
                and fields[node.attr] not in held
            ):
                lock = fields[node.attr]
                yield self.finding(
                    mod,
                    node,
                    f"'self.{node.attr}' is declared guarded-by "
                    f"'{lock}' but is accessed outside "
                    f"'with self.{lock}:'",
                )
            for child in ast.iter_child_nodes(node):
                yield from visit(child, held)

        yield from visit(fn, frozenset())
