"""Page-pool observatory: derived views over PageAllocator ownership.

The paged KV pool is the scarcest serving resource (concurrent users
per chip is bounded by HBM bytes per KV token — the framing of the
Gemma-on-TPU serving comparison, PAPERS.md arXiv 2605.25645), and
until this module its live state was invisible: the allocator knew
refcounts, the scheduler knew block tables, the prefix cache knew its
entries, and no surface showed WHO holds WHICH page, for how long, or
how churned the free list is. This module is that surface's math:

  * ``fragmentation_ratio`` — largest contiguous free-page-id run over
    total free pages. Block tables indirect every access, so physical
    contiguity never gates correctness; what the ratio measures is
    free-list CHURN under the slot-growth pattern (LIFO recycling keeps
    a healthy pool near 1.0 — page ids hand back in runs; a pool
    shredded by interleaved grow/evict/cache-churn trends toward
    1/free). A falling ratio with stable occupancy is the signature of
    eviction thrash, not capacity pressure — see docs/OBSERVABILITY.md.
  * ``summarize`` — one dict from ``PageAllocator.snapshot()``: state
    counts (free/slot/cache/shared partition the pool), fragmentation,
    tenancy-age and idle quantiles of resident pages. The same
    implementation feeds ``GET /debug/pages?format=summary``, the OOM
    forensic records (utils/forensics.py) and the loadgen memory block.
  * ``PoolObservatory`` — the metrics bridge: raw-named
    ``oryx_pool_{free,slot,cache,shared}_pages`` +
    ``oryx_pool_size_pages`` + ``oryx_pool_min_free_pages`` gauges and
    ``oryx_pool_fragmentation_ratio``, refreshed by a scrape-time
    collector, plus the free-time ``oryx_page_lifetime_seconds`` /
    ``oryx_page_idle_seconds`` histograms the allocator feeds through
    its ``observer`` hook the moment a page's refcount reaches 0.

Dependency-free except for the shared metrics helpers; never imports
jax.
"""

from __future__ import annotations

import time
from typing import Any, Callable

from oryx_tpu.utils.metrics import (
    PAGE_LIFETIME_BUCKETS,
    Registry,
    sample_quantile,
)


def fragmentation_ratio(free_pages: list[int], num_free: int | None = None
                        ) -> float:
    """Largest contiguous run of free page IDS over the free total.

    `free_pages` must be sorted ascending (PageAllocator.snapshot's
    `free_pages` field is). 1.0 = unfragmented (one run, or an empty
    free list — nothing to fragment); the floor is 1/num_free (every
    free page an island)."""
    n = len(free_pages) if num_free is None else num_free
    if n <= 0:
        return 1.0
    best = run = 1
    for prev, cur in zip(free_pages, free_pages[1:]):
        run = run + 1 if cur == prev + 1 else 1
        best = max(best, run)
    return round(best / n, 6)


def _quantiles(values: list[float]) -> dict[str, float | None]:
    if not values:
        return {"n": 0, "p50": None, "p95": None, "max": None}
    return {
        "n": len(values),
        "p50": round(sample_quantile(values, 0.5), 6),
        "p95": round(sample_quantile(values, 0.95), 6),
        "max": round(max(values), 6),
    }


def summarize(snapshot: dict) -> dict[str, Any]:
    """Derived summary of one ``PageAllocator.snapshot()``: the state
    partition (free + slot + cache + shared == num_pages — the
    reconciliation invariant scripts/check_serving_endpoints.py gates),
    fragmentation, peak occupancy since boot, and resident-page
    age/idle quantiles."""
    counts = {"free": 0, "slot": 0, "cache": 0, "shared": 0}
    ages: list[float] = []
    idles: list[float] = []
    for rec in snapshot["pages"]:
        counts[rec["state"]] += 1
        if rec["age_s"] is not None:
            ages.append(rec["age_s"])
        if rec["idle_s"] is not None:
            idles.append(rec["idle_s"])
    total = snapshot["num_pages"]
    return {
        "num_pages": total,
        "page_size": snapshot["page_size"],
        **counts,
        "reconciled": sum(counts.values()) == total,
        "peak_pages_in_use": total - snapshot["min_free"],
        "fragmentation_ratio": fragmentation_ratio(
            snapshot["free_pages"], snapshot["num_free"]
        ),
        "resident_age_s": _quantiles(ages),
        "resident_idle_s": _quantiles(idles),
    }


class PoolObservatory:
    """Registry bridge for one engine's page pool.

    Construct ONCE per scheduler (families may not be re-declared);
    the allocator is read through ``allocator_fn`` so pool rebuilds
    (`_reset_pool`, supervisor restart) are followed automatically —
    re-``attach`` each fresh allocator so free-time histograms keep
    flowing. The scrape-time collector reads only the allocator's own
    plain lists (best-effort under a live engine, exact quiesced —
    the same contract as ``PageAllocator.snapshot``), and is
    TTL-rate-limited like the HBM collector: the walk is O(num_pages)
    plus a free-list sort, and the router's aggregation fan-out
    would otherwise pay it per replica per scrape. Consumers that
    need exactness (``scheduler.pool_snapshot`` — the /debug/pages
    reconciliation surface) force a refresh."""

    def __init__(self, registry: Registry,
                 allocator_fn: Callable[[], Any],
                 ttl_s: float = 1.0):
        self._allocator_fn = allocator_fn
        self._ttl_s = ttl_s
        self._last = float("-inf")
        self._free = registry.gauge("oryx_pool_free_pages", raw_name=True)
        self._slot = registry.gauge("oryx_pool_slot_pages", raw_name=True)
        self._cache = registry.gauge(
            "oryx_pool_cache_pages", raw_name=True
        )
        self._shared = registry.gauge(
            "oryx_pool_shared_pages", raw_name=True
        )
        self._size = registry.gauge("oryx_pool_size_pages", raw_name=True)
        self._min_free = registry.gauge(
            "oryx_pool_min_free_pages", raw_name=True
        )
        self._frag = registry.gauge(
            "oryx_pool_fragmentation_ratio", raw_name=True
        )
        self._lifetime = registry.histogram(
            "oryx_page_lifetime_seconds", PAGE_LIFETIME_BUCKETS,
            raw_name=True,
        )
        self._idle = registry.histogram(
            "oryx_page_idle_seconds", PAGE_LIFETIME_BUCKETS,
            raw_name=True,
        )
        registry.register_collector(self.collect)
        self.collect()

    def attach(self, allocator) -> None:
        """Point the allocator's free-time telemetry here (call again
        after every pool rebuild — a fresh allocator starts with
        ``observer=None``). Forces a refresh: gauges must never keep
        reporting the dead pool."""
        allocator.observer = self
        self.collect(force=True)

    def page_freed(self, lifetime_s: float, idle_s: float) -> None:
        """Allocator callback at refcount 0: one page's whole tenancy
        (alloc → last free) and its idle tail (last ref transition →
        free) land in the histograms."""
        self._lifetime.observe(max(0.0, lifetime_s))
        self._idle.observe(max(0.0, idle_s))

    def collect(self, force: bool = False) -> None:
        """Refresh the oryx_pool_* gauges from the live allocator
        (registered as a scrape-time collector). Rate-limited to one
        walk per ``ttl_s`` (0 disables the cache); ``force`` bypasses
        it — the /debug/pages path forces so its summary and the
        gauges always agree on a quiesced engine."""
        now = time.monotonic()
        if not force and self._ttl_s and now - self._last < self._ttl_s:
            return
        self._last = now
        alloc = self._allocator_fn()
        if alloc is None:
            return
        counts = {"free": 0, "slot": 0, "cache": 0, "shared": 0}
        for p in range(alloc.num_pages):
            counts[alloc.classify(alloc._refs[p], alloc._owners[p])] += 1
        self._free.set(counts["free"])
        self._slot.set(counts["slot"])
        self._cache.set(counts["cache"])
        self._shared.set(counts["shared"])
        self._size.set(alloc.num_pages)
        self._min_free.set(alloc.min_free)
        self._frag.set(fragmentation_ratio(sorted(alloc._free)))
