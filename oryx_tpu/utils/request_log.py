"""Wide-event request log: one canonical JSONL event per terminal
request.

The serving stack already records what a request cost (the PR 7 cost
ledger), why it was slow (trace spans), where it ran (replica build
info) and how speculation paid off (accepted-tokens accounting) — but
in four different places with four different lifetimes. This module
merges them into ONE wide event at the moment a request reaches any
terminal state (finish / error / cancel / reject), in the
wide-event-logging shape: a flat JSON object per line, every field
drawn from a declared registry (`utils.metrics.REQUEST_EVENT_KEYS`, a
superset of `REQUEST_COST_KEYS`), so offline analysis can slice the
whole fleet's traffic by any dimension without joining debug surfaces.

Two sinks, same events:

  * a bounded in-memory ring, exported at
    ``GET /debug/requests?format=jsonl`` (replica and router — the
    router merges its replicas');
  * optionally a size-capped ``requests.jsonl`` file
    (``--requests-log``), rolling to ``<path>.1`` past ``max_bytes``
    exactly like the anomaly events sink (utils/anomaly.py): rotate
    AFTER the crossing write so the live file is never a torn JSONL,
    one generation of history kept, disk usage <= ~2x the cap.

Schema discipline is enforced twice: ``build_request_event`` rejects
undeclared or non-snake_case keys at runtime, and oryxlint's
`metric-name` rule checks the literal keyword fields of every
``build_request_event(...)`` call site against the registry at review
time — the JSONL schema cannot drift silently from the histograms.
"""

from __future__ import annotations

import json
import os
import re
import time
from collections import deque
from typing import Any

from oryx_tpu.analysis.sanitizers import named_lock
from oryx_tpu.utils.metrics import (
    AUDIT_EVENT_KEYS,
    OOM_EVENT_KEYS,
    REQUEST_EVENT_KEYS,
)
from oryx_tpu.utils.rolling_sink import RollingSink

# The current wide-event schema version, stamped into every event so
# offline consumers can dispatch on it when fields are added.
EVENT_SCHEMA = 1

_SNAKE_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_KEYSET = frozenset(REQUEST_EVENT_KEYS)
# Non-request wide events share the sink but carry their own declared
# schema, dispatched on the `kind` field ("kind" is deliberately NOT a
# request-event key, so a request event can never be mistaken for one).
_KIND_KEYSETS = {
    "oom_pressure": frozenset(OOM_EVENT_KEYS),
    "audit": frozenset(AUDIT_EVENT_KEYS),
}


def build_request_event(**fields: Any) -> dict[str, Any]:
    """Assemble one wide event from keyword fields, validating every
    key against the declared registry. `schema` and `ts_unix_s` are
    filled when absent. Raises ValueError on an undeclared or
    non-snake_case key — schema drift fails loudly at the write site,
    never silently in a consumer."""
    bad = sorted(
        k for k in fields
        if k not in _KEYSET or not _SNAKE_RE.match(k)
    )
    if bad:
        raise ValueError(
            f"undeclared request-event field(s) {bad}: add them to "
            "utils.metrics.REQUEST_EVENT_KEYS (the wide-event schema "
            "registry) or fix the name"
        )
    ev: dict[str, Any] = {"schema": EVENT_SCHEMA, "ts_unix_s": time.time()}
    ev.update(fields)
    return ev


def build_oom_event(**fields: Any) -> dict[str, Any]:
    """Assemble one memory-pressure wide event (`kind="oom_pressure"`),
    validated against utils.metrics.OOM_EVENT_KEYS — the flat one-line
    spelling of a forensic record (utils/forensics.py holds the full
    artifact; `forensic_index` joins the two). Same loud-failure
    contract as build_request_event."""
    bad = sorted(
        k for k in fields
        if k not in _KIND_KEYSETS["oom_pressure"] or not _SNAKE_RE.match(k)
    )
    if bad:
        raise ValueError(
            f"undeclared oom-event field(s) {bad}: add them to "
            "utils.metrics.OOM_EVENT_KEYS (the memory-pressure schema "
            "registry) or fix the name"
        )
    ev: dict[str, Any] = {
        "schema": EVENT_SCHEMA, "ts_unix_s": time.time(),
        "kind": "oom_pressure",
    }
    ev.update(fields)
    return ev


def build_audit_event(**fields: Any) -> dict[str, Any]:
    """Assemble one output-audit wide event (`kind="audit"`), validated
    against utils.metrics.AUDIT_EVENT_KEYS — the flat one-line spelling
    of an audit record (serve/audit.py holds the full artifact at
    /debug/audit; `audit_index` joins the two). Same loud-failure
    contract as build_request_event."""
    bad = sorted(
        k for k in fields
        if k not in _KIND_KEYSETS["audit"] or not _SNAKE_RE.match(k)
    )
    if bad:
        raise ValueError(
            f"undeclared audit-event field(s) {bad}: add them to "
            "utils.metrics.AUDIT_EVENT_KEYS (the output-audit schema "
            "registry) or fix the name"
        )
    ev: dict[str, Any] = {
        "schema": EVENT_SCHEMA, "ts_unix_s": time.time(),
        "kind": "audit",
    }
    ev.update(fields)
    return ev


class RequestLog:
    """Bounded ring + optional rotating JSONL file of wide events.

    ``append`` is called from the engine thread's terminal paths (and
    from submit() on rejection); readers are debug-endpoint handler
    threads. All shared state sits under one leaf lock
    (`request_log._lock` in the declared order) held only for the ring
    edit and the file write — never across anything that blocks."""

    def __init__(self, path: str | None = None, *, keep: int = 512,
                 max_bytes: int = 16 * 1024 * 1024):
        self.path = os.path.abspath(path) if path else None
        self.max_bytes = max_bytes
        self._lock = named_lock("request_log._lock")
        self._ring: deque[dict[str, Any]] = deque(  # guarded-by: _lock
            maxlen=max(1, keep)
        )
        self._total = 0  # guarded-by: _lock
        self._sink = None  # guarded-by: _lock
        if self.path:
            self._sink = RollingSink(self.path, max_bytes=max_bytes)

    def append(self, event: dict[str, Any]) -> None:
        """Record one event (normally built by build_request_event /
        build_oom_event / build_audit_event; re-validated here so a
        hand-rolled dict can't bypass a registry). The schema is
        dispatched on `kind`: absent = a request event, "oom_pressure"
        = the memory-pressure schema, "audit" = the output-audit
        schema."""
        keyset = _KIND_KEYSETS.get(event.get("kind"), _KEYSET)
        bad = sorted(k for k in event if k not in keyset)
        if bad:
            raise ValueError(
                f"undeclared request-event field(s) {bad} "
                "(utils.metrics.REQUEST_EVENT_KEYS / OOM_EVENT_KEYS "
                "is the schema)"
            )
        line = json.dumps(event)
        with self._lock:
            self._ring.append(event)
            self._total += 1
            if self._sink is not None:
                # Rotation contract (rotate AFTER the crossing write,
                # one `.1` generation) lives in utils/rolling_sink.py,
                # shared with the anomaly and journal sinks.
                self._sink.write(line)

    # ---- readers ---------------------------------------------------------

    @property
    def total(self) -> int:
        with self._lock:
            return self._total

    def snapshot(self, n: int | None = None) -> list[dict[str, Any]]:
        """Oldest-first copies of the retained events (last `n` when
        given) — log order, the same order the file carries."""
        with self._lock:
            events = list(self._ring)
        if n is not None:
            events = events[-max(0, int(n)):]
        return [dict(e) for e in events]

    def export_jsonl(self, n: int | None = None) -> str:
        """The ring as JSONL text (the ?format=jsonl body)."""
        lines = [json.dumps(e) for e in self.snapshot(n)]
        return "\n".join(lines) + ("\n" if lines else "")

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None
