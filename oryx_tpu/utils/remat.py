"""Rematerialization (gradient-checkpointing) policy selection.

The reference gets one knob — HF `gradient_checkpointing=True`, i.e.
recompute everything per decoder block (SURVEY.md §2b "Gradient
checkpointing"). On TPU the memory/FLOPs trade is tunable: XLA can save
the MXU (matmul) outputs and recompute only the cheap elementwise/VPU
work, buying back most of the remat recompute FLOPs wherever HBM has
headroom. `wrap_remat` is used by every scan-block body (decoder, ViT).

Policies:
  * False / "none" — no checkpointing: all intermediates saved (fastest
    backward, highest memory).
  * True / "block" — `jax.checkpoint` of the whole block: only the block
    inputs survive the forward; everything is recomputed in the backward
    (the reference-equivalent default).
  * "dots" — checkpoint with `checkpoint_dots`: matmul outputs are saved,
    elementwise ops recomputed. ~the activation memory of "none" minus
    fusion temporaries, but the backward skips all MXU recompute.
  * "attn" — save only the flash-attention kernel outputs + logsumexp
    (named "flash_out"/"flash_lse" in ops/pallas/flash_attention._fwd):
    a thin slice of "dots" costing ~2 bytes/token/layer/head-dim that
    spares the backward from re-running the forward attention kernel —
    the most expensive single op in a block recompute.
"""

from __future__ import annotations

import jax

POLICIES = ("none", "block", "dots", "attn")


def wrap_remat(body, remat: bool | str):
    """Wrap a scan-step body per the remat policy (see module docstring)."""
    if remat in (False, None, "none"):
        return body
    if remat in (True, "block"):
        return jax.checkpoint(body, prevent_cse=False)
    if remat == "dots":
        return jax.checkpoint(
            body,
            prevent_cse=False,
            policy=jax.checkpoint_policies.checkpoint_dots,
        )
    if remat == "attn":
        return jax.checkpoint(
            body,
            prevent_cse=False,
            policy=jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"
            ),
        )
    raise ValueError(f"unknown remat policy {remat!r}; have {POLICIES}")
