"""Rematerialization (gradient-checkpointing) policy selection.

The reference gets one knob — HF `gradient_checkpointing=True`, i.e.
recompute everything per decoder block (SURVEY.md §2b "Gradient
checkpointing"). On TPU the memory/FLOPs trade is tunable: XLA can save
the MXU (matmul) outputs and recompute only the cheap elementwise/VPU
work, buying back most of the remat recompute FLOPs wherever HBM has
headroom. `wrap_remat` is used by every scan-block body (decoder, ViT).

Policies:
  * False / "none" — no checkpointing: all intermediates saved (fastest
    backward, highest memory).
  * True / "block" — `jax.checkpoint` of the whole block: only the block
    inputs survive the forward; everything is recomputed in the backward
    (the reference-equivalent default).
  * "dots" — checkpoint with `checkpoint_dots`: matmul outputs are saved,
    elementwise ops recomputed. ~the activation memory of "none" minus
    fusion temporaries, but the backward skips all MXU recompute.
  * "attn" — save only the flash-attention outputs + logsumexp
    (named "flash_out"/"flash_lse"): a thin slice of "dots" costing
    ~2 bytes/token/layer/head-dim that spares the backward from
    re-running the forward attention — the most expensive single op in a
    block recompute. Every attn_impl carries the tags: the Pallas kernel
    (ops/pallas/flash_attention._fwd) and the flash-inner ring
    (ops/ring_attention._ring_flash_forward) save out+lse so their
    custom-VJP backward needs no forward re-run; the XLA path
    (ops/attention.attention) and the plain ring shard name only
    "flash_out" (no explicit lse exists there), which still cuts the
    recompute tree for the o_proj/MLP backward while dq/dk/dv recompute
    softmax internals.
  * "attn_qkv" — "attn" plus the post-rope q/k/v projections (named
    "attn_q"/"attn_k"/"attn_v" in models/qwen2._block): the backward
    additionally skips the three projection matmuls and the rope —
    ~3 bytes/token/layer/(q+2kv head-dim) more HBM than "attn".
  * "attn_o" — "attn_qkv" plus the o_proj output (named "attn_o" in
    models/qwen2._block): the mid-block residual h + o_out is rebuilt
    from the saved projection, so the only matmuls the backward
    recomputes are gate/up (down's input) — the rest of the recompute
    tree is two RMS norms and a silu (VPU work). Costs ~2 more
    bytes/token/layer/hidden over "attn_qkv"; the best FLOPs/memory
    point wherever it fits.
"""

from __future__ import annotations

import jax

POLICIES = ("none", "block", "dots", "attn", "attn_qkv", "attn_o")

_SAVED_NAMES = {
    "attn": ("flash_out", "flash_lse"),
    "attn_qkv": ("flash_out", "flash_lse", "attn_q", "attn_k", "attn_v"),
    "attn_o": (
        "flash_out", "flash_lse", "attn_q", "attn_k", "attn_v", "attn_o",
    ),
}


def wrap_remat(body, remat: bool | str):
    """Wrap a scan-step body per the remat policy (see module docstring)."""
    if remat in (False, None, "none"):
        return body
    if remat in (True, "block"):
        return jax.checkpoint(body, prevent_cse=False)
    if remat == "dots":
        return jax.checkpoint(
            body,
            prevent_cse=False,
            policy=jax.checkpoint_policies.checkpoint_dots,
        )
    if remat in _SAVED_NAMES:
        return jax.checkpoint(
            body,
            prevent_cse=False,
            policy=jax.checkpoint_policies.save_only_these_names(
                *_SAVED_NAMES[remat]
            ),
        )
    raise ValueError(f"unknown remat policy {remat!r}; have {POLICIES}")
