"""Metrics / logging / observability.

Two layers:

  * `MetricLogger` / `rank0_print` — HF Trainer `report_to` parity
    (SURVEY.md §5 "Metrics"): a structured JSONL writer plus stdout
    logging on process 0, tracking the north-star metric
    tokens/sec/chip; TensorBoard attaches via the same record dict.
  * A dependency-free **metrics registry** (`Registry`) in the
    Prometheus data model: Counter / Gauge / Histogram families with
    labels, one text-exposition renderer, pluggable collectors
    (process / device-memory), and a small `TelemetryServer` that
    serves `/metrics` + `/healthz` + `/readyz` over stdlib HTTP.
    `ServingMetrics` (the serving `/metrics` surface) and the trainer
    exporter (train/telemetry.py) are both clients of it, so train and
    serve share one exposition path and one naming discipline.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from typing import Any

import jax


def rank0_print(*args, **kwargs) -> None:
    if jax.process_index() == 0:
        print(*args, **kwargs)
        sys.stdout.flush()


class MetricLogger:
    """JSONL metric stream + rolling throughput (tokens/sec/chip).

    tensorboard_dir: optional `report_to=tensorboard` parity — every
    logged record also lands as TB scalars (torch's SummaryWriter, a
    host-side dependency already in the image; gated so its absence
    only disables TB, never training).
    """

    def __init__(
        self,
        path: str | None = None,
        *,
        log_every: int = 10,
        tensorboard_dir: str | None = None,
    ):
        self.path = path
        self.log_every = log_every
        self._f = None
        self._tb = None
        if path and jax.process_index() == 0:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "a")
        if tensorboard_dir and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(tensorboard_dir)
            # fault-boundary: TB is optional — its absence only
            # disables TB, never training
            except Exception as e:
                rank0_print(f"tensorboard disabled: {e!r}")
        self._last_time = time.perf_counter()
        self._last_step = 0
        self._tokens_since = 0
        self._skipped_since = 0

    def log_step(self, step: int, metrics: dict[str, Any]) -> None:
        self._tokens_since += int(metrics.get("num_tokens", 0))
        # Accumulated, not sampled: a skip on a step that isn't a
        # log_every multiple must still show in the next record.
        self._skipped_since += int(metrics.get("skipped", 0))
        if step % self.log_every != 0:
            return
        now = time.perf_counter()
        dt = max(now - self._last_time, 1e-9)
        nsteps = max(step - self._last_step, 1)
        n_chips = jax.device_count()
        def js(v):
            # Non-finite floats serialize as JSON null: with the skip
            # guard on, a NaN loss is a normal recurring condition, and
            # json.dumps would otherwise emit the non-RFC `NaN` token
            # that breaks strict JSONL consumers (jq, JSON.parse).
            f = float(v)
            return f if math.isfinite(f) else None

        rec = {
            "step": step,
            **{
                k: js(v) for k, v in metrics.items()
                if k not in ("num_tokens", "skipped")
            },
            "steps_per_sec": nsteps / dt,
            "tokens_per_sec_per_chip": self._tokens_since / dt / n_chips,
        }
        if "skipped" in metrics:
            rec["skipped"] = self._skipped_since
        self._last_time, self._last_step = now, step
        self._tokens_since = 0
        self._skipped_since = 0
        rank0_print(
            f"step {step}: " + " ".join(
                f"{k}={'nan' if v is None else format(v, '.4g')}"
                for k, v in rec.items() if k != "step"
            )
        )
        if self._f:
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
        if self._tb:
            for k, v in rec.items():
                if k != "step" and v is not None:
                    self._tb.add_scalar(f"train/{k}", v, step)

    def close(self) -> None:
        if self._f:
            self._f.close()
        if self._tb:
            self._tb.close()


# ---------------------------------------------------------------------------
# Metrics registry (Prometheus data model, dependency-free)
# ---------------------------------------------------------------------------


def _escape_label(v: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return (
        v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _label_str(labelnames: tuple[str, ...],
               labelvalues: tuple[str, ...]) -> str:
    if not labelnames:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"'
        for k, v in zip(labelnames, labelvalues)
    )
    return "{" + inner + "}"


class Counter:
    """Monotone counter (one label combination of a family)."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock):
        self.value = 0.0
        self._lock = lock  # lock-name: metrics.family

    def inc(self, n: float = 1) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self.value += n


class Gauge:
    """Settable gauge (one label combination of a family)."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock):
        self.value = 0.0
        self._lock = lock  # lock-name: metrics.family

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self.value += n


class Histogram:
    """Fixed-bucket histogram in the Prometheus cumulative-`le` shape.

    Buckets are upper bounds; +Inf is implicit (the total count)."""

    __slots__ = ("buckets", "counts", "total", "sum", "_lock")

    def __init__(self, buckets: tuple[float, ...], lock=None):
        from oryx_tpu.analysis.sanitizers import named_lock

        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * len(self.buckets)
        self.total = 0
        self.sum = 0.0
        self._lock = lock or named_lock("metrics.family")

    def observe(self, value: float) -> None:
        with self._lock:
            self.total += 1
            self.sum += float(value)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self.counts[i] += 1

    def render(self, name: str, out: list[str], labels: str = "") -> None:
        # Bucket lines carry the family labels plus le; counts are
        # already cumulative (observe touches every bucket whose bound
        # covers the value).
        with self._lock:
            counts, total, s = list(self.counts), self.total, self.sum
        pre = labels[:-1] + "," if labels else "{"
        for b, c in zip(self.buckets, counts):
            out.append(f'{name}_bucket{pre}le="{b:g}"}} {c}')
        out.append(f'{name}_bucket{pre}le="+Inf"}} {total}')
        out.append(f"{name}_sum{labels} {s:.17g}")
        out.append(f"{name}_count{labels} {total}")


class MetricFamily:
    """One named metric family: a fixed type + label names, holding one
    child (Counter/Gauge/Histogram) per label-values combination. A
    family declared with no label names IS its single child — inc/set/
    observe proxy to it, so unlabeled metrics need no `.labels()` hop."""

    def __init__(self, name: str, mtype: str,
                 labelnames: tuple[str, ...] = (),
                 buckets: tuple[float, ...] | None = None,
                 lock=None):
        from oryx_tpu.analysis.sanitizers import named_lock

        self.name = name
        self.mtype = mtype
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(buckets)) if buckets else None
        self._lock = lock or named_lock("metrics.family")
        self._children: dict[tuple[str, ...], Any] = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        if self.mtype == "counter":
            return Counter(self._lock)
        if self.mtype == "gauge":
            return Gauge(self._lock)
        return Histogram(self.buckets or PER_TOKEN_BUCKETS, self._lock)

    def labels(self, **kv: str):
        """Child for one label-values combination (created on first
        touch). Label names must match the family declaration exactly."""
        if tuple(sorted(kv)) != tuple(sorted(self.labelnames)):
            raise ValueError(
                f"{self.name}: got labels {sorted(kv)}, family declares "
                f"{sorted(self.labelnames)}"
            )
        key = tuple(str(kv[k]) for k in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
        return child

    # Unlabeled-family conveniences.
    def inc(self, n: float = 1) -> None:
        self._children[()].inc(n)

    def set(self, value: float) -> None:
        self._children[()].set(value)

    def observe(self, value: float) -> None:
        self._children[()].observe(value)

    @property
    def value(self) -> float:
        return self._children[()].value

    def render(self, out: list[str]) -> None:
        with self._lock:
            children = sorted(self._children.items())
        out.append(f"# TYPE {self.name} {self.mtype}")
        for key, child in children:
            labels = _label_str(self.labelnames, key)
            if self.mtype == "histogram":
                child.render(self.name, out, labels)
            else:
                # Full precision (%g rounds to 6 significant digits,
                # which quantizes large counters and hides increments).
                out.append(f"{self.name}{labels} {child.value:.17g}")


class Registry:
    """Named metric families + text exposition + collectors.

    `prefix` is prepended (with `_`) to every family name unless the
    family is created with `raw_name=True` — used for families shared
    verbatim across registries (e.g. `oryx_anomaly_total`, the same
    series name whether train or serve fired it). One family per name,
    enforced: re-declaring with a different type/labels/buckets raises,
    so one exposition can never carry duplicate families.

    Collectors are zero-arg callables run at the top of `render()` —
    they refresh gauges whose truth lives elsewhere (process RSS, HBM
    in use) so scrapes always see current values without a background
    sampler thread."""

    def __init__(self, prefix: str = ""):
        from oryx_tpu.analysis.sanitizers import named_lock

        self.prefix = prefix
        self._lock = named_lock("registry._lock")
        self._families: dict[str, MetricFamily] = {}  # guarded-by: _lock
        self._info_names: set[str] = set()  # guarded-by: _lock
        self._collectors: list[Any] = []  # guarded-by: _lock

    def full_name(self, name: str, raw_name: bool = False) -> str:
        return name if (raw_name or not self.prefix) \
            else f"{self.prefix}_{name}"

    def _family(self, name: str, mtype: str,
                labelnames: tuple[str, ...] = (),
                buckets: tuple[float, ...] | None = None,
                raw_name: bool = False) -> MetricFamily:
        full = self.full_name(name, raw_name)
        with self._lock:
            fam = self._families.get(full)
            if fam is None:
                fam = self._families[full] = MetricFamily(
                    full, mtype, labelnames, buckets
                )
                return fam
        want = (mtype, tuple(labelnames),
                tuple(sorted(buckets)) if buckets else fam.buckets)
        have = (fam.mtype, fam.labelnames, fam.buckets)
        if want != have:
            raise ValueError(
                f"metric family {full!r} re-declared as {want}, "
                f"already registered as {have}"
            )
        return fam

    def counter(self, name: str, labelnames: tuple[str, ...] = (),
                *, raw_name: bool = False) -> MetricFamily:
        return self._family(name, "counter", labelnames,
                            raw_name=raw_name)

    def gauge(self, name: str, labelnames: tuple[str, ...] = (),
              *, raw_name: bool = False) -> MetricFamily:
        return self._family(name, "gauge", labelnames, raw_name=raw_name)

    def histogram(self, name: str,
                  buckets: tuple[float, ...],
                  labelnames: tuple[str, ...] = (),
                  *, raw_name: bool = False) -> MetricFamily:
        return self._family(name, "histogram", labelnames, buckets,
                            raw_name=raw_name)

    def info(self, name: str, labels: dict[str, str],
             *, raw_name: bool = False) -> None:
        """Info metric: a gauge pinned to 1 whose labels carry build /
        deploy identity (git revision, engine, model). Re-setting an
        INFO family replaces its labels (identity, not a series per
        value); replacing a non-info family of the same name raises —
        the no-duplicate-family invariant holds on this path too."""
        full = self.full_name(name, raw_name)
        with self._lock:
            if full in self._families and full not in self._info_names:
                raise ValueError(
                    f"metric family {full!r} already registered as a "
                    f"{self._families[full].mtype}; info() would "
                    "silently replace it"
                )
            self._info_names.add(full)
            self._families[full] = fam = MetricFamily(
                full, "gauge",
                tuple(sorted(str(k) for k in labels)),
            )
        fam.labels(**{str(k): str(v) for k, v in labels.items()}).set(1)

    def register_collector(self, fn) -> None:
        with self._lock:
            self._collectors.append(fn)

    def existing(self, name: str,
                 *, raw_name: bool = False) -> MetricFamily | None:
        with self._lock:
            return self._families.get(self.full_name(name, raw_name))

    def get(self, name: str, *, raw_name: bool = False) -> float:
        """Current value of an unlabeled counter/gauge, 0 when never
        registered — or when the name is labeled or a histogram, which
        have no single scalar value (test/bench convenience)."""
        with self._lock:
            fam = self._families.get(self.full_name(name, raw_name))
        if fam is None or fam.labelnames or fam.mtype == "histogram":
            return 0.0
        return fam.value

    def render(self) -> str:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            # fault-boundary: a broken collector must never break the
            # scrape
            except Exception:
                pass
        with self._lock:
            families = sorted(self._families.items())
        out: list[str] = []
        for _, fam in families:
            fam.render(out)
        return "\n".join(out) + "\n"


# Default latency bucket ladders (seconds): TTFT spans prefill compiles;
# per-token latency spans a decode step.
TTFT_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                30.0, 60.0)
PER_TOKEN_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5)
# Prefill chunk sizes (tokens per admission dispatch): powers of two up
# to the longest plausible single dispatch — the shape of this histogram
# shows whether chunked prefill is actually bounding admission work.
PREFILL_CHUNK_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                         256.0, 512.0, 1024.0, 2048.0, 4096.0)
# Valid query rows per device dispatch (the occupancy of the packed
# ragged buffer, or the live-row count of a split prefill/decode
# dispatch): powers of two up to the largest plausible packed buffer
# (num_slots + prefill lanes). A fused path that is working shows this
# distribution shifted right vs the split path at equal load —
# prefill and decode rows ride the SAME dispatch.
DISPATCH_ROWS_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                         256.0, 512.0, 1024.0, 2048.0, 4096.0)
# Tokens a slot advanced per speculative engine step (1 fed token + the
# accepted drafts): integers 1..k+1, so unit-ish buckets — the
# oryx_serving_accepted_tokens_per_step histogram whose sum/count mean
# is the speculation headline (gate: > 1.5 on repetitive workloads,
# scripts/bench_paged_attention.py --smoke).
SPEC_ACCEPT_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0,
                       32.0)
# Lock wait/hold times for the LockOrderSanitizer's
# oryx_lock_{wait,hold}_seconds{lock=} histograms: microseconds (the
# healthy regime for every lock in the declared order) up to the one
# second that would mean a lock is held across device work.
LOCK_SECONDS_BUCKETS = (1e-6, 5e-6, 1e-5, 5e-5, 1e-4, 5e-4, 1e-3,
                        5e-3, 0.025, 0.1, 0.5, 1.0)

# Per-request cost-ledger ladders (the `oryx_serving_request_*` families
# the continuous scheduler observes when a request reaches any terminal
# state; docs/OBSERVABILITY.md "Capacity & load testing"). Token counts
# run in powers of two to past the context ceiling; page-seconds — the
# pages-held x wall-time integral, the real HBM currency — spans a
# sub-chunk hold through minutes-long residency.
REQUEST_TOKEN_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                         256.0, 512.0, 1024.0, 2048.0, 4096.0, 8192.0)
REQUEST_SECONDS_BUCKETS = TTFT_BUCKETS + (120.0, 300.0)
PAGE_SECONDS_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                        5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0)
# One page's whole tenancy (alloc -> refcount-0 free) and its idle
# tail, fed at free time by the allocator's observer hook
# (utils/pagemap.PoolObservatory): sub-chunk holds through minutes of
# cache residency. The oryx_page_{lifetime,idle}_seconds ladders.
PAGE_LIFETIME_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.25, 0.5, 1.0,
                         2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
                         600.0)

# The canonical per-request cost-ledger keys: what the scheduler writes
# into handle.debug["cost"] / the trace meta at every terminal state,
# what the final SSE chunk carries under "oryx", and what the capacity
# harness (scripts/loadgen.py) asserts is complete for every finished
# request in /debug/requests.
REQUEST_COST_KEYS = (
    "prefill_tokens", "cached_tokens", "decode_steps", "decode_tokens",
    "page_seconds", "queue_s", "prefill_s", "decode_s", "e2e_s",
    # HBM high-water mark: the most pages the request held at once,
    # and the page-seconds it had accumulated when it reached that
    # peak — together they say whether a request's HBM cost was a
    # short spike or a long plateau (docs/OBSERVABILITY.md "Memory &
    # device time").
    "peak_pages", "peak_page_seconds",
)

# The canonical wide-event schema: every field a terminal request's
# JSONL event (utils/request_log.py, /debug/requests?format=jsonl) may
# carry. A strict SUPERSET of REQUEST_COST_KEYS — the event embeds the
# whole cost ledger — plus identity/outcome/routing/speculation fields.
# Declared HERE (next to the cost keys and the histogram ladders) so
# the JSONL schema, the /debug surfaces and the oryx_serving_request_*
# histograms share one source of truth; oryxlint's metric-name rule
# checks literal event fields against this tuple, and
# request_log.build_request_event rejects undeclared keys at runtime,
# so the schema cannot drift silently from the metrics.
REQUEST_EVENT_KEYS = REQUEST_COST_KEYS + (
    "schema",                    # event-schema version (int)
    "ts_unix_s",                 # wall-clock time the request ended
    "request_id",                # == X-Request-Id / the trace id
    "engine",                    # continuous | sharded | ...
    "replica",                   # --replica-id, null standalone
    "routed",                    # request arrived via the router
    "status",                    # ok | error | cancelled | rejected
    "error_kind",                # handle.error_kind, null on ok
    "finish_reason",             # stop | length, null unless ok
    "prompt_tokens",
    "completion_tokens",
    "streaming",
    "evictions",                 # replay re-admissions this request paid
    "accepted_tokens_per_step",  # speculation yield, null off spec
    "journal_seq",               # seq of this request's decision-journal
                                 # submit entry (serve/journal.py), null
                                 # when the journal is disarmed — the
                                 # join key from a wide event into the
                                 # replayable decision stream
)

# The memory-pressure wide-event schema: one flat event per
# OutOfPagesError / degraded-mode escalation, emitted through the same
# request-log sink (kind distinguishes it from request events; the
# full forensic record — top-K residents, cache LRU, timeline tail —
# lives in the bounded ring utils/forensics.py serves at /debug/oom,
# this event is the greppable one-liner in requests.jsonl). Declared
# next to REQUEST_EVENT_KEYS for the same reason: one source of truth
# for sink validation.
# Logit-drift ladders for the output auditor (serve/audit.py): the
# max-abs-diff ladder spans exact parity (the fp path's expected 0)
# through bf16 rounding noise to "a different model"; the KL ladder is
# the same story in distribution space. Both are raw-named
# oryx_audit_* families, pre-registered so the ladders render at zero
# before the first audit.
AUDIT_DIFF_BUCKETS = (0.0, 1e-6, 1e-5, 1e-4, 1e-3, 5e-3, 0.025, 0.1,
                      0.5, 1.0, 4.0, 16.0)
AUDIT_KL_BUCKETS = (0.0, 1e-8, 1e-6, 1e-5, 1e-4, 1e-3, 0.01, 0.1, 0.5,
                    1.0, 4.0)

# The output-audit wide-event schema (kind="audit"): one flat line per
# completed audit through the request-log sink, joining the verdict
# counters to the forensic ring (`audit_index` is the /debug/audit
# join key, like `forensic_index` for oom_pressure). Declared next to
# the other schemas so sink validation and oryxlint's event-builder
# check share one source of truth.
AUDIT_EVENT_KEYS = (
    "schema", "ts_unix_s",
    "kind",                   # always "audit"
    "request_id",             # the audited request (joins its trace)
    "engine", "replica",
    "verdict",                # pass | drift | fail
    "first_divergence",       # token index of the first mismatch, -1
    "replayed_tokens",        # tokens the replay regenerated
    "positions_checked",      # logit positions compared
    "logit_max_abs_diff",     # max over the checked positions
    "kl",                     # max KL over the checked positions
    "evictions",              # replays the LIVE request paid (the
                              # determinism the auditor leans on)
    "audit_index",            # index of the full record in /debug/audit
)

OOM_EVENT_KEYS = (
    "schema", "ts_unix_s",
    "kind",                  # always "oom_pressure"
    "trigger",               # oom (an allocation raised) |
                             # pool_pressure (free-list shortfall
                             # episode, defer/evict path) |
                             # degraded_escalation (SLO ladder moved)
    "detail",                # the OutOfPagesError text / ladder step
    "engine", "replica",
    "degraded_mode",
    "queue_depth", "live_slots",
    "free_pages", "slot_pages", "cache_pages", "shared_pages",
    "fragmentation_ratio",
    "top_request_id",        # largest resident by pages held
    "top_request_pages",
    "forensic_index",        # index of the full record in /debug/oom
)

# The decision-journal entry schema (serve/journal.py): every field a
# journal entry may carry, across all entry kinds (`kind` dispatches —
# submit / reject / admit / splice / evict / step / degraded / fault /
# restart / finish). One flat registry, like REQUEST_EVENT_KEYS, so
# build_journal_event validates at the write site and oryxlint's
# metric-name rule checks literal call-site fields at review time; the
# replay harness (scripts/replay_journal.py) depends on these names
# never drifting from what the journal wrote.
JOURNAL_EVENT_KEYS = (
    "schema", "ts_unix_s",
    "kind",                 # the entry's decision kind (see above)
    "seq",                  # monotone per-journal entry index
    "step",                 # engine dispatches completed when recorded
    "request_id",
    # -- submit / reject -------------------------------------------------
    "arrival_seq",          # monotone per-journal submit index
    "prompt",               # text-only request payload (question,
                            # history) — replayable
    "prompt_sha256",        # fingerprint when the payload has media
                            # (sidecar needed; not replayable)
    "prompt_len",           # prompt tokens (stamped at admit)
    "sampling",             # the request's sampling dict, post-clamp
    "max_new",              # effective cap (degraded clamp applied)
    "streaming",
    "reason",               # reject: admission-control reason
    # -- admit / splice / evict ------------------------------------------
    "slot",
    "admit_seq",            # eviction-age order stamp
    "replay_tokens",        # tokens skipped on re-admission / eviction
    "spliced_tokens",       # prefix-cache splice length
    "shared_pages",         # pages shared from the cache
    "cow_pages",            # copy-on-write tail copies
    "host_reload_pages",    # host-tier pages re-uploaded for the splice
    "victim_request_id",    # evict: whose pages were taken
    # -- step -------------------------------------------------------------
    "dispatch",             # prefill | decode | ragged | spec | fused
                            # | fused_spec
    "rows",
    "live_slots",
    "accepted_tokens",
    "free_pages",
    "fused_k",              # megastep: logical steps in this dispatch
    "fused_j",              # megastep: this entry's index within it
                            # (0..fused_k-1; absent on K=1 dispatches)
    # -- degraded / fault / restart ---------------------------------------
    "mode",                 # degraded-mode ladder level
    "site",                 # fault-point site name
    "fires",                # cumulative firings at that site
    "restarts",             # supervisor restart count
    "requeued",             # in-flight requests requeued by the restart
    # -- finish -----------------------------------------------------------
    "status",               # ok | error | cancelled
    "finish_reason",
    "error_kind",
    "completion_tokens",
    "reply_sha256",         # reply TEXT bytes fingerprint
    "tokens_sha256",        # emitted token-id stream fingerprint
    "cost",                 # the deterministic cost-ledger subset
)


# ---------------------------------------------------------------------------
# Quantile helpers (shared by the loadgen report, the serving-endpoint
# CI gate, and tests — one implementation of the bucket math)
# ---------------------------------------------------------------------------


def histogram_quantile(q: float, buckets: tuple[float, ...] | list[float],
                       counts: list[int],
                       total: int | None = None) -> float:
    """Quantile from a cumulative-`le` histogram (Prometheus shape).

    `buckets` are the finite upper bounds in ascending order; `counts`
    the CUMULATIVE observation count at each bound (the `_bucket`
    series); `total` the +Inf count (defaults to the last cumulative
    count). Linear interpolation inside the covering bucket, with the
    first bucket's lower edge at 0; ranks past the last finite bound
    clamp to that bound (the Prometheus `histogram_quantile`
    convention). Returns NaN for an empty histogram."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"q must be in [0, 1], got {q}")
    n = total if total is not None else (counts[-1] if counts else 0)
    if n <= 0 or not buckets:
        return float("nan")
    rank = q * n
    prev_bound, prev_count = 0.0, 0
    for b, c in zip(buckets, counts):
        if c >= rank and c > prev_count:
            frac = (rank - prev_count) / (c - prev_count)
            return prev_bound + (float(b) - prev_bound) * frac
        prev_bound, prev_count = float(b), c
    return float(buckets[-1])


def parse_prom_histogram(
    text: str, family: str
) -> tuple[list[float], list[int], int, float] | None:
    """Extract one UNLABELED histogram family from a Prometheus text
    exposition: (finite bounds, cumulative counts, total count, sum).
    Returns None when the family has no bucket lines. Feed the result
    to `histogram_quantile` (two scrapes subtract element-wise for a
    windowed quantile)."""
    import re

    bounds: list[float] = []
    counts: list[int] = []
    total = 0
    for m in re.finditer(
        rf'^{re.escape(family)}_bucket\{{le="([^"]+)"\}} (\d+)$',
        text, re.M,
    ):
        le, c = m.group(1), int(m.group(2))
        if le == "+Inf":
            total = c
        else:
            bounds.append(float(le))
            counts.append(c)
    if not bounds and total == 0:
        return None
    s = 0.0
    if sm := re.search(
        rf"^{re.escape(family)}_sum ([0-9.eE+-]+)$", text, re.M
    ):
        s = float(sm.group(1))
    return bounds, counts, total, s


def inject_exposition_label(text: str, label: str, value: str) -> str:
    """Stamp `label="value"` onto every SAMPLE line of a Prometheus
    text exposition (comment/TYPE lines pass through untouched).

    The router's aggregation endpoint (serve/router.py
    /metrics/aggregate) uses this to re-export each replica's scrape
    with a `replica=` identity — the label plumbing that makes
    `oryx_serving_*` series from N backends distinguishable in one
    scrape without teaching every engine metric about replicas."""
    import re

    sample = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?( .+)$")
    esc = _escape_label(str(value))
    out = []
    for line in text.splitlines():
        m = sample.match(line) if line and line[0] != "#" else None
        if m is None:
            out.append(line)
            continue
        name, labels, rest = m.groups()
        if labels:
            if f'{label}="' in labels:
                # The series already carries this label (a replica's
                # own build_info): injecting again would produce a
                # duplicate label name — malformed exposition.
                out.append(line)
                continue
            labels = labels[:-1] + f',{label}="{esc}"}}'
        else:
            labels = f'{{{label}="{esc}"}}'
        out.append(name + labels + rest)
    return "\n".join(out) + ("\n" if text.endswith("\n") else "")


def sample_quantile(values: list[float], q: float) -> float:
    """Exact quantile of raw samples: linear interpolation between
    order statistics. NaN on an empty list."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"q must be in [0, 1], got {q}")
    if not values:
        return float("nan")
    vs = sorted(values)
    if len(vs) == 1:
        return float(vs[0])
    pos = q * (len(vs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(vs) - 1)
    return float(vs[lo] + (vs[hi] - vs[lo]) * (pos - lo))


# ---------------------------------------------------------------------------
# Collectors (process / runtime / device memory)
# ---------------------------------------------------------------------------


def register_process_collector(reg: Registry) -> None:
    """Process/runtime gauges in the standard Prometheus shapes (CPU
    seconds, RSS, open fds, thread count), refreshed at scrape time.
    Registered THROUGH the registry so they carry its prefix — two
    exporters on one host must not collide on bare `process_*` names."""
    import threading

    start = time.time()
    cpu = reg.gauge("process_cpu_seconds_total")
    rss = reg.gauge("process_resident_memory_bytes")
    fds = reg.gauge("process_open_fds")
    thr = reg.gauge("process_threads")
    reg.gauge("process_start_time_seconds").set(start)

    def collect() -> None:
        t = os.times()
        cpu.set(t.user + t.system)
        try:
            with open("/proc/self/statm") as f:
                rss.set(int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE"))
        except (OSError, ValueError):
            pass  # non-Linux: RSS stays at its last (or zero) value
        try:
            fds.set(len(os.listdir("/proc/self/fd")))
        except OSError:
            pass
        thr.set(threading.active_count())

    reg.register_collector(collect)


def register_device_memory_collector(reg: Registry,
                                     ttl_s: float = 1.0) -> None:
    """Device (HBM) telemetry at scrape time, shared by train and serve:

      hbm_live_bytes   — sum of nbytes over `jax.live_arrays()`: what
                         the framework is actually holding (params,
                         optimizer state, KV pages).
      hbm_bytes_in_use / hbm_peak_bytes / hbm_limit_bytes — the
                         allocator's view via `device.memory_stats()`
                         (absent on backends that don't expose it, e.g.
                         CPU and the axon remote transport — those
                         gauges then hold 0 while live_bytes stays
                         real).

    Rate-limited: `jax.live_arrays()` walks EVERY live array, so an
    aggressive scraper (or the router's aggregation fan-out) would
    otherwise pay O(live arrays) per scrape. Refreshes at most once
    per `ttl_s` (monotonic clock; 0 disables the cache) — scrapes
    inside the window re-serve the last values, which for gauges whose
    truth changes per engine step is indistinguishable from a
    marginally earlier scrape."""
    live = reg.gauge("hbm_live_bytes")
    in_use = reg.gauge("hbm_bytes_in_use")
    peak = reg.gauge("hbm_peak_bytes")
    limit = reg.gauge("hbm_limit_bytes")
    last = [float("-inf")]

    def collect() -> None:
        now = time.monotonic()
        if ttl_s and now - last[0] < ttl_s:
            return
        last[0] = now
        live.set(sum(
            getattr(a, "nbytes", 0) for a in jax.live_arrays()
        ))
        try:
            stats = jax.devices()[0].memory_stats() or {}
        except Exception:
            stats = {}
        in_use.set(stats.get("bytes_in_use", 0))
        peak.set(stats.get("peak_bytes_in_use", 0))
        limit.set(stats.get("bytes_limit", 0))

    reg.register_collector(collect)


# ---------------------------------------------------------------------------
# Serving metrics (api_server GET /metrics)
# ---------------------------------------------------------------------------


class ServingMetrics:
    """Thread-safe counters / gauges / histograms for the serving path —
    a name-on-first-touch client of `Registry`, so the scheduler and the
    window batcher never pre-register, while `GET /metrics` renders the
    shared Prometheus text exposition (device-memory gauges included)."""

    def __init__(self, prefix: str = "oryx_serving",
                 registry: Registry | None = None):
        self.prefix = prefix
        self.registry = registry or Registry(prefix=prefix)
        # Pre-created so the latency ladders render (at zero) from the
        # first scrape, before any request flowed.
        self.registry.histogram("ttft_seconds", TTFT_BUCKETS)
        self.registry.histogram(
            "time_per_output_token_seconds", PER_TOKEN_BUCKETS
        )
        register_device_memory_collector(self.registry)

    # The pass-through below is the name-on-first-touch plumbing the
    # metric-name rule checks CALLERS of — the parameterized registry
    # calls here are the abstraction, not declarations.
    def inc(self, name: str, n: float = 1,
            labels: dict[str, str] | None = None) -> None:
        if labels:
            self.registry.counter(  # oryxlint: disable=metric-name
                name, tuple(sorted(labels))
            ).labels(**labels).inc(n)
        else:
            self.registry.counter(name).inc(n)  # oryxlint: disable=metric-name

    def set_gauge(self, name: str, value: float) -> None:
        self.registry.gauge(name).set(value)  # oryxlint: disable=metric-name

    def set_info(self, name: str, labels: dict[str, str]) -> None:
        """Info metric: a gauge pinned to 1 whose labels carry build /
        deploy identity (git revision, engine, model)."""
        self.registry.info(name, labels)  # oryxlint: disable=metric-name

    def observe(self, name: str, value: float,
                buckets: tuple[float, ...] = PER_TOKEN_BUCKETS) -> None:
        # `buckets` is creation-only (first touch wins): callers pass a
        # ladder defensively without knowing whether the family exists.
        fam = self.registry.existing(name)
        if fam is None:
            fam = self.registry.histogram(name, buckets)  # oryxlint: disable=metric-name
        fam.observe(value)

    def get(self, name: str) -> float:
        """Current counter (or gauge) value, 0 when never touched."""
        return self.registry.get(name)

    def render(self) -> str:
        return self.registry.render()


# ---------------------------------------------------------------------------
# Telemetry HTTP server (/metrics + /healthz + /readyz)
# ---------------------------------------------------------------------------

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4"


class TelemetryServer:
    """Background stdlib HTTP endpoint around one Registry:

      GET /metrics — the registry's Prometheus text exposition
      GET /healthz — 200 while the process is up (liveness)
      GET /readyz  — 200/503 from `ready_check`, a zero-arg callable
                     returning (ready, reason); load balancers and CI
                     gates probe this instead of driving real traffic.

    Binds at construction (port 0 = ephemeral, see `.port`); `start()`
    begins serving on a daemon thread; `close()` shuts down."""

    def __init__(self, registry: Registry, *, host: str = "127.0.0.1",
                 port: int = 0, ready_check=None):
        import json as json_lib
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.registry = registry
        self.ready_check = ready_check

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet access log
                pass

            def _send(self, code: int, data: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/metrics":
                    self._send(200, outer.registry.render().encode(),
                               PROMETHEUS_CONTENT_TYPE)
                elif self.path == "/healthz":
                    self._send(200, b'{"status": "ok"}\n',
                               "application/json")
                elif self.path == "/readyz":
                    ready, reason = True, "ok"
                    if outer.ready_check is not None:
                        try:
                            ready, reason = outer.ready_check()
                        except Exception as e:
                            ready, reason = False, f"{type(e).__name__}: {e}"
                    body = json_lib.dumps({
                        "ready": bool(ready), "reason": reason,
                    }).encode() + b"\n"
                    self._send(200 if ready else 503, body,
                               "application/json")
                else:
                    self._send(404, b'{"error": "not found"}\n',
                               "application/json")

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self._thread = None

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def start(self) -> "TelemetryServer":
        import threading

        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name="telemetry-server",
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
