"""Metrics / logging / observability.

Reference parity: HF Trainer `report_to` (wandb/tensorboard) with loss,
LR, grad-norm, it/s, plus `rank0_print` (SURVEY.md §5 "Metrics"). Here:
a structured CSV/JSONL writer plus stdout logging on process 0, tracking
the north-star metric tokens/sec/chip; TensorBoard/wandb attach via the
same record dict if present.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from typing import Any

import jax


def rank0_print(*args, **kwargs) -> None:
    if jax.process_index() == 0:
        print(*args, **kwargs)
        sys.stdout.flush()


class MetricLogger:
    """JSONL metric stream + rolling throughput (tokens/sec/chip).

    tensorboard_dir: optional `report_to=tensorboard` parity — every
    logged record also lands as TB scalars (torch's SummaryWriter, a
    host-side dependency already in the image; gated so its absence
    only disables TB, never training).
    """

    def __init__(
        self,
        path: str | None = None,
        *,
        log_every: int = 10,
        tensorboard_dir: str | None = None,
    ):
        self.path = path
        self.log_every = log_every
        self._f = None
        self._tb = None
        if path and jax.process_index() == 0:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "a")
        if tensorboard_dir and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(tensorboard_dir)
            except Exception as e:  # TB optional: log and continue
                rank0_print(f"tensorboard disabled: {e!r}")
        self._last_time = time.perf_counter()
        self._last_step = 0
        self._tokens_since = 0
        self._skipped_since = 0

    def log_step(self, step: int, metrics: dict[str, Any]) -> None:
        self._tokens_since += int(metrics.get("num_tokens", 0))
        # Accumulated, not sampled: a skip on a step that isn't a
        # log_every multiple must still show in the next record.
        self._skipped_since += int(metrics.get("skipped", 0))
        if step % self.log_every != 0:
            return
        now = time.perf_counter()
        dt = max(now - self._last_time, 1e-9)
        nsteps = max(step - self._last_step, 1)
        n_chips = jax.device_count()
        def js(v):
            # Non-finite floats serialize as JSON null: with the skip
            # guard on, a NaN loss is a normal recurring condition, and
            # json.dumps would otherwise emit the non-RFC `NaN` token
            # that breaks strict JSONL consumers (jq, JSON.parse).
            f = float(v)
            return f if math.isfinite(f) else None

        rec = {
            "step": step,
            **{
                k: js(v) for k, v in metrics.items()
                if k not in ("num_tokens", "skipped")
            },
            "steps_per_sec": nsteps / dt,
            "tokens_per_sec_per_chip": self._tokens_since / dt / n_chips,
        }
        if "skipped" in metrics:
            rec["skipped"] = self._skipped_since
        self._last_time, self._last_step = now, step
        self._tokens_since = 0
        self._skipped_since = 0
        rank0_print(
            f"step {step}: " + " ".join(
                f"{k}={'nan' if v is None else format(v, '.4g')}"
                for k, v in rec.items() if k != "step"
            )
        )
        if self._f:
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
        if self._tb:
            for k, v in rec.items():
                if k != "step" and v is not None:
                    self._tb.add_scalar(f"train/{k}", v, step)

    def close(self) -> None:
        if self._f:
            self._f.close()
        if self._tb:
            self._tb.close()


# ---------------------------------------------------------------------------
# Serving metrics (api_server GET /metrics)
# ---------------------------------------------------------------------------


class Histogram:
    """Fixed-bucket histogram in the Prometheus cumulative-`le` shape.

    Buckets are upper bounds; +Inf is implicit (the total count). Thread
    safety comes from the owning ServingMetrics lock.
    """

    def __init__(self, buckets: tuple[float, ...]):
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * len(self.buckets)
        self.total = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        self.total += 1
        self.sum += float(value)
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1

    def render(self, name: str, out: list[str]) -> None:
        out.append(f"# TYPE {name} histogram")
        for b, c in zip(self.buckets, self.counts):
            # counts are already cumulative (observe touches every
            # bucket whose bound covers the value)
            out.append(f'{name}_bucket{{le="{b:g}"}} {c}')
        out.append(f'{name}_bucket{{le="+Inf"}} {self.total}')
        out.append(f"{name}_sum {self.sum:.17g}")
        out.append(f"{name}_count {self.total}")


def _escape_label(v: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return (
        v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


# Default latency bucket ladders (seconds): TTFT spans prefill compiles;
# per-token latency spans a decode step.
TTFT_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                30.0, 60.0)
PER_TOKEN_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5)


class ServingMetrics:
    """Thread-safe counters / gauges / histograms for the serving path,
    rendered in the Prometheus text exposition format.

    The scheduler (serve/scheduler.py) and the window batcher both feed
    one instance; `GET /metrics` renders it. Metric names are created on
    first touch so callers never pre-register."""

    def __init__(self, prefix: str = "oryx_serving"):
        import threading

        self.prefix = prefix
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        # name -> label dict, rendered as a constant-1 gauge with the
        # labels attached (the Prometheus "info metric" convention,
        # e.g. oryx_serving_build_info{revision=...,engine=...} 1).
        self._infos: dict[str, dict[str, str]] = {}
        self._hists: dict[str, Histogram] = {
            "ttft_seconds": Histogram(TTFT_BUCKETS),
            "time_per_output_token_seconds": Histogram(PER_TOKEN_BUCKETS),
        }

    def inc(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def set_info(self, name: str, labels: dict[str, str]) -> None:
        """Info metric: a gauge pinned to 1 whose labels carry build /
        deploy identity (git revision, engine, model)."""
        with self._lock:
            self._infos[name] = {str(k): str(v) for k, v in labels.items()}

    def observe(self, name: str, value: float,
                buckets: tuple[float, ...] = PER_TOKEN_BUCKETS) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(buckets)
            h.observe(value)

    def get(self, name: str) -> float:
        """Current counter (or gauge) value, 0 when never touched."""
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, 0.0)

    def render(self) -> str:
        out: list[str] = []
        with self._lock:
            # Full precision (%g rounds to 6 significant digits, which
            # quantizes large counters and hides small increments).
            for name in sorted(self._counters):
                full = f"{self.prefix}_{name}"
                out.append(f"# TYPE {full} counter")
                out.append(f"{full} {self._counters[name]:.17g}")
            for name in sorted(self._gauges):
                full = f"{self.prefix}_{name}"
                out.append(f"# TYPE {full} gauge")
                out.append(f"{full} {self._gauges[name]:.17g}")
            for name in sorted(self._infos):
                full = f"{self.prefix}_{name}"
                labels = ",".join(
                    f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(self._infos[name].items())
                )
                out.append(f"# TYPE {full} gauge")
                out.append(f"{full}{{{labels}}} 1")
            for name in sorted(self._hists):
                self._hists[name].render(f"{self.prefix}_{name}", out)
        return "\n".join(out) + "\n"
