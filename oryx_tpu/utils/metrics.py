"""Metrics / logging / observability.

Reference parity: HF Trainer `report_to` (wandb/tensorboard) with loss,
LR, grad-norm, it/s, plus `rank0_print` (SURVEY.md §5 "Metrics"). Here:
a structured CSV/JSONL writer plus stdout logging on process 0, tracking
the north-star metric tokens/sec/chip; TensorBoard/wandb attach via the
same record dict if present.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from typing import Any

import jax


def rank0_print(*args, **kwargs) -> None:
    if jax.process_index() == 0:
        print(*args, **kwargs)
        sys.stdout.flush()


class MetricLogger:
    """JSONL metric stream + rolling throughput (tokens/sec/chip).

    tensorboard_dir: optional `report_to=tensorboard` parity — every
    logged record also lands as TB scalars (torch's SummaryWriter, a
    host-side dependency already in the image; gated so its absence
    only disables TB, never training).
    """

    def __init__(
        self,
        path: str | None = None,
        *,
        log_every: int = 10,
        tensorboard_dir: str | None = None,
    ):
        self.path = path
        self.log_every = log_every
        self._f = None
        self._tb = None
        if path and jax.process_index() == 0:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "a")
        if tensorboard_dir and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(tensorboard_dir)
            except Exception as e:  # TB optional: log and continue
                rank0_print(f"tensorboard disabled: {e!r}")
        self._last_time = time.perf_counter()
        self._last_step = 0
        self._tokens_since = 0
        self._skipped_since = 0

    def log_step(self, step: int, metrics: dict[str, Any]) -> None:
        self._tokens_since += int(metrics.get("num_tokens", 0))
        # Accumulated, not sampled: a skip on a step that isn't a
        # log_every multiple must still show in the next record.
        self._skipped_since += int(metrics.get("skipped", 0))
        if step % self.log_every != 0:
            return
        now = time.perf_counter()
        dt = max(now - self._last_time, 1e-9)
        nsteps = max(step - self._last_step, 1)
        n_chips = jax.device_count()
        def js(v):
            # Non-finite floats serialize as JSON null: with the skip
            # guard on, a NaN loss is a normal recurring condition, and
            # json.dumps would otherwise emit the non-RFC `NaN` token
            # that breaks strict JSONL consumers (jq, JSON.parse).
            f = float(v)
            return f if math.isfinite(f) else None

        rec = {
            "step": step,
            **{
                k: js(v) for k, v in metrics.items()
                if k not in ("num_tokens", "skipped")
            },
            "steps_per_sec": nsteps / dt,
            "tokens_per_sec_per_chip": self._tokens_since / dt / n_chips,
        }
        if "skipped" in metrics:
            rec["skipped"] = self._skipped_since
        self._last_time, self._last_step = now, step
        self._tokens_since = 0
        self._skipped_since = 0
        rank0_print(
            f"step {step}: " + " ".join(
                f"{k}={'nan' if v is None else format(v, '.4g')}"
                for k, v in rec.items() if k != "step"
            )
        )
        if self._f:
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
        if self._tb:
            for k, v in rec.items():
                if k != "step" and v is not None:
                    self._tb.add_scalar(f"train/{k}", v, step)

    def close(self) -> None:
        if self._f:
            self._f.close()
        if self._tb:
            self._tb.close()
