"""Rolling-window anomaly detection for train and serve.

The metrics layer (utils/metrics.py) makes the system scrape-able; this
module watches the same signals ONLINE and turns "a human would have
noticed that in the dashboard" into a machine event the moment it
happens — the MegaScale/production-training posture where NaN losses,
grad-norm explosions and throughput collapses page immediately instead
of burning a day of chips.

Detectors (all host-side, O(window) memory, no deps):

  * ``nan_loss``        — loss is NaN/Inf.
  * ``loss_spike``      — loss > `loss_spike_factor` x rolling median.
  * ``grad_norm_explosion`` — grad norm > `grad_norm_factor` x rolling
                          median.
  * ``throughput_collapse`` — tokens/sec < `throughput_floor_frac` x
                          rolling median.
  * ``ttft_slo``        — serving time-to-first-token above the SLO.
  * ``queue_depth_slo`` — serving admission queue above the SLO.
  * ``entropy_collapse`` — logits entropy < `entropy_floor_frac` x
                          rolling median (the distribution collapsing
                          to a delta; utils/numerics.py probes feed it).
  * ``absmax_explosion`` — logits/grad absmax > `absmax_factor` x
                          rolling median (fp overflow on approach).
  * ``audit_drift``     — the output auditor (serve/audit.py) returned
                          a non-pass verdict; re-arms on the next pass
                          (one event per drift EPISODE, not per audit).
  * ``spec_accept_collapse`` — speculative accept-rate <
                          `spec_accept_floor_frac` x rolling baseline
                          (the drafter stopped earning its lanes);
                          default-armed whenever --speculate is set.

Every firing produces exactly one of each, not a flood: a detector is
ARMED, fires once when its condition becomes true, and re-arms only
after the condition clears (hysteresis for queue depth). A firing emits
a structured JSONL event (the ``events.jsonl`` sink), increments the
shared ``oryx_anomaly_total{kind=...}`` counter (the SAME family name in
the train and serve registries, so one alert rule covers both), and
writes one log line.

Event schema (one JSON object per line)::

    {"time_unix_s": float, "source": "train"|"serve", "kind": str,
     "message": str, "value": float, "threshold": float,
     "context": {...}}        # step / request_id / window median ...

The sink is size-capped: past ``events_max_bytes`` the file rolls to
``events.jsonl.1`` (one rotation generation kept) so a long-lived
server can never fill a disk with anomaly history.

Policy is the CALLER's job: the trainer raises ``AnomalyHalt`` under
``--on-anomaly=halt``; serving only counts and logs (a serving SLO
breach is load, not corruption — you never want the server to kill
itself over it).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import os
import statistics
import threading
import time
from collections import deque
from typing import Any

from oryx_tpu.analysis.sanitizers import named_lock
from oryx_tpu.utils.rolling_sink import RollingSink

_LOG = logging.getLogger("oryx.anomaly")


class AnomalyHalt(RuntimeError):
    """Raised by the trainer when an anomaly fires under
    --on-anomaly=halt. Carries the triggering events."""

    def __init__(self, events: list["AnomalyEvent"]):
        self.events = events
        super().__init__(
            "anomaly halt: " + "; ".join(e.message for e in events)
        )


@dataclasses.dataclass(frozen=True)
class AnomalyThresholds:
    """Detector configuration. A None SLO disables that detector; the
    statistical detectors stay silent until `min_window` finite
    observations exist (a cold start must not alert on noise)."""

    window: int = 32
    min_window: int = 8
    loss_spike_factor: float = 3.0
    grad_norm_factor: float = 10.0
    throughput_floor_frac: float = 0.3
    ttft_slo_s: float | None = None
    queue_depth_slo: int | None = None
    # Numerics sentinels (utils/numerics.py probes feed these): logits
    # entropy collapsing toward a delta, absmax heading for overflow.
    entropy_floor_frac: float = 0.25
    absmax_factor: float = 10.0
    # Speculation drift guard: accept-rate (tokens advanced per spec
    # step) falling off its own rolling baseline.
    spec_accept_floor_frac: float = 0.5


@dataclasses.dataclass(frozen=True)
class AnomalyEvent:
    kind: str
    source: str
    message: str
    value: float
    threshold: float
    time_unix_s: float
    context: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        def js(v):
            # Non-finite floats -> JSON null (same RFC-strictness rule
            # as MetricLogger: a NaN value is the NORMAL payload of a
            # nan_loss event and must not emit the non-RFC NaN token).
            if isinstance(v, float) and not math.isfinite(v):
                return None
            return v

        return {
            "time_unix_s": self.time_unix_s,
            "source": self.source,
            "kind": self.kind,
            "message": self.message,
            "value": js(self.value),
            "threshold": js(self.threshold),
            "context": {k: js(v) for k, v in self.context.items()},
        }


class _Window:
    """Rolling window of finite observations + armed flag."""

    __slots__ = ("values", "armed")

    def __init__(self, size: int):
        self.values = deque(maxlen=size)
        self.armed = True

    def median(self) -> float | None:
        if not self.values:
            return None
        return float(statistics.median(self.values))


class AnomalyMonitor:
    """One monitor per engine (trainer / scheduler), thread-safe.

    ``observe_*`` calls return the events they fired (empty list when
    healthy) so the caller can apply policy; the side effects (JSONL
    sink, counter, log line) have already happened by then."""

    def __init__(
        self,
        *,
        source: str = "train",
        thresholds: AnomalyThresholds | None = None,
        events_path: str | None = None,
        registry=None,
        keep: int = 256,
        events_max_bytes: int = 16 * 1024 * 1024,
    ):
        self.source = source
        self.thresholds = thresholds or AnomalyThresholds()
        # Size-capped rotation: a long-lived server's sink must not
        # grow without bound. When the file crosses events_max_bytes
        # the current file rolls to `<events_path>.1` (replacing the
        # previous roll) and a fresh file starts — one generation of
        # history survives, disk usage stays <= ~2x the cap. 0 disables
        # rotation.
        self.events_path = (
            os.path.abspath(events_path) if events_path else None
        )
        self.events_max_bytes = events_max_bytes
        self.recent: deque[AnomalyEvent] = deque(maxlen=keep)
        self.counts: dict[str, int] = {}
        self.total = 0
        self._lock = named_lock("anomaly._lock")
        self._sink = None
        if self.events_path:
            self._sink = RollingSink(
                self.events_path, max_bytes=events_max_bytes
            )
        # The shared cross-registry family: oryx_anomaly_total{kind=}.
        # raw_name — deliberately NOT prefixed, so the train and serve
        # exporters publish the same series name and one Prometheus
        # alert rule (`rate(oryx_anomaly_total[5m]) > 0`) covers both.
        self._counter = None
        if registry is not None:
            self._counter = registry.counter(
                "oryx_anomaly_total", ("kind",), raw_name=True
            )
        t = self.thresholds
        self._loss = _Window(t.window)
        self._gnorm = _Window(t.window)
        self._tput = _Window(t.window)
        self._entropy = _Window(t.window)
        self._absmax = _Window(t.window)
        self._spec = _Window(t.window)
        self._nan_armed = True
        self._ttft_armed = True
        self._queue_armed = True
        self._audit_armed = True

    # ---- firing ----------------------------------------------------------

    def _fire(self, kind: str, message: str, value: float,
              threshold: float, **context: Any) -> AnomalyEvent:
        ev = AnomalyEvent(
            kind=kind, source=self.source, message=message,
            value=float(value), threshold=float(threshold),
            time_unix_s=time.time(), context=context,
        )
        with self._lock:
            self.recent.append(ev)
            self.counts[kind] = self.counts.get(kind, 0) + 1
            self.total += 1
            if self._sink is not None:
                # Rotation contract (rotate AFTER the crossing write,
                # one `.1` generation) lives in utils/rolling_sink.py,
                # shared with the request-log and journal sinks.
                self._sink.write(json.dumps(ev.to_dict()))
        if self._counter is not None:
            self._counter.labels(kind=kind).inc()
        _LOG.warning("anomaly[%s] %s: %s", self.source, kind, message)
        return ev

    # ---- training signals ------------------------------------------------

    def observe_train_step(
        self,
        step: int,
        loss: float,
        grad_norm: float | None = None,
        tokens_per_sec: float | None = None,
    ) -> list[AnomalyEvent]:
        """Feed one step's host metrics; returns the anomalies fired."""
        t = self.thresholds
        out: list[AnomalyEvent] = []
        loss = float(loss)
        if not math.isfinite(loss):
            if self._nan_armed:
                self._nan_armed = False
                out.append(self._fire(
                    "nan_loss",
                    f"non-finite loss {loss} at step {step}",
                    loss, 0.0, step=step,
                ))
        else:
            self._nan_armed = True
            med = self._loss.median()
            if (
                med is not None
                and len(self._loss.values) >= t.min_window
                and loss > t.loss_spike_factor * med
            ):
                if self._loss.armed:
                    self._loss.armed = False
                    out.append(self._fire(
                        "loss_spike",
                        f"loss {loss:.4g} > {t.loss_spike_factor:g}x "
                        f"rolling median {med:.4g} at step {step}",
                        loss, t.loss_spike_factor * med,
                        step=step, window_median=med,
                    ))
            else:
                self._loss.armed = True
            self._loss.values.append(loss)
        if grad_norm is not None:
            g = float(grad_norm)
            if math.isfinite(g):
                med = self._gnorm.median()
                if (
                    med is not None and med > 0
                    and len(self._gnorm.values) >= t.min_window
                    and g > t.grad_norm_factor * med
                ):
                    if self._gnorm.armed:
                        self._gnorm.armed = False
                        out.append(self._fire(
                            "grad_norm_explosion",
                            f"grad norm {g:.4g} > {t.grad_norm_factor:g}x "
                            f"rolling median {med:.4g} at step {step}",
                            g, t.grad_norm_factor * med,
                            step=step, window_median=med,
                        ))
                else:
                    self._gnorm.armed = True
                self._gnorm.values.append(g)
        if tokens_per_sec is not None:
            tp = float(tokens_per_sec)
            if math.isfinite(tp) and tp >= 0:
                med = self._tput.median()
                if (
                    med is not None and med > 0
                    and len(self._tput.values) >= t.min_window
                    and tp < t.throughput_floor_frac * med
                ):
                    if self._tput.armed:
                        self._tput.armed = False
                        out.append(self._fire(
                            "throughput_collapse",
                            f"throughput {tp:.4g} tok/s < "
                            f"{t.throughput_floor_frac:g}x rolling median "
                            f"{med:.4g} at step {step}",
                            tp, t.throughput_floor_frac * med,
                            step=step, window_median=med,
                        ))
                    # Collapsed values do NOT enter the window: they
                    # would drag the median down and silently re-baseline
                    # the detector onto the collapsed level.
                else:
                    self._tput.armed = True
                    self._tput.values.append(tp)
        return out

    # ---- serving signals -------------------------------------------------

    def observe_ttft(self, seconds: float,
                     request_id: str = "") -> list[AnomalyEvent]:
        slo = self.thresholds.ttft_slo_s
        if slo is None:
            return []
        if seconds > slo:
            if self._ttft_armed:
                self._ttft_armed = False
                return [self._fire(
                    "ttft_slo",
                    f"TTFT {seconds:.3f}s > SLO {slo:g}s"
                    + (f" (request {request_id})" if request_id else ""),
                    seconds, slo, request_id=request_id,
                )]
        else:
            self._ttft_armed = True
        return []

    def observe_queue_depth(self, depth: int) -> list[AnomalyEvent]:
        slo = self.thresholds.queue_depth_slo
        if slo is None:
            return []
        if depth > slo:
            if self._queue_armed:
                self._queue_armed = False
                return [self._fire(
                    "queue_depth_slo",
                    f"admission queue depth {depth} > SLO {slo}",
                    depth, slo,
                )]
        elif depth <= slo // 2:
            # Hysteresis: re-arm only once the backlog has genuinely
            # drained, not on every oscillation around the line.
            self._queue_armed = True
        return []

    # ---- numerics & output-quality signals -------------------------------

    def observe_numerics(self, *, entropy: float | None = None,
                         absmax: float | None = None,
                         **context: Any) -> list[AnomalyEvent]:
        """Feed one numerics probe sample (utils/numerics.py, from the
        serving dispatch or a sampled train step). entropy_collapse
        mirrors throughput_collapse (collapsed values never enter the
        window — they would re-baseline the detector onto the collapsed
        level); absmax_explosion mirrors grad_norm_explosion (spikes
        enter the window: a new, genuinely higher plateau should stop
        firing once it IS the baseline)."""
        t = self.thresholds
        out: list[AnomalyEvent] = []
        if entropy is not None:
            e = float(entropy)
            if math.isfinite(e) and e >= 0:
                med = self._entropy.median()
                if (
                    med is not None and med > 0
                    and len(self._entropy.values) >= t.min_window
                    and e < t.entropy_floor_frac * med
                ):
                    if self._entropy.armed:
                        self._entropy.armed = False
                        out.append(self._fire(
                            "entropy_collapse",
                            f"logits entropy {e:.4g} < "
                            f"{t.entropy_floor_frac:g}x rolling median "
                            f"{med:.4g}",
                            e, t.entropy_floor_frac * med,
                            window_median=med, **context,
                        ))
                else:
                    self._entropy.armed = True
                    self._entropy.values.append(e)
        if absmax is not None:
            a = float(absmax)
            if math.isfinite(a):
                med = self._absmax.median()
                if (
                    med is not None and med > 0
                    and len(self._absmax.values) >= t.min_window
                    and a > t.absmax_factor * med
                ):
                    if self._absmax.armed:
                        self._absmax.armed = False
                        out.append(self._fire(
                            "absmax_explosion",
                            f"absmax {a:.4g} > {t.absmax_factor:g}x "
                            f"rolling median {med:.4g}",
                            a, t.absmax_factor * med,
                            window_median=med, **context,
                        ))
                else:
                    self._absmax.armed = True
                self._absmax.values.append(a)
        return out

    def observe_audit(self, verdict: str, *,
                      request_id: str = "",
                      **context: Any) -> list[AnomalyEvent]:
        """Feed one output-audit verdict (serve/audit.py). Fires
        `audit_drift` once per drift EPISODE: armed, fires on the first
        non-pass verdict, re-arms only after a pass — a systematically
        drifting path produces one page, not one per sampled request."""
        if verdict == "pass":
            self._audit_armed = True
            return []
        if not self._audit_armed:
            return []
        self._audit_armed = False
        return [self._fire(
            "audit_drift",
            f"output audit verdict {verdict!r}"
            + (f" (request {request_id})" if request_id else ""),
            1.0, 0.0, verdict=verdict, request_id=request_id, **context,
        )]

    def observe_spec_accept(self, rate: float,
                            **context: Any) -> list[AnomalyEvent]:
        """Feed one speculative step's accept signal (mean tokens a
        live slot advanced this dispatch, 1.0 = every draft rejected).
        Same collapsed-values-stay-out-of-the-window contract as
        throughput_collapse: a degraded drafter must not silently
        become its own baseline."""
        t = self.thresholds
        r = float(rate)
        if not (math.isfinite(r) and r > 0):
            return []
        med = self._spec.median()
        if (
            med is not None and med > 0
            and len(self._spec.values) >= t.min_window
            and r < t.spec_accept_floor_frac * med
        ):
            if self._spec.armed:
                self._spec.armed = False
                return [self._fire(
                    "spec_accept_collapse",
                    f"speculative accept {r:.4g} tokens/step < "
                    f"{t.spec_accept_floor_frac:g}x rolling baseline "
                    f"{med:.4g}",
                    r, t.spec_accept_floor_frac * med,
                    window_median=med, **context,
                )]
        else:
            self._spec.armed = True
            self._spec.values.append(r)
        return []

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None
