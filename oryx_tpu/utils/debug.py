"""Debug / sanitizer posture: NaN checks and numeric assertions.

Reference parity: the reference has no sanitizers (Python-level; trusts
NCCL/CUDA — SURVEY.md §5 "Race detection / sanitizers"). XLA programs are
data-race-free by construction, so the TPU equivalent is numeric
debugging: `jax_debug_nans` to fault on the first non-finite value,
`jax_disable_jit` to step through op-by-op, and chex assertions used by
the test suite.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import jax
import jax.numpy as jnp


def enable_nan_checks(enable: bool = True) -> None:
    """Fault (with a host traceback) on the first NaN/Inf produced inside
    any jitted computation. Costs a device sync per op — debug runs only."""
    jax.config.update("jax_debug_nans", enable)


@contextlib.contextmanager
def debug_mode(*, nan_checks: bool = True, disable_jit: bool = False
               ) -> Iterator[None]:
    """Scoped debug posture: NaN faulting and optional op-by-op eager
    execution (jit disabled) for bisecting a bad op."""
    prev_nans = jax.config.jax_debug_nans
    prev_jit = jax.config.jax_disable_jit
    jax.config.update("jax_debug_nans", nan_checks)
    jax.config.update("jax_disable_jit", disable_jit)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev_nans)
        jax.config.update("jax_disable_jit", prev_jit)


def assert_finite_tree(tree, name: str = "tree") -> None:
    """Host-side check that every leaf of a pytree is finite (grads/params
    after a suspect step). Raises with the offending leaf paths."""
    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.floating) and not bool(
            jnp.all(jnp.isfinite(arr))
        ):
            bad.append(jax.tree_util.keystr(path))
    if bad:
        raise FloatingPointError(f"non-finite leaves in {name}: {bad}")
