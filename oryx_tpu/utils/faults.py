"""Deterministic fault injection: named fault points, armed by spec.

Chaos engineering for the serving and training stacks: production code
plants `fault_point("site")` calls at the places that fail in real
fleets (page allocation, prefill/decode dispatch, checkpoint save and
restore, data-loader next, client sockets), and a SPEC — from the
`--faults` CLI flag or the `ORYX_FAULTS` env var — arms a subset of
them to raise, delay, or request corruption on a deterministic,
seeded schedule. Everything the suite asserts about containment
(`scripts/chaos_suite.py`) is therefore reproducible run-to-run:
same spec, same seed, same failures at the same hits.

Spec grammar (sites separated by `;`, options by `,`)::

    page_alloc_oom:p=0.05,seed=7;engine_crash:after=40
    decode_dispatch:delay=2.0,after=3;checkpoint_save:times=2

Per-site options:

  * trigger (pick one; default fires on every hit):
      - ``p=<float>``     Bernoulli per hit, from a `seed=`-ed RNG
      - ``after=<n>``     the n+1-th hit fires (count starts at 1:
                          ``after=0`` fires on the first hit)
      - ``every=<n>``     every n-th hit fires
  * ``times=<k>``         cap total firings (default: 1 for `after`,
                          unlimited otherwise)
  * ``seed=<int>``        RNG seed for `p=` (default 0)
  * action (default: raise :class:`FaultInjected`):
      - ``delay=<s>``     sleep `s` seconds instead of raising (hung
                          dispatch / slow I/O simulation)
      - ``corrupt=1``     `fault_point` returns True instead of
                          raising; the call site applies its own
                          corruption (e.g. a NaN batch)

Design rules: dependency-free (stdlib only), and ZERO overhead while
disarmed — `fault_point` is one module-global truthiness check. Call
sites that need a specific exception type pass a factory via ``exc=``
(e.g. the page allocator raises its own `OutOfPagesError`), so this
module never imports the code it tests.

Every firing increments `oryx_faults_injected_total{site=}` in any
registry bound via :func:`bind_registry` (raw-named, like
`oryx_anomaly_total`, so serve and train expose the same family) and
an internal per-site count (:func:`injected_count`) the chaos suite
reconciles against the metric.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time

_LOG = logging.getLogger("oryx.faults")

_ENV_VAR = "ORYX_FAULTS"


class FaultInjected(RuntimeError):
    """Default exception raised by an armed fault point."""

    def __init__(self, site: str):
        self.site = site
        super().__init__(f"injected fault at {site!r}")


class FaultSpecError(ValueError):
    """The fault spec string does not parse."""


class _Site:
    """Armed state of one fault site (guarded by the module lock)."""

    __slots__ = (
        "name", "p", "after", "every", "times", "delay", "corrupt",
        "rng", "hits", "fired",
    )

    def __init__(self, name: str, *, p: float | None, after: int | None,
                 every: int | None, times: int | None, seed: int,
                 delay: float | None, corrupt: bool):
        self.name = name
        self.p = p
        self.after = after
        self.every = every
        # `after` defaults to a single firing: "crash once at hit N,
        # then recover" is the scenario it exists for.
        self.times = times if times is not None else (
            1 if after is not None else None
        )
        self.delay = delay
        self.corrupt = corrupt
        self.rng = random.Random(seed)
        self.hits = 0
        self.fired = 0

    def should_fire(self) -> bool:
        self.hits += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.after is not None:
            if self.hits <= self.after:
                return False
        elif self.every is not None:
            if self.hits % self.every:
                return False
        elif self.p is not None:
            if self.rng.random() >= self.p:
                return False
        self.fired += 1
        return True


def parse_spec(spec: str) -> dict[str, dict[str, float]]:
    """Parse a fault spec into {site: options}; raises FaultSpecError
    with the offending fragment on malformed input (a bad --faults flag
    should fail at startup, never silently disarm a scenario)."""
    out: dict[str, dict[str, float]] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        site, _, opts = part.partition(":")
        site = site.strip()
        if not site or not site.replace("_", "").isalnum():
            raise FaultSpecError(f"bad fault site name {site!r} in {part!r}")
        kv: dict[str, float] = {}
        for opt in opts.split(","):
            opt = opt.strip()
            if not opt:
                continue
            key, eq, val = opt.partition("=")
            key = key.strip()
            if not eq or key not in (
                "p", "seed", "after", "every", "times", "delay", "corrupt"
            ):
                raise FaultSpecError(
                    f"bad fault option {opt!r} for site {site!r} "
                    "(known: p, seed, after, every, times, delay, corrupt)"
                )
            try:
                kv[key] = float(val)
            except ValueError:
                raise FaultSpecError(
                    f"non-numeric value in {opt!r} for site {site!r}"
                ) from None
        if kv.get("p") is not None and not 0.0 <= kv["p"] <= 1.0:
            raise FaultSpecError(
                f"p must be in [0, 1], got {kv['p']} for site {site!r}"
            )
        if site in out:
            raise FaultSpecError(f"site {site!r} appears twice in spec")
        out[site] = kv
    return out


# Module state: `_SITES` is None while disarmed. `_ARMED` is the single
# global the hot path reads — fault_point costs one dict-is-None check
# per call when nothing is configured.
_LOCK = threading.Lock()
_SITES: dict[str, _Site] | None = None
_ARMED = False
_REGISTRIES: list = []  # bound metric registries (weakly-owned)
# Firing observers: fn(site, fired_count) called OUTSIDE the module
# lock on every firing. The decision journal (serve/journal.py) records
# fault firings through this hook; the seeded schedule makes the stream
# of (site, count) pairs reproducible run-to-run, which is what lets
# the replay harness assert fault-for-fault equality.
_OBSERVERS: list = []


def configure(spec: str | None) -> None:
    """Arm the registry from a spec string; None/'' disarms. Resets all
    hit/fired counts (each scenario starts from a clean schedule)."""
    global _SITES, _ARMED
    with _LOCK:
        if not spec:
            _SITES = None
            _ARMED = False
            return
        parsed = parse_spec(spec)
        sites: dict[str, _Site] = {}
        for name, kv in parsed.items():
            sites[name] = _Site(
                name,
                p=kv.get("p"),
                after=int(kv["after"]) if "after" in kv else None,
                every=int(kv["every"]) if "every" in kv else None,
                times=int(kv["times"]) if "times" in kv else None,
                seed=int(kv.get("seed", 0)),
                delay=kv.get("delay"),
                corrupt=bool(kv.get("corrupt", 0)),
            )
        _SITES = sites
        _ARMED = True
        _LOG.warning("fault injection ARMED: %s", spec)


def configure_from_env() -> bool:
    """Arm from $ORYX_FAULTS when set; returns whether armed. Called
    by the trainer CLI (train/cli.py); the API server reads the same
    env var through its --faults fallback. Never called at import (a
    library import must not arm faults as a side effect)."""
    spec = os.environ.get(_ENV_VAR)
    if spec:
        configure(spec)
    return armed()


def reset() -> None:
    """Disarm and clear counts (test isolation)."""
    global _SITES, _ARMED
    with _LOCK:
        _SITES = None
        _ARMED = False
        _REGISTRIES.clear()
        _OBSERVERS.clear()


def armed() -> bool:
    return _ARMED


def bind_registry(registry) -> None:
    """Publish firings as `oryx_faults_injected_total{site=}` in this
    registry (raw-named: serve and train expose the same family). Safe
    to call disarmed; idempotent per registry."""
    with _LOCK:
        if registry not in _REGISTRIES:
            # Declare the family now so the ladder renders (at zero)
            # before the first firing.
            registry.counter(
                "oryx_faults_injected_total", ("site",), raw_name=True
            )
            _REGISTRIES.append(registry)


def add_observer(fn) -> None:
    """Register `fn(site, fired_count)` to run on every firing (after
    the counters, outside the module lock). Cleared by reset(); safe to
    call disarmed; idempotent per observer."""
    with _LOCK:
        if fn not in _OBSERVERS:
            _OBSERVERS.append(fn)


def remove_observer(fn) -> None:
    with _LOCK:
        if fn in _OBSERVERS:
            _OBSERVERS.remove(fn)


def injected_count(site: str | None = None) -> int:
    """Total firings (optionally one site's) since configure()."""
    with _LOCK:
        if _SITES is None:
            return 0
        if site is not None:
            s = _SITES.get(site)
            return s.fired if s is not None else 0
        return sum(s.fired for s in _SITES.values())


def fault_point(site: str, *, exc=None) -> bool:
    """One named fault site. Disarmed: returns False at the cost of a
    single global read. Armed and scheduled to fire: sleeps (`delay=`),
    returns True (`corrupt=1` — the caller applies the corruption), or
    raises `exc()` (default :class:`FaultInjected`)."""
    if not _ARMED:
        return False
    with _LOCK:
        assert _SITES is not None
        s = _SITES.get(site)
        if s is None or not s.should_fire():
            return False
        delay, corrupt = s.delay, s.corrupt
        registries = list(_REGISTRIES)
        observers = list(_OBSERVERS)
        fired = s.fired
    for reg in registries:
        reg.counter(
            "oryx_faults_injected_total", ("site",), raw_name=True
        ).labels(site=site).inc()
    for fn in observers:
        fn(site, fired)
    _LOG.warning("fault injected at %r (%s)", site,
                 "delay" if delay is not None
                 else "corrupt" if corrupt else "raise")
    if delay is not None:
        # A hung operation, not a failed one: the caller proceeds
        # normally after the stall (False = "do not corrupt").
        time.sleep(delay)
        return False
    if corrupt:
        return True
    raise (exc() if exc is not None else FaultInjected(site))
