"""Analytic model-FLOPs accounting shared by bench.py and the trainer
telemetry exporter (train/telemetry.py).

One definition of "model FLOPs" so the MFU a benchmark prints and the
MFU the trainer exports at /metrics can never drift apart: the standard
6*N FLOPs per token (fwd 2N + bwd 4N matmul work) for the decoder and
the ViT, plus the attention matmuls (QK^T and PV, fwd 2+2 flops/elem,
bwd 2x). Remat recompute is deliberately NOT counted — recompute is
overhead, not useful work, and counting it would let a worse remat
policy inflate MFU.
"""

from __future__ import annotations

# Peak dense bf16 FLOPs/s per chip kind (public spec sheets). Substring
# match against device_kind.lower(); ordered so the more specific tag
# wins (v5p before v5).
PEAK_FLOPS = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
)


def chip_peak_flops(device_kind: str) -> float | None:
    """Peak dense bf16 FLOPs/s for a device kind string, None when
    unknown (CPU, exotic backends) — callers must then skip MFU rather
    than fake it."""
    kl = (device_kind or "").lower()
    for tag, f in PEAK_FLOPS:
        if tag in kl:
            return f
    return None


def count_llm_params(c) -> int:
    """Parameter count of an LLMConfig-shaped decoder (embeddings
    included)."""
    h, i, v, d = c.hidden_size, c.intermediate_size, c.vocab_size, c.head_dim
    qo = h * c.num_heads * d * 2
    kv = h * c.num_kv_heads * d * 2
    bias = (c.num_heads + 2 * c.num_kv_heads) * d if c.attention_bias else 0
    mlp = 3 * h * i
    per_layer = qo + kv + bias + mlp + 2 * h
    embeds = v * h * (1 if c.tie_word_embeddings else 2)
    return c.num_layers * per_layer + embeds + h


def train_step_flops(
    cfg,
    n_llm_params: int,
    *,
    batch: int,
    seq_len: int,
    patch_tokens: int,
) -> float:
    """Model FLOPs for one SFT step over a [batch, seq_len] token batch
    with `patch_tokens` packed visual patches through the vision tower.

    Dense-matmul dominated: 6*N_dense per token for the decoder (the
    embedding gather excluded, lm_head included), 6*N_vit per patch for
    the tower, plus quadratic attention matmul FLOPs for both.
    """
    lc, vc = cfg.llm, cfg.vision
    tok = float(batch * seq_len)
    # Decoder dense matmuls (exclude the embedding gather, include lm_head).
    n_dense = n_llm_params - lc.vocab_size * lc.hidden_size
    f = 6.0 * n_dense * tok
    # Decoder attention: per layer fwd 4*T^2*heads*d flops (QK+PV), x3 bwd.
    f += 12.0 * lc.num_layers * batch * seq_len * seq_len \
        * lc.num_heads * lc.head_dim
    # Vision tower over the packed patch buffer.
    P = float(patch_tokens)
    n_vit = vc.num_layers * (
        4 * vc.hidden_size * vc.num_heads * vc.head_dim
        + 2 * vc.hidden_size * vc.intermediate_size
    ) + (vc.patch_size**2 * 3) * vc.hidden_size
    f += 6.0 * n_vit * P
    f += 12.0 * vc.num_layers * P * P * vc.num_heads * vc.head_dim
    return f
