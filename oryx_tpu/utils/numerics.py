"""Numerics sentinels: tensor-stat probes for serving and training.

The observability planes so far watch *where time and memory go* (PR 12
traces, PR 13 page/device observatory); this module watches *what the
model computes*. Two halves:

  * **In-dispatch logit probes** (`init_logit_stats` /
    `accumulate_logit_stats` / `finalize_logit_stats`): a tiny
    fixed-shape accumulator that rides INSIDE an existing jitted engine
    step (models/generate.paged_decode_chunk / paged_ragged_step under
    `numerics=True`) — finite fraction, absmax, rms, softmax entropy,
    top-1 margin over the step's live decode rows. The stats are a [6]
    float32 extra OUTPUT of the same dispatch: zero additional
    dispatches, token streams untouched (the probe reads the logits the
    sampler already computed), and the `numerics` flag is a STATIC
    argument, so arming it adds exactly one more stable compiled
    program per shape class — recompile-watchdog-clean.
  * **Tree probes for the trainer** (`tree_absmax` /
    `stacked_layer_absmax`): grad/activation absmax — whole-tree and
    per-stacked-layer — computed inside `train_step_fn` under the same
    static `numerics` flag and returned through the step's metrics
    dict.

Both feed the raw-named ``oryx_numerics_*`` metric families (the same
series names from the train and serve registries, like
``oryx_anomaly_total``) and the utils/anomaly.py sentinels
(`entropy_collapse`, `absmax_explosion`): a logits distribution
collapsing to a delta function or an activation/grad blowing up pages
the moment it happens instead of surfacing as a bad eval days later.

Dependency-light: jax + numpy only, no engine imports (the scheduler
and trainer import THIS module, never the reverse).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# Order of the scalar slots in the [6] accumulator / the finalized
# dict. `finite_frac` is a fraction in [0, 1]; `absmax` is a max (not a
# mean) across every observed row; the rest are per-row means.
NUMERICS_STAT_KEYS = (
    "rows", "finite_frac", "absmax", "rms", "entropy", "top1_margin",
)

# The raw-named gauge families the probes feed (one list so serve,
# train, docs and the CI family assertions agree; the `oryx_` prefix
# is part of the name — raw_name=True, shared across registries).
NUMERICS_GAUGES = (
    "oryx_numerics_logits_finite_frac",
    "oryx_numerics_logits_absmax",
    "oryx_numerics_logits_rms",
    "oryx_numerics_logits_entropy",
    "oryx_numerics_logits_top1_margin",
)


def init_logit_stats() -> jnp.ndarray:
    """Fresh accumulator: [rows, finite_sum, absmax, rms_sum,
    entropy_sum, margin_sum] in float32 (sums are over rows; the
    finalizer divides)."""
    return jnp.zeros((len(NUMERICS_STAT_KEYS),), jnp.float32)


def accumulate_logit_stats(
    acc: jnp.ndarray,  # [6] float32 (init_logit_stats)
    logits: jnp.ndarray,  # [S, V]
    live: jnp.ndarray,  # [S] bool — rows that really decoded this step
) -> jnp.ndarray:
    """Fold one step's live-row logit stats into the accumulator
    (traced; rides inside the engine step's scan). Dead rows contribute
    nothing — their logits are frozen filler and would poison every
    mean. Non-finite values are sanitized to 0 INSIDE each reduction so
    one NaN row reports a finite_frac < 1 instead of NaN-ing the whole
    accumulator (the probe must survive the exact corruption it
    exists to detect)."""
    x = logits.astype(jnp.float32)
    finite = jnp.isfinite(x)
    safe = jnp.where(finite, x, 0.0)
    w = live.astype(jnp.float32)  # [S]
    rows = jnp.sum(w)
    finite_frac = jnp.mean(finite.astype(jnp.float32), axis=-1)  # [S]
    absmax_row = jnp.max(jnp.abs(safe), axis=-1)  # [S]
    rms_row = jnp.sqrt(jnp.mean(safe * safe, axis=-1))  # [S]
    # Entropy/margin on the sanitized logits: the softmax of a NaN row
    # is meaningless either way, and finite_frac already flags it.
    p = jax.nn.softmax(safe, axis=-1)
    ent_row = -jnp.sum(
        p * jnp.log(jnp.maximum(p, jnp.finfo(jnp.float32).tiny)), axis=-1
    )
    top2 = jax.lax.top_k(safe, 2)[0]  # [S, 2]
    margin_row = top2[:, 0] - top2[:, 1]
    return acc + jnp.stack([
        rows,
        jnp.sum(w * finite_frac),
        # absmax is a MAX, not a sum: keep the running max in its slot
        # (acc slot 2 minus itself plus the new max = new max).
        jnp.maximum(jnp.max(jnp.where(live, absmax_row, 0.0)), acc[2])
        - acc[2],
        jnp.sum(w * rms_row),
        jnp.sum(w * ent_row),
        jnp.sum(w * margin_row),
    ])


def finalize_logit_stats(acc: Any) -> dict[str, float] | None:
    """Host-side: the accumulator (device or numpy) -> a stat dict
    keyed by NUMERICS_STAT_KEYS. None when no live row was observed
    (a prefill-only or idle dispatch has nothing to report)."""
    a = np.asarray(acc, np.float64)
    rows = float(a[0])
    if rows <= 0:
        return None
    return {
        "rows": rows,
        "finite_frac": float(a[1] / rows),
        "absmax": float(a[2]),
        "rms": float(a[3] / rows),
        "entropy": float(a[4] / rows),
        "top1_margin": float(a[5] / rows),
    }


# ---------------------------------------------------------------------------
# Tree probes (trainer grads / activations)
# ---------------------------------------------------------------------------


def tree_absmax(tree: Any) -> jnp.ndarray:
    """Scalar absmax over every leaf of a pytree (traced — rides inside
    the jitted train step). Empty tree -> 0."""
    leaves = [
        jnp.max(jnp.abs(leaf.astype(jnp.float32)))
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating)
    ]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.max(jnp.stack(leaves))


def stacked_layer_absmax(layers: Any) -> jnp.ndarray | None:
    """Per-layer absmax over a STACKED-layer subtree (every leaf
    carries the [L, ...] leading scan axis, the qwen2 decoder layout):
    reduces each leaf over its non-leading axes and maxes across
    leaves -> [L] float32. None when the subtree has no stacked float
    leaves (e.g. LoRA-frozen trees with scalars mixed in)."""
    per_leaf = []
    L = None
    for leaf in jax.tree_util.tree_leaves(layers):
        if not (
            hasattr(leaf, "dtype")
            and jnp.issubdtype(leaf.dtype, jnp.floating)
            and getattr(leaf, "ndim", 0) >= 2
        ):
            continue
        if L is None:
            L = leaf.shape[0]
        if leaf.shape[0] != L:
            continue  # not on the shared stacked axis
        x = jnp.abs(leaf.astype(jnp.float32))
        per_leaf.append(jnp.max(x.reshape(L, -1), axis=-1))
    if not per_leaf:
        return None
    return jnp.max(jnp.stack(per_leaf), axis=0)


def is_finite(value: Any) -> bool:
    try:
        return math.isfinite(float(value))
    except (TypeError, ValueError):
        return False
