"""Span tracing, request flight recorder, and stall watchdog.

The metrics layer (utils/metrics.py) answers "how is the system doing
on average"; this module answers "why was THIS request slow" and "what
was the system doing when it stalled" — the per-request/per-step
attribution loop the TPU-serving literature treats as the primary
iteration tool (PAPERS.md: per-phase latency attribution; decode-step
device time is where scheduler decisions pay off or don't).

Three pieces, all dependency-free stdlib:

  * ``Trace`` / ``Tracer`` — a thread-safe span tracer. A Trace is one
    request (serving) or one step (training): a flat append-only list
    of ``Span``s with parent indices, timed on a perf_counter clock
    anchored to wall nanoseconds at import so span windows are directly
    comparable to xplane device timestamps (utils/xplane.py). Exports
    as Chrome trace-event JSON (loads in Perfetto / chrome://tracing)
    and as structured JSONL.
  * a bounded in-memory **flight recorder** — the Tracer keeps the last
    N traces (in-flight and finished); ``GET /debug/requests`` serves
    its summaries and ``GET /debug/trace?id=`` one span tree.
  * ``StallWatchdog`` — a daemon thread that dumps every Python thread
    stack plus the flight-recorder tail to stderr when no unit of
    progress (decode chunk / train step) completes within a deadline.
    Exactly one dump per stall: re-armed by the next ``beat()``.

Context propagation: ``activate(trace)`` binds a trace to the current
context (``contextvars``, so it follows async tasks and is isolated
per thread); the module-level ``span(...)`` / ``add_complete(...)``
helpers then record into whichever trace is active and no-op when none
is — library code (serve/pipeline.py) adds spans without ever holding
a tracer reference.
"""

from __future__ import annotations

import contextlib
import contextvars
import io
import json
import re
import sys
import threading
import time
import traceback
import uuid
from collections import deque
from typing import Any, Iterable, Iterator

from oryx_tpu.analysis.sanitizers import named_lock

# perf_counter anchored to the wall clock once at import: spans get the
# monotonicity of perf_counter AND absolute unix-ns starts comparable
# across processes and to xplane device timestamps.
_WALL_ANCHOR_NS = time.time_ns()
_PERF_ANCHOR = time.perf_counter()


def now_ns() -> int:
    """Monotonic unix-epoch nanoseconds (perf_counter past the anchor)."""
    return _WALL_ANCHOR_NS + int(
        (time.perf_counter() - _PERF_ANCHOR) * 1e9
    )


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


# Client-supplied request ids (X-Request-Id) are honored end-to-end —
# but they land in log lines, file names adjacent surfaces and debug
# URLs, so they are validated, never trusted: short, printable,
# URL/label-safe. Anything else falls back to a minted id.
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


def sanitize_request_id(raw: str | None) -> str | None:
    """The client-supplied id when it is safe to honor, else None
    (caller mints). Strips surrounding whitespace; 1-64 chars of
    [A-Za-z0-9._-] starting alphanumeric."""
    if not raw:
        return None
    raw = raw.strip()
    return raw if _REQUEST_ID_RE.match(raw) else None


class Span:
    """One timed region. ``dur_ns`` is None while the span is open;
    ``parent`` indexes the owning Trace's span list (None = root)."""

    __slots__ = ("name", "start_ns", "dur_ns", "parent", "args")

    def __init__(self, name: str, start_ns: int,
                 parent: int | None = None,
                 args: dict[str, Any] | None = None):
        self.name = name
        self.start_ns = start_ns
        self.dur_ns: int | None = None
        self.parent = parent
        self.args = args or None

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name, "start_ns": self.start_ns,
            "dur_ns": self.dur_ns, "parent": self.parent,
        }
        if self.args:
            d["args"] = self.args
        return d


class Trace:
    """Span tree for ONE request / train step.

    Spans are appended by the owning thread; readers (debug endpoints,
    the watchdog) take snapshots under ``_lock``, so a trace can be
    serialized mid-flight without torn state.
    """

    def __init__(self, kind: str, label: str = "",
                 id: str | None = None):
        self.id = id or new_request_id()
        self.kind = kind
        self.label = label
        self.created_ns = now_ns()
        self.end_ns: int | None = None
        self.meta: dict[str, Any] = {}
        self.done = False
        # Writers (owner thread) and readers (debug endpoints, the
        # watchdog) both touch the span list; oryxlint holds every
        # access to the lock.
        self.spans: list[Span] = []  # guarded-by: _lock
        self._stack: list[int] = []  # open-span indices # guarded-by: _lock
        self._lock = named_lock("trace._lock")

    # ---- recording -------------------------------------------------------

    def begin(self, name: str, **args) -> int:
        """Open a span (child of the innermost open span); returns a
        handle for ``end``. For spans that outlive one scope — e.g. the
        scheduler's queue_wait, opened in submit() and closed at
        admission."""
        with self._lock:
            parent = self._stack[-1] if self._stack else None
            self.spans.append(Span(name, now_ns(), parent, args))
            idx = len(self.spans) - 1
            self._stack.append(idx)
            return idx

    def end(self, handle: int) -> None:
        with self._lock:
            span = self.spans[handle]
            if span.dur_ns is None:
                span.dur_ns = max(0, now_ns() - span.start_ns)
            if handle in self._stack:
                self._stack.remove(handle)

    @contextlib.contextmanager
    def span(self, name: str, **args) -> Iterator[Span]:
        h = self.begin(name, **args)
        # Resolve the handle under the lock (surfaced by the oryxlint
        # lock-discipline self-application: an index into the mutable
        # span list must not be chased while another thread appends).
        with self._lock:
            sp = self.spans[h]
        try:
            yield sp
        finally:
            self.end(h)

    def add_complete(self, name: str, start_ns: int,
                     dur_ns: int | None = None, **args) -> None:
        """Record an already-elapsed region (e.g. a device chunk whose
        window is only known after the dispatch returns)."""
        with self._lock:
            parent = self._stack[-1] if self._stack else None
            s = Span(name, start_ns, parent, args)
            s.dur_ns = (
                max(0, now_ns() - start_ns) if dur_ns is None
                else max(0, int(dur_ns))
            )
            self.spans.append(s)

    def event(self, name: str, **args) -> None:
        """Instant (zero-duration) marker, e.g. an eviction."""
        self.add_complete(name, now_ns(), 0, **args)

    def annotate(self, **meta) -> None:
        """Merge metadata into the trace without closing it (finish()
        also merges; this is for annotations known mid-flight, e.g.
        the router parent-span id a routed request carries). Under the
        lock like every other meta writer, so a concurrent summary()
        never reads a half-updated dict."""
        with self._lock:
            self.meta.update(meta)

    def finish(self, **meta) -> None:
        """Close the trace: any still-open spans end now."""
        with self._lock:
            t = now_ns()
            for idx in self._stack:
                if self.spans[idx].dur_ns is None:
                    self.spans[idx].dur_ns = max(
                        0, t - self.spans[idx].start_ns
                    )
            self._stack.clear()
            self.meta.update(meta)
            self.end_ns = t
            self.done = True

    def span_seconds(self) -> dict[str, float]:
        """Total recorded duration per span NAME, in seconds (open
        spans count up to now). The scheduler's cost ledger reads its
        queue/prefill/decode wall-time attribution from here instead of
        keeping parallel stopwatches."""
        t = now_ns()
        with self._lock:
            out: dict[str, float] = {}
            for s in self.spans:
                d = s.dur_ns if s.dur_ns is not None \
                    else max(0, t - s.start_ns)
                out[s.name] = out.get(s.name, 0.0) + d / 1e9
            return out

    # ---- serialization ---------------------------------------------------

    def summary(self) -> dict[str, Any]:
        with self._lock:
            end = self.end_ns or now_ns()
            return {
                "id": self.id, "kind": self.kind, "label": self.label,
                "created_unix_s": self.created_ns / 1e9,
                "duration_ms": (end - self.created_ns) / 1e6,
                "done": self.done,
                "num_spans": len(self.spans),
                "meta": dict(self.meta),
            }

    def to_dict(self) -> dict[str, Any]:
        with self._lock:
            spans = [s.to_dict() for s in self.spans]
        out = self.summary()
        out["spans"] = spans
        return out

    def chrome_events(self, tid: int = 0) -> list[dict[str, Any]]:
        """Chrome trace-event "X" (complete) events — open spans are
        drawn up to now. ts/dur are microseconds (the format's unit)."""
        t_now = now_ns()
        with self._lock:
            snap = [
                (s.name, s.start_ns,
                 s.dur_ns if s.dur_ns is not None
                 else max(0, t_now - s.start_ns),
                 s.args)
                for s in self.spans
            ]
        events: list[dict[str, Any]] = [{
            "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
            "args": {"name": f"{self.kind} {self.id} {self.label}".strip()},
        }]
        for name, start, dur, args in snap:
            ev: dict[str, Any] = {
                "name": name, "cat": self.kind, "ph": "X",
                "ts": start / 1e3, "dur": dur / 1e3,
                "pid": 0, "tid": tid,
            }
            if args:
                ev["args"] = args
            events.append(ev)
        return events


class Tracer:
    """Trace factory + bounded flight recorder of the last N traces.

    One Tracer per engine (scheduler, window batcher, trainer) or one
    shared — traces register at creation so in-flight work is visible
    in ``/debug/requests`` before it completes."""

    def __init__(self, capacity: int = 256):
        # Clamp: capacity 0 would make the eviction pop index an empty
        # deque on the very first start_trace (and a recorder that
        # records nothing has no disable semantics worth supporting).
        self.capacity = max(1, capacity)
        self._lock = named_lock("tracer._lock")
        self._traces: deque[Trace] = deque(maxlen=self.capacity)  # guarded-by: _lock
        self._by_id: dict[str, Trace] = {}  # guarded-by: _lock

    def start_trace(self, kind: str, label: str = "",
                    id: str | None = None) -> Trace:
        """New registered trace. A caller-supplied `id` (an honored
        client X-Request-Id) is dropped in favor of a minted one when
        the recorder still holds that id — checked and registered
        under ONE lock hold, so two concurrent requests carrying the
        same id can never both claim it (an id names one trace)."""
        with self._lock:
            if id is not None and id in self._by_id:
                id = None  # collision: mint instead
            tr = Trace(kind, label, id=id)
            if len(self._traces) == self.capacity:
                evicted = self._traces[0]
                self._by_id.pop(evicted.id, None)
            self._traces.append(tr)
            self._by_id[tr.id] = tr
        return tr

    def get(self, id: str) -> Trace | None:
        with self._lock:
            return self._by_id.get(id)

    def traces(self) -> list[Trace]:
        with self._lock:
            return list(self._traces)

    def snapshot(self) -> list[dict[str, Any]]:
        """Newest-first summaries (the /debug/requests body)."""
        return [t.summary() for t in reversed(self.traces())]

    def chrome_trace(
        self, traces: Iterable[Trace] | None = None
    ) -> dict[str, Any]:
        """Perfetto/chrome://tracing-loadable JSON object. Each trace
        gets its own tid track."""
        events: list[dict[str, Any]] = []
        for tid, tr in enumerate(traces or self.traces()):
            events.extend(tr.chrome_events(tid=tid))
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write_jsonl(self, path: str) -> int:
        """Append every recorded trace as one JSON object per line;
        returns the number written. The post-hoc xplane join
        (scripts/capture_trace.py) reads this format back."""
        traces = self.traces()
        with open(path, "a") as f:
            for tr in traces:
                f.write(json.dumps(tr.to_dict()) + "\n")
        return len(traces)


# ---------------------------------------------------------------------------
# Context propagation
# ---------------------------------------------------------------------------

_active: contextvars.ContextVar[Trace | None] = contextvars.ContextVar(
    "oryx_active_trace", default=None
)


@contextlib.contextmanager
def activate(trace: Trace | None) -> Iterator[Trace | None]:
    """Bind `trace` as the current context's active trace; the
    module-level span helpers below record into it. contextvars keep
    the binding per-thread/per-task, so concurrent requests never see
    each other's traces."""
    token = _active.set(trace)
    try:
        yield trace
    finally:
        _active.reset(token)


def current() -> Trace | None:
    return _active.get()


@contextlib.contextmanager
def span(name: str, **args) -> Iterator[None]:
    """Span on the context-active trace; no-op when none is active —
    library code adds spans unconditionally and pays nothing outside a
    traced request."""
    tr = _active.get()
    if tr is None:
        yield None
        return
    with tr.span(name, **args):
        yield None


def add_complete(name: str, start_ns: int, dur_ns: int | None = None,
                 **args) -> None:
    tr = _active.get()
    if tr is not None:
        tr.add_complete(name, start_ns, dur_ns, **args)


def event(name: str, **args) -> None:
    tr = _active.get()
    if tr is not None:
        tr.event(name, **args)


# ---------------------------------------------------------------------------
# Post-hoc span <-> xplane join helpers
# ---------------------------------------------------------------------------


def windows_from_traces(
    traces: Iterable[dict[str, Any]], span_name: str = "decode_chunk"
) -> list[tuple[str, int, int]]:
    """Flight-recorder JSONL/`to_dict` records → (label, start_ns,
    end_ns) windows for `span_name` spans, the input shape
    utils/xplane.attribute_device_time expects. Labels are
    ``<trace-id>:<span-name>[<ordinal>]``."""
    windows: list[tuple[str, int, int]] = []
    for rec in traces:
        n = 0
        for s in rec.get("spans", []):
            if s.get("name") != span_name or s.get("dur_ns") is None:
                continue
            windows.append((
                f"{rec.get('id', '?')}:{span_name}[{n}]",
                int(s["start_ns"]),
                int(s["start_ns"]) + int(s["dur_ns"]),
            ))
            n += 1
    return windows


def windows_from_jsonl(
    path: str, span_name: str = "decode_chunk"
) -> list[tuple[str, int, int]]:
    with open(path) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    return windows_from_traces(recs, span_name)


# ---------------------------------------------------------------------------
# Stall watchdog
# ---------------------------------------------------------------------------


class StallWatchdog:
    """Daemon thread that dumps all Python thread stacks + the flight
    recorder tail to `out` when no ``beat()`` arrives within
    `deadline_s` while work is in flight (``set_active(True)``).

    Exactly ONE dump per stall: after dumping, the watchdog holds fire
    until the next beat re-arms it — a wedged device program produces a
    single actionable report, not a log flood."""

    def __init__(self, tracer: Tracer | None, deadline_s: float,
                 *, name: str = "oryx", tail: int = 8, out=None):
        self.tracer = tracer
        self.deadline_s = float(deadline_s)
        self.name = name
        self.tail = tail
        self.out = out  # None => sys.stderr resolved at dump time
        self.dumps = 0
        self._last_beat = time.perf_counter()  # guarded-by: _lock
        self._active = False  # guarded-by: _lock
        self._armed = True  # guarded-by: _lock
        self._lock = named_lock("watchdog._lock")
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"stall-watchdog-{name}", daemon=True
        )

    def start(self) -> "StallWatchdog":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)

    def beat(self) -> None:
        """A unit of progress (decode chunk / train step) completed."""
        with self._lock:
            self._last_beat = time.perf_counter()
            self._armed = True

    def set_active(self, active: bool) -> None:
        """Only in-flight work can stall; an idle engine never dumps."""
        with self._lock:
            if active and not self._active:
                self._last_beat = time.perf_counter()
                self._armed = True
            self._active = active

    def stalled(self) -> bool:
        """True while in-flight work has gone `deadline_s` without a
        beat — the /readyz signal (a stalled engine must stop taking
        load-balancer traffic even though the process is alive)."""
        with self._lock:
            return (
                self._active
                and time.perf_counter() - self._last_beat > self.deadline_s
            )

    def _run(self) -> None:
        interval = max(0.01, min(self.deadline_s / 4, 1.0))
        while not self._stop.wait(interval):
            with self._lock:
                stalled = (
                    self._active and self._armed
                    and time.perf_counter() - self._last_beat
                    > self.deadline_s
                )
                if stalled:
                    self._armed = False  # one dump per stall
            if stalled:
                self.dump()

    def dump(self) -> None:
        """Thread stacks + recorder tail. Built in a buffer and written
        in one call so concurrent stderr writers can't interleave."""
        buf = io.StringIO()
        buf.write(
            f"\n==== STALL WATCHDOG [{self.name}]: no progress beat in "
            f"{self.deadline_s:g}s ====\n"
        )
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in frames.items():
            buf.write(
                f"\n-- thread {names.get(ident, '?')} ({ident}) --\n"
            )
            buf.write("".join(traceback.format_stack(frame)))
        if self.tracer is not None:
            buf.write(
                f"\n-- flight recorder tail (last {self.tail}) --\n"
            )
            for rec in self.tracer.traces()[-self.tail:]:
                buf.write(json.dumps(rec.to_dict()) + "\n")
        buf.write(f"==== END STALL DUMP [{self.name}] ====\n")
        out = self.out or sys.stderr
        out.write(buf.getvalue())
        try:
            out.flush()
        # fault-boundary: a closed/broken sink must not turn the stall
        # dump itself into a second crash
        except Exception:
            pass
        self.dumps += 1
