"""Checkpoint / resume via orbax (async, sharded-native).

Reference parity: HF Trainer `save_steps` checkpoints + DeepSpeed ZeRO
per-rank partitioned state + `zero_to_fp32.py` consolidation +
`safe_save_model_for_hf_trainer` / projector-only partial saves
(SURVEY.md §5 "Checkpoint / resume"). Orbax writes sharded arrays
natively, so there is no consolidation step; interop with reference
checkpoints goes through models/import_hf (safetensors import/export).
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

Params = dict[str, Any]


class CheckpointManager:
    """Async step-numbered checkpoints with retention, plus resume."""

    def __init__(self, directory: str, *, max_to_keep: int = 3) -> None:
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True,
                enable_async_checkpointing=True,
            ),
            # Register the handler up front so `item_metadata` works on a
            # fresh manager (without it, metadata() returns None until a
            # save has happened in-process).
            item_handlers=ocp.StandardCheckpointHandler(),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Async-save a pytree (TrainState or bare params)."""
        return self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )

    def restore(self, state_like: Any = None, step: int | None = None) -> Any:
        """Restore into the structure/shardings of `state_like` (an
        abstract or concrete pytree of the same shape). With state_like=None,
        restores the checkpoint's own saved structure (host numpy)."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        if state_like is None:
            return self._mgr.restore(step)
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(state_like)
        )

    def restore_partial(self, target: Any, step: int | None = None) -> Any:
        """Restore only the non-PLACEHOLDER leaves of `target` (abstract
        arrays, optionally with shardings so shards land straight on
        their devices); `ocp.PLACEHOLDER` leaves are never read from
        disk. The Standard handler rejects placeholders, so this goes
        through the underlying PyTree layer."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        path = os.path.join(self.directory, str(step), "default")

        # PyTreeRestore takes placement from restore_args, NOT from the
        # target's ShapeDtypeStruct.sharding (which it silently ignores,
        # restoring with the save-time sharding instead).
        def rargs(leaf):
            if isinstance(leaf, jax.ShapeDtypeStruct):
                return ocp.ArrayRestoreArgs(
                    sharding=leaf.sharding, global_shape=leaf.shape,
                    dtype=leaf.dtype,
                )
            return ocp.RestoreArgs()

        return ocp.PyTreeCheckpointer().restore(
            path,
            args=ocp.args.PyTreeRestore(
                item=target, restore_args=jax.tree.map(rargs, target)
            ),
        )

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def metadata(self, step: int | None = None) -> Any:
        """Saved-tree structure as abstract leaves (shape/dtype, no data)
        — the basis for building a sharded restore target without ever
        materializing the checkpoint on host."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        meta = self._mgr.item_metadata(step)
        if meta is None:
            raise RuntimeError(
                f"no item metadata for step {step} in {self.directory}"
            )
        return meta

    def wait(self) -> None:
        """Block until pending async saves finish."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def _npz_path(path: str) -> str:
    """np.savez appends '.npz' when missing but np.load does not; normalize
    so save/load round-trip on the same argument."""
    return path if path.endswith(".npz") else path + ".npz"


def save_projector_only(path: str, params: Params) -> None:
    """Stage-1-style partial checkpoint: compressor/projector weights only
    (the reference's `mm_projector.bin` analog), as a flat npz."""
    flat = jax.tree_util.tree_flatten_with_path(params["compressor"])[0]
    arrays = {
        "/".join(p.key for p in path): np.asarray(leaf)
        for path, leaf in flat
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    np.savez(_npz_path(path), **arrays)


def load_projector_only(path: str, params: Params) -> Params:
    """Merge a projector-only checkpoint into a full param tree (the
    reference's `pretrain_mm_mlp_adapter` load path, SURVEY.md §3.3)."""
    data = np.load(_npz_path(path))
    comp = params["compressor"]

    def fill(path, leaf):
        key = "/".join(p.key for p in path)
        if key in data:
            arr = data[key]
            assert arr.shape == leaf.shape, (key, arr.shape, leaf.shape)
            return jax.numpy.asarray(arr, dtype=leaf.dtype)
        return leaf

    new_comp = jax.tree_util.tree_map_with_path(fill, comp)
    return {**params, "compressor": new_comp}
