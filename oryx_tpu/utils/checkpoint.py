"""Checkpoint / resume via orbax (async, sharded-native).

Reference parity: HF Trainer `save_steps` checkpoints + DeepSpeed ZeRO
per-rank partitioned state + `zero_to_fp32.py` consolidation +
`safe_save_model_for_hf_trainer` / projector-only partial saves
(SURVEY.md §5 "Checkpoint / resume"). Orbax writes sharded arrays
natively, so there is no consolidation step; interop with reference
checkpoints goes through models/import_hf (safetensors import/export).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable

import jax
import numpy as np
import orbax.checkpoint as ocp

from oryx_tpu.utils import faults
from oryx_tpu.utils.retry import BackoffPolicy, retry_call

Params = dict[str, Any]


class _Placeholder:
    """Stand-in for `ocp.PLACEHOLDER` on orbax versions that predate it
    (restore_partial then falls back to a full host restore and drops
    these leaves afterwards)."""

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return "PLACEHOLDER"


# Leaf marker for restore_partial targets: "do not restore this leaf".
# Native on new orbax; emulated on old (see restore_partial).
PLACEHOLDER = getattr(ocp, "PLACEHOLDER", None)
_NATIVE_PLACEHOLDER = PLACEHOLDER is not None
if PLACEHOLDER is None:
    PLACEHOLDER = _Placeholder()


class CheckpointManager:
    """Async step-numbered checkpoints with retention, plus resume.

    Failure containment: orbax itself writes each step into a temp
    location and renames on finalize (a torn write can never become
    "latest"); on top of that, `save` retries transient failures with
    bounded exponential backoff (`save_retry`) — and a persistent
    failure still fails loudly after the budget. Scope honestly: the
    retry wraps the SYNCHRONOUS phase of an async save (directory
    prep, serialization enqueue — and the `checkpoint_save` chaos
    site). A failure in the background commit thread surfaces on the
    NEXT save()/wait() call; the next save runs under this same
    policy, so a transient background failure costs at most the one
    torn checkpoint (which temp+rename keeps out of "latest") rather
    than the run. `save_retries` counts the recoveries for
    telemetry/tests. `sleep` is injectable so tests pin the schedule
    without wall-clock waits."""

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_retry: BackoffPolicy | None = None,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        self.directory = os.path.abspath(directory)
        self._save_retry = save_retry or BackoffPolicy(
            retries=3, base_s=0.5, factor=2.0, max_s=10.0
        )
        self._sleep = sleep
        self.save_retries = 0
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True,
                enable_async_checkpointing=True,
            ),
            # Register the handler up front so `item_metadata` works on a
            # fresh manager (without it, metadata() returns None until a
            # save has happened in-process).
            item_handlers=ocp.StandardCheckpointHandler(),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Async-save a pytree (TrainState or bare params), retrying
        transient failures per `save_retry`. The chaos site
        `checkpoint_save` injects failures HERE, before orbax runs, so
        the retry schedule is exercised deterministically."""

        def attempt() -> bool:
            faults.fault_point("checkpoint_save")
            return self._mgr.save(
                step, args=ocp.args.StandardSave(state), force=force
            )

        def count(_attempt, _exc, _delay) -> None:
            self.save_retries += 1

        return retry_call(
            attempt, policy=self._save_retry, retry_on=(Exception,),
            sleep=self._sleep, on_retry=count,
            describe=f"checkpoint save (step {step})",
        )

    def restore(self, state_like: Any = None, step: int | None = None) -> Any:
        """Restore into the structure/shardings of `state_like` (an
        abstract or concrete pytree of the same shape). With state_like=None,
        restores the checkpoint's own saved structure (host numpy)."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        # Chaos site: restore failure — the resume path's caller (or
        # the operator) decides whether an older step is acceptable.
        faults.fault_point("checkpoint_restore")
        if state_like is None:
            return self._mgr.restore(step)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(state_like)
        )
        # Older orbax restores on the default device and silently drops
        # the template's shardings; re-place any leaf whose sharding
        # disagrees with the target (no-op copy-wise on new orbax).
        # Single-device template leaves (step counters, optax schedule
        # counts) were UNCOMMITTED arrays; orbax hands back committed
        # ones, which jit refuses to mix with multi-device args —
        # rebuild those uncommitted.
        from jax.sharding import SingleDeviceSharding

        def place(t, r):
            want = getattr(t, "sharding", None)
            if want is None or not hasattr(r, "sharding"):
                return r
            if r.sharding != want:
                return jax.device_put(r, want)
            if isinstance(want, SingleDeviceSharding):
                return jax.numpy.asarray(np.asarray(r))
            return r

        return jax.tree.map(place, state_like, restored)

    def restore_partial(self, target: Any, step: int | None = None) -> Any:
        """Restore only the non-PLACEHOLDER leaves of `target` (abstract
        arrays, optionally with shardings so shards land straight on
        their devices); `ocp.PLACEHOLDER` leaves are never read from
        disk. The Standard handler rejects placeholders, so this goes
        through the underlying PyTree layer."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        path = os.path.join(self.directory, str(step), "default")

        if not _NATIVE_PLACEHOLDER:
            # orbax predates PLACEHOLDER: restore the whole tree on the
            # host, then place only the wanted leaves per the target's
            # sharding/dtype; placeholder positions pass the restored
            # value through (callers drop those subtrees anyway).
            full = ocp.PyTreeCheckpointer().restore(path)

            def place(t, r):
                if isinstance(t, jax.ShapeDtypeStruct):
                    return jax.device_put(
                        np.asarray(r).astype(t.dtype), t.sharding
                    )
                return r

            return jax.tree.map(place, target, full)

        # PyTreeRestore takes placement from restore_args, NOT from the
        # target's ShapeDtypeStruct.sharding (which it silently ignores,
        # restoring with the save-time sharding instead).
        def rargs(leaf):
            if isinstance(leaf, jax.ShapeDtypeStruct):
                return ocp.ArrayRestoreArgs(
                    sharding=leaf.sharding, global_shape=leaf.shape,
                    dtype=leaf.dtype,
                )
            return ocp.RestoreArgs()

        return ocp.PyTreeCheckpointer().restore(
            path,
            args=ocp.args.PyTreeRestore(
                item=target, restore_args=jax.tree.map(rargs, target)
            ),
        )

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def metadata(self, step: int | None = None) -> Any:
        """Saved-tree structure as abstract leaves (shape/dtype, no data)
        — the basis for building a sharded restore target without ever
        materializing the checkpoint on host."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        meta = self._mgr.item_metadata(step)
        if meta is None:
            raise RuntimeError(
                f"no item metadata for step {step} in {self.directory}"
            )
        return meta

    def wait(self) -> None:
        """Block until pending async saves finish."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def _npz_path(path: str) -> str:
    """np.savez appends '.npz' when missing but np.load does not; normalize
    so save/load round-trip on the same argument."""
    return path if path.endswith(".npz") else path + ".npz"


def save_projector_only(path: str, params: Params) -> None:
    """Stage-1-style partial checkpoint: compressor/projector weights only
    (the reference's `mm_projector.bin` analog), as a flat npz.

    Atomic: written to a temp sibling then os.replace'd, so a crash
    mid-write can never leave a torn file at the published path."""
    flat = jax.tree_util.tree_flatten_with_path(params["compressor"])[0]
    arrays = {
        "/".join(p.key for p in path): np.asarray(leaf)
        for path, leaf in flat
    }
    final = _npz_path(path)
    os.makedirs(os.path.dirname(os.path.abspath(final)), exist_ok=True)
    tmp = final + ".tmp"
    try:
        np.savez(tmp, **arrays)
        # np.savez may append .npz to the temp name too; normalize.
        written = tmp if os.path.exists(tmp) else _npz_path(tmp)
        os.replace(written, final)
    finally:
        for leftover in (tmp, _npz_path(tmp)):
            if os.path.exists(leftover):
                os.remove(leftover)


def load_projector_only(path: str, params: Params) -> Params:
    """Merge a projector-only checkpoint into a full param tree (the
    reference's `pretrain_mm_mlp_adapter` load path, SURVEY.md §3.3)."""
    data = np.load(_npz_path(path))
    comp = params["compressor"]

    def fill(path, leaf):
        key = "/".join(p.key for p in path)
        if key in data:
            arr = data[key]
            assert arr.shape == leaf.shape, (key, arr.shape, leaf.shape)
            return jax.numpy.asarray(arr, dtype=leaf.dtype)
        return leaf

    new_comp = jax.tree_util.tree_map_with_path(fill, comp)
    return {**params, "compressor": new_comp}
