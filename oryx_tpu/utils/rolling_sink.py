"""Size-capped append-only JSONL sink with `.1`-roll rotation.

One implementation of the rotation contract that three sinks
previously each hand-rolled — the anomaly ``events.jsonl``
(utils/anomaly.py), the wide-event ``requests.jsonl``
(utils/request_log.py) and the decision journal (serve/journal.py):

  * append one complete JSON line, then flush — the live file is never
    a torn JSONL;
  * rotate AFTER the write that crossed ``max_bytes``: the crossing
    line lands in ``<path>.1`` with its episode-mates, the fresh file
    starts empty;
  * exactly one rotation generation is kept (``os.replace`` clobbers
    the previous ``.1``), so disk usage stays <= ~2x the cap;
  * ``max_bytes=0`` disables rotation (unbounded append).

An optional ``prologue`` line (the decision journal's header) is
re-written at the top of every fresh file — including the one a
rotation opens — so a consumer holding only the live file always sees
the sink's self-describing first line.

Thread safety is the CALLER's job: every owner already serializes its
writes under its own leaf lock (``anomaly._lock``,
``request_log._lock``, ``journal._lock``), and pushing a second lock
down here would just double the acquisitions on those hot paths.
"""

from __future__ import annotations

import os


class RollingSink:
    """Append-only line sink over ``path``, rolling to ``<path>.1``
    after the write that crosses ``max_bytes``."""

    def __init__(self, path: str, *, max_bytes: int = 16 * 1024 * 1024,
                 prologue: str | None = None):
        self.path = os.path.abspath(path)
        self.max_bytes = max_bytes
        self._prologue = prologue
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "a")
        if self._prologue is not None and self._f.tell() == 0:
            self._write_line(self._prologue)

    def set_prologue(self, line: str) -> None:
        """Install (or replace) the fresh-file first line. Written
        immediately when the live file is still empty — the owner may
        only learn its header after constructing the sink."""
        self._prologue = line
        if self._f is not None and self._f.tell() == 0:
            self._write_line(line)

    def _write_line(self, line: str) -> None:
        self._f.write(line + "\n")
        self._f.flush()

    def write(self, line: str) -> None:
        """Append one complete JSON line and flush; rotate after the
        crossing write (the live file is always whole JSONL, the
        crossing line keeps its episode-mates in ``.1``)."""
        if self._f is None:
            raise ValueError(f"sink {self.path} is closed")
        self._write_line(line)
        if self.max_bytes and self._f.tell() >= self.max_bytes:
            self._f.close()
            os.replace(self.path, self.path + ".1")
            self._f = open(self.path, "a")
            if self._prologue is not None:
                self._write_line(self._prologue)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
