"""Minimal pure-python reader for XLA profiler traces (xplane.pb).

`jax.profiler.trace` writes TensorBoard-format `*.xplane.pb` files, but
the usual consumers (tensorboard_plugin_profile + a matching tensorflow
pywrap build) are version-locked and broken on this box. The XSpace
schema is stable and tiny, and protobuf wire format skips unknown
fields, so this module decodes just the subset an op-level summary
needs: planes -> lines -> events, with per-plane event-metadata names.

Field numbers follow tsl/profiler/protobuf/xplane.proto:
  XSpace.planes=1; XPlane.name=2 .lines=3 .event_metadata=4(map)
  .stat_metadata=5(map) .stats=6;
  XLine.name=2 .timestamp_ns=3 .events=4;
  XEvent.metadata_id=1 .offset_ps=2 .duration_ps=3;
  XEventMetadata(map value).id=1 .name=2 .display_name=4;
  XStat.metadata_id=1 .uint64_value=3 .int64_value=4.

Timestamps: an event's absolute start is line.timestamp_ns +
event.offset_ps/1000 (unix-epoch ns, the same clock utils/trace.py
anchors host spans to) — which is what lets attribute_device_time()
join device op time back onto host-side decode-chunk/step spans.

No dependency on tensorflow or protobuf. Used by
scripts/capture_trace.py for the on-chip "profile, iterate" loop.
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass, field


def _varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message buffer.
    value: int for varint/fixed, bytes for length-delimited."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:  # varint
            val, i = _varint(buf, i)
        elif wtype == 2:  # length-delimited
            ln, i = _varint(buf, i)
            if i + ln > n:  # short slice = mid-write truncation
                raise ValueError("length-delimited field runs off buffer")
            val = buf[i:i + ln]
            i += ln
        elif wtype == 5:  # 32-bit
            if i + 4 > n:
                raise ValueError("fixed32 field runs off buffer")
            val = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        elif wtype == 1:  # 64-bit
            if i + 8 > n:
                raise ValueError("fixed64 field runs off buffer")
            val = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        else:  # groups (3/4) do not occur in proto3 xplane
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


@dataclass
class Event:
    name: str
    duration_ps: int
    offset_ps: int = 0  # start offset within the owning line


@dataclass
class Line:
    name: str
    events: list[Event] = field(default_factory=list)
    timestamp_ns: int = 0  # line start (unix epoch)


@dataclass
class Plane:
    name: str
    lines: list[Line] = field(default_factory=list)
    # Integer-valued plane stats (e.g. the "Task Environment" plane's
    # profile_start_time / profile_stop_time in epoch ns — the clock
    # anchor the span<->device join needs).
    stats: dict[str, int] = field(default_factory=dict)


def _parse_event(buf: bytes) -> tuple[int, int, int]:
    meta_id = dur = offset = 0
    for fnum, _, val in _fields(buf):
        if fnum == 1:
            meta_id = val
        elif fnum == 2:
            offset = val
        elif fnum == 3:
            dur = val
    return meta_id, dur, offset


def _parse_metadata_entry(buf: bytes) -> tuple[int, str]:
    """One map<int64, XEventMetadata> entry → (id, best name)."""
    key, name, display = 0, "", ""
    for fnum, _, val in _fields(buf):
        if fnum == 1:
            key = val
        elif fnum == 2:
            for f2, _, v2 in _fields(val):
                if f2 == 2:
                    name = v2.decode("utf-8", "replace")
                elif f2 == 4:
                    display = v2.decode("utf-8", "replace")
    return key, display or name


def _parse_line(buf: bytes, names: dict[int, str]) -> Line:
    line = Line(name="")
    for fnum, _, val in _fields(buf):
        if fnum == 2:
            line.name = val.decode("utf-8", "replace")
        elif fnum == 3:
            line.timestamp_ns = val
        elif fnum == 4:
            meta_id, dur, offset = _parse_event(val)
            line.events.append(
                Event(names.get(meta_id, str(meta_id)), dur, offset)
            )
    return line


def _parse_plane(buf: bytes) -> Plane:
    name = ""
    metadata: dict[int, str] = {}
    stat_names: dict[int, str] = {}
    stat_vals: list[tuple[int, int]] = []  # (metadata_id, int value)
    line_bufs: list[bytes] = []
    for fnum, _, val in _fields(buf):
        if fnum == 2:
            name = val.decode("utf-8", "replace")
        elif fnum == 3:
            line_bufs.append(val)
        elif fnum == 4:
            k, v = _parse_metadata_entry(val)
            metadata[k] = v
        elif fnum == 5:
            k, v = _parse_metadata_entry(val)
            stat_names[k] = v
        elif fnum == 6:
            mid = ival = None
            for f2, _, v2 in _fields(val):
                if f2 == 1:
                    mid = v2
                elif f2 in (3, 4):  # uint64 / int64 value
                    ival = v2
            if mid is not None and ival is not None:
                stat_vals.append((mid, ival))
    return Plane(
        name,
        [_parse_line(b, metadata) for b in line_bufs],
        {
            stat_names[mid]: v for mid, v in stat_vals
            if mid in stat_names
        },
    )


def parse_xspace(path: str) -> list[Plane]:
    """Raises ValueError (not IndexError) on a truncated/corrupt file —
    e.g. a profiler killed mid-write by a step timeout."""
    with open(path, "rb") as f:
        buf = f.read()
    try:
        return [
            _parse_plane(val) for fnum, _, val in _fields(buf) if fnum == 1
        ]
    except (IndexError, ValueError) as e:
        raise ValueError(f"truncated/corrupt xplane file: {path}") from e


def find_xplane_files(trace_dir: str) -> list[str]:
    return sorted(
        glob.glob(
            os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
        )
    )


def op_totals(
    planes: list[Plane],
    plane_filter: str = "",
    line_filter: str = "",
) -> dict[str, int]:
    """Total duration_ps per event name over matching planes/lines.

    TPU device planes are named like '/device:TPU:0' with 'XLA Ops' /
    'XLA Modules' lines; pass plane_filter='TPU', line_filter='Ops' for
    a per-op device-time profile."""
    totals: dict[str, int] = {}
    for plane in planes:
        if plane_filter and plane_filter not in plane.name:
            continue
        for line in plane.lines:
            if line_filter and line_filter not in line.name:
                continue
            for ev in line.events:
                totals[ev.name] = totals.get(ev.name, 0) + ev.duration_ps
    return totals


def top_ops(
    planes: list[Plane], n: int = 25, **kw
) -> list[tuple[str, float]]:
    """Top-n (name, total_ms) by duration."""
    totals = op_totals(planes, **kw)
    ranked = sorted(totals.items(), key=lambda kv: -kv[1])[:n]
    return [(name, ps / 1e9) for name, ps in ranked]


# Line timestamps below this are clearly not unix-epoch ns (10**15 ns
# past 1970 is mid-2001; any real wall clock is ~1.7e18): such a
# timeline is relative to some process-local clock and needs aligning.
_EPOCH_THRESHOLD_NS = 10**15


def profile_start_time_ns(planes: list[Plane]) -> int:
    """Epoch-ns start of the profiler session, from the "Task
    Environment" plane's stats (0 when absent). Relative line
    timestamps are offsets from this instant."""
    for plane in planes:
        if (t := plane.stats.get("profile_start_time", 0)):
            return t
    return 0


def _plane_shift_ns(plane: Plane, session_end_ns: int) -> int:
    """Fallback alignment shift for a relative-timeline plane in a
    file with no profile_start_time stat. Anchor on the trace END:
    every event a profiler session records ends at or before
    stop_trace, and the last one (thread/session-lifetime events
    included) ends AT it — so `session_end_ns - max(event end)` maps
    the plane's timeline onto the wall clock to within the stop_trace
    teardown latency (~ms)."""
    max_end = 0
    for line in plane.lines:
        for ev in line.events:
            end = line.timestamp_ns + (
                ev.offset_ps + ev.duration_ps
            ) // 1000
            max_end = max(max_end, end)
    return session_end_ns - max_end


def merge_intervals(
    intervals: list[tuple[int, int]]
) -> list[tuple[int, int]]:
    """Sorted DISJOINT union of [start_ns, end_ns) intervals — busy
    time, not summed durations, so nested/overlapping events (host
    python stacks, fused op sub-events) can never count the same wall
    nanosecond twice."""
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [intervals[0]]
    for s, e in intervals[1:]:
        cs, ce = out[-1]
        if s > ce:
            out.append((s, e))
        elif e > ce:
            out[-1] = (cs, e)
    return out


def clipped_us(merged: list[tuple[int, int]], t0_ns: int,
               t1_ns: int) -> int:
    """Microseconds of already-merged intervals inside [t0, t1) — the
    per-window clip, O(len(merged)), run against one precomputed
    merge for any number of windows."""
    total = 0
    for s, e in merged:
        lo, hi = max(s, t0_ns), min(e, t1_ns)
        if hi > lo:
            total += hi - lo
    return total // 1000


def busiest_line_spans(
    planes: list[Plane],
    plane_filter: str = "",
    line_filter: str = "",
    line_exclude: str = "",
    session_end_ns: int = 0,
) -> list[tuple[int, int]]:
    """The merged busy intervals (epoch ns) of the BUSIEST matching
    line — precomputed ONCE per capture; per-window attribution is
    then a cheap clip (utils/profiling.attribute_capture runs up to
    hundreds of windows on the engine thread, so a per-window rescan
    of every event would stall the dispatch loop).

    One line = one execution stream (a TPU core's 'XLA Ops' line, a
    host thread), so the per-line interval union is genuine busy time
    and an in-window clip can never exceed the window. Taking the
    busiest line (rather than summing lines) keeps the host-event
    fallback honest — host captures carry one line per python thread
    and summing them would charge idle threads' tracer overhead as
    device time. Clock alignment follows attribute_device_time: epoch
    timestamps pass through, relative planes anchor on the file's own
    profile_start_time stat, else on session_end_ns."""
    start_anchor = profile_start_time_ns(planes)
    best: list[tuple[int, int]] = []
    best_total = 0
    for plane in planes:
        if plane_filter and plane_filter not in plane.name:
            continue
        relative = any(
            line.timestamp_ns < _EPOCH_THRESHOLD_NS
            for line in plane.lines if line.events
        )
        shift = 0
        if relative:
            shift = start_anchor or _plane_shift_ns(
                plane, session_end_ns
            )
        for line in plane.lines:
            if line_filter and line_filter not in line.name:
                continue
            if line_exclude and line_exclude in line.name:
                continue
            base = line.timestamp_ns + shift
            merged = merge_intervals([
                (base + ev.offset_ps // 1000,
                 base + (ev.offset_ps + ev.duration_ps) // 1000)
                for ev in line.events
            ])
            total = sum(e - s for s, e in merged)
            if total > best_total:
                best, best_total = merged, total
    return best


def busy_time_us(
    planes: list[Plane],
    t0_ns: int,
    t1_ns: int,
    plane_filter: str = "",
    line_filter: str = "",
    line_exclude: str = "",
    session_end_ns: int = 0,
) -> tuple[int, int]:
    """(busy_us inside [t0_ns, t1_ns), busy_us over the whole capture)
    on the busiest matching line — the one-window convenience over
    busiest_line_spans (multi-window callers precompute the spans and
    clip per window instead)."""
    merged = busiest_line_spans(
        planes, plane_filter=plane_filter, line_filter=line_filter,
        line_exclude=line_exclude, session_end_ns=session_end_ns,
    )
    return (
        clipped_us(merged, t0_ns, t1_ns),
        sum(e - s for s, e in merged) // 1000,
    )


def chrome_trace(planes: list[Plane], limit: int = 50000) -> dict:
    """Chrome trace-event JSON from parsed planes — loads directly in
    Perfetto / chrome://tracing (the GET /debug/profile response body).
    Planes become processes, lines become threads (named via metadata
    events); timestamps are each line's own clock in microseconds.
    `limit` caps the event count so one capture can never produce an
    unbounded response; the cap is reported when it bites."""
    events: list[dict] = []
    truncated = False
    for pi, plane in enumerate(planes):
        events.append({
            "name": "process_name", "ph": "M", "pid": pi, "tid": 0,
            "args": {"name": plane.name or f"plane {pi}"},
        })
        for li, line in enumerate(plane.lines):
            events.append({
                "name": "thread_name", "ph": "M", "pid": pi, "tid": li,
                "args": {"name": line.name or f"line {li}"},
            })
            base_us = line.timestamp_ns / 1e3
            for ev in line.events:
                if len(events) >= limit:
                    truncated = True
                    break
                events.append({
                    "name": ev.name, "ph": "X",
                    "ts": base_us + ev.offset_ps / 1e6,
                    "dur": max(ev.duration_ps / 1e6, 1e-3),
                    "pid": pi, "tid": li,
                })
            if truncated:
                break
        if truncated:
            break
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "truncated": truncated,
    }


def attribute_device_time(
    planes: list[Plane],
    windows: list[tuple[str, int, int]],
    plane_filter: str = "",
    line_filter: str = "",
    session_end_ns: int = 0,
) -> dict[str, int]:
    """Attribute device-event time onto host-side span windows.

    windows: (label, start_ns, end_ns) in unix-epoch ns — e.g. the
    decode-chunk / train-step spans a utils/trace.py flight recorder
    produced (trace.windows_from_traces). Each matching device event is
    credited, by its midpoint, to the window containing it; events
    outside every window land in "_unattributed". Returns
    label -> total duration_ps. Windows with zero matching events still
    appear (value 0), so a run whose clocks don't line up reads as
    all-unattributed instead of silently empty.

    Relative (non-epoch) line timestamps are offsets from the
    profiler-session start, which the file itself records (the "Task
    Environment" plane's profile_start_time stat) — that is the
    preferred anchor. session_end_ns (wall-clock ns at
    jax.profiler.stop_trace; profiling.op_profile records it as
    OpProfile.trace_end_ns) is the fallback for writers without the
    stat: the plane's last event end is anchored at it. Epoch-stamped
    planes need no alignment.
    """
    totals: dict[str, int] = {label: 0 for label, _, _ in windows}
    totals["_unattributed"] = 0
    spans = sorted(windows, key=lambda w: w[1])
    start_anchor = profile_start_time_ns(planes)
    for plane in planes:
        if plane_filter and plane_filter not in plane.name:
            continue
        relative = any(
            line.timestamp_ns < _EPOCH_THRESHOLD_NS
            for line in plane.lines if line.events
        )
        shift = 0
        if relative:
            shift = start_anchor or _plane_shift_ns(
                plane, session_end_ns
            )
        for line in plane.lines:
            if line_filter and line_filter not in line.name:
                continue
            base = line.timestamp_ns + shift
            for ev in line.events:
                mid_ns = base + (
                    ev.offset_ps + ev.duration_ps // 2
                ) // 1000
                hits = [
                    label for label, t0, t1 in spans
                    if t0 <= mid_ns < t1
                ]
                if not hits:
                    totals["_unattributed"] += ev.duration_ps
                    continue
                # Overlapping windows split the credit: the scheduler
                # stamps one shared decode dispatch onto EVERY live
                # request, so identical windows are the normal case in
                # a live-recorder join — first-match-wins would hand
                # all device time to one request and 0 to the rest.
                share = ev.duration_ps // len(hits)
                for label in hits:
                    totals[label] += share
                totals[hits[0]] += ev.duration_ps - share * len(hits)
    return totals
