"""Minimal pure-python reader for XLA profiler traces (xplane.pb).

`jax.profiler.trace` writes TensorBoard-format `*.xplane.pb` files, but
the usual consumers (tensorboard_plugin_profile + a matching tensorflow
pywrap build) are version-locked and broken on this box. The XSpace
schema is stable and tiny, and protobuf wire format skips unknown
fields, so this module decodes just the subset an op-level summary
needs: planes -> lines -> events, with per-plane event-metadata names.

Field numbers follow tsl/profiler/protobuf/xplane.proto:
  XSpace.planes=1; XPlane.name=2 .lines=3 .event_metadata=4(map);
  XLine.name=2 .events=4; XEvent.metadata_id=1 .duration_ps=3;
  XEventMetadata(map value).id=1 .name=2 .display_name=4.

No dependency on tensorflow or protobuf. Used by
scripts/capture_trace.py for the on-chip "profile, iterate" loop.
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass, field


def _varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message buffer.
    value: int for varint/fixed, bytes for length-delimited."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:  # varint
            val, i = _varint(buf, i)
        elif wtype == 2:  # length-delimited
            ln, i = _varint(buf, i)
            if i + ln > n:  # short slice = mid-write truncation
                raise ValueError("length-delimited field runs off buffer")
            val = buf[i:i + ln]
            i += ln
        elif wtype == 5:  # 32-bit
            if i + 4 > n:
                raise ValueError("fixed32 field runs off buffer")
            val = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        elif wtype == 1:  # 64-bit
            if i + 8 > n:
                raise ValueError("fixed64 field runs off buffer")
            val = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        else:  # groups (3/4) do not occur in proto3 xplane
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


@dataclass
class Event:
    name: str
    duration_ps: int


@dataclass
class Line:
    name: str
    events: list[Event] = field(default_factory=list)


@dataclass
class Plane:
    name: str
    lines: list[Line] = field(default_factory=list)


def _parse_event(buf: bytes) -> tuple[int, int]:
    meta_id = dur = 0
    for fnum, _, val in _fields(buf):
        if fnum == 1:
            meta_id = val
        elif fnum == 3:
            dur = val
    return meta_id, dur


def _parse_metadata_entry(buf: bytes) -> tuple[int, str]:
    """One map<int64, XEventMetadata> entry → (id, best name)."""
    key, name, display = 0, "", ""
    for fnum, _, val in _fields(buf):
        if fnum == 1:
            key = val
        elif fnum == 2:
            for f2, _, v2 in _fields(val):
                if f2 == 2:
                    name = v2.decode("utf-8", "replace")
                elif f2 == 4:
                    display = v2.decode("utf-8", "replace")
    return key, display or name


def _parse_line(buf: bytes, names: dict[int, str]) -> Line:
    line = Line(name="")
    for fnum, _, val in _fields(buf):
        if fnum == 2:
            line.name = val.decode("utf-8", "replace")
        elif fnum == 4:
            meta_id, dur = _parse_event(val)
            line.events.append(Event(names.get(meta_id, str(meta_id)), dur))
    return line


def _parse_plane(buf: bytes) -> Plane:
    name = ""
    metadata: dict[int, str] = {}
    line_bufs: list[bytes] = []
    for fnum, _, val in _fields(buf):
        if fnum == 2:
            name = val.decode("utf-8", "replace")
        elif fnum == 3:
            line_bufs.append(val)
        elif fnum == 4:
            k, v = _parse_metadata_entry(val)
            metadata[k] = v
    return Plane(
        name, [_parse_line(b, metadata) for b in line_bufs]
    )


def parse_xspace(path: str) -> list[Plane]:
    """Raises ValueError (not IndexError) on a truncated/corrupt file —
    e.g. a profiler killed mid-write by a step timeout."""
    with open(path, "rb") as f:
        buf = f.read()
    try:
        return [
            _parse_plane(val) for fnum, _, val in _fields(buf) if fnum == 1
        ]
    except (IndexError, ValueError) as e:
        raise ValueError(f"truncated/corrupt xplane file: {path}") from e


def find_xplane_files(trace_dir: str) -> list[str]:
    return sorted(
        glob.glob(
            os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
        )
    )


def op_totals(
    planes: list[Plane],
    plane_filter: str = "",
    line_filter: str = "",
) -> dict[str, int]:
    """Total duration_ps per event name over matching planes/lines.

    TPU device planes are named like '/device:TPU:0' with 'XLA Ops' /
    'XLA Modules' lines; pass plane_filter='TPU', line_filter='Ops' for
    a per-op device-time profile."""
    totals: dict[str, int] = {}
    for plane in planes:
        if plane_filter and plane_filter not in plane.name:
            continue
        for line in plane.lines:
            if line_filter and line_filter not in line.name:
                continue
            for ev in line.events:
                totals[ev.name] = totals.get(ev.name, 0) + ev.duration_ps
    return totals


def top_ops(
    planes: list[Plane], n: int = 25, **kw
) -> list[tuple[str, float]]:
    """Top-n (name, total_ms) by duration."""
    totals = op_totals(planes, **kw)
    ranked = sorted(totals.items(), key=lambda kv: -kv[1])[:n]
    return [(name, ps / 1e9) for name, ps in ranked]
