"""Weight-only int8 quantization for serving.

TPU-first rationale: single-chip decode is HBM-bandwidth-bound — every
step streams the full weight set. Symmetric per-output-channel int8
halves the bytes (Oryx-7B: ~15.2 GB bf16 → ~7.6 GB, fitting a 16 GB
v5e WITH its KV cache), and XLA fuses the dequant (convert + scale
multiply) into the matmul's operand read so int8 is what crosses HBM.
The reference serves its 34B across 8 GPUs with `device_map` instead
(SURVEY.md §2 "Model builder"); this is the one-chip alternative.

`Q8Weight` is a registered pytree node that impersonates a weight array
at its use sites: `.astype(dt)` dequantizes (matmul operands), `[idx]`
gathers-then-dequantizes (embedding rows), `.T` transposes the
dequantized tensor (tied lm_head). `lax.scan` over stacked-layer params
slices its children's leading axis like any leaf, so the decoder scan
needs no changes. Training never sees Q8Weight — quantization happens
at serving load (`serve.builder.load_pipeline(quantize="int8")`).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]

# Leaves smaller than this stay in float (biases, norms, pos embeds):
# no bandwidth win, and tiny tensors are precision-sensitive.
MIN_QUANT_SIZE = 1 << 16


@jax.tree_util.register_pytree_node_class
class Q8Weight:
    """Symmetric per-output-channel int8 weight + float scale.

    q: int8 [..., in, out]; scale: [..., 1, out] (last axis = output
    channels; leading axes, e.g. the stacked-layer axis, are preserved
    so `lax.scan` can slice them)."""

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # ---- array impersonation at the weight-use sites -----------------

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):  # the LOGICAL dtype consumers see after dequant
        return self.scale.dtype

    def astype(self, dt):
        return self.q.astype(dt) * self.scale.astype(dt)

    def __getitem__(self, idx):
        # Embedding-table gather: rows out of q, then per-column scale.
        # 2-D tables share one scale row ([1, out]); stacked 3-D weights
        # must gather the MATCHING per-layer scales.
        s = self.scale[idx] if self.q.ndim > 2 else self.scale[0]
        return self.q[idx].astype(self.scale.dtype) * s

    @property
    def T(self):
        return self.astype(self.scale.dtype).T

    def __repr__(self):
        return f"Q8Weight(shape={self.q.shape}, scale={self.scale.shape})"


def quantize_array(w: jnp.ndarray) -> Q8Weight:
    """Symmetric int8 over the -2 (input) axis: one scale per output
    channel (and per leading/stacked index)."""
    w = jnp.asarray(w)
    amax = jnp.max(jnp.abs(w), axis=-2, keepdims=True)
    scale = (amax / 127.0 + jnp.finfo(jnp.float32).tiny).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return Q8Weight(q, scale)


def _should_quantize(path: tuple[str, ...], leaf) -> bool:
    name = path[-1] if path else ""
    if getattr(leaf, "ndim", 0) < 2 or leaf.size < MIN_QUANT_SIZE:
        return False
    if name == "kernel":
        return True
    # The embedding table ([V, H], the single largest tensor) — but not
    # norm weights or the interpolated pos-embed grid.
    return name == "weight" and len(path) >= 2 and path[-2] == "embed"


def quantize_params(params: Params, cast=None) -> Params:
    """Quantize every large matmul/embedding weight in a param tree;
    biases, norms and small tensors pass through `cast` (identity by
    default). One leaf is processed at a time, so quantizing a
    HOST-restored tree peaks device memory at int8-total + one float
    leaf — a 7B model quantizes ON LOAD within a 16 GB chip (a
    device-side full-precision tree would already be ~15-28 GB)."""
    cast = cast or (lambda x: x)

    def walk(node, path):
        if isinstance(node, dict):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        if _should_quantize(path, node):
            return quantize_array(node)
        return cast(node)

    return walk(params, ())


# ---------------------------------------------------------------------------
# Round-trip error statistics (the int8 / fp8 paged-KV groundwork)
# ---------------------------------------------------------------------------

# Symmetric quantization targets for the paged KV pool. int8 is the
# shipping format; fp8-e4m3 shares the SAME layout (codes + one fp32
# scale per token row, page-major) so the pool is fp8-ready by
# construction — flipping the storage dtype changes one table entry,
# not the write path, the kernels, or the COW/spill byte semantics.
KV_STORAGE_DTYPES: dict[str, tuple[Any, float]] = {
    # name -> (storage dtype, symmetric max representable magnitude)
    "int8": (jnp.int8, 127.0),
    "fp8_e4m3": (jnp.float8_e4m3fn, 448.0),
}


def kv_storage_dtype(name: str) -> tuple[Any, float]:
    """(storage dtype, qmax) for a KV quantization format name; raises
    with the known names on a typo."""
    try:
        return KV_STORAGE_DTYPES[name]
    except KeyError:
        raise ValueError(
            f"unknown KV storage dtype {name!r} "
            f"(known: {sorted(KV_STORAGE_DTYPES)})"
        ) from None


def dequantize(q: jnp.ndarray, scale: jnp.ndarray,
               dtype=jnp.float32) -> jnp.ndarray:
    """Invert `quantize_array`'s mapping (or any symmetric int8/fp8 +
    scale pair, e.g. the per-page KV quantizer's output)."""
    return q.astype(dtype) * scale.astype(dtype)


def _encode(x: jnp.ndarray, scale: jnp.ndarray, fmt: str) -> jnp.ndarray:
    """Symmetric encode of pre-scaled rows: int8 rounds-and-clips,
    fp8 relies on the hardware format's own rounding (the cast). One
    helper so every quantization site in the repo maps values to codes
    identically — the byte-determinism the COW/spill planes rely on."""
    dt, qmax = kv_storage_dtype(fmt)
    y = x / scale
    if fmt == "int8":
        return jnp.clip(jnp.round(y), -qmax, qmax).astype(dt)
    return jnp.clip(y, -qmax, qmax).astype(dt)


def quantize_kv_rows(
    x: jnp.ndarray, fmt: str = "int8"
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-TOKEN-ROW symmetric quantization of packed KV rows
    [..., Hk, D]: one fp32 scale per leading index (amax over the
    trailing head × dim axes). This is the paged pool's write-side
    quantizer (ops/paged_kv.write_pages*): scale-per-row makes the
    encoding a PURE FUNCTION of the token's own K/V value, so bytes
    never depend on chunk grouping, write order, or pool history —
    which is exactly what keeps cold-vs-cached, replay, COW and
    host-spill/reload byte-identical on the quantized path (see
    docs/DESIGN.md "KV quantization & cache tiering").

    Returns (codes [...same shape], scale [...leading] fp32)."""
    xf = jnp.asarray(x).astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=(-2, -1))
    _, qmax = kv_storage_dtype(fmt)
    scale = (amax / qmax + jnp.finfo(jnp.float32).tiny).astype(jnp.float32)
    return _encode(xf, scale[..., None, None], fmt), scale


def dequantize_kv_rows(
    q: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.float32
) -> jnp.ndarray:
    """Invert `quantize_kv_rows`: codes [..., Hk, D] x scale [...]."""
    return q.astype(dtype) * scale[..., None, None].astype(dtype)


def roundtrip_error_stats(
    w: jnp.ndarray, *, axis: int = -2, fmt: str = "int8"
) -> dict[str, float]:
    """Quantize-dequantize `w` through the symmetric path of `fmt`
    (int8 or fp8_e4m3 — same API, same scale convention) and report
    the reconstruction error: max-abs and rms, absolute and relative
    to the tensor's own absmax. One call answers "is this format good
    enough for THIS tensor" — the standing spot-check ROADMAP item 3's
    quantized-KV PR gates against (and what test_quant.py pins so the
    quantizer's error envelope cannot drift silently).

    axis: the reduction axis the scale spans (-2 = per-output-channel,
    the weight path's convention)."""
    _, qmax = kv_storage_dtype(fmt)
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = amax / qmax + jnp.finfo(jnp.float32).tiny
    q = _encode(w, scale, fmt)
    err = jnp.abs(dequantize(q, scale) - w)
    overall = float(jnp.max(jnp.abs(w)))
    max_abs = float(jnp.max(err))
    rms = float(jnp.sqrt(jnp.mean(err * err)))
    return {
        "max_abs_err": max_abs,
        "rms_err": rms,
        "rel_max_abs_err": max_abs / overall if overall else 0.0,
        "rel_rms_err": rms / overall if overall else 0.0,
    }


def page_roundtrip_error(
    pages: jnp.ndarray,  # [P, page, Hk, D] one layer's K or V pool
    *, fmt: str = "int8",
) -> dict[str, jnp.ndarray]:
    """PER-PAGE symmetric round-trip error over a paged KV pool layer
    in format `fmt` (int8 or fp8_e4m3): one scale per page, errors
    reduced per page so the answer is a [P] vector an operator (or the
    audit plane) can rank: which resident's pages would quantization
    hurt most. Returns {"max_abs_err": [P], "rms_err": [P],
    "scale": [P]}."""
    _, qmax = kv_storage_dtype(fmt)
    x = jnp.asarray(pages, jnp.float32)
    P = x.shape[0]
    flat = x.reshape(P, -1)
    amax = jnp.max(jnp.abs(flat), axis=1)
    scale = amax / qmax + jnp.finfo(jnp.float32).tiny
    q = _encode(flat, scale[:, None], fmt)
    err = jnp.abs(q.astype(jnp.float32) * scale[:, None] - flat)
    return {
        "max_abs_err": jnp.max(err, axis=1),
        "rms_err": jnp.sqrt(jnp.mean(err * err, axis=1)),
        "scale": scale,
    }


def quantized_bytes(params: Params) -> int:
    """Total serving bytes of a (possibly quantized) param tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
