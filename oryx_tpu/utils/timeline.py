"""Per-step engine timeline: a bounded lock-free ring of step records.

The metrics layer answers "how is the system doing on average" and the
tracer answers "why was THIS request slow"; neither answers "what was
the ENGINE doing, step by step, when the loadgen knee moved". This
module is that third view — a flight data recorder for the engine
loop: one fixed-shape record per device dispatch (step wall time,
dispatch kind, packed rows, live slots, accepted tokens, queue depth,
free pages, degraded mode), kept in a bounded ring and served at
``GET /debug/timeline?n=`` (serve/api_server.py) plus snapshotted into
loadgen per-stage reports (scripts/loadgen.py). Knee diagnosis becomes
"read the timeline at the knee stage" instead of inferring engine
state from counter deltas.

Dependency-free stdlib, like utils/trace.py.

Concurrency model: the ring is single-writer (the engine thread owns
``record``; the scheduler calls it from its dispatch-accounting path)
and lock-free by design — readers (debug endpoints, loadgen) take
best-effort snapshots without ever making the engine hot path wait on
a reader. Records are immutable dicts swapped into the ring wholesale
(one reference assignment), so a reader can observe a slightly stale
ring but never a torn record. The per-kind counters are cumulative
since construction, so dispatch-kind reconciliation against
``oryx_serving_dispatches_total`` deltas works over ANY window — it
never depends on the ring being deep enough to hold the window.
"""

from __future__ import annotations

import time
from typing import Any

# The fixed record shape: every record carries exactly these keys (the
# /debug/timeline consumers and the loadgen snapshot depend on it).
STEP_RECORD_KEYS = (
    "step",             # monotone step ordinal (1-based, never wraps)
    "ts_unix_s",        # wall-clock time the dispatch COMPLETED
    "dur_s",            # step wall time (dispatch + harvest sync)
    "kind",             # ragged | spec | prefill | decode
    "rows",             # valid query rows the dispatch carried
    "live_slots",       # slots decoding during the dispatch
    "accepted_tokens",  # client-progress tokens this step (all slots)
    "queue_depth",      # admission queue depth at the step
    "free_pages",       # allocator free pages at the step
    "degraded_mode",    # degraded-ladder level at the step
    "device_us",        # device busy time inside the step window, from
                        # the sampled profiler capture bracketing this
                        # dispatch (utils/profiling.DeviceTimeSampler);
                        # null on unsampled steps
)


class StepTimeline:
    """Bounded ring of per-engine-step records (see module docstring).

    ``record`` is engine-thread-only and never blocks on readers;
    ``snapshot``/``counts_by_kind`` are safe from any thread.
    """

    def __init__(self, capacity: int = 1024):
        # Same clamp rationale as the trace flight recorder: capacity 0
        # has no useful disable semantics.
        self.capacity = max(1, int(capacity))
        self._buf: list[dict[str, Any] | None] = [None] * self.capacity
        # Monotone write counter: doubles as the step ordinal and the
        # total-steps count. Written only by the engine thread; a bare
        # int read is atomic for readers.
        self._n = 0
        # Cumulative dispatch count per kind since construction —
        # written by the engine thread only, read racily by the
        # reconciliation consumers (plain dict of ints: a reader sees
        # the value before or after one increment, never garbage).
        self._by_kind: dict[str, int] = {}

    # ---- writer (engine thread) ------------------------------------------

    def record(
        self,
        *,
        dur_s: float,
        kind: str,
        rows: int,
        live_slots: int,
        accepted_tokens: int,
        queue_depth: int,
        free_pages: int,
        degraded_mode: int,
        device_us: int | None = None,
        ts_unix_s: float | None = None,
    ) -> None:
        """Append one step record. The dict is built fresh and swapped
        into the ring in one reference assignment — readers never see a
        half-written record."""
        n = self._n + 1
        rec = {
            "step": n,
            "ts_unix_s": time.time() if ts_unix_s is None else ts_unix_s,
            "dur_s": round(float(dur_s), 6),
            "kind": kind,
            "rows": int(rows),
            "live_slots": int(live_slots),
            "accepted_tokens": int(accepted_tokens),
            "queue_depth": int(queue_depth),
            "free_pages": int(free_pages),
            "degraded_mode": int(degraded_mode),
            "device_us": None if device_us is None else int(device_us),
        }
        self._buf[(n - 1) % self.capacity] = rec
        self._by_kind[kind] = self._by_kind.get(kind, 0) + 1
        self._n = n  # publish last: a reader indexing off _n sees rec

    # ---- readers (any thread) --------------------------------------------

    @property
    def total_steps(self) -> int:
        return self._n

    def counts_by_kind(self) -> dict[str, int]:
        """Cumulative dispatch count per kind since construction.
        Deltas of this dict reconcile exactly against deltas of
        ``oryx_serving_dispatches_total{kind=}`` over the same window —
        the acceptance check scripts/check_serving_endpoints.py runs."""
        return dict(self._by_kind)

    def snapshot(self, n: int | None = None) -> list[dict[str, Any]]:
        """Newest-first copies of the last ``n`` records (all retained
        records when None). Best-effort under a concurrent writer: a
        record may be superseded between the counter read and the slot
        read, in which case the newer record is returned in its place —
        still a real, whole record."""
        end = self._n
        avail = min(end, self.capacity)
        want = avail if n is None else max(0, min(int(n), avail))
        out: list[dict[str, Any]] = []
        for i in range(want):
            rec = self._buf[(end - 1 - i) % self.capacity]
            if rec is not None:
                out.append(dict(rec))
        return out

    def to_dict(self, n: int | None = None) -> dict[str, Any]:
        """The /debug/timeline response body (minus the engine label
        the server adds)."""
        return {
            "capacity": self.capacity,
            "total_steps": self.total_steps,
            "counts_by_kind": self.counts_by_kind(),
            "records": self.snapshot(n),
        }
