"""Tracing / profiling: jax.profiler glue + per-step timing.

Reference parity: the reference has no first-class tracing — ad-hoc torch
profiler + DeepSpeed wall-clock timers / flops_profiler toggles
(SURVEY.md §5 "Tracing / profiling"). Here profiling is first-class:
Perfetto/TensorBoard traces via jax.profiler, named annotations around the
ViT / compressor / decoder phases, and a step timer that reports the
north-star metric (tokens/sec/chip) continuously.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator

import jax


def start_server(port: int = 9999) -> None:
    """Start the profiler RPC server (connect TensorBoard / xprof to it)."""
    jax.profiler.start_server(port)


@contextlib.contextmanager
def trace(logdir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a trace viewable in TensorBoard/Perfetto."""
    opts = jax.profiler.ProfileOptions()
    opts.host_tracer_level = host_tracer_level
    jax.profiler.start_trace(logdir, profiler_options=opts)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Context manager naming a region in the profiler timeline. Wrap host
    dispatch of model phases (vit / compressor / decoder / data)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Rolling wall-clock step stats: step time and tokens/sec/chip.

    Call `tick(num_tokens)` once per optimizer step AFTER the host has
    synchronized on the step's results (e.g. after device_get of metrics —
    under async dispatch an unsynced tick measures only dispatch time).
    """

    def __init__(self, window: int = 20, n_chips: int | None = None) -> None:
        self.window = window
        self.n_chips = n_chips or jax.device_count()
        self._times: list[float] = []
        self._tokens: list[int] = []
        self._last: float | None = None

    def tick(self, num_tokens: int) -> dict[str, float] | None:
        """Record a step boundary; returns rolling stats (None on the first
        tick, which only arms the timer)."""
        now = time.perf_counter()
        if self._last is None:
            self._last = now
            return None
        dt = now - self._last
        self._last = now
        self._times.append(dt)
        self._tokens.append(num_tokens)
        if len(self._times) > self.window:
            self._times.pop(0)
            self._tokens.pop(0)
        total_t = sum(self._times)
        total_tok = sum(self._tokens)
        return {
            "step_time_s": dt,
            "step_time_avg_s": total_t / len(self._times),
            "tokens_per_sec": total_tok / total_t,
            "tokens_per_sec_per_chip": total_tok / total_t / self.n_chips,
        }
