"""Tracing / profiling: jax.profiler glue + per-step timing.

Reference parity: the reference has no first-class tracing — ad-hoc torch
profiler + DeepSpeed wall-clock timers / flops_profiler toggles
(SURVEY.md §5 "Tracing / profiling"). Here profiling is first-class:
Perfetto/TensorBoard traces via jax.profiler, named annotations around the
ViT / compressor / decoder phases, and a step timer that reports the
north-star metric (tokens/sec/chip) continuously.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Iterator

import jax


def start_server(port: int = 9999) -> None:
    """Start the profiler RPC server (connect TensorBoard / xprof to it)."""
    jax.profiler.start_server(port)


@contextlib.contextmanager
def trace(logdir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a trace viewable in TensorBoard/Perfetto.

    ProfileOptions only exists on newer jax; older versions take no
    options and default to host tracing on — fall back rather than
    making every profile capture version-locked."""
    if hasattr(jax.profiler, "ProfileOptions"):
        opts = jax.profiler.ProfileOptions()
        opts.host_tracer_level = host_tracer_level
        jax.profiler.start_trace(logdir, profiler_options=opts)
    else:
        jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Context manager naming a region in the profiler timeline. Wrap host
    dispatch of model phases (vit / compressor / decoder / data)."""
    return jax.profiler.TraceAnnotation(name)


@dataclasses.dataclass
class OpProfile:
    """Result of op_profile: ranked (name, total_ms) plus provenance —
    `source` distinguishes real device op time ("tpu_xla_ops") from the
    host-event fallback ("host_fallback"), which measures python/dispatch
    and must never be mistaken for device time when optimizing."""

    top: list[tuple[str, float]]
    source: str
    xplane_path: str
    plane_names: list[str]
    # Wall-clock ns bracketing the profiler session: xplane lines may
    # stamp timestamps on a process-local clock, and
    # xplane.attribute_device_time aligns them by anchoring the last
    # event end at trace_end_ns when joining host spans to device
    # events.
    trace_start_ns: int = 0
    trace_end_ns: int = 0


def op_profile(
    fn, *args, trace_dir: str, steps: int = 3, top_n: int = 25, sync=None
) -> OpProfile:
    """Run `fn(*args)` `steps` times under a trace and return an
    OpProfile: top ops by total device time — self-contained: the
    written xplane.pb is decoded by utils/xplane.py, no TensorBoard
    tooling needed. On TPU this reads the 'XLA Ops' device lines; on CPU
    it falls back to host events (module aggregates excluded), flagged
    via `.source`.

    fn should already be compiled (call it once beforehand) — compile
    time inside the trace would swamp the profile. `sync` receives the
    last result and must block on it (default: jax.block_until_ready;
    pass a device_get-based sync over remote transports where
    block_until_ready is a no-op)."""
    from oryx_tpu.utils import trace as trace_lib
    from oryx_tpu.utils import xplane

    sync = sync or jax.block_until_ready
    with trace(trace_dir):
        t_start = trace_lib.now_ns()
        out = None
        for _ in range(steps):
            out = fn(*args)
        sync(out)
        t_end = trace_lib.now_ns()
    files = xplane.find_xplane_files(trace_dir)
    if not files:
        raise RuntimeError(f"no xplane.pb written under {trace_dir}")
    planes = xplane.parse_xspace(files[-1])
    names = [p.name for p in planes]
    device = xplane.top_ops(
        planes, n=top_n, plane_filter="TPU", line_filter="Ops"
    )
    if device:
        return OpProfile(
            device, "tpu_xla_ops", files[-1], names, t_start, t_end
        )
    host = [
        xplane.Plane(p.name, [l for l in p.lines if "Modules" not in l.name])
        for p in planes
    ]
    return OpProfile(
        xplane.top_ops(host, n=top_n), "host_fallback", files[-1], names,
        t_start, t_end,
    )


class StepTimer:
    """Rolling wall-clock step stats: step time and tokens/sec/chip.

    Call `tick(num_tokens)` once per optimizer step AFTER the host has
    synchronized on the step's results (e.g. after device_get of metrics —
    under async dispatch an unsynced tick measures only dispatch time).
    """

    def __init__(self, window: int = 20, n_chips: int | None = None) -> None:
        self.window = window
        self.n_chips = n_chips or jax.device_count()
        self._times: list[float] = []
        self._tokens: list[int] = []
        self._last: float | None = None

    def tick(self, num_tokens: int) -> dict[str, float] | None:
        """Record a step boundary; returns rolling stats (None on the first
        tick, which only arms the timer)."""
        now = time.perf_counter()
        if self._last is None:
            self._last = now
            return None
        dt = now - self._last
        self._last = now
        self._times.append(dt)
        self._tokens.append(num_tokens)
        if len(self._times) > self.window:
            self._times.pop(0)
            self._tokens.pop(0)
        total_t = sum(self._times)
        total_tok = sum(self._tokens)
        return {
            "step_time_s": dt,
            "step_time_avg_s": total_t / len(self._times),
            "tokens_per_sec": total_tok / total_t,
            "tokens_per_sec_per_chip": total_tok / total_t / self.n_chips,
        }
