"""Tracing / profiling: jax.profiler glue + per-step timing.

Reference parity: the reference has no first-class tracing — ad-hoc torch
profiler + DeepSpeed wall-clock timers / flops_profiler toggles
(SURVEY.md §5 "Tracing / profiling"). Here profiling is first-class:
Perfetto/TensorBoard traces via jax.profiler, named annotations around the
ViT / compressor / decoder phases, and a step timer that reports the
north-star metric (tokens/sec/chip) continuously.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Iterator

import jax


def start_server(port: int = 9999) -> None:
    """Start the profiler RPC server (connect TensorBoard / xprof to it)."""
    jax.profiler.start_server(port)


def _start_trace(logdir: str, *, host_tracer_level: int = 2) -> None:
    """jax.profiler.start_trace with the ProfileOptions fallback —
    newer jax takes options, older versions take none and default to
    host tracing on; one helper so every capture path (the trace()
    context manager, the continuous DeviceTimeSampler) shares it."""
    if hasattr(jax.profiler, "ProfileOptions"):
        opts = jax.profiler.ProfileOptions()
        opts.host_tracer_level = host_tracer_level
        jax.profiler.start_trace(logdir, profiler_options=opts)
    else:
        jax.profiler.start_trace(logdir)


@contextlib.contextmanager
def trace(logdir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a trace viewable in TensorBoard/Perfetto.

    ProfileOptions only exists on newer jax; older versions take no
    options and default to host tracing on — fall back rather than
    making every profile capture version-locked."""
    _start_trace(logdir, host_tracer_level=host_tracer_level)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Context manager naming a region in the profiler timeline. Wrap host
    dispatch of model phases (vit / compressor / decoder / data)."""
    return jax.profiler.TraceAnnotation(name)


@dataclasses.dataclass
class OpProfile:
    """Result of op_profile: ranked (name, total_ms) plus provenance —
    `source` distinguishes real device op time ("tpu_xla_ops") from the
    host-event fallback ("host_fallback"), which measures python/dispatch
    and must never be mistaken for device time when optimizing."""

    top: list[tuple[str, float]]
    source: str
    xplane_path: str
    plane_names: list[str]
    # Wall-clock ns bracketing the profiler session: xplane lines may
    # stamp timestamps on a process-local clock, and
    # xplane.attribute_device_time aligns them by anchoring the last
    # event end at trace_end_ns when joining host spans to device
    # events.
    trace_start_ns: int = 0
    trace_end_ns: int = 0


def op_profile(
    fn, *args, trace_dir: str, steps: int = 3, top_n: int = 25, sync=None
) -> OpProfile:
    """Run `fn(*args)` `steps` times under a trace and return an
    OpProfile: top ops by total device time — self-contained: the
    written xplane.pb is decoded by utils/xplane.py, no TensorBoard
    tooling needed. On TPU this reads the 'XLA Ops' device lines; on CPU
    it falls back to host events (module aggregates excluded), flagged
    via `.source`.

    fn should already be compiled (call it once beforehand) — compile
    time inside the trace would swamp the profile. `sync` receives the
    last result and must block on it (default: jax.block_until_ready;
    pass a device_get-based sync over remote transports where
    block_until_ready is a no-op)."""
    from oryx_tpu.utils import trace as trace_lib
    from oryx_tpu.utils import xplane

    sync = sync or jax.block_until_ready
    with trace(trace_dir):
        t_start = trace_lib.now_ns()
        out = None
        for _ in range(steps):
            out = fn(*args)
        sync(out)
        t_end = trace_lib.now_ns()
    files = xplane.find_xplane_files(trace_dir)
    if not files:
        raise RuntimeError(f"no xplane.pb written under {trace_dir}")
    planes = xplane.parse_xspace(files[-1])
    names = [p.name for p in planes]
    device = xplane.top_ops(
        planes, n=top_n, plane_filter="TPU", line_filter="Ops"
    )
    if device:
        return OpProfile(
            device, "tpu_xla_ops", files[-1], names, t_start, t_end
        )
    host = [
        xplane.Plane(p.name, [l for l in p.lines if "Modules" not in l.name])
        for p in planes
    ]
    return OpProfile(
        xplane.top_ops(host, n=top_n), "host_fallback", files[-1], names,
        t_start, t_end,
    )


# ---------------------------------------------------------------------------
# Continuous device-time attribution (docs/OBSERVABILITY.md "Memory &
# device time")
# ---------------------------------------------------------------------------

# The dispatch kinds the serving engine emits (utils/timeline.py) plus
# the "other" bucket for capture time outside every window.
DISPATCH_KINDS = ("ragged", "spec", "prefill", "decode", "other")


def attribute_capture(
    planes, windows: list[tuple[str, int, int]],
    session_end_ns: int = 0,
) -> dict:
    """Pure attribution of one parsed capture onto labeled host
    windows: per-label busy microseconds (interval union on the
    busiest execution line, clipped per window — in-window time can
    never exceed the window), plus "other" (capture busy time outside
    every window) and the provenance source. TPU device planes ('XLA
    Ops' lines) are preferred; without them the host-event fallback
    measures python/dispatch time ('Modules' aggregate lines excluded)
    — same convention as op_profile, and the source says which you
    got. Unit-tested against synthetic planes (tests/test_device_time
    .py); DeviceTimeSampler feeds it live captures."""
    from oryx_tpu.utils import xplane

    # Precompute the busiest line's merged spans ONCE; each window is
    # then a cheap clip — an on-demand capture may carry hundreds of
    # windows and this runs on the engine thread.
    spans = xplane.busiest_line_spans(
        planes, plane_filter="TPU", line_filter="Ops",
        session_end_ns=session_end_ns,
    )
    source = "tpu_xla_ops"
    if not spans:
        spans = xplane.busiest_line_spans(
            planes, line_exclude="Modules",
            session_end_ns=session_end_ns,
        )
        source = "host_fallback"
    out: dict = {"by_kind_us": {}, "other_us": 0, "source": source}
    windowed = 0
    for label, t0, t1 in windows:
        busy = xplane.clipped_us(spans, t0, t1)
        out["by_kind_us"][label] = out["by_kind_us"].get(label, 0) + busy
        windowed += busy
    total_busy = sum(e - s for s, e in spans) // 1000
    out["other_us"] = max(0, total_busy - windowed)
    return out


class DeviceTimeSampler:
    """Always-on sampled device-time attributor for the serving engine.

    Every N engine steps (``every``; 0 = off) the scheduler brackets
    ONE dispatch in a ``jax.profiler`` capture to a private temp dir,
    and the capture's busy time inside the dispatch window lands on
    ``oryx_device_time_seconds_total{kind=}`` (the window's dispatch
    kind; capture busy time outside the window goes to kind="other")
    with the sampled wall window on
    ``oryx_profile_sampled_wall_seconds_total{kind=}`` — so
    device/wall per kind is a ratio of two counters scraped together.
    The same begin/finish machinery serves the on-demand
    ``GET /debug/profile?steps=K`` capture (a multi-window capture
    returning the Perfetto-loadable Chrome trace).

    Failure contract (the satellite bar): a capture that cannot start,
    stop, parse or attribute increments
    ``oryx_profile_capture_errors_total{stage=}`` and the engine step
    proceeds untouched — sampling may lose a sample, never a token.
    Profiling never alters the computation: the dispatch itself is
    byte-identical sampled or not (gated by tests/test_device_time.py).

    Engine-thread-owned; one sampler per engine, but jax's profiler is
    process-global — a concurrent capture elsewhere in the process
    surfaces as a counted stage="start" error, not a crash."""

    def __init__(self, registry=None, *, every: int = 0):
        self.every = max(0, int(every))
        self._step = 0  # thread-owned: engine
        self._dir: str | None = None  # thread-owned: engine
        self._dev = self._wall = self._errs = self._caps = None
        if registry is not None:
            self._dev = registry.counter(
                "oryx_device_time_seconds_total", ("kind",),
                raw_name=True,
            )
            self._wall = registry.counter(
                "oryx_profile_sampled_wall_seconds_total", ("kind",),
                raw_name=True,
            )
            self._errs = registry.counter(
                "oryx_profile_capture_errors_total", ("stage",),
                raw_name=True,
            )
            self._caps = registry.counter(
                "oryx_profile_captures_total", raw_name=True
            )

    def _err(self, stage: str) -> None:
        if self._errs is not None:
            self._errs.labels(stage=stage).inc()

    def tick(self) -> bool:
        """Advance the engine-step counter; True when THIS step is due
        a sample (every Nth step; never with every=0)."""
        self._step += 1
        return self.every > 0 and self._step % self.every == 0

    def begin(self) -> bool:
        """Start one capture into a fresh temp dir. False (with the
        labeled error counted) when the profiler cannot start —
        callers then run the step unprofiled."""
        import shutil
        import tempfile

        d = tempfile.mkdtemp(prefix="oryx-devtime-")
        try:
            _start_trace(d)
        except Exception:
            self._err("start")
            shutil.rmtree(d, ignore_errors=True)
            return False
        self._dir = d
        return True

    def abort(self) -> None:
        """Discard an in-flight capture (the dispatch-failure
        containment path): stop the process-global profiler if this
        sampler started it and reclaim the temp dir, reporting
        nothing. Without this, a dispatch exception between begin()
        and end() would leave the profiler running forever and every
        later capture failing at start."""
        import shutil

        d, self._dir = self._dir, None
        if d is None:
            return
        try:
            jax.profiler.stop_trace()
        except Exception:
            self._err("stop")
        shutil.rmtree(d, ignore_errors=True)

    def _stop_and_parse(self):
        """Stop the in-flight capture and parse its xplane file;
        returns (planes, session_end_ns) or None with the stage
        counted. Always reclaims the temp dir."""
        import shutil

        from oryx_tpu.utils import trace as trace_lib
        from oryx_tpu.utils import xplane

        d, self._dir = self._dir, None
        try:
            jax.profiler.stop_trace()
            end_ns = trace_lib.now_ns()
        except Exception:
            self._err("stop")
            shutil.rmtree(d, ignore_errors=True)
            return None
        try:
            files = xplane.find_xplane_files(d)
            if not files:
                raise RuntimeError(f"no xplane.pb written under {d}")
            planes = xplane.parse_xspace(files[-1])
        except Exception:
            self._err("parse")
            return None
        finally:
            shutil.rmtree(d, ignore_errors=True)
        return planes, end_ns

    def _credit(self, att: dict, windows) -> None:
        if self._dev is None:
            return
        for kind, us in att["by_kind_us"].items():
            if us:
                self._dev.labels(kind=kind).inc(us / 1e6)
        if att["other_us"]:
            self._dev.labels(kind="other").inc(att["other_us"] / 1e6)
        for kind, t0, t1 in windows:
            self._wall.labels(kind=kind).inc(max(0, t1 - t0) / 1e9)
        if self._caps is not None:
            self._caps.inc()

    def end(self, kind: str, t0_ns: int, t1_ns: int) -> int | None:
        """Close a per-step sample around one dispatch window: counters
        fed, temp dir reclaimed; returns the window's device
        microseconds (the timeline record's device_us field) or None
        on a counted failure."""
        parsed = self._stop_and_parse()
        if parsed is None:
            return None
        planes, end_ns = parsed
        try:
            att = attribute_capture(
                planes, [(kind, t0_ns, t1_ns)], session_end_ns=end_ns
            )
        except Exception:
            self._err("attribute")
            return None
        self._credit(att, [(kind, t0_ns, t1_ns)])
        return att["by_kind_us"].get(kind, 0)

    def finish_capture(self, windows: list[tuple[str, int, int]]) -> dict:
        """Close an on-demand multi-step capture: the /debug/profile
        response — Perfetto-loadable Chrome trace + per-kind
        attribution over the captured dispatch windows. Errors come
        back as {"error": ...} (and the stage counter), never raised
        into the engine loop."""
        from oryx_tpu.utils import xplane

        parsed = self._stop_and_parse()
        if parsed is None:
            return {"error": "profile capture failed (see "
                    "oryx_profile_capture_errors_total)"}
        planes, end_ns = parsed
        try:
            att = attribute_capture(planes, windows,
                                    session_end_ns=end_ns)
            body = xplane.chrome_trace(planes)
        except Exception as e:
            self._err("attribute")
            return {"error": f"profile attribution failed: "
                    f"{type(e).__name__}: {e}"}
        self._credit(att, windows)
        body["steps"] = len(windows)
        body["device_time_us"] = att["by_kind_us"]
        body["other_us"] = att["other_us"]
        body["source"] = att["source"]
        return body


class StepTimer:
    """Rolling wall-clock step stats: step time and tokens/sec/chip.

    Call `tick(num_tokens)` once per optimizer step AFTER the host has
    synchronized on the step's results (e.g. after device_get of metrics —
    under async dispatch an unsynced tick measures only dispatch time).
    """

    def __init__(self, window: int = 20, n_chips: int | None = None) -> None:
        self.window = window
        self.n_chips = n_chips or jax.device_count()
        self._times: list[float] = []
        self._tokens: list[int] = []
        self._last: float | None = None

    def tick(self, num_tokens: int) -> dict[str, float] | None:
        """Record a step boundary; returns rolling stats (None on the first
        tick, which only arms the timer)."""
        now = time.perf_counter()
        if self._last is None:
            self._last = now
            return None
        dt = now - self._last
        self._last = now
        self._times.append(dt)
        self._tokens.append(num_tokens)
        if len(self._times) > self.window:
            self._times.pop(0)
            self._tokens.pop(0)
        total_t = sum(self._times)
        total_tok = sum(self._tokens)
        return {
            "step_time_s": dt,
            "step_time_avg_s": total_t / len(self._times),
            "tokens_per_sec": total_tok / total_t,
            "tokens_per_sec_per_chip": total_tok / total_t / self.n_chips,
        }
