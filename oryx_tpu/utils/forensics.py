"""OOM forensics: a bounded ring of memory-pressure incident records.

An `OutOfPagesError` is designed to be survivable — the scheduler
defers, evicts or recomputes and the client never sees it — which is
exactly why capacity incidents have been UNDIAGNOSABLE after the fact:
by the time an operator looks, the pool has recovered and the only
residue is a counter. This module is the flight recorder for that
moment: every OOM (and every degraded-mode escalation) captures one
bounded record — pool-state summary (utils/pagemap.summarize), the
top-K resident requests by pages held with their in-flight cost
ledgers, the prefix cache's LRU tail, and the engine step-timeline
tail — so `GET /debug/oom?n=` replays the incident from one artifact.

The scheduler is the only writer (captures happen on the engine
thread, at the catch site, while the state that caused the pressure is
still live); debug-endpoint threads read snapshots. One leaf lock
(`forensics._lock`, declared in oryx_tpu/concurrency.py) guards the
ring — held only for the append/copy, never across capture assembly.

Dependency-free stdlib, like utils/timeline.py.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any

from oryx_tpu.analysis.sanitizers import named_lock

# Residents / cache entries retained per record: enough to name the
# pressure sources, bounded so a record is always one readable screen.
TOP_K = 8


class ForensicRing:
    """Bounded newest-last ring of forensic records (see module
    docstring). `append` returns the record's monotone index — the
    join key the oom_pressure wide event carries."""

    def __init__(self, keep: int = 64):
        self._lock = named_lock("forensics._lock")
        self._ring: deque[dict[str, Any]] = deque(  # guarded-by: _lock
            maxlen=max(1, int(keep))
        )
        self._total = 0  # guarded-by: _lock

    def append(self, record: dict[str, Any]) -> int:
        """Record one incident; stamps ts_unix_s/index when absent and
        returns the monotone index."""
        with self._lock:
            idx = self._total
            record.setdefault("ts_unix_s", time.time())
            record["index"] = idx
            self._ring.append(record)
            self._total += 1
        return idx

    @property
    def total(self) -> int:
        with self._lock:
            return self._total

    def snapshot(self, n: int | None = None) -> list[dict[str, Any]]:
        """Newest-first copies of the retained records (last `n` when
        given)."""
        with self._lock:
            records = list(self._ring)
        if n is not None:
            records = records[-max(0, int(n)):]
        return [dict(r) for r in reversed(records)]

    def to_dict(self, n: int | None = None) -> dict[str, Any]:
        """The /debug/oom response body (minus the engine label the
        server adds)."""
        return {
            "total": self.total,
            "records": self.snapshot(n),
        }
