"""Bounded exponential backoff with deterministic jitter.

One retry policy for every transient-failure boundary in the stack —
checkpoint saves (utils/checkpoint.py), the HTTP clients the check /
chaos scripts point at a (possibly restarting) server — so "how many
times, how long, growing how fast" is written once and pinned by unit
test instead of re-invented per call site.

Determinism contract: the full delay schedule is a pure function of
(policy, seed) — `backoff_delays` returns it up front, jitter comes
from a seeded RNG, and `retry_call` takes an injectable `sleep` so
tests assert the exact schedule with zero wall-clock sleeping.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Any, Callable

_LOG = logging.getLogger("oryx.retry")


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """`retries` attempts AFTER the first, delayed base*factor^i each,
    capped at `max_s`, then jittered by ±`jitter` fraction."""

    retries: int = 3
    base_s: float = 0.1
    factor: float = 2.0
    max_s: float = 10.0
    jitter: float = 0.1

    def __post_init__(self):
        if self.retries < 0:
            raise ValueError(f"retries must be >= 0, got {self.retries}")
        if self.base_s < 0 or self.max_s < 0:
            raise ValueError("delays must be >= 0")
        if self.factor < 1.0:
            raise ValueError(f"factor must be >= 1, got {self.factor}")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")


def backoff_delays(policy: BackoffPolicy, *, seed: int = 0) -> list[float]:
    """The exact sleep schedule `retry_call` will use: one delay per
    retry, exponential, capped, deterministically jittered."""
    rng = random.Random(seed)
    out = []
    for i in range(policy.retries):
        d = min(policy.base_s * policy.factor**i, policy.max_s)
        if policy.jitter:
            d *= 1.0 + policy.jitter * (2.0 * rng.random() - 1.0)
        out.append(d)
    return out


def retry_call(
    fn: Callable[[], Any],
    *,
    policy: BackoffPolicy | None = None,
    retry_on: tuple[type[BaseException], ...] = (Exception,),
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Callable[[int, BaseException, float], None] | None = None,
    seed: int = 0,
    describe: str = "",
) -> Any:
    """Call `fn` up to 1 + policy.retries times; re-raises the LAST
    exception when the budget is exhausted (bounded — a permanently
    broken dependency fails loudly instead of spinning forever).
    `on_retry(attempt, exc, delay_s)` fires before each sleep."""
    policy = policy or BackoffPolicy()
    delays = backoff_delays(policy, seed=seed)
    for attempt, delay in enumerate(delays + [None]):
        try:
            return fn()
        except retry_on as e:
            if delay is None:
                raise
            _LOG.warning(
                "%s failed (attempt %d/%d): %s; retrying in %.3gs",
                describe or getattr(fn, "__name__", "call"),
                attempt + 1, policy.retries + 1, e, delay,
            )
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)


def urlopen_json(
    url: str,
    *,
    timeout: float = 30.0,
    data: bytes | None = None,
    headers: dict[str, str] | None = None,
    policy: BackoffPolicy | None = None,
    sleep: Callable[[float], None] = time.sleep,
) -> tuple[int, Any, dict[str, str]]:
    """GET/POST `url` and parse the JSON body, retrying connection
    errors per `policy` — the HTTP client the check/chaos scripts use
    to ride out an engine restart window. Returns (status, body,
    headers); HTTP error statuses are returned, not raised, so callers
    can assert on 429/503 responses directly."""
    import json
    import urllib.error
    import urllib.request

    def attempt():
        req = urllib.request.Request(
            url, data=data, headers=headers or {}
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.load(r), dict(r.headers)
        except urllib.error.HTTPError as e:
            body = e.read()
            try:
                parsed = json.loads(body) if body else None
            except ValueError:
                parsed = body.decode(errors="replace")
            return e.code, parsed, dict(e.headers or {})

    return retry_call(
        attempt,
        policy=policy or BackoffPolicy(retries=4, base_s=0.2, max_s=2.0),
        retry_on=(OSError,),
        sleep=sleep,
        describe=f"fetch {url}",
    )
