"""Prefix-affinity front-end router: N replica servers behind one door.

The multi-replica serving tier (ROADMAP item 2). A stdlib-HTTP process
that fronts N `api_server` replicas (any Engine shape behind each) and
gives the fleet the three behaviors one replica cannot:

  * **Prefix affinity.** The shared-prefix KV cache (serve/
    prefix_cache.py) only pays off if look-alike requests land on the
    replica that already holds their prefix. The router fingerprints
    each request's prompt prefix — the (role, content) stream of its
    messages, byte-blocked through the SAME `TokenTrie` block hashing
    the prefix cache indexes with — and routes to the replica whose
    cache is hottest for that prefix: the deepest trie node owned by a
    healthy replica wins; a miss picks the least-loaded healthy
    replica and claims the path for it. A burst of requests sharing a
    system prompt therefore admits cold exactly once fleet-wide, and
    `oryx_router_affinity_hit_rate` is the live measure of how often
    routing preserved cache locality.
  * **Health ejection & drain awareness.** A prober thread polls every
    replica's /readyz (the contract PR 6 pinned: it flips 503 the
    moment drain starts, and stays 503 through a crash-loop give-up).
    A non-200 ejects the replica from rotation — in-flight streams
    keep draining through their open connections untouched — and a
    recovered 200 restores it. An upstream 503 or connection failure
    mid-request ejects immediately (no waiting for the next poll) and
    the request retries on another replica.
  * **Bounded retry.** Retries follow `utils/retry.BackoffPolicy`
    (deterministic schedule, one attempt per distinct healthy replica,
    503/connection-error only — a 429 is backpressure for the CLIENT
    to honor and is forwarded untouched). Retried-then-served
    responses carry `X-Oryx-Router-Retries`; a request that exhausts
    the fleet gets 503 + `X-Oryx-Router-Error: no_healthy_replica`, so
    load tooling (scripts/loadgen.py --router) can tell router-level
    unavailability from a backend's own 503.

Observability: the router owns an `oryx_router_*` Prometheus registry
(routed/retried/ejected/restored counters with per-replica labels,
healthy-replica and affinity gauges, an upstream-TTFB histogram) at
GET /metrics, and GET /metrics/aggregate re-exports every replica's
own scrape with a `replica="<id>"` label injected per sample line
(utils/metrics.inject_exposition_label) — one scrape shows the fleet.
GET /debug/requests merges the replicas' flight recorders (per-replica
totals preserved; ?format=jsonl concatenates their wide-event logs)
and GET /debug/timeline their engine step timelines. Distributed
tracing: every proxied request gets a router-side trace (route_decide
/ upstream_connect / upstream_ttfb spans, retry + eject events) under
the SAME request id the replica adopts — a sanitized client
X-Request-Id is honored, and the id + parent span ride the
X-Oryx-Trace header upstream — so GET /debug/trace?id= returns ONE
merged Perfetto-loadable trace: router spans on track 0, the owning
replica's engine spans (eviction/restart replays included) on track 1,
re-anchored onto the router's clock. /healthz is process liveness;
/readyz is "≥ 1 healthy replica and not draining". SIGTERM drains:
/readyz flips 503 immediately, new POSTs get 503 + Retry-After,
streams already proxying run to completion.

    python -m oryx_tpu.serve.router --port 8100 \
        --replica r0=http://127.0.0.1:8000 \
        --replica r1=http://127.0.0.1:8001

Concurrency model (oryx_tpu/concurrency.py): the replica table and the
affinity trie are guarded by `router._lock` (held only for table/trie
edits — never across network I/O); the prober thread and HTTP handler
threads are the only writers. Metric bumps nest under the lock in the
declared order (`router._lock < registry._lock`).
"""

from __future__ import annotations

import argparse
import http.client
import json
import logging
import threading
import time
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import numpy as np

from oryx_tpu.analysis import sanitizers
from oryx_tpu.analysis.sanitizers import named_lock
from oryx_tpu.serve.prefix_cache import TokenTrie
from oryx_tpu.utils import trace as trace_lib
from oryx_tpu.utils.metrics import (
    TTFT_BUCKETS,
    Registry,
    inject_exposition_label,
)
from oryx_tpu.utils.retry import BackoffPolicy, backoff_delays

_LOG = logging.getLogger("oryx.serve.router")

# Upper bound on the bytes of prompt prefix that participate in the
# fingerprint: affinity only needs the SHARED head of a conversation
# (system prompt, early turns); hashing megabyte prompts would buy
# nothing past the first divergence.
FINGERPRINT_CAP = 4096


def prefix_fingerprint(messages: list[dict[str, Any]],
                       cap: int = FINGERPRINT_CAP) -> np.ndarray:
    """The prompt's affinity stream: role/content of each message in
    order, byte-encoded, capped. Block-hashed through `TokenTrie`
    exactly like the prefix cache hashes token ids — two requests
    sharing a system prompt (and any number of identical early turns)
    share a leading block path, so the trie's longest-prefix walk IS
    the cache-locality estimate. Content-part lists contribute their
    text parts; media parts contribute their type tag only (the router
    never decodes payloads — a re-sent image keys the same replica by
    its surrounding text)."""
    parts = []
    for m in messages:
        content = m.get("content", "")
        if isinstance(content, list):
            content = "\n".join(
                str(p.get("text", p.get("type", "")))
                for p in content if isinstance(p, dict)
            )
        parts.append(f"{m.get('role', '')}\x1f{content}")
    raw = "\x1e".join(parts).encode("utf-8", "replace")[:cap]
    return np.frombuffer(raw, dtype=np.uint8).astype(np.int64)


class Replica:
    """One backend in the rotation. Mutable fields are edited only
    under the router's `_lock` (table scans in `route`/prober) — kept
    lock-adjacent rather than annotation-guarded because the lock
    lives on the router, not here."""

    __slots__ = ("rid", "url", "host", "port", "healthy", "inflight",
                 "reason", "ejections")

    def __init__(self, rid: str, url: str):
        u = urllib.parse.urlsplit(url)
        if u.scheme != "http" or not u.hostname:
            raise ValueError(
                f"replica {rid!r}: need an http://host:port URL, "
                f"got {url!r}"
            )
        self.rid = rid
        self.url = url.rstrip("/")
        self.host = u.hostname
        self.port = u.port or 80
        self.healthy = True  # optimistic: first prober pass corrects
        self.inflight = 0
        self.reason = "unprobed"
        self.ejections = 0


class PrefixAffinityRouter:
    """Replica table + affinity trie + the oryx_router registry.

    `route()` is the one decision point; the HTTP layer (build_router)
    and the prober thread are thin shells around it. Separable from
    the server so tests drive routing/ejection logic directly."""

    def __init__(
        self,
        replicas: list[tuple[str, str]],  # (id, url)
        *,
        block: int = 32,
        max_trie_nodes: int = 4096,
        retry_policy: BackoffPolicy | None = None,
        registry: Registry | None = None,
        flight_recorder_size: int = 256,
    ):
        if not replicas:
            raise ValueError("router needs at least one replica")
        ids = [rid for rid, _ in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self._lock = named_lock("router._lock")
        # The id->Replica MAPPING is immutable after construction (read
        # lock-free everywhere); the mutable fields inside each Replica
        # (healthy/inflight/reason) are edited only under _lock.
        self.replicas: dict[str, Replica] = {
            rid: Replica(rid, url) for rid, url in replicas
        }
        self.trie = TokenTrie(block)  # guarded-by: _lock
        self.max_trie_nodes = max_trie_nodes
        self.block = block
        # One attempt per distinct replica; the delay schedule between
        # attempts is the shared deterministic backoff policy.
        self.retry_policy = retry_policy or BackoffPolicy(
            retries=max(1, len(replicas) - 1), base_s=0.05, max_s=1.0,
        )
        self._hits = 0  # guarded-by: _lock
        self._misses = 0  # guarded-by: _lock
        # Router-side flight recorder: one trace per proxied request
        # (route_decide / upstream_connect / upstream_ttfb spans, retry
        # and eject events), keyed by the SAME request id the replica's
        # trace carries — /debug/trace?id= merges the two into one
        # story (docs/OBSERVABILITY.md "Fleet tracing").
        self.tracer = trace_lib.Tracer(flight_recorder_size)
        self.registry = registry or Registry(prefix="oryx_router")
        reg = self.registry
        # Pre-registered so the whole surface renders (at zero) from
        # the first scrape — same discipline as the scheduler.
        reg.counter("requests_total", ("replica",))
        reg.counter("retried_total", ("replica",))
        reg.counter("ejected_total", ("replica",))
        reg.counter("restored_total", ("replica",))
        reg.counter("affinity_hits_total")
        reg.counter("affinity_misses_total")
        reg.counter("unavailable_total")
        reg.gauge("affinity_hit_rate")
        reg.gauge("healthy_replicas")
        reg.gauge("replica_healthy", ("replica",))
        reg.histogram("upstream_ttfb_seconds", TTFT_BUCKETS)
        self._publish_health({r: True for r in self.replicas})

    # ---- health ----------------------------------------------------------

    def _publish_health(self, healthy_by_id: dict[str, bool]) -> None:
        reg = self.registry
        for rid, h in healthy_by_id.items():
            reg.gauge("replica_healthy", ("replica",)).labels(
                replica=rid
            ).set(1.0 if h else 0.0)
        reg.gauge("healthy_replicas").set(
            sum(1 for h in healthy_by_id.values() if h)
        )

    def set_health(self, rid: str, healthy: bool, reason: str) -> bool:
        """Record one probe/upstream observation; returns True when the
        state CHANGED (the transition is what ejection/restoration
        counters and logs track)."""
        with self._lock:
            r = self.replicas[rid]
            changed = r.healthy != healthy
            r.healthy = healthy
            r.reason = reason
            if changed and not healthy:
                r.ejections += 1
            snapshot = {x.rid: x.healthy for x in self.replicas.values()}
        if changed:
            if healthy:
                self.registry.counter(
                    "restored_total", ("replica",)
                ).labels(replica=rid).inc()
            else:
                self.registry.counter(
                    "ejected_total", ("replica",)
                ).labels(replica=rid).inc()
            _LOG.warning(
                "replica %s %s (%s)", rid,
                "ejected" if not healthy else "restored", reason,
            )
        self._publish_health(snapshot)
        return changed

    def probe_all(self, timeout: float = 2.0) -> None:
        """One prober pass: GET each replica's /readyz. 200 = in
        rotation; anything else (503 draining / crash-loop give-up,
        connection refused) = ejected. In-flight proxied streams are
        untouched — ejection only stops NEW routing."""
        for rid, url in [
            (r.rid, r.url) for r in list(self.replicas.values())
        ]:
            try:
                req = urllib.request.Request(url + "/readyz")
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    ok = resp.status == 200
                    reason = "ok" if ok else f"readyz {resp.status}"
            except urllib.error.HTTPError as e:
                body = e.read()
                try:
                    reason = (json.loads(body) or {}).get(
                        "reason", f"readyz {e.code}"
                    )
                except ValueError:
                    reason = f"readyz {e.code}"
                ok = False
                e.close()
            except OSError as e:
                ok, reason = False, f"unreachable: {e}"
            self.set_health(rid, ok, reason)

    def healthy_ids(self) -> list[str]:
        with self._lock:
            return [r.rid for r in self.replicas.values() if r.healthy]

    # ---- routing ---------------------------------------------------------

    def route(self, tokens: np.ndarray,
              exclude: set[str] = frozenset()) -> tuple[Replica | None, bool]:
        """Pick the replica for one request: the deepest affinity-trie
        node along `tokens` owned by a healthy (non-excluded) replica,
        else the least-loaded healthy replica. The chosen replica then
        (re)claims the path — nodes owned by nobody, or by an ejected
        replica, re-own to the winner, which is exactly how traffic
        rebalances after an ejection without a flag day. Returns
        (replica, affinity_hit); (None, False) when nothing is
        routable."""
        with self._lock:
            healthy = [
                r for r in self.replicas.values()
                if r.healthy and r.rid not in exclude
            ]
            if not healthy:
                choice = None, False
            else:
                path = self.trie.walk(tokens)
                chosen = None
                hit = False
                for node in reversed(path):
                    owner = self.replicas.get(node.payload)
                    if (
                        owner is not None and owner.healthy
                        and owner.rid not in exclude
                    ):
                        chosen, hit = owner, True
                        break
                if chosen is None:
                    chosen = min(
                        healthy, key=lambda r: (r.inflight, r.rid)
                    )
                for node in self.trie.extend(tokens):
                    owner = self.replicas.get(node.payload)
                    if (
                        owner is None or not owner.healthy
                        or owner.rid in exclude
                    ):
                        node.payload = chosen.rid
                # Keep the affinity index bounded: drop least-recently
                # touched leaves past max_trie_nodes (the same LRU
                # stamps the prefix cache evicts by).
                while len(self.trie) > self.max_trie_nodes:
                    leaves = sorted(
                        self.trie.leaves(), key=lambda n: n.stamp
                    )
                    if not leaves:
                        break
                    for victim in leaves[: max(1, len(leaves) // 4)]:
                        self.trie.remove(victim)
                if hit:
                    self._hits += 1
                else:
                    self._misses += 1
                rate = self._hits / max(1, self._hits + self._misses)
                choice = chosen, hit
        reg = self.registry
        if choice[0] is not None:
            if choice[1]:
                reg.counter("affinity_hits_total").inc()
            else:
                reg.counter("affinity_misses_total").inc()
            reg.gauge("affinity_hit_rate").set(rate)
        return choice

    def begin_request(self, rid: str) -> None:
        with self._lock:
            self.replicas[rid].inflight += 1

    def end_request(self, rid: str) -> None:
        with self._lock:
            self.replicas[rid].inflight -= 1

    def total_inflight(self) -> int:
        """Requests currently proxying across the fleet (the drain
        wait's exit condition)."""
        with self._lock:
            return sum(r.inflight for r in self.replicas.values())

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                r.rid: {
                    "url": r.url, "healthy": r.healthy,
                    "reason": r.reason, "inflight": r.inflight,
                    "ejections": r.ejections,
                }
                for r in self.replicas.values()
            }


def _merge_clock_offset_us(router_meta: dict[str, Any],
                           replica_request: dict[str, Any]) -> float:
    """Microseconds to ADD to the replica's chrome-trace timestamps so
    the merged trace sits on the router's clock.

    Both sides stamp spans on a wall-anchored perf clock
    (utils/trace.py), so on one host — or NTP-synced hosts — the
    offset is ~0 and re-anchoring would only erase real queueing
    delay; the replica's trace is kept where it is. When the replica's
    trace-creation time is IMPLAUSIBLE against the router's recorded
    send time (created before the request was sent, or absurdly after
    it), the clocks disagree and the replica trace re-anchors to the
    router's send instant — slightly compressing the network hop, but
    putting every span on one readable axis."""
    sent_ns = router_meta.get("upstream_sent_ns")
    created_s = replica_request.get("created_unix_s")
    if not sent_ns or not created_s:
        return 0.0
    sent_us = sent_ns / 1e3
    created_us = float(created_s) * 1e6
    # 10ms of backwards slack (float rounding, sub-ms skew) and 120s
    # forward (a request can sit in the replica's accept queue, but
    # not for minutes before its trace even starts).
    if sent_us - 1e4 <= created_us <= sent_us + 120e6:
        return 0.0
    return round(sent_us - created_us, 3)


def build_router(
    replicas: list[tuple[str, str]],
    *,
    host: str = "127.0.0.1",
    port: int = 8100,
    poll_s: float = 0.25,
    probe_timeout: float = 2.0,
    upstream_timeout: float = 600.0,
    block: int = 32,
    retry_policy: BackoffPolicy | None = None,
    probe: bool = True,
) -> ThreadingHTTPServer:
    """Construct (not start) the router HTTP server. Mirrors
    api_server.build_server's shape: the returned server carries
    `.router` (the PrefixAffinityRouter), `.registry`, and
    `.begin_drain()`; callers thread `serve_forever` themselves.
    probe=False skips the background prober (tests drive
    `router.probe_all()` deterministically)."""
    sanitizers.maybe_arm_from_env()
    router = PrefixAffinityRouter(
        replicas, block=block, retry_policy=retry_policy
    )
    sanitizers.bind_lock_metrics(router.registry)
    from oryx_tpu.serve.api_server import _git_revision

    router.registry.info("build_info", {
        "revision": _git_revision(), "engine": "router",
        "replicas": str(len(replicas)),
    })
    draining = threading.Event()
    halt = threading.Event()

    def probe_loop() -> None:
        while not halt.wait(poll_s):
            router.probe_all(timeout=probe_timeout)

    prober = threading.Thread(
        target=probe_loop, daemon=True, name="router-prober"
    )

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet access log
            pass

        # ---- plumbing ----------------------------------------------------

        def _json(self, code: int, body: dict[str, Any],
                  extra_headers: dict[str, str] | None = None) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _router_error(self, code: int, reason: str, retries: int,
                          retry_after: float = 1.0) -> None:
            """A failure the ROUTER is answering for (vs a forwarded
            backend response): tagged X-Oryx-Router-Error so load
            tooling can split router-level unavailability from a
            backend's own 503s."""
            router.registry.counter("unavailable_total").inc()
            self._json(code, {"error": {
                "message": f"router: {reason}",
                "type": "unavailable_error",
                "reason": reason,
            }}, extra_headers={
                "Retry-After": str(max(1, round(retry_after))),
                "X-Oryx-Router-Error": reason,
                "X-Oryx-Router-Retries": str(retries),
            })

        def _replica_get(self, r: Replica, path: str,
                         timeout: float = 5.0) -> tuple[int, bytes]:
            """GET one replica endpoint; error statuses are returned,
            not raised (the merge endpoints propagate a replica's 400s
            verbatim). The timeout is deliberately SHORT: the merge
            endpoints walk replicas sequentially, and one wedged
            backend must degrade to a `scrape failed` line — never
            stall fleet observability past a Prometheus scrape window
            during the exact incident it exists to show."""
            try:
                with urllib.request.urlopen(
                    r.url + path, timeout=timeout
                ) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as e:
                body = e.read()
                e.close()
                return e.code, body

        # ---- GET surface -------------------------------------------------

        def do_GET(self):
            path, _, query = self.path.partition("?")
            if path == "/healthz":
                self._json(200, {"status": "ok"})
            elif path == "/readyz":
                if draining.is_set():
                    self._json(503, {"ready": False, "reason": "draining"})
                    return
                healthy = router.healthy_ids()
                if healthy:
                    self._json(200, {
                        "ready": True, "reason": "ok",
                        "healthy_replicas": len(healthy),
                    })
                else:
                    self._json(503, {
                        "ready": False, "reason": "no_healthy_replica",
                        "replicas": router.snapshot(),
                    })
            elif path == "/metrics":
                data = router.registry.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif path == "/metrics/aggregate":
                self._aggregate_metrics()
            elif path == "/debug/replicas":
                self._json(200, {
                    "draining": draining.is_set(),
                    "replicas": router.snapshot(),
                })
            elif path == "/debug/requests":
                self._merged_debug_requests(query)
            elif path == "/debug/timeline":
                self._merged_replica_json("/debug/timeline", query)
            elif path == "/debug/pages":
                # The fleet's page-ownership maps, keyed by replica —
                # same degrade-to-error-entry contract as the timeline
                # merge (a wedged replica never stalls the fleet view).
                self._merged_replica_json("/debug/pages", query)
            elif path == "/debug/oom":
                # The fleet's OOM forensic rings, keyed by replica.
                self._merged_replica_json("/debug/oom", query)
            elif path == "/debug/audit":
                # The fleet's output-audit rings, keyed by replica —
                # same degrade-to-error-entry merge contract.
                self._merged_replica_json("/debug/audit", query)
            elif path == "/debug/journal":
                # The fleet's decision-journal rings, keyed by replica
                # (disarmed replicas answer armed=false bodies) — same
                # degrade-to-error-entry merge contract.
                self._merged_replica_json("/debug/journal", query)
            elif path == "/debug/profile":
                self._proxy_profile(query)
            elif path == "/debug/trace":
                self._find_trace(query)
            elif path == "/v1/models":
                self._proxy_get_first(path)
            else:
                self._json(404, {"error": "not found"})

        def _aggregate_metrics(self) -> None:
            """The fleet in one scrape: the router's own families,
            then each replica's exposition with `replica="<id>"`
            injected per sample line. Replica sections drop their
            comment lines (duplicate # TYPE headers across replicas
            would make the merged text ill-formed); a failed scrape
            becomes one comment line instead of failing the whole
            aggregation."""
            out = [router.registry.render()]
            for rid, info in sorted(router.snapshot().items()):
                r = router.replicas[rid]
                try:
                    status, body = self._replica_get(r, "/metrics")
                    if status != 200:
                        raise OSError(f"/metrics -> {status}")
                    labeled = inject_exposition_label(
                        body.decode(), "replica", rid
                    )
                    out.append(f"# replica {rid} {r.url}\n" + "\n".join(
                        line for line in labeled.splitlines()
                        if line and not line.startswith("#")
                    ) + "\n")
                except (OSError, ValueError) as e:
                    out.append(f"# replica {rid} scrape failed: {e}\n")
            data = "".join(out).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _merged_replica_json(self, path: str, query: str) -> None:
            """Generic per-replica JSON merge (the /debug/timeline,
            /debug/pages and /debug/oom views): each replica's
            response (same query string) under its id, a wedged
            replica degrading to an error entry — never a stalled
            endpoint (same contract as the metrics aggregation)."""
            per: dict[str, Any] = {}
            for rid, info in sorted(router.snapshot().items()):
                r = router.replicas[rid]
                try:
                    status, body = self._replica_get(
                        r, path + (f"?{query}" if query else ""),
                    )
                    if status != 200:
                        raise OSError(f"{path} -> {status}")
                    per[rid] = json.loads(body)
                except (OSError, ValueError) as e:
                    per[rid] = {"error": str(e)}
            self._json(200, {"engine": "router", "replicas": per})

        def _proxy_profile(self, query: str) -> None:
            """Proxy /debug/profile to the OWNING replica: ?replica=
            names it explicitly, otherwise the busiest healthy replica
            (most in-flight requests — profiling needs live
            dispatches) with the first healthy one as the idle-fleet
            fallback. Long timeout: the capture spans real engine
            steps."""
            q = urllib.parse.parse_qs(query)
            want = (q.get("replica") or [""])[0]
            snap = router.snapshot()
            if want:
                if want not in snap:
                    self._json(404, {
                        "error": f"unknown replica {want!r} "
                        f"(known: {sorted(snap)})",
                    })
                    return
                rid = want
            else:
                healthy = router.healthy_ids()
                if not healthy:
                    self._router_error(503, "no_healthy_replica", 0)
                    return
                rid = max(
                    healthy,
                    key=lambda i: snap.get(i, {}).get("inflight", 0),
                )
            pass_q = urllib.parse.urlencode(
                [(k, v[0]) for k, v in q.items() if k != "replica"]
            )
            # The socket timeout must outlive the replica's own wait
            # (it clamps ?timeout= to [1, 300]); a fixed proxy timeout
            # below it would 503 while the replica capture stays in
            # flight and refuses the retry.
            try:
                upstream_t = float((q.get("timeout") or ["30"])[0])
            except ValueError:
                upstream_t = 30.0
            try:
                status, body = self._replica_get(
                    router.replicas[rid],
                    "/debug/profile" + (f"?{pass_q}" if pass_q else ""),
                    timeout=max(1.0, min(upstream_t, 300.0)) + 30.0,
                )
            except OSError as e:
                self._json(503, {
                    "error": f"replica {rid} profile failed: {e}",
                })
                return
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.send_header("X-Oryx-Router-Replica", rid)
            self.end_headers()
            self.wfile.write(body)

        def _merged_debug_requests(self, query: str) -> None:
            """One flight-recorder view of the fleet: each replica's
            /debug/requests (same query string) merged, per-replica
            totals preserved, ?limit= re-applied to the merge.
            ?format=jsonl concatenates the replicas' wide-event logs
            (each event already carries its replica identity)."""
            q = urllib.parse.parse_qs(query)
            if (q.get("format") or [""])[0] == "jsonl":
                try:
                    limit = int((q.get("limit") or ["0"])[0])
                    if limit < 0:
                        raise ValueError
                except ValueError:
                    self._json(400, {
                        "error": "limit must be a non-negative integer",
                    })
                    return
                lines: list[str] = []
                for rid, info in sorted(router.snapshot().items()):
                    r = router.replicas[rid]
                    try:
                        status, body = self._replica_get(
                            r, f"/debug/requests?{query}"
                        )
                        if status == 200:
                            lines += [
                                ln for ln in body.decode().splitlines()
                                if ln
                            ]
                    except OSError:
                        pass  # scrape failed: skip replica
                # ?limit= bounds the MERGE, like the JSON path below:
                # interleave by event time first, so the newest N of
                # the fleet survive — not N per replica.
                def ev_ts(ln: str) -> float:
                    try:
                        return float(json.loads(ln).get("ts_unix_s") or 0)
                    except ValueError:
                        return 0.0

                lines.sort(key=ev_ts)
                if limit:
                    lines = lines[-limit:]
                data = ("\n".join(lines) + ("\n" if lines else "")).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            try:
                limit = int((q.get("limit") or ["0"])[0])
                if limit < 0:
                    raise ValueError
            except ValueError:
                self._json(400, {
                    "error": "limit must be a non-negative integer",
                })
                return
            merged: list[dict] = []
            per_replica: dict[str, Any] = {}
            total = 0
            for rid, info in sorted(router.snapshot().items()):
                r = router.replicas[rid]
                try:
                    status, body = self._replica_get(
                        r, "/debug/requests" + (f"?{query}" if query else "")
                    )
                    if status != 200:
                        # Propagate a replica's validation answer (a
                        # bogus ?state= must stay a 400 through the
                        # router, not be silently swallowed).
                        self.send_response(status)
                        self.send_header(
                            "Content-Type", "application/json"
                        )
                        self.send_header(
                            "Content-Length", str(len(body))
                        )
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    rep = json.loads(body)
                    for rec in rep.get("requests", []):
                        rec["replica"] = rid
                        merged.append(rec)
                    total += rep.get("total", 0)
                    per_replica[rid] = {
                        "total": rep.get("total", 0),
                        "engine": rep.get("engine"),
                    }
                except (OSError, ValueError) as e:
                    per_replica[rid] = {"error": str(e)}
            # Interleave by recency BEFORE truncating: each replica
            # returned its own newest-first list, and a rid-ordered
            # concatenation cut at ?limit= would silently drop a later
            # replica's strictly newer entries — exactly the requests
            # an operator is hunting mid-incident.
            merged.sort(
                key=lambda rec: rec.get("created_unix_s") or 0.0,
                reverse=True,
            )
            if limit:
                merged = merged[:limit]
            self._json(200, {
                "engine": "router",
                "total": total,
                "returned": len(merged),
                "replicas": per_replica,
                "requests": merged,
            })

        def _find_trace(self, query: str) -> None:
            """ONE merged Perfetto-loadable trace for ?id=: the
            router's own spans (route_decide, upstream_connect,
            upstream_ttfb, retries, ejects) on track 0 and the owning
            replica's spans — queue_wait, prefill (eviction/restart
            replays included), decode_chunk, emission — on track 1,
            re-anchored onto the router's clock, so a routed (and even
            a replayed) request reads as one story. Falls back to the
            replica's own trace when the router never saw the id (it
            predates this router process, or the recorder rolled)."""
            q = urllib.parse.parse_qs(query)
            rid_param = (q.get("id") or [""])[0]
            if not rid_param:
                self._json(400, {"error": "missing ?id=<request id>"})
                return
            own = router.tracer.get(rid_param)
            # Locate the replica-side trace: the owner recorded on the
            # router trace first, then the rest of the fleet (the id
            # may predate this router's recorder window).
            candidates = []
            if own is not None:
                owner = own.summary()["meta"].get("replica")
                if owner in router.replicas:
                    candidates.append(owner)
            candidates += [
                rid for rid in sorted(router.replicas)
                if rid not in candidates
            ]
            rep_json = rep_rid = None
            for rid in candidates:
                try:
                    status, body = self._replica_get(
                        router.replicas[rid], f"/debug/trace?{query}"
                    )
                except OSError:
                    continue
                if status == 200:
                    try:
                        rep_json = json.loads(body)
                    except ValueError:
                        continue
                    rep_rid = rid
                    break
            if own is None and rep_json is None:
                self._json(404, {
                    "error": "neither the router nor any replica "
                    f"holds a trace for id {rid_param!r}"
                })
                return
            if own is None:
                # Replica-only view (pre-router id): forward verbatim.
                rep_json["merged"] = False
                self._json(200, rep_json, extra_headers={
                    "X-Oryx-Router-Replica": rep_rid,
                })
                return
            events = own.chrome_events(tid=0)
            merged: dict[str, Any] = {
                "traceEvents": events,
                "displayTimeUnit": "ms",
                "request": own.summary(),
                "merged": False,
            }
            if rep_json is not None:
                offset_us = _merge_clock_offset_us(
                    own.summary()["meta"], rep_json.get("request") or {}
                )
                for ev in rep_json.get("traceEvents", []):
                    ev = dict(ev)
                    ev["tid"] = 1
                    if ev.get("ph") == "M":
                        name = (ev.get("args") or {}).get("name", "")
                        ev["args"] = {
                            "name": f"replica {rep_rid} {name}".strip()
                        }
                    elif "ts" in ev:
                        ev["ts"] = ev["ts"] + offset_us
                    events.append(ev)
                merged["merged"] = True
                merged["replica"] = rep_rid
                merged["clock_offset_us"] = offset_us
                merged["replica_request"] = rep_json.get("request")
            # The PR 9 header contract survives the merge: consumers
            # keyed on X-Oryx-Router-Replica keep working.
            self._json(200, merged, extra_headers=(
                {"X-Oryx-Router-Replica": rep_rid} if rep_rid else None
            ))

        def _proxy_get_first(self, path: str) -> None:
            for rid in router.healthy_ids():
                r = router.replicas[rid]
                try:
                    status, body = self._replica_get(r, path)
                except OSError:
                    continue
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            self._router_error(503, "no_healthy_replica", 0)

        # ---- the completion proxy ----------------------------------------

        def do_POST(self):
            if self.path != "/v1/chat/completions":
                self._json(404, {"error": "not found"})
                return
            if draining.is_set():
                self._router_error(503, "draining", 0)
                return
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
            # The REPLICA owns request validation (it answers the
            # 400s): any malformed shape here — non-JSON, a non-object
            # body, a non-list messages, non-dict entries — just means
            # "no affinity signal", never a dropped connection.
            try:
                parsed = json.loads(body)
            except ValueError:
                parsed = None
            messages = (
                parsed.get("messages") if isinstance(parsed, dict)
                else None
            )
            if not isinstance(messages, list):
                messages = []
            tokens = prefix_fingerprint(
                [m for m in messages if isinstance(m, dict)]
            )
            # Distributed tracing: one router-side trace per proxied
            # request. A sanitized client X-Request-Id is honored as
            # the trace id — the SAME id the chosen replica will adopt
            # (propagated via X-Oryx-Trace), so /debug/trace?id= can
            # merge the two sides into one story. Colliding or unsafe
            # ids fall back to minting.
            rid_pref = trace_lib.sanitize_request_id(
                self.headers.get("X-Request-Id")
            )
            tr = router.tracer.start_trace(
                "router", label="chat", id=rid_pref,  # minted on collision
            )
            # One attempt per distinct healthy replica, delays from the
            # shared deterministic backoff schedule. 503s and transport
            # errors rotate; anything else — success, 400, 429, 504 —
            # is the client's answer and forwards as-is.
            delays = [0.0] + backoff_delays(router.retry_policy)
            tried: set[str] = set()
            retries = 0
            for delay in delays:
                if delay:
                    time.sleep(delay)
                with tr.span("route_decide", attempt=retries):
                    replica, hit = router.route(tokens, exclude=tried)
                if replica is None:
                    break
                outcome = self._try_upstream(replica, body, retries, tr)
                if outcome is None:
                    tr.finish(
                        replica=replica.rid, retries=retries,
                        affinity_hit=hit,
                    )
                    return  # response (or client hangup) fully handled
                tried.add(replica.rid)
                retries += 1
                tr.event("retry", replica=replica.rid, reason=outcome)
                router.registry.counter(
                    "retried_total", ("replica",)
                ).labels(replica=replica.rid).inc()
                _LOG.info(
                    "retrying off replica %s (%s)", replica.rid, outcome
                )
            tr.finish(error="no_healthy_replica", retries=retries)
            self._router_error(
                503, "no_healthy_replica", retries,
                retry_after=router.retry_policy.base_s * 10,
            )

        def _try_upstream(self, replica: Replica, body: bytes,
                          retries: int,
                          tr: trace_lib.Trace) -> str | None:
            """Proxy one attempt to `replica`. Returns None when the
            client got an answer (including a forwarded error or a
            mid-stream hangup), or a reason string meaning "rotate to
            another replica" — only ever BEFORE any response byte has
            been forwarded, so a retry can never splice two streams."""
            router.begin_request(replica.rid)
            conn = http.client.HTTPConnection(
                replica.host, replica.port, timeout=upstream_timeout
            )
            t0 = time.monotonic()
            uc = tr.begin("upstream_connect", replica=replica.rid)
            ttfb_h = -1
            try:
                try:
                    # Clock anchor for the merged trace: the replica's
                    # spans re-anchor onto this send timestamp when the
                    # two processes' clocks visibly disagree.
                    tr.annotate(
                        replica=replica.rid,
                        upstream_sent_ns=trace_lib.now_ns(),
                    )
                    conn.request(
                        "POST", "/v1/chat/completions", body=body,
                        headers={
                            "Content-Type": "application/json",
                            # Trace context, router -> replica: the
                            # replica adopts this request id as its own
                            # trace id and records the parent span, so
                            # the fleet shares ONE id per request.
                            "X-Oryx-Trace": f"{tr.id};{uc}",
                        },
                    )
                    tr.end(uc)
                    ttfb_h = tr.begin(
                        "upstream_ttfb", replica=replica.rid
                    )
                    resp = conn.getresponse()
                    tr.end(ttfb_h)
                except OSError as e:
                    tr.end(uc)
                    if ttfb_h >= 0:
                        tr.end(ttfb_h)
                    # Transport failure before a single response byte:
                    # eject now (the prober would take a poll interval
                    # to notice a dead process) and rotate.
                    tr.event(
                        "eject", replica=replica.rid,
                        reason=f"connect failed: {e}",
                    )
                    router.set_health(
                        replica.rid, False, f"connect failed: {e}"
                    )
                    return f"transport: {e}"
                router.registry.histogram(
                    "upstream_ttfb_seconds", TTFT_BUCKETS
                ).observe(time.monotonic() - t0)
                if resp.status == 503:
                    # Drain-aware removal: a 503 body from a replica
                    # means draining / shedding / supervisor give-up —
                    # take it out of rotation immediately and retry
                    # the request elsewhere.
                    resp.read()
                    tr.event(
                        "eject", replica=replica.rid,
                        reason="upstream 503",
                    )
                    router.set_health(
                        replica.rid, False, "upstream 503"
                    )
                    return "upstream 503"
                tr.annotate(status=resp.status)
                # Counted only once a response is actually FORWARDED
                # from this replica (failed attempts show in
                # retried_total instead), so requests_total is a true
                # served-traffic split, not an attempt count.
                router.registry.counter(
                    "requests_total", ("replica",)
                ).labels(replica=replica.rid).inc()
                try:
                    with tr.span("proxy_stream", replica=replica.rid):
                        self._forward(resp, replica, retries)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    # CLIENT hung up mid-stream: closing the upstream
                    # connection (finally) propagates the cancel to
                    # the replica's SSE writer.
                    pass
                return None
            finally:
                conn.close()
                router.end_request(replica.rid)

        def _forward(self, resp, replica: Replica, retries: int) -> None:
            """Stream one upstream response to the client verbatim.
            Content-Length responses copy in one read; SSE responses
            (no length, close-delimited) relay line-by-line, flushing
            at event boundaries so TTFT through the router tracks the
            replica's, not a buffer's."""
            self.send_response(resp.status)
            passthrough = (
                "Content-Type", "Cache-Control", "Retry-After",
                "X-Request-Id",
            )
            for name in passthrough:
                v = resp.getheader(name)
                if v is not None:
                    self.send_header(name, v)
            self.send_header("X-Oryx-Router-Replica", replica.rid)
            self.send_header("X-Oryx-Router-Retries", str(retries))
            cl = resp.getheader("Content-Length")
            if cl is not None:
                data = resp.read(int(cl))
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            self.end_headers()
            while True:
                line = resp.readline()
                if not line:
                    break
                self.wfile.write(line)
                if line == b"\n":
                    self.wfile.flush()  # SSE event boundary
            self.wfile.flush()

    srv = ThreadingHTTPServer((host, port), Handler)
    srv.router = router
    srv.registry = router.registry
    srv.draining = draining

    def begin_drain() -> None:
        """Router drain: /readyz flips 503 NOW and new completions are
        refused; streams already proxying finish on their open
        connections. (Replica drains are their own — a router drain
        does not cascade.)"""
        draining.set()

    def close() -> None:
        halt.set()

    srv.begin_drain = begin_drain
    srv.stop_prober = close
    if probe:
        router.probe_all(timeout=probe_timeout)  # no cold 503 window
        prober.start()
    return srv


def _parse_replica_arg(value: str, index: int) -> tuple[str, str]:
    """--replica [id=]http://host:port; ids default to r0, r1, ..."""
    rid, sep, url = value.partition("=")
    if not sep:
        return f"r{index}", value
    return rid, url


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(
        description="Oryx-TPU prefix-affinity front-end router"
    )
    ap.add_argument(
        "--replica", action="append", required=True, metavar="[ID=]URL",
        help="backend api_server base URL (repeat per replica); "
        "e.g. r0=http://127.0.0.1:8000",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8100)
    ap.add_argument(
        "--poll-interval", type=float, default=1.0,
        help="seconds between /readyz probes of each replica",
    )
    ap.add_argument(
        "--probe-timeout", type=float, default=2.0,
        help="per-probe timeout; an unreachable replica is ejected",
    )
    ap.add_argument(
        "--upstream-timeout", type=float, default=600.0,
        help="per-request upstream socket timeout",
    )
    ap.add_argument(
        "--affinity-block", type=int, default=32,
        help="fingerprint block size in bytes (the TokenTrie block "
        "the affinity index hashes prompt prefixes with)",
    )
    ap.add_argument(
        "--drain-timeout", type=float, default=60.0,
        help="seconds to wait after SIGTERM for in-flight proxied "
        "streams to finish before exiting anyway",
    )
    args = ap.parse_args(argv)
    replicas = [
        _parse_replica_arg(v, i) for i, v in enumerate(args.replica)
    ]
    srv = build_router(
        replicas, host=args.host, port=args.port,
        poll_s=args.poll_interval, probe_timeout=args.probe_timeout,
        upstream_timeout=args.upstream_timeout,
        block=args.affinity_block,
    )

    def _drain_and_exit() -> None:
        # The drain CONTRACT ("streams already proxying finish") needs
        # an actual wait: handler threads are daemons, so exiting
        # straight after shutdown() would sever mid-decode streams.
        deadline = time.monotonic() + args.drain_timeout
        while srv.router.total_inflight() > 0:
            if time.monotonic() >= deadline:
                print(f"drain timed out after {args.drain_timeout:g}s "
                      f"({srv.router.total_inflight()} stream(s) "
                      "still proxying)")
                break
            time.sleep(0.1)
        else:
            print("drain complete")
        srv.shutdown()

    def _on_sigterm(signum, frame):
        print("SIGTERM: router draining (/readyz now 503)")
        srv.begin_drain()
        threading.Thread(target=_drain_and_exit, daemon=True).start()

    import signal

    signal.signal(signal.SIGTERM, _on_sigterm)
    print(
        f"routing {len(replicas)} replica(s) on "
        f"http://{args.host}:{args.port}: "
        + ", ".join(f"{rid}={url}" for rid, url in replicas)
    )
    srv.serve_forever()


if __name__ == "__main__":
    main()
