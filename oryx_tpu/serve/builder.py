"""Model builder: assemble tokenizer + params + config from a checkpoint dir.

Reference parity: `load_pretrained_model()` in `oryx/model/builder.py`
(SURVEY.md §2 "Model builder", §3.2) — loads the tokenizer, the causal LM,
the vision tower and the image processor in one call. Here the checkpoint
can be either:

  * an oryx_tpu-native directory: `oryx_config.json` + an orbax checkpoint
    tree (as written by utils/checkpoint.CheckpointManager), or
  * a pair of HF safetensors directories (LLM + vision tower), imported via
    models/import_hf with a freshly initialized compressor (the reference's
    "stage-0" state before projector pretraining), optionally merged with a
    projector-only npz (`pretrain_mm_mlp_adapter` analog).

There is no separate "image processor" object: native-resolution
preprocessing is pure host numpy (data/mm_utils.py), configured entirely by
`cfg.vision` — `OryxInference` applies it.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp

from oryx_tpu.config import OryxConfig
from oryx_tpu.models import import_hf, oryx
from oryx_tpu.utils import checkpoint as ckpt_lib

Params = dict[str, Any]

CONFIG_NAME = "oryx_config.json"


def save_pretrained(
    directory: str, cfg: OryxConfig, state_or_params: Any, *, step: int = 0
) -> None:
    """Write a self-contained model directory loadable by
    `load_pretrained_model`: config json + orbax checkpoint.

    Multi-host: must be called on ALL processes — orbax coordinates the
    sharded write (each host persists the shards it owns). Saving from a
    single process would device_get remote shards and deadlock a pod
    (SURVEY.md §5 "Checkpoint / resume").
    """
    os.makedirs(directory, exist_ok=True)
    if jax.process_index() == 0:
        with open(os.path.join(directory, CONFIG_NAME), "w") as f:
            f.write(cfg.to_json())
    mgr = ckpt_lib.CheckpointManager(os.path.join(directory, "ckpt"))
    mgr.save(step, state_or_params, force=True)
    mgr.wait()
    mgr.close()


def load_tokenizer(model_path: str):
    """HF tokenizer from the checkpoint dir (tokenizer.json et al.)."""
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(model_path, use_fast=True)


def serving_param_shardings(mesh, params_like: Any, mode: str = "tp"):
    """Inference-time placement over a mesh (the reference's 34B
    `device_map` across 8 GPUs, SURVEY.md §2 "Model builder"):

      "tp"    weights split over attention heads / MLP columns (tp axis);
              embeddings/norms replicated — decode-friendly, no per-layer
              weight gathers.
      "fsdp"  memory-sharded over the fsdp axis (ZeRO-3-style); each
              layer's weights are all-gathered when used.

    params_like may be concrete or abstract (ShapeDtypeStructs).
    """
    from oryx_tpu.parallel import sharding as sharding_lib

    rules_mode = {"tp": "zero2", "fsdp": "fsdp"}.get(mode)
    if rules_mode is None:
        raise ValueError(f"unknown serving sharding mode {mode!r}: tp|fsdp")
    return sharding_lib.param_shardings(mesh, params_like, rules_mode)


def _serving_restore_target(meta, cfg: OryxConfig, mesh, mode: str, dtype):
    """Map checkpoint METADATA (bare params or a full TrainState) to an
    orbax restore target that pulls ONLY the model weights, sharded
    straight onto their serving devices: param leaves become abstract
    arrays with serving shardings (no host-RAM or single-device copy of
    a 34B tree); TrainState extras (optimizer moments, step) become
    `ckpt_lib.PLACEHOLDER` and are never read. The dtype override applies to
    floating leaves only."""
    import orbax.checkpoint as ocp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import numpy as np

    from oryx_tpu.models import oryx

    params_shape = jax.eval_shape(
        lambda: oryx.init_params(cfg, jax.random.key(0))
    )
    specs = serving_param_shardings(mesh, params_shape, mode)
    flat_specs = [
        (tuple(str(p) for p in path), s.spec)
        for path, s in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: hasattr(x, "spec")
        )[0]
    ]
    meta_paths = [
        tuple(str(p) for p in path)
        for path, _ in jax.tree_util.tree_flatten_with_path(meta)[0]
    ]
    # TrainState-shaped checkpoints carry the weights under a top-level
    # "params" node; bare-params checkpoints ARE the weights (their top
    # level is llm/vit/compressor).
    state_shaped = any("params" in keys[0] for keys in meta_paths)

    def build(path, leaf):
        keys = tuple(str(p) for p in path)
        wanted = "params" in keys[0] if state_shaped else True
        if not wanted:
            return ckpt_lib.PLACEHOLDER
        spec = P()
        for ppath, s in flat_specs:
            if keys[-len(ppath):] == ppath and len(leaf.shape) == len(s):
                spec = s
                break
        d = leaf.dtype
        if dtype is not None and np.issubdtype(leaf.dtype, np.floating):
            d = dtype
        return jax.ShapeDtypeStruct(
            leaf.shape, d, sharding=NamedSharding(mesh, spec)
        )

    return jax.tree_util.tree_map_with_path(build, meta), state_shaped


def load_pretrained_model(
    model_path: str,
    *,
    tokenizer_path: str | None = None,
    tokenizer: Any | None = None,
    cfg: OryxConfig | None = None,
    dtype=jnp.float32,
    mesh=None,
    sharding_mode: str = "tp",
    quantize: str | None = None,
) -> tuple[Any, Params, OryxConfig]:
    """Load (tokenizer, params, cfg) from an oryx_tpu model directory.

    tokenizer_path defaults to model_path; pass the HF backbone dir when the
    model dir carries no tokenizer files, or inject `tokenizer` directly.

    mesh: when given, params are restored SHARDED over it per
    `serving_param_shardings(mode=sharding_mode)` — required for models
    that exceed one chip (34B-class serving); pass the same mesh to
    `OryxInference`.

    quantize="int8": weight-only per-channel int8 for single-chip
    serving (utils/quant.py) — halves weight HBM so 7B-class models fit
    one v5e. Mutually exclusive with mesh (sharded restore would need
    Q8-aware specs).
    """
    if quantize not in (None, "int8"):
        raise ValueError(f"quantize={quantize!r}: int8 or None")
    if quantize and mesh is not None:
        raise ValueError(
            "quantize='int8' is single-chip serving; drop --shard "
            "(sharded serving streams weights over ICI instead)"
        )
    cfg_file = os.path.join(model_path, CONFIG_NAME)
    if cfg is None:
        if not os.path.exists(cfg_file):
            raise FileNotFoundError(
                f"{cfg_file} not found; pass cfg= explicitly or use "
                "load_from_hf() for raw HF checkpoints"
            )
        with open(cfg_file) as f:
            cfg = OryxConfig.from_json(f.read())

    ckpt_dir = os.path.join(model_path, "ckpt")
    if not os.path.isdir(ckpt_dir):
        raise FileNotFoundError(f"no orbax checkpoint under {ckpt_dir}")
    mgr = ckpt_lib.CheckpointManager(ckpt_dir)
    try:
        if mesh is None:
            # Restore the checkpoint's own structure (orbax rejects a
            # target tree that is a strict subtree, so a bare-params
            # abstract target would fail on TrainState-shaped
            # checkpoints).
            restored = mgr.restore()
            cast = lambda x: jnp.asarray(x, dtype)  # noqa: E731
        else:
            target, _ = _serving_restore_target(
                mgr.metadata(), cfg, mesh, sharding_mode, dtype
            )
            restored = mgr.restore_partial(target)
            cast = lambda x: x  # dtype applied in the restore target
    finally:
        mgr.close()
    # Both checkpoint shapes: take the weights subtree of a TrainState.
    if isinstance(restored, dict) and "params" in restored:
        restored = restored["params"]
    if quantize == "int8":
        from oryx_tpu.utils.quant import quantize_params

        # Quantize leaf-by-leaf straight off the host restore: the full
        # float tree never lands on the device (it wouldn't fit the very
        # chip --quantize targets).
        params = quantize_params(restored, cast=cast)
    else:
        params = jax.tree.map(cast, restored)

    if tokenizer is None:
        tokenizer = load_tokenizer(tokenizer_path or model_path)
    return tokenizer, params, cfg


def load_from_hf(
    llm_path: str,
    vision_path: str,
    cfg: OryxConfig,
    *,
    projector_path: str | None = None,
    lora_path: str | None = None,
    dtype=jnp.float32,
    seed: int = 0,
) -> tuple[Any, Params, OryxConfig]:
    """Assemble params from HF safetensors checkpoints (SURVEY.md §3.3
    `initialize_vision_modules`): Qwen2/Yi LLM + SigLIP-family tower, fresh
    compressor (or merged from a projector-only npz). lora_path merges a
    PEFT adapter into the LLM (the reference builder's model_base+LoRA
    path)."""
    llm_sd = import_hf.load_safetensors_dir(llm_path)
    vit_sd = import_hf.load_safetensors_dir(vision_path)
    llm = import_hf.import_qwen2(llm_sd, cfg.llm, dtype)
    if lora_path is not None:
        llm = import_hf.merge_lora_dir(llm, lora_path, cfg.llm)
    params: Params = {
        "llm": llm,
        "vit": import_hf.import_siglip(vit_sd, cfg.vision, dtype),
        "compressor": oryx.init_params(cfg, jax.random.key(seed), dtype)[
            "compressor"
        ],
    }
    if projector_path is not None:
        params = ckpt_lib.load_projector_only(projector_path, params)
    tokenizer = load_tokenizer(llm_path)
    return tokenizer, params, cfg


def load_pipeline(
    model_path: str,
    *,
    tokenizer_path: str | None = None,
    tokenizer: Any | None = None,
    shard: str | None = None,
    mesh=None,
    sharding_mode: str = "tp",
    template: str = "qwen",
    quantize: str | None = None,
):
    """One-call serving setup shared by the serve/eval/API CLIs:
    (optionally sharded, optionally int8-quantized) model load →
    OryxInference. Pass either a `--shard`-style string (`shard="tp=8"`)
    or a pre-built mesh + mode (CLIs parse the string themselves so
    malformed values surface as argparse usage errors, not load
    failures)."""
    from oryx_tpu.serve.pipeline import OryxInference

    if shard is not None:
        from oryx_tpu.parallel.mesh import parse_shard_arg

        mesh, sharding_mode = parse_shard_arg(shard)
    tokenizer, params, cfg = load_pretrained_model(
        model_path, tokenizer_path=tokenizer_path, tokenizer=tokenizer,
        mesh=mesh, sharding_mode=sharding_mode, quantize=quantize,
    )
    return OryxInference(
        tokenizer, params, cfg, template=template, mesh=mesh,
        sharding_mode=sharding_mode,
    )


def export_hf(directory: str, cfg: OryxConfig, params: Params) -> None:
    """Write a reference-layout checkpoint (LLM + vision safetensors +
    projector npz) for interop with reference-stack users."""
    import_hf.save_hf_checkpoint(params, cfg.llm, cfg.vision, directory)
