"""Model builder: assemble tokenizer + params + config from a checkpoint dir.

Reference parity: `load_pretrained_model()` in `oryx/model/builder.py`
(SURVEY.md §2 "Model builder", §3.2) — loads the tokenizer, the causal LM,
the vision tower and the image processor in one call. Here the checkpoint
can be either:

  * an oryx_tpu-native directory: `oryx_config.json` + an orbax checkpoint
    tree (as written by utils/checkpoint.CheckpointManager), or
  * a pair of HF safetensors directories (LLM + vision tower), imported via
    models/import_hf with a freshly initialized compressor (the reference's
    "stage-0" state before projector pretraining), optionally merged with a
    projector-only npz (`pretrain_mm_mlp_adapter` analog).

There is no separate "image processor" object: native-resolution
preprocessing is pure host numpy (data/mm_utils.py), configured entirely by
`cfg.vision` — `OryxInference` applies it.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp

from oryx_tpu.config import OryxConfig
from oryx_tpu.models import import_hf, oryx
from oryx_tpu.utils import checkpoint as ckpt_lib

Params = dict[str, Any]

CONFIG_NAME = "oryx_config.json"


def save_pretrained(
    directory: str, cfg: OryxConfig, state_or_params: Any, *, step: int = 0
) -> None:
    """Write a self-contained model directory loadable by
    `load_pretrained_model`: config json + orbax checkpoint.

    Multi-host: must be called on ALL processes — orbax coordinates the
    sharded write (each host persists the shards it owns). Saving from a
    single process would device_get remote shards and deadlock a pod
    (SURVEY.md §5 "Checkpoint / resume").
    """
    os.makedirs(directory, exist_ok=True)
    if jax.process_index() == 0:
        with open(os.path.join(directory, CONFIG_NAME), "w") as f:
            f.write(cfg.to_json())
    mgr = ckpt_lib.CheckpointManager(os.path.join(directory, "ckpt"))
    mgr.save(step, state_or_params, force=True)
    mgr.wait()
    mgr.close()


def load_tokenizer(model_path: str):
    """HF tokenizer from the checkpoint dir (tokenizer.json et al.)."""
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(model_path, use_fast=True)


def load_pretrained_model(
    model_path: str,
    *,
    tokenizer_path: str | None = None,
    tokenizer: Any | None = None,
    cfg: OryxConfig | None = None,
    dtype=jnp.float32,
) -> tuple[Any, Params, OryxConfig]:
    """Load (tokenizer, params, cfg) from an oryx_tpu model directory.

    tokenizer_path defaults to model_path; pass the HF backbone dir when the
    model dir carries no tokenizer files, or inject `tokenizer` directly.
    """
    cfg_file = os.path.join(model_path, CONFIG_NAME)
    if cfg is None:
        if not os.path.exists(cfg_file):
            raise FileNotFoundError(
                f"{cfg_file} not found; pass cfg= explicitly or use "
                "load_from_hf() for raw HF checkpoints"
            )
        with open(cfg_file) as f:
            cfg = OryxConfig.from_json(f.read())

    ckpt_dir = os.path.join(model_path, "ckpt")
    if not os.path.isdir(ckpt_dir):
        raise FileNotFoundError(f"no orbax checkpoint under {ckpt_dir}")
    mgr = ckpt_lib.CheckpointManager(ckpt_dir)
    try:
        # Restore the checkpoint's own structure (orbax rejects a target
        # tree that is a strict subtree, so a bare-params abstract target
        # would fail on TrainState-shaped checkpoints), then take params.
        restored = mgr.restore()
    finally:
        mgr.close()
    # Accept both bare-params and TrainState-shaped checkpoints.
    if isinstance(restored, dict) and "params" in restored:
        restored = restored["params"]
    params = jax.tree.map(lambda x: jnp.asarray(x, dtype), restored)

    if tokenizer is None:
        tokenizer = load_tokenizer(tokenizer_path or model_path)
    return tokenizer, params, cfg


def load_from_hf(
    llm_path: str,
    vision_path: str,
    cfg: OryxConfig,
    *,
    projector_path: str | None = None,
    lora_path: str | None = None,
    dtype=jnp.float32,
    seed: int = 0,
) -> tuple[Any, Params, OryxConfig]:
    """Assemble params from HF safetensors checkpoints (SURVEY.md §3.3
    `initialize_vision_modules`): Qwen2/Yi LLM + SigLIP-family tower, fresh
    compressor (or merged from a projector-only npz). lora_path merges a
    PEFT adapter into the LLM (the reference builder's model_base+LoRA
    path)."""
    llm_sd = import_hf.load_safetensors_dir(llm_path)
    vit_sd = import_hf.load_safetensors_dir(vision_path)
    llm = import_hf.import_qwen2(llm_sd, cfg.llm, dtype)
    if lora_path is not None:
        llm = import_hf.merge_lora_dir(llm, lora_path, cfg.llm)
    params: Params = {
        "llm": llm,
        "vit": import_hf.import_siglip(vit_sd, cfg.vision, dtype),
        "compressor": oryx.init_params(cfg, jax.random.key(seed), dtype)[
            "compressor"
        ],
    }
    if projector_path is not None:
        params = ckpt_lib.load_projector_only(projector_path, params)
    tokenizer = load_tokenizer(llm_path)
    return tokenizer, params, cfg


def export_hf(directory: str, cfg: OryxConfig, params: Params) -> None:
    """Write a reference-layout checkpoint (LLM + vision safetensors +
    projector npz) for interop with reference-stack users."""
    import_hf.save_hf_checkpoint(params, cfg.llm, cfg.vision, directory)
