"""Inference CLI: `python -m oryx_tpu.serve.cli --model-path ... --image ...`.

Reference parity: the README inference example / demo CLI (SURVEY.md §2
"Inference example / demo"). Video input is a directory of frame images or
any file decodable by PIL per frame; native video decode (decord/ffmpeg)
stays an optional host-side dependency (SURVEY.md §2a last row).
"""

from __future__ import annotations

import argparse
import sys

from oryx_tpu.data import media


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="Oryx-TPU inference")
    ap.add_argument("--model-path", required=True)
    ap.add_argument("--tokenizer-path", default=None)
    ap.add_argument("--question", required=True)
    ap.add_argument("--image", action="append", default=[],
                    help="image path (repeatable)")
    ap.add_argument("--video", default=None,
                    help="video file (decord) or directory of frames")
    ap.add_argument("--num-frames", type=int, default=64)
    ap.add_argument("--max-new-tokens", type=int, default=None)
    ap.add_argument("--template", default="qwen")
    args = ap.parse_args(argv)

    from oryx_tpu.serve.builder import load_pretrained_model
    from oryx_tpu.serve.pipeline import OryxInference

    tokenizer, params, cfg = load_pretrained_model(
        args.model_path, tokenizer_path=args.tokenizer_path
    )
    pipe = OryxInference(tokenizer, params, cfg, template=args.template)

    if args.video is not None:
        frames = media.load_video_frames(args.video, args.num_frames)
        answer = pipe.chat_video(
            frames, args.question, max_new_tokens=args.max_new_tokens
        )
    else:
        images = [media.load_image(p) for p in args.image]
        answer = pipe.chat(
            args.question, images=images or None,
            max_new_tokens=args.max_new_tokens,
        )
    print(answer)


if __name__ == "__main__":
    main(sys.argv[1:])
