"""Inference CLI: `python -m oryx_tpu.serve.cli --model-path ... --image ...`.

Reference parity: the README inference example / demo CLI (SURVEY.md §2
"Inference example / demo"). Video input is a directory of frame images or
any file decodable by PIL per frame; native video decode (decord/ffmpeg)
stays an optional host-side dependency (SURVEY.md §2a last row).
"""

from __future__ import annotations

import argparse
import sys

from oryx_tpu.data import media


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="Oryx-TPU inference")
    ap.add_argument("--model-path", required=True)
    ap.add_argument("--tokenizer-path", default=None)
    ap.add_argument(
        "--question", default=None,
        help="one-shot question (omit with --interactive)",
    )
    ap.add_argument(
        "--interactive", action="store_true",
        help="multi-turn REPL over the given media (reference CLI loop); "
        "':reset' clears history, ':q' exits",
    )
    ap.add_argument("--image", action="append", default=[],
                    help="image path (repeatable)")
    ap.add_argument("--video", default=None,
                    help="video file (decord) or directory of frames")
    ap.add_argument("--num-frames", type=int, default=64)
    ap.add_argument("--max-new-tokens", type=int, default=None)
    ap.add_argument("--template", default="qwen")
    ap.add_argument(
        "--shard", default=None, metavar="MODE=N",
        help="multi-chip serving over all visible devices, e.g. tp=8 or "
        "fsdp=8 (34B-class models; the reference's device_map analog)",
    )
    ap.add_argument(
        "--quantize", default=None, choices=["int8"],
        help="weight-only int8 for single-chip serving (halves weight "
        "HBM; mutually exclusive with --shard)",
    )
    args = ap.parse_args(argv)
    if args.question is None and not args.interactive:
        ap.error("--question is required unless --interactive")
    if args.quantize and args.shard:
        ap.error("--quantize is single-chip serving; drop --shard")

    from oryx_tpu.parallel.mesh import parse_shard_arg
    from oryx_tpu.serve.builder import load_pipeline
    from oryx_tpu.serve.pipeline import ChatSession

    try:
        mesh, mode = parse_shard_arg(args.shard)
    except ValueError as e:
        ap.error(str(e))
    pipe = load_pipeline(
        args.model_path, tokenizer_path=args.tokenizer_path,
        mesh=mesh, sharding_mode=mode, template=args.template,
        quantize=args.quantize,
    )

    if args.video is not None:
        images = media.load_video_frames(args.video, args.num_frames)
        is_video = True
    else:
        images = [media.load_image(p) for p in args.image]
        is_video = False

    if args.interactive:
        # shared=True: a `:reset` (or a future session over the same
        # media) re-seeds from the pipe-level prefix index instead of
        # cold-prefilling the media + system prompt again.
        session = ChatSession(
            pipe, images=images, is_video=is_video, shared=True
        )

        def answer(q: str) -> None:
            print("assistant: ", end="", flush=True)
            for delta in session.ask_stream(
                q, max_new_tokens=args.max_new_tokens
            ):
                print(delta, end="", flush=True)
            print()

        if args.question:
            print(f"user: {args.question}")
            answer(args.question)
        while True:
            try:
                q = input("user: ").strip()
            except EOFError:
                break
            if q in (":q", ":quit", ":exit"):
                break
            if q == ":reset":
                session.reset()
                continue
            if not q:
                continue
            answer(q)
        return

    answer = pipe.chat(
        args.question, images=images or None, is_video=is_video,
        max_new_tokens=args.max_new_tokens,
    )
    print(answer)


if __name__ == "__main__":
    main(sys.argv[1:])
