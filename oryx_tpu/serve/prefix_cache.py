"""Shared-prefix KV reuse: one block-aligned token-ID radix index, two
storage planes.

Real Oryx traffic is dominated by a shared per-conversation prefix (the
system prompt, the media context, earlier turns), and the TPU kernel
side is indifferent to which request owns a KV page (ragged paged
attention, PAPERS.md arXiv 2604.15464) — so "have I already computed
this prefix?" should be answered ONCE, by one index, for every serving
engine. `TokenTrie` below is that index: a radix trie over fixed-size
blocks of token ids (block size == the KV page size, so a cached prefix
is always page-aligned), with LRU stamps for eviction. Two clients give
its nodes meaning:

  * `PagedPrefixCache` — the continuous scheduler's plane. Each node
    owns ONE page of the paged pool (the cache's own reference, via
    `PageAllocator.share`); admission splices matched pages into the
    new slot's block table (sharing full pages, copy-on-writing a
    partially-consumed one) and prefills only the suffix. Under pool
    pressure, refcount-1 entries (pages nobody but the cache holds) are
    LRU-evicted back to the free list — cached pages go before live
    requests ever do.
  * `SessionPrefixCache` — the dense-cache plane for the pipeline /
    window-engine path. Nodes hold whole `PrefixCacheState` snapshots,
    so a fresh `ChatSession` over the same media + system prompt seeds
    itself from a finished session's KV instead of cold-prefilling.
    Capacity-bounded (dense caches are HBM-expensive), LRU.

Matching is on token IDS (vLLM-style): a tokenizer boundary merge just
shortens the reuse, never changes a reply. Multimodal streams key their
visual slots positionally, so both planes root their tries at a media
fingerprint — a cache built over different media can never be matched.
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np


class TrieNode:
    __slots__ = ("children", "payload", "stamp", "parent", "key")

    def __init__(self, parent: "TrieNode | None", key: bytes):
        self.children: dict[bytes, TrieNode] = {}
        self.payload: Any = None
        self.stamp = 0
        self.parent = parent
        self.key = key


class TokenTrie:
    """Radix trie over fixed-size BLOCKS of token ids.

    Only whole blocks index (a partial tail block never creates a
    node), so every match length is a multiple of `block` — the
    page-alignment invariant both cache planes rely on. `root_key`
    partitions the trie (media fingerprints); `stamp` is a global LRU
    clock bumped on every walk/extend touch.
    """

    def __init__(self, block: int):
        if block < 1:
            raise ValueError(f"block size must be >= 1, got {block}")
        self.block = block
        self.roots: dict[tuple, TrieNode] = {}
        self._clock = 0

    @staticmethod
    def _block_key(tokens: np.ndarray) -> bytes:
        return np.ascontiguousarray(tokens, np.int64).tobytes()

    def _touch(self, node: TrieNode) -> None:
        self._clock += 1
        node.stamp = self._clock

    def walk(self, tokens, root_key: tuple = ()) -> list[TrieNode]:
        """Longest-prefix match: the node path for the leading full
        blocks of `tokens` present in the trie (LRU-touched), possibly
        empty. Matched length is `len(result) * block` tokens."""
        tokens = np.asarray(tokens)
        node = self.roots.get(root_key)
        path: list[TrieNode] = []
        if node is None:
            return path
        for i in range(len(tokens) // self.block):
            key = self._block_key(
                tokens[i * self.block: (i + 1) * self.block]
            )
            child = node.children.get(key)
            if child is None:
                break
            path.append(child)
            node = child
        for n in path:
            self._touch(n)
        return path

    def extend(self, tokens, root_key: tuple = ()) -> list[TrieNode]:
        """Walk + create: the node path for ALL leading full blocks of
        `tokens`, creating missing nodes (payload None) along the way."""
        tokens = np.asarray(tokens)
        node = self.roots.get(root_key)
        if node is None:
            node = self.roots[root_key] = TrieNode(None, b"")
        path: list[TrieNode] = []
        for i in range(len(tokens) // self.block):
            key = self._block_key(
                tokens[i * self.block: (i + 1) * self.block]
            )
            child = node.children.get(key)
            if child is None:
                child = node.children[key] = TrieNode(node, key)
            path.append(child)
            node = child
        for n in path:
            self._touch(n)
        return path

    def remove(self, node: TrieNode) -> None:
        """Detach a LEAF node (asserted) from its parent; empty roots
        are pruned."""
        if node.children:
            raise ValueError("only leaf nodes can be removed")
        parent = node.parent
        if parent is not None:
            del parent.children[node.key]
            if parent.parent is None and not parent.children:
                for rk, root in list(self.roots.items()):
                    if root is parent:
                        del self.roots[rk]
        node.parent = None

    def nodes(self) -> Iterable[TrieNode]:
        """Every block node (roots are structural, not yielded)."""
        stack = list(self.roots.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.parent is not None:
                yield n

    def leaves(self) -> list[TrieNode]:
        return [n for n in self.nodes() if not n.children]

    def __len__(self) -> int:
        return sum(1 for _ in self.nodes())


class PagedPrefixCache:
    """The continuous scheduler's shared-prefix page cache.

    Each trie node owns one page of the paged pool: `insert` takes the
    cache's OWN reference on newly indexed pages (`allocator.share`), so
    a donated page outlives the request that computed it; `lookup`
    returns the matched page list for the caller to splice (the CALLER
    shares the pages it keeps — lookup itself takes no references).
    `evict` walks leaves least-recently-used first and frees pages only
    the cache still holds (refcount 1); entries shared with a live slot
    are pinned until that slot releases them.
    """

    def __init__(self, allocator, *, metrics=None):
        self.allocator = allocator
        self.page_size = allocator.page_size
        # No locks BY DESIGN: the cache (trie + page accounting) is
        # engine-thread-owned — admission splice, insert-at-donate,
        # LRU eviction and clear all run on the engine loop. That
        # ownership is not folklore: the `# thread-owned:` annotations
        # are enforced by the armed race detector
        # (analysis/sanitizers.py), which flags any touch from a
        # second live thread. The supervisor/drain paths may rebuild
        # the cache only once the engine thread is dead (thread death
        # is the happens-before edge the detector honors).
        self.trie = TokenTrie(allocator.page_size)  # thread-owned: engine
        self.metrics = metrics
        self._pages = 0  # thread-owned: engine
        # Publish zeros now: a cache rebuilt after a pool reset must not
        # leave the gauges reporting the dead pool's values.
        self._gauges()

    # ---- accounting ------------------------------------------------------

    @property
    def pages(self) -> int:
        """Pages the cache holds a reference to (== trie nodes)."""
        return self._pages

    @property
    def entries(self) -> int:
        """Distinct cached prefixes (trie leaves)."""
        return len(self.trie.leaves())

    def held_pages(self) -> list[int]:
        """Every page the cache holds one reference to (for the pool
        invariant check)."""
        return [n.payload for n in self.trie.nodes()]

    def evictable_pages(self, exclude=()) -> int:
        """Upper bound on what `evict` could free right now: pages only
        the cache holds (refcount 1), minus `exclude` (pages the caller
        is about to pin). An inner refcount-1 node blocked by a shared
        descendant is counted but unreachable — callers use this as a
        feasibility screen, not a promise."""
        exclude = set(exclude)
        return sum(
            1 for n in self.trie.nodes()
            if n.payload not in exclude
            and self.allocator.refcount(n.payload) == 1
        )

    def _gauges(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("prefix_cache_pages", self._pages)
            self.metrics.set_gauge("prefix_cache_entries", self.entries)

    # ---- the cache surface -----------------------------------------------

    def lookup(self, tokens, root_key: tuple = ()) -> tuple[int, list[int]]:
        """Longest page-aligned cached prefix of `tokens` →
        (matched_tokens, pages). pages[i] holds tokens
        [i*page_size, (i+1)*page_size). Takes no page references."""
        path = self.trie.walk(tokens, root_key)
        return len(path) * self.page_size, [n.payload for n in path]

    def insert(self, tokens, pages: list[int], root_key: tuple = ()) -> int:
        """Index the full-page prefix of `tokens`, whose KV lives in
        `pages` (one per block, in order). Newly indexed pages get one
        cache-owned reference (`share`); blocks already present keep
        their existing page — the duplicate stays the caller's to
        release — and just have their LRU refreshed. Returns the number
        of pages newly indexed."""
        n_full = min(len(tokens) // self.page_size, len(pages))
        if n_full <= 0:
            return 0
        path = self.trie.extend(
            np.asarray(tokens)[: n_full * self.page_size], root_key
        )
        new = 0
        for node, page in zip(path, pages):
            if node.payload is None:
                # "cache" is the ownership-map stamp the page-pool
                # observatory classifies cache-owned pages by.
                self.allocator.share([int(page)], owner="cache")
                node.payload = int(page)
                new += 1
        self._pages += new
        self._gauges()
        return new

    def evict(self, need_pages: int) -> int:
        """Free at least `need_pages` pages the cache alone holds
        (refcount 1), least-recently-used leaves first — cached pages
        are reclaimed before any live request is ever evicted. Returns
        the number actually freed (may be fewer: entries shared with
        live slots are pinned)."""
        freed = 0
        while freed < need_pages:
            # One gather per ROUND, oldest first (removing a leaf never
            # un-leafs another gathered leaf); parents exposed as new
            # leaves are picked up by the next round only if still
            # short — O(rounds x trie), not O(pages x trie).
            candidates = sorted(
                (
                    n for n in self.trie.leaves()
                    if self.allocator.refcount(n.payload) == 1
                ),
                key=lambda n: n.stamp,
            )
            if not candidates:
                break
            for victim in candidates:
                if freed >= need_pages:
                    break
                self.allocator.release([victim.payload], owner="cache")
                self.trie.remove(victim)
                self._pages -= 1
                freed += 1
        if freed and self.metrics is not None:
            self.metrics.inc("prefix_cache_evicted_pages_total", freed)
        self._gauges()
        return freed

    def clear(self) -> None:
        """Drop every entry, releasing the cache's references (used when
        the scheduler rebuilds a consumed pool)."""
        for node in list(self.trie.nodes()):
            if node.payload is not None:
                self.allocator.release([node.payload], owner="cache")
        self.trie = TokenTrie(self.page_size)
        self._pages = 0
        self._gauges()


class SessionPrefixCache:
    """Dense-cache plane: longest-prefix lookup over `PrefixCacheState`
    snapshots (serve/pipeline.py), so a fresh ChatSession over the same
    media + system prompt inherits a finished session's KV instead of
    cold-prefilling it.

    A state is reachable from EVERY node along its id stream's path —
    a new prompt diverges from a stored stream at its own question, so
    the useful hit is the deepest COMMON node, not the stored stream's
    end. `lookup` returns the state at that node; the pipeline's
    `_prefix_plan` then computes the exact longest common token prefix
    against it and re-prefills only the rest (so an over-long candidate
    only ever shortens the reuse, never corrupts it). Dense caches are
    HBM-expensive: capacity bounds the number of live states, LRU.
    """

    def __init__(self, block_size: int = 16, capacity: int = 4):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.trie = TokenTrie(block_size)
        self.capacity = capacity
        self._states: dict[int, Any] = {}  # id(state) -> state, LRU order

    @property
    def entries(self) -> int:
        return len(self._states)

    def lookup(self, flat_ids, media_key: tuple = ()):
        """The state stored at the deepest node along `flat_ids`' block
        path (LRU-refreshed), or None."""
        path = self.trie.walk(flat_ids, root_key=tuple(media_key))
        for node in reversed(path):
            if node.payload is not None:
                state = node.payload
                self._states.pop(id(state), None)
                self._states[id(state)] = state
                return state
        return None

    def insert(self, state) -> None:
        """Store `state` along its full block path (streams shorter than
        one block are not worth caching), evicting the least-recently-
        used stored state beyond capacity. States the overwrite leaves
        with no reachable node (the normal multi-turn case: each turn's
        stream extends the last, shadowing its whole path) are dropped
        immediately — an unreachable state would otherwise pin a dense
        HBM cache against capacity for zero hit value."""
        path = self.trie.extend(
            np.asarray(state.ids), root_key=tuple(state.media_key)
        )
        if not path:
            return
        displaced = {
            id(n.payload): n.payload for n in path
            if n.payload is not None and n.payload is not state
        }
        for node in path:
            node.payload = state
        self._states.pop(id(state), None)
        self._states[id(state)] = state
        if displaced:
            reachable = {
                id(n.payload) for n in self.trie.nodes()
                if n.payload is not None
            }
            for sid in displaced.keys() - reachable:
                self._states.pop(sid, None)
        while len(self._states) > self.capacity:
            _, victim = next(iter(self._states.items()))
            self._drop(victim)

    def _drop(self, state) -> None:
        self._states.pop(id(state), None)
        for node in list(self.trie.nodes()):
            if node.payload is state:
                node.payload = None
        # Prune now-useless branches (childless, payload-less).
        changed = True
        while changed:
            changed = False
            for leaf in self.trie.leaves():
                if leaf.payload is None:
                    self.trie.remove(leaf)
                    changed = True
