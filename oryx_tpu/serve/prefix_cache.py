"""Shared-prefix KV reuse: one block-aligned token-ID radix index, two
storage planes.

Real Oryx traffic is dominated by a shared per-conversation prefix (the
system prompt, the media context, earlier turns), and the TPU kernel
side is indifferent to which request owns a KV page (ragged paged
attention, PAPERS.md arXiv 2604.15464) — so "have I already computed
this prefix?" should be answered ONCE, by one index, for every serving
engine. `TokenTrie` below is that index: a radix trie over fixed-size
blocks of token ids (block size == the KV page size, so a cached prefix
is always page-aligned), with LRU stamps for eviction. Two clients give
its nodes meaning:

  * `PagedPrefixCache` — the continuous scheduler's plane. Each node
    owns ONE page of the paged pool (the cache's own reference, via
    `PageAllocator.share`); admission splices matched pages into the
    new slot's block table (sharing full pages, copy-on-writing a
    partially-consumed one) and prefills only the suffix. Under pool
    pressure, refcount-1 entries (pages nobody but the cache holds) are
    LRU-evicted back to the free list — cached pages go before live
    requests ever do.
  * `SessionPrefixCache` — the dense-cache plane for the pipeline /
    window-engine path. Nodes hold whole `PrefixCacheState` snapshots,
    so a fresh `ChatSession` over the same media + system prompt seeds
    itself from a finished session's KV instead of cold-prefilling.
    Capacity-bounded (dense caches are HBM-expensive), LRU.

Matching is on token IDS (vLLM-style): a tokenizer boundary merge just
shortens the reuse, never changes a reply. Multimodal streams key their
visual slots positionally, so both planes root their tries at a media
fingerprint — a cache built over different media can never be matched.
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np

from oryx_tpu.utils import faults


class TrieNode:
    __slots__ = ("children", "payload", "stamp", "parent", "key")

    def __init__(self, parent: "TrieNode | None", key: bytes):
        self.children: dict[bytes, TrieNode] = {}
        self.payload: Any = None
        self.stamp = 0
        self.parent = parent
        self.key = key


class TokenTrie:
    """Radix trie over fixed-size BLOCKS of token ids.

    Only whole blocks index (a partial tail block never creates a
    node), so every match length is a multiple of `block` — the
    page-alignment invariant both cache planes rely on. `root_key`
    partitions the trie (media fingerprints); `stamp` is a global LRU
    clock bumped on every walk/extend touch.
    """

    def __init__(self, block: int):
        if block < 1:
            raise ValueError(f"block size must be >= 1, got {block}")
        self.block = block
        self.roots: dict[tuple, TrieNode] = {}
        self._clock = 0

    @staticmethod
    def _block_key(tokens: np.ndarray) -> bytes:
        return np.ascontiguousarray(tokens, np.int64).tobytes()

    def _touch(self, node: TrieNode) -> None:
        self._clock += 1
        node.stamp = self._clock

    def walk(self, tokens, root_key: tuple = ()) -> list[TrieNode]:
        """Longest-prefix match: the node path for the leading full
        blocks of `tokens` present in the trie (LRU-touched), possibly
        empty. Matched length is `len(result) * block` tokens."""
        tokens = np.asarray(tokens)
        node = self.roots.get(root_key)
        path: list[TrieNode] = []
        if node is None:
            return path
        for i in range(len(tokens) // self.block):
            key = self._block_key(
                tokens[i * self.block: (i + 1) * self.block]
            )
            child = node.children.get(key)
            if child is None:
                break
            path.append(child)
            node = child
        for n in path:
            self._touch(n)
        return path

    def extend(self, tokens, root_key: tuple = ()) -> list[TrieNode]:
        """Walk + create: the node path for ALL leading full blocks of
        `tokens`, creating missing nodes (payload None) along the way."""
        tokens = np.asarray(tokens)
        node = self.roots.get(root_key)
        if node is None:
            node = self.roots[root_key] = TrieNode(None, b"")
        path: list[TrieNode] = []
        for i in range(len(tokens) // self.block):
            key = self._block_key(
                tokens[i * self.block: (i + 1) * self.block]
            )
            child = node.children.get(key)
            if child is None:
                child = node.children[key] = TrieNode(node, key)
            path.append(child)
            node = child
        for n in path:
            self._touch(n)
        return path

    def remove(self, node: TrieNode) -> None:
        """Detach a LEAF node (asserted) from its parent; empty roots
        are pruned."""
        if node.children:
            raise ValueError("only leaf nodes can be removed")
        parent = node.parent
        if parent is not None:
            del parent.children[node.key]
            if parent.parent is None and not parent.children:
                for rk, root in list(self.roots.items()):
                    if root is parent:
                        del self.roots[rk]
        node.parent = None

    def nodes(self) -> Iterable[TrieNode]:
        """Every block node (roots are structural, not yielded)."""
        stack = list(self.roots.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.parent is not None:
                yield n

    def leaves(self) -> list[TrieNode]:
        return [n for n in self.nodes() if not n.children]

    def __len__(self) -> int:
        return sum(1 for _ in self.nodes())


class HostEntry:
    """One spilled cache page living in host RAM: the byte-verbatim
    device blob (every layer's K/V — and scale blocks on a quantized
    pool — for one page, from ops/paged_kv.fetch_page) plus its byte
    size for the --host-cache-bytes budget."""

    __slots__ = ("blob", "nbytes")

    def __init__(self, blob, nbytes: int):
        self.blob = blob
        self.nbytes = int(nbytes)


class PagedPrefixCache:
    """The continuous scheduler's shared-prefix page cache.

    Each trie node owns one page of the paged pool: `insert` takes the
    cache's OWN reference on newly indexed pages (`allocator.share`), so
    a donated page outlives the request that computed it; `lookup`
    returns the matched page list for the caller to splice (the CALLER
    shares the pages it keeps — lookup itself takes no references).
    `evict` walks leaves least-recently-used first and frees pages only
    the cache still holds (refcount 1); entries shared with a live slot
    are pinned until that slot releases them.

    Host-RAM spill tier (docs/DESIGN.md "KV quantization & cache
    tiering"): with `host_cache_bytes > 0` and the two device-copy
    callbacks wired, an LRU-evicted entry SPILLS to pinned host RAM —
    a byte-verbatim copy of the page (and, on a quantized pool, its
    scale block) — instead of dying. The device page still returns to
    the free list (eviction's whole point), but the prefix survives in
    a parallel host-side trie: a later lookup that walks past the
    device-resident prefix into spilled blocks re-uploads those pages
    ahead of the suffix prefill (`reload`), so cache capacity is
    bounded by HOST RAM, not HBM. Spill/reload is lossless by
    construction (same dtype both ways, no re-encode), so a reloaded
    splice is byte-identical to never having evicted. A failed
    re-upload (fault site `host_spill_upload`, or pool pressure at
    reload time) just shortens the match — the suffix recomputes cold,
    never crashes.

      spill_fetch(page) -> (blob, nbytes): device -> host page copy.
      spill_upload(blob, page) -> None: host -> device, into a page
        the cache just allocated.
    """

    def __init__(self, allocator, *, metrics=None,
                 host_cache_bytes: int = 0,
                 spill_fetch=None, spill_upload=None):
        self.allocator = allocator
        self.page_size = allocator.page_size
        if host_cache_bytes < 0:
            raise ValueError(
                f"host_cache_bytes must be >= 0, got {host_cache_bytes}"
            )
        self.host_cache_bytes = int(host_cache_bytes)
        self.spill_fetch = spill_fetch
        self.spill_upload = spill_upload
        self.spill_enabled = bool(
            host_cache_bytes > 0
            and spill_fetch is not None and spill_upload is not None
        )
        # The host tier's own trie (same block geometry; payloads are
        # HostEntry blobs, no pool pages) + its byte ledger. Engine-
        # thread-owned like the device trie.
        self._host = TokenTrie(allocator.page_size)  # thread-owned: engine
        self._host_bytes = 0  # thread-owned: engine
        self._spilled = 0  # thread-owned: engine
        # No locks BY DESIGN: the cache (trie + page accounting) is
        # engine-thread-owned — admission splice, insert-at-donate,
        # LRU eviction and clear all run on the engine loop. That
        # ownership is not folklore: the `# thread-owned:` annotations
        # are enforced by the armed race detector
        # (analysis/sanitizers.py), which flags any touch from a
        # second live thread. The supervisor/drain paths may rebuild
        # the cache only once the engine thread is dead (thread death
        # is the happens-before edge the detector honors).
        self.trie = TokenTrie(allocator.page_size)  # thread-owned: engine
        self.metrics = metrics
        self._pages = 0  # thread-owned: engine
        # Publish zeros now: a cache rebuilt after a pool reset must not
        # leave the gauges reporting the dead pool's values.
        self._gauges()

    # ---- accounting ------------------------------------------------------

    @property
    def pages(self) -> int:
        """Pages the cache holds a reference to (== trie nodes)."""
        return self._pages

    @property
    def entries(self) -> int:
        """Distinct cached prefixes (trie leaves)."""
        return len(self.trie.leaves())

    def held_pages(self) -> list[int]:
        """Every page the cache holds one reference to (for the pool
        invariant check)."""
        return [n.payload for n in self.trie.nodes()]

    def evictable_pages(self, exclude=()) -> int:
        """Upper bound on what `evict` could free right now: pages only
        the cache holds (refcount 1), minus `exclude` (pages the caller
        is about to pin). An inner refcount-1 node blocked by a shared
        descendant is counted but unreachable — callers use this as a
        feasibility screen, not a promise."""
        exclude = set(exclude)
        return sum(
            1 for n in self.trie.nodes()
            if n.payload not in exclude
            and self.allocator.refcount(n.payload) == 1
        )

    @property
    def spilled_pages(self) -> int:
        """Host-tier entries (pages living in host RAM only)."""
        return self._spilled

    @property
    def host_bytes(self) -> int:
        """Host RAM the spill tier currently holds."""
        return self._host_bytes

    def _gauges(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("prefix_cache_pages", self._pages)
            self.metrics.set_gauge("prefix_cache_entries", self.entries)
            reg = self.metrics.registry
            reg.gauge("oryx_cache_spilled_pages", raw_name=True).set(
                self._spilled
            )
            reg.gauge("oryx_cache_host_bytes", raw_name=True).set(
                self._host_bytes
            )

    # ---- the cache surface -----------------------------------------------

    def lookup(self, tokens, root_key: tuple = ()) -> tuple[int, list[int]]:
        """Longest page-aligned cached prefix of `tokens` →
        (matched_tokens, pages). pages[i] holds tokens
        [i*page_size, (i+1)*page_size). Takes no page references.
        Device tier only — `lookup_tiered` also surfaces the host-side
        continuation."""
        pages = self._device_pages(self.trie.walk(tokens, root_key))
        return len(pages) * self.page_size, pages

    @staticmethod
    def _device_pages(path: list[TrieNode]) -> list[int]:
        """The walked path's page ids, truncated at the first node
        without one. Payload-less device nodes cannot arise through
        the public surface (insert/reload always set payloads along
        the path), but a hole must shorten the match, never reach the
        splice as int(None)."""
        pages: list[int] = []
        for n in path:
            if n.payload is None:
                break
            pages.append(n.payload)
        return pages

    def lookup_tiered(
        self, tokens, root_key: tuple = ()
    ) -> tuple[int, list[int], list[TrieNode]]:
        """`lookup` plus the spilled continuation: (device_matched
    tokens, device_pages, host_nodes) where host_nodes are the
    host-tier trie nodes for the blocks immediately FOLLOWING the
    device-resident prefix, contiguous and each holding a HostEntry
    (a hole — a hard-evicted block — ends the run: everything past
    it must recompute anyway). Takes no references; pass the nodes
    to `reload` to bring them back on device."""
        pages = self._device_pages(self.trie.walk(tokens, root_key))
        host_nodes: list[TrieNode] = []
        if self.spill_enabled:
            hpath = self._host.walk(tokens, root_key)
            for node in hpath[len(pages):]:
                if node.payload is None:
                    break
                host_nodes.append(node)
        return len(pages) * self.page_size, pages, host_nodes

    def reload(self, tokens, host_nodes: list[TrieNode],
               root_key: tuple = ()) -> list[int]:
        """Re-upload spilled blocks onto fresh device pages, ahead of
        the caller's suffix prefill: for each host node in order,
        allocate one page (cache-owned), upload the blob byte-verbatim
        (fault site `host_spill_upload`), and re-index the block in the
        DEVICE trie — the entry is device-resident again, exactly as if
        it had never been evicted. Stops at the first failure
        (allocation or upload) and returns the device pages of the
        blocks actually reloaded: a partial reload is a shorter splice,
        and the suffix recomputes cold — degradation, never a crash."""
        depth0 = self._depth(host_nodes[0]) if host_nodes else 0
        reloaded: list[int] = []
        for node in host_nodes:
            entry = node.payload
            try:
                page = self.allocator.alloc(1, owner="cache")[0]
            except Exception:
                break
            try:
                # Chaos site: host->device re-upload failure. The
                # contract under it: free the page, shorten the match,
                # let admission recompute the suffix cold.
                faults.fault_point(
                    "host_spill_upload",
                    exc=lambda: RuntimeError(
                        "injected host-tier re-upload failure"
                    ),
                )
                self.spill_upload(entry.blob, page)
            # fault-boundary: a failed re-upload degrades to a cold
            # recompute of the suffix — the page returns, the spilled
            # entry stays for the next attempt, nothing leaks
            except Exception:
                self.allocator.free([page], owner="cache")
                break
            reloaded.append(page)
            self._host_forget_node(node)
        if reloaded:
            path = self.trie.extend(
                np.asarray(tokens)[
                    : (depth0 + len(reloaded)) * self.page_size
                ],
                root_key,
            )
            for i, page in enumerate(reloaded):
                node = path[depth0 + i]
                if node.payload is None:
                    node.payload = int(page)
                    self._pages += 1
                else:  # unreachable by the engine-thread ownership
                    self.allocator.free([page], owner="cache")
            if self.metrics is not None:
                reg = self.metrics.registry
                reg.counter(
                    "oryx_cache_reload_hit_total", raw_name=True
                ).inc()
                reg.counter(
                    "oryx_cache_reload_upload_total", raw_name=True
                ).inc(len(reloaded))
        self._gauges()
        return reloaded

    # ---- host tier internals --------------------------------------------

    @staticmethod
    def _depth(node: TrieNode) -> int:
        """Block index of a trie node (root children are index 0; the
        structural root is not a block and does not count)."""
        d = -1
        while node is not None and node.parent is not None:
            d += 1
            node = node.parent
        return d

    def _node_tokens(self, node: TrieNode) -> np.ndarray:
        """The full token stream a device-trie node indexes (its path's
        concatenated block keys) — what keys the host twin on spill."""
        keys: list[bytes] = []
        while node is not None and node.parent is not None:
            keys.append(node.key)
            node = node.parent
        return np.frombuffer(b"".join(reversed(keys)), np.int64)

    def _node_root_key(self, node: TrieNode) -> tuple:
        """The root partition a node lives under (media fingerprint)."""
        while node.parent is not None:
            node = node.parent
        for rk, root in self.roots_of(self.trie):
            if root is node:
                return rk
        return ()

    @staticmethod
    def roots_of(trie: TokenTrie):
        return list(trie.roots.items())

    def _spill(self, victim: TrieNode) -> bool:
        """Move a device-trie victim's page contents to the host tier
        (byte-verbatim). Returns False — caller falls back to a plain
        eviction — when the tier is off, the budget cannot fit the
        entry even after LRU drops, or the device copy fails."""
        if not self.spill_enabled:
            return False
        try:
            blob, nbytes = self.spill_fetch(victim.payload)
        # fault-boundary: a failed device->host copy demotes the spill
        # to a plain eviction; the entry dies, nothing leaks
        except Exception:
            return False
        if nbytes > self.host_cache_bytes:
            return False
        if self._host_bytes + nbytes > self.host_cache_bytes:
            # ONE LRU scan per spill, dropping oldest leaf entries
            # until the new blob fits (a per-drop rescan would make a
            # budget-pressure spill storm quadratic on the engine
            # thread — same discipline as the device evict's
            # one-gather-per-round loop).
            victims = sorted(
                (n for n in self._host.leaves()
                 if n.payload is not None),
                key=lambda n: n.stamp,
            )
            for v in victims:
                if self._host_bytes + nbytes <= self.host_cache_bytes:
                    break
                self._host_bytes -= v.payload.nbytes
                self._spilled -= 1
                v.payload = None
                self._host_prune_chain(v)
            if self._host_bytes + nbytes > self.host_cache_bytes:
                return False
        tokens = self._node_tokens(victim)
        root_key = self._node_root_key(victim)
        hpath = self._host.extend(tokens, root_key)
        node = hpath[-1]
        if node.payload is not None:
            self._host_bytes -= node.payload.nbytes
            self._spilled -= 1
        node.payload = HostEntry(blob, nbytes)
        self._host_bytes += nbytes
        self._spilled += 1
        return True

    def _host_forget_node(self, node: TrieNode) -> None:
        """Drop one host entry's bytes (reloaded or superseded) and
        prune whatever chain that leaves dead."""
        if node.payload is not None:
            self._host_bytes -= node.payload.nbytes
            self._spilled -= 1
            node.payload = None
        self._host_prune_chain(node)

    def _host_prune_chain(self, node: TrieNode | None) -> None:
        """Remove the dead suffix of ONE path: walking UP from `node`,
        drop childless payload-less nodes until a live ancestor (or
        the root). O(depth) per forget/drop — a full-trie rescan here
        made reload and LRU churn quadratic on the engine thread
        (dead nodes only ever appear along the path just touched, so
        the upward walk reaches every one a rescan would)."""
        while (
            node is not None and node.parent is not None
            and not node.children and node.payload is None
        ):
            parent = node.parent
            self._host.remove(node)
            node = parent

    def insert(self, tokens, pages: list[int], root_key: tuple = ()) -> int:
        """Index the full-page prefix of `tokens`, whose KV lives in
        `pages` (one per block, in order). Newly indexed pages get one
        cache-owned reference (`share`); blocks already present keep
        their existing page — the duplicate stays the caller's to
        release — and just have their LRU refreshed. Returns the number
        of pages newly indexed."""
        n_full = min(len(tokens) // self.page_size, len(pages))
        if n_full <= 0:
            return 0
        path = self.trie.extend(
            np.asarray(tokens)[: n_full * self.page_size], root_key
        )
        new = 0
        for node, page in zip(path, pages):
            if node.payload is None:
                # "cache" is the ownership-map stamp the page-pool
                # observatory classifies cache-owned pages by.
                self.allocator.share([int(page)], owner="cache")
                node.payload = int(page)
                new += 1
        self._pages += new
        if new and self.spill_enabled:
            # Blocks recomputed cold (e.g. after a failed re-upload)
            # are device-resident again: their host twins are stale
            # duplicates now — drop them so the budget holds live
            # spill value only.
            hpath = self._host.walk(
                np.asarray(tokens)[: n_full * self.page_size], root_key
            )
            for dnode, hnode in zip(path, hpath):
                if hnode.payload is not None and dnode.payload is not None:
                    self._host_forget_node(hnode)
        self._gauges()
        return new

    def evict(self, need_pages: int, *, exclude=()) -> int:
        """Free at least `need_pages` pages the cache alone holds
        (refcount 1), least-recently-used leaves first — cached pages
        are reclaimed before any live request is ever evicted. With the
        host tier armed, each victim's bytes SPILL to host RAM before
        its device page returns (the entry survives, reloadable);
        otherwise the entry dies. Returns the number of device pages
        actually freed (may be fewer: entries shared with live slots
        are pinned).

        exclude: page ids that must NOT be evicted this call. The
        reload path passes the device prefix it just matched — those
        pages are still refcount-1 (lookup takes no references; the
        requester's share lands only after reload), so without the
        exclusion an eviction round could free the very pages the
        splice is about to share."""
        exclude = {int(p) for p in exclude}
        freed = 0
        while freed < need_pages:
            # One gather per ROUND, oldest first (removing a leaf never
            # un-leafs another gathered leaf); parents exposed as new
            # leaves are picked up by the next round only if still
            # short — O(rounds x trie), not O(pages x trie).
            candidates = sorted(
                (
                    n for n in self.trie.leaves()
                    if n.payload not in exclude
                    and self.allocator.refcount(n.payload) == 1
                ),
                key=lambda n: n.stamp,
            )
            if not candidates:
                break
            for victim in candidates:
                if freed >= need_pages:
                    break
                self._spill(victim)
                self.allocator.release([victim.payload], owner="cache")
                self.trie.remove(victim)
                self._pages -= 1
                freed += 1
        if freed and self.metrics is not None:
            self.metrics.inc("prefix_cache_evicted_pages_total", freed)
        self._gauges()
        return freed

    def clear(self) -> None:
        """Drop every entry — device references AND the host tier
        (used when the scheduler rebuilds a consumed pool, and by
        degraded-mode cache shedding: a shed must actually free the
        host RAM too)."""
        for node in list(self.trie.nodes()):
            if node.payload is not None:
                self.allocator.release([node.payload], owner="cache")
        self.trie = TokenTrie(self.page_size)
        self._pages = 0
        self._host = TokenTrie(self.page_size)
        self._host_bytes = 0
        self._spilled = 0
        self._gauges()


class SessionPrefixCache:
    """Dense-cache plane: longest-prefix lookup over `PrefixCacheState`
    snapshots (serve/pipeline.py), so a fresh ChatSession over the same
    media + system prompt inherits a finished session's KV instead of
    cold-prefilling it.

    A state is reachable from EVERY node along its id stream's path —
    a new prompt diverges from a stored stream at its own question, so
    the useful hit is the deepest COMMON node, not the stored stream's
    end. `lookup` returns the state at that node; the pipeline's
    `_prefix_plan` then computes the exact longest common token prefix
    against it and re-prefills only the rest (so an over-long candidate
    only ever shortens the reuse, never corrupts it). Dense caches are
    HBM-expensive: capacity bounds the number of live states, LRU.
    """

    def __init__(self, block_size: int = 16, capacity: int = 4):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.trie = TokenTrie(block_size)
        self.capacity = capacity
        self._states: dict[int, Any] = {}  # id(state) -> state, LRU order

    @property
    def entries(self) -> int:
        return len(self._states)

    def lookup(self, flat_ids, media_key: tuple = ()):
        """The state stored at the deepest node along `flat_ids`' block
        path (LRU-refreshed), or None."""
        path = self.trie.walk(flat_ids, root_key=tuple(media_key))
        for node in reversed(path):
            if node.payload is not None:
                state = node.payload
                self._states.pop(id(state), None)
                self._states[id(state)] = state
                return state
        return None

    def insert(self, state) -> None:
        """Store `state` along its full block path (streams shorter than
        one block are not worth caching), evicting the least-recently-
        used stored state beyond capacity. States the overwrite leaves
        with no reachable node (the normal multi-turn case: each turn's
        stream extends the last, shadowing its whole path) are dropped
        immediately — an unreachable state would otherwise pin a dense
        HBM cache against capacity for zero hit value."""
        path = self.trie.extend(
            np.asarray(state.ids), root_key=tuple(state.media_key)
        )
        if not path:
            return
        displaced = {
            id(n.payload): n.payload for n in path
            if n.payload is not None and n.payload is not state
        }
        for node in path:
            node.payload = state
        self._states.pop(id(state), None)
        self._states[id(state)] = state
        if displaced:
            reachable = {
                id(n.payload) for n in self.trie.nodes()
                if n.payload is not None
            }
            for sid in displaced.keys() - reachable:
                self._states.pop(sid, None)
        while len(self._states) > self.capacity:
            _, victim = next(iter(self._states.items()))
            self._drop(victim)

    def _drop(self, state) -> None:
        self._states.pop(id(state), None)
        for node in list(self.trie.nodes()):
            if node.payload is state:
                node.payload = None
        # Prune now-useless branches (childless, payload-less).
        changed = True
        while changed:
            changed = False
            for leaf in self.trie.leaves():
                if leaf.payload is None:
                    self.trie.remove(leaf)
                    changed = True
